"""History-driven feedback control: the server that tunes itself
(docs/tuning.md).

PR 14-19 built the *sensors* — the persistent query history, the
per-signature aggregates, the doctor's verdict taxonomy, SLO burn
tracking — and left the *actuation* to the operator: the doctor names
a culprit conf, a human flips it. This module closes the loop. The
server embeds a :class:`TuningController`
(``spark.rapids.sql.serve.tuning.enabled``; requires
``telemetry.history.dir``) that, at server start and on a periodic
tick, scores the history through the ``signature_aggregates`` +
doctor-verdict pipeline and applies per-signature actions from the
declared :data:`ACTION_CATALOG`:

- ``compileStorm`` -> **prewarmCaches**: replay the signature's
  recorded SQL through the planning path at server start so the plan
  template exists before the first client hits it, and protect the
  entry from LRU eviction (``plan_cache.set_prewarm_digests``);
- ``retrySpill`` -> **limitConcurrency** (narrow that signature's
  admission concurrency — fewer copies of a spill-prone shape in
  flight means each gets more HBM headroom) and/or **seedOutOfCore**
  (turn the budget oracle on so joins/aggs partition up front,
  docs/out_of_core.md);
- ``kernelFallback`` -> **kernelFallback**: flip the culprit kernel
  conf named by the record's ``kernelFallbacksByName`` and
  re-baseline (``kernel.*.enabled`` is signature-relevant, so the
  flip starts a NEW signature history);
- SLO burn -> **tenantWeight**: shift the burning tenant's admission
  weight up so it gets a larger fair share.

Every action is BOUNDED (per-knob min/max clamps declared in the
catalog), LOGGED (a ``tuning`` record in the same history store — the
audit trail rides the store's durability), EXPORTED (``srt_tuning_*``
Prometheus families), INSPECTABLE (``tools tuning``; pin/revert by
epoch), and GUARDED: each applied action remembers the pre-action
p50/p99 baseline, and once ``serve.tuning.guardWindowQueries``
post-action finished records exist for its scope the controller diffs
observed p50/p99 against that baseline with the same relative-change
discipline ``tools bench-diff`` gates on — a regression past
``serve.tuning.revertThreshold`` auto-reverts the action and logs a
``revert`` record. ``site:tuning:N`` in the fault grammar injects a
deliberately harmful synthetic action at the Nth tick so the
observe-and-revert loop is deterministically testable.

State (action list, epoch counter, pre-warm ledger) persists in
``<history_dir>/tuning-state.json``: applied actions re-apply at the
next server start — a retry-storm shape admitted narrowly today is
admitted narrowly tomorrow — and ``tools tuning --pin/--revert``
writes control flags the controller honors at its next tick, so the
CLI never races the live server's knob writes.

Tuning never changes what a query COMPUTES — only admission shaping,
cache residency, and kernel-tier routing, all of which are
bit-identity-preserving by their own contracts.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from spark_rapids_tpu.conf import (SERVE_TUNING_ENABLED,
                                   SERVE_TUNING_GUARD_WINDOW,
                                   SERVE_TUNING_INTERVAL_S,
                                   SERVE_TUNING_MAX_ACTIONS,
                                   SERVE_TUNING_MAX_PREWARM,
                                   SERVE_TUNING_REVERT_THRESHOLD,
                                   TELEMETRY_HISTORY_DIR)
from spark_rapids_tpu.telemetry.history import (STATUS_FINISHED,
                                                STATUS_REVERT,
                                                STATUS_TUNING,
                                                build_tuning_record,
                                                read_records, sig_digest,
                                                store_for)

STATE_FILE = "tuning-state.json"
STATE_VERSION = 1

# Internal (non-conf) knobs an action may write. Everything else a
# catalog entry names must be a REGISTERED conf key — the tpu-lint
# `tuning-action` rule enforces both.
KNOB_SIGNATURE_CONCURRENCY = "signatureConcurrency"
KNOB_TENANT_WEIGHT = "tenantWeight"
KNOB_PREWARM = "prewarm"
INTERNAL_KNOBS = (KNOB_SIGNATURE_CONCURRENCY, KNOB_TENANT_WEIGHT,
                  KNOB_PREWARM)

# The declared action vocabulary. PURE LITERALS ONLY: the tpu-lint
# `tuning-action` rule parses this dict from the AST — every action
# the controller constructs (`_new_action("<name>", ...)`) must be a
# key here, and every `spark.rapids.*` knob string below must be a
# registered conf key. The generated docs/tuning.md action table
# renders from this dict, so code, lint, and docs share one source.
# Bounds are inclusive clamps on the written value (booleans clamp on
# 0/1); `verdict` is the doctor verdict (or `sloBurn`) that motivates
# the action.
ACTION_CATALOG: Dict[str, Dict[str, Any]] = {
    "prewarmCaches": {
        "verdict": "compileStorm",
        "knob": "prewarm",
        "min": 0, "max": 1,
        "doc": "add the signature to the pre-warm ledger: its recorded "
               "SQL replays through the planning path at server start "
               "(plan template built before the first client hits it) "
               "and the plan-cache entry is protected from LRU "
               "eviction; ledger size bounded by "
               "serve.tuning.maxPrewarm",
    },
    "limitConcurrency": {
        "verdict": "retrySpill",
        "knob": "signatureConcurrency",
        "min": 1, "max": 4,
        "doc": "cap the signature's concurrent admissions "
               "(AdmissionController per-signature limit): fewer "
               "copies of a spill-prone shape in flight means each "
               "gets more HBM headroom instead of riding the "
               "spill-and-retry loop",
    },
    "seedOutOfCore": {
        "verdict": "retrySpill",
        "knob": "spark.rapids.sql.outOfCore.enabled",
        "min": 0, "max": 1,
        "doc": "turn the budget oracle on server-wide so joins/aggs "
               "over-budget partition UP FRONT (docs/out_of_core.md) "
               "instead of discovering the overflow via retry storms",
    },
    "kernelFallback": {
        "verdict": "kernelFallback",
        "knob": "spark.rapids.sql.kernel.groupbyHash.enabled",
        "knobs": ["spark.rapids.sql.kernel.groupbyHash.enabled",
                  "spark.rapids.sql.kernel.joinProbe.enabled",
                  "spark.rapids.sql.kernel.decodeFused.enabled"],
        "min": 0, "max": 1,
        "doc": "flip the culprit kernel conf (named by the record's "
               "kernelFallbacksByName) to false: a shape whose oracle "
               "keeps falling back pays the probe cost for nothing. "
               "kernel.*.enabled is signature-relevant, so the flip "
               "RE-BASELINES — the new signature accumulates its own "
               "history (accepted immediately; manual revert only)",
    },
    "tenantWeight": {
        "verdict": "sloBurn",
        "knob": "tenantWeight",
        "min": 0.25, "max": 4.0,
        "doc": "raise the burning tenant's admission weight "
               "(AdmissionController fair-share cap scales by it) so "
               "the tenant missing its p99 objective gets a larger "
               "share of the in-flight budget",
    },
}

# how many distinct sql<->signature pairs the controller remembers for
# the prewarm ledger / admission hints (bounded: ad-hoc shapes must
# not grow it without limit)
_SQL_MAP_CAP = 256


# ---------------------------------------------------------------------------
# State file (the CLI's integration point)
# ---------------------------------------------------------------------------

def state_path(history_dir: str) -> str:
    return os.path.join(history_dir, STATE_FILE)


def load_state(history_dir: str) -> Dict[str, Any]:
    """The persisted controller state (empty skeleton when absent or
    unreadable — a torn write must not take the server down)."""
    try:
        with open(state_path(history_dir), encoding="utf-8") as f:
            st = json.load(f)
        if isinstance(st, dict) and isinstance(st.get("actions"), list):
            st.setdefault("version", STATE_VERSION)
            st.setdefault("epoch", 0)
            st.setdefault("prewarm", {})
            return st
    except (OSError, ValueError):
        pass
    return {"version": STATE_VERSION, "epoch": 0, "actions": [],
            "prewarm": {}}


def save_state(history_dir: str, state: Dict[str, Any]) -> None:
    """Atomic replace (tmp + rename): the CLI and a crashing server
    must never leave a half-written state file."""
    try:
        os.makedirs(history_dir, exist_ok=True)
        tmp = state_path(history_dir) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(state, f, indent=1, default=str)
        os.replace(tmp, state_path(history_dir))
    except OSError:
        pass


def format_tuning(state: Dict[str, Any]) -> str:
    """The `tools tuning` table: one row per action, newest first."""
    acts = list(state.get("actions") or [])
    lines = ["=== TPU Tuning Controller ===",
             f"epoch {state.get('epoch', 0)}, "
             f"{len(acts)} action(s) on record", ""]
    if not acts:
        lines.append("no tuning actions recorded")
        return "\n".join(lines)
    lines.append(
        f"  {'epoch':>5s} {'action':17s} {'scope':18s} {'knob':24s} "
        f"{'old->new':14s} {'state':9s} flags")
    for a in sorted(acts, key=lambda a: -int(a.get("epoch", 0))):
        scope = str(a.get("scope") or "-")
        if len(scope) > 18:
            scope = scope[:15] + "..."
        flags = []
        if a.get("pinned"):
            flags.append("pinned")
        if a.get("revertRequested"):
            flags.append("revert-requested")
        if (a.get("evidence") or {}).get("injected"):
            flags.append("injected")
        ov = a.get("oldValue")
        delta = (("-" if ov is None else str(ov)) + "->"
                 + str(a.get("newValue")))
        lines.append(
            f"  {a.get('epoch', 0):5d} {a.get('action', '?'):17s} "
            f"{scope:18s} {str(a.get('knob') or '-'):24s} "
            f"{delta:14s} "
            f"{a.get('state', '?'):9s} {','.join(flags) or '-'}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Controller
# ---------------------------------------------------------------------------

class TuningController:
    """The feedback-control loop the QueryServer embeds.

    Collaborators are passed explicitly (never reached through the
    server object) so the controller is testable standalone:

    - ``admission``: an AdmissionController (set_signature_limit /
      signature_limit / set_tenant_weight / tenant_weight);
    - ``slo``: an SloTracker (or None) for the sloBurn action;
    - ``session_for(tenant)``: a session factory for the start-of-
      server pre-warm replay (None disables replay — the protection
      set still installs);
    - ``set_conf(key, value)`` / ``get_conf(key)``: server-wide conf
      write/read for conf-knob actions (kernel flips, out-of-core
      seeding); ``value=None`` removes the override.
    """

    def __init__(self, conf_obj, admission=None, slo=None,
                 session_for: Optional[Callable[[str], Any]] = None,
                 set_conf: Optional[Callable[[str, Any], None]] = None,
                 get_conf: Optional[Callable[[str], Any]] = None):
        self._conf = conf_obj
        self._admission = admission
        self._slo = slo
        self._session_for = session_for
        self._set_conf = set_conf
        self._get_conf = get_conf
        self._dir = str(conf_obj.get(TELEMETRY_HISTORY_DIR) or "")
        self._interval_s = float(conf_obj.get(SERVE_TUNING_INTERVAL_S))
        self._max_actions = int(conf_obj.get(SERVE_TUNING_MAX_ACTIONS))
        self._guard_window = int(conf_obj.get(SERVE_TUNING_GUARD_WINDOW))
        self._revert_threshold = float(
            conf_obj.get(SERVE_TUNING_REVERT_THRESHOLD))
        self._max_prewarm = int(conf_obj.get(SERVE_TUNING_MAX_PREWARM))
        self._lock = threading.RLock()
        self._state = load_state(self._dir) if self._dir else {
            "version": STATE_VERSION, "epoch": 0, "actions": [],
            "prewarm": {}}
        # sql <-> signature learning (observe()): digest -> {sql,
        # tenant} feeds the prewarm ledger; sql -> digest feeds the
        # admission hint (planning happens AFTER admission, so the
        # server can only shape admission for shapes it has seen)
        self._sig_sql: Dict[str, Dict[str, str]] = {}
        self._sql_sig: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (stats() -> srt_tuning_* families)
        self.ticks = 0
        self.actions_applied = 0
        self.actions_reverted = 0
        self.prewarm_replayed = 0
        self.last_scan_ts = 0.0

    @property
    def enabled(self) -> bool:
        return bool(self._dir) and bool(
            self._conf.get(SERVE_TUNING_ENABLED))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Re-apply persisted actions, replay the pre-warm ledger, run
        the start-of-server scan, then start the tick thread."""
        if not self.enabled:
            return
        with self._lock:
            self._reapply_persisted()
            self._replay_prewarm()
        self.tick()
        if self._interval_s > 0:
            self._thread = threading.Thread(
                target=self._tick_loop, name="srt-tuning-tick",
                daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _tick_loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            self.tick()

    # -- learning hooks (the server's request path) ------------------------

    def observe(self, sql: str, signature: Optional[str],
                tenant: Optional[str] = None) -> None:
        """Learn one executed query's sql<->signature pairing (digest
        form). Bounded maps; never raises."""
        if not sql or not signature:
            return
        with self._lock:
            if len(self._sql_sig) >= _SQL_MAP_CAP:
                self._sql_sig.clear()
                self._sig_sql.clear()
            self._sql_sig[sql] = signature
            self._sig_sql[signature] = {"sql": sql,
                                        "tenant": tenant or "default"}

    def signature_hint(self, sql: str) -> Optional[str]:
        """The signature digest this sql planned to last time (None for
        never-seen text) — the admission layer's per-signature limits
        need the digest BEFORE planning resolves it."""
        with self._lock:
            return self._sql_sig.get(sql)

    # -- the scan tick -----------------------------------------------------

    def tick(self) -> None:
        """One control iteration: honor CLI control flags, judge
        applied actions against their guard windows, then scan the
        history for new evidence and apply up to maxActionsPerTick new
        actions. Never raises — tuning must not take the server down."""
        if not self.enabled:
            return
        try:
            with self._lock:
                self.ticks += 1
                self.last_scan_ts = time.time()
                self._merge_control_flags()
                records = read_records(self._dir)
                self._honor_revert_requests()
                self._evaluate_guardrails(records)
                budget = self._max_actions
                budget -= self._maybe_inject_harmful()
                if budget > 0:
                    self._scan_and_apply(records, budget)
                save_state(self._dir, self._state)
        except Exception:
            pass

    def _merge_control_flags(self) -> None:
        """Take `pinned` / `revertRequested` per epoch from the ON-DISK
        state: `tools tuning` writes those flags (possibly while this
        server runs), and honoring them here means the CLI never races
        the controller's own knob writes."""
        disk = load_state(self._dir)
        by_epoch = {int(a.get("epoch", 0)): a
                    for a in disk.get("actions", [])}
        for a in self._state["actions"]:
            d = by_epoch.get(int(a.get("epoch", 0)))
            if d is not None:
                a["pinned"] = bool(d.get("pinned"))
                a["revertRequested"] = bool(d.get("revertRequested"))

    def _honor_revert_requests(self) -> None:
        for a in self._state["actions"]:
            if a.get("state") in ("applied", "accepted") and \
                    a.get("revertRequested"):
                self._revert(a, why="operator revert via tools tuning")

    # -- action construction / application ---------------------------------

    def _new_action(self, action: str, scope: str, knob: str,
                    old_value, new_value,
                    evidence: Dict[str, Any]) -> Dict[str, Any]:
        """The ONE construction point for actions (the tpu-lint
        `tuning-action` rule pins the literal name passed here to
        ACTION_CATALOG). Clamps the new value to the catalog bounds,
        assigns the epoch, and validates the knob against the catalog
        declaration."""
        cat = ACTION_CATALOG[action]
        allowed = cat.get("knobs", [cat["knob"]])
        if knob not in allowed and knob not in INTERNAL_KNOBS:
            raise ValueError(f"knob {knob!r} not declared for "
                             f"action {action!r}")
        if isinstance(new_value, (int, float)) \
                and not isinstance(new_value, bool):
            clamped = min(cat["max"], max(cat["min"], new_value))
        else:
            # bool / conf-string values ("true"/"false") have no
            # numeric range; the [min, max] column documents them as
            # the 0/1 domain
            clamped = new_value
        self._state["epoch"] = int(self._state.get("epoch", 0)) + 1
        return {
            "epoch": self._state["epoch"],
            "action": action,
            "scope": scope,
            "knob": knob,
            "oldValue": old_value,
            "newValue": clamped,
            "evidence": evidence,
            "state": "applied",
            "pinned": False,
            "revertRequested": False,
            "appliedTs": time.time(),
        }

    def _active(self, action: str, scope: str) -> bool:
        return any(a.get("action") == action and a.get("scope") == scope
                   and a.get("state") in ("applied", "accepted")
                   for a in self._state["actions"])

    def _write_knob(self, act: Dict[str, Any], value) -> None:
        """Actuate one knob write (apply or revert). Internal knobs go
        to the admission controller / pre-warm ledger; conf knobs go
        through the server's conf hook."""
        knob = act["knob"]
        scope = act["scope"]
        if knob == KNOB_SIGNATURE_CONCURRENCY:
            if self._admission is not None:
                self._admission.set_signature_limit(
                    scope, None if value is None else int(value))
        elif knob == KNOB_TENANT_WEIGHT:
            tenant = scope.split(":", 1)[1] if ":" in scope else scope
            if self._admission is not None:
                self._admission.set_tenant_weight(
                    tenant, 1.0 if value is None else float(value))
        elif knob == KNOB_PREWARM:
            if value:
                # prefer the live sql<->signature map, but fall back
                # to the persisted entry: at server start the re-apply
                # runs before any query is observed, and the ledger's
                # recorded SQL must survive the restart (it IS the
                # replay input)
                info = self._sig_sql.get(scope) \
                    or self._state["prewarm"].get(scope) or {}
                self._state["prewarm"][scope] = {
                    "sql": info.get("sql", ""),
                    "tenant": info.get("tenant", "default")}
                # ledger bound: oldest entries drop first (dict order
                # is insertion order)
                while len(self._state["prewarm"]) > self._max_prewarm:
                    self._state["prewarm"].pop(
                        next(iter(self._state["prewarm"])))
            else:
                self._state["prewarm"].pop(scope, None)
            from spark_rapids_tpu import plan_cache as PC
            PC.set_prewarm_digests(set(self._state["prewarm"]))
        else:
            if self._set_conf is not None:
                self._set_conf(knob, value)

    def _record(self, status: str, act: Dict[str, Any],
                old_value, new_value,
                evidence: Dict[str, Any]) -> None:
        store = store_for(self._conf)
        if store is None:
            return
        scope = act["scope"]
        sig = scope if not scope.startswith("tenant:") else None
        tenant = scope.split(":", 1)[1] \
            if scope.startswith("tenant:") else None
        store.append(build_tuning_record(
            status=status, action=act["action"], scope=scope,
            knob=act["knob"], old_value=old_value, new_value=new_value,
            evidence=evidence, epoch=act["epoch"], tenant=tenant,
            signature=sig))

    def _apply(self, act: Dict[str, Any]) -> None:
        self._write_knob(act, act["newValue"])
        self._state["actions"].append(act)
        self.actions_applied += 1
        self._record(STATUS_TUNING, act, act["oldValue"],
                     act["newValue"], act["evidence"])

    def _revert(self, act: Dict[str, Any], why: str,
                observed: Optional[Dict[str, Any]] = None) -> None:
        self._write_knob(act, act["oldValue"])
        act["state"] = "reverted"
        act["revertRequested"] = False
        act["revertedTs"] = time.time()
        self.actions_reverted += 1
        ev = {"why": why}
        if observed:
            ev["observed"] = observed
        ev["baseline"] = (act.get("evidence") or {}).get("baseline")
        self._record(STATUS_REVERT, act, act["newValue"],
                     act["oldValue"], ev)

    # -- persisted re-apply + pre-warm replay (server start) ---------------

    def _reapply_persisted(self) -> None:
        """Applied/accepted actions from the state file actuate again
        at start: the knobs live in server memory, the DECISIONS live
        on disk — a retry-storm shape admitted narrowly yesterday is
        admitted narrowly from query one today."""
        for a in self._state["actions"]:
            if a.get("state") in ("applied", "accepted") and \
                    not a.get("revertRequested"):
                try:
                    self._write_knob(a, a["newValue"])
                except Exception:
                    pass

    def _replay_prewarm(self) -> None:
        """Plan each pre-warm ledger entry's recorded SQL so the plan
        cache holds its template BEFORE the first client request (the
        compile-storm action's whole point). Best-effort per entry: a
        view that no longer exists skips, never fails the start."""
        from spark_rapids_tpu import plan_cache as PC
        PC.set_prewarm_digests(set(self._state["prewarm"]))
        if self._session_for is None:
            return
        for digest, info in list(self._state["prewarm"].items()):
            sql = info.get("sql") or ""
            if not sql:
                continue
            try:
                s = self._session_for(info.get("tenant", "default"))
                s.plan_physical(s.sql(sql).plan)
                self.prewarm_replayed += 1
                self._sql_sig[sql] = digest
                self._sig_sql[digest] = dict(info)
            except Exception:
                pass

    # -- guardrail ---------------------------------------------------------

    def _scope_walls(self, records: List[Dict[str, Any]],
                     scope: str, since: float) -> List[float]:
        """Post-action finished walls for an action's scope (signature
        digest or tenant:<id>), cache-served and control-plane records
        excluded — the same hygiene every baseline in the package
        applies."""
        tenant = scope.split(":", 1)[1] \
            if scope.startswith("tenant:") else None
        out = []
        for r in records:
            if r.get("status") != STATUS_FINISHED \
                    or r.get("resultCacheHit"):
                continue
            if float(r.get("ts", 0)) <= since:
                continue
            if tenant is not None:
                if r.get("tenant") != tenant:
                    continue
            elif r.get("signature") != scope:
                continue
            out.append(float(r.get("wallSeconds", 0.0)))
        return out

    def _evaluate_guardrails(self, records: List[Dict[str, Any]]
                             ) -> None:
        """Judge each applied, unpinned action once its guard window
        filled: relative change = (baseline - observed) / baseline for
        p50 and p99 (lower-is-better, the bench-diff discipline); a
        change below -revertThreshold on either reverts, otherwise the
        action graduates to accepted."""
        from spark_rapids_tpu.lifecycle import percentile
        for a in self._state["actions"]:
            if a.get("state") != "applied" or a.get("pinned"):
                continue
            if a.get("action") == "kernelFallback":
                # the flip re-baselines (new signature): the old
                # scope's window can never fill — accepted at birth,
                # manual revert only (documented in the catalog)
                a["state"] = "accepted"
                continue
            base = (a.get("evidence") or {}).get("baseline") or {}
            bp50 = float(base.get("p50", 0.0))
            bp99 = float(base.get("p99", 0.0))
            if bp50 <= 0:
                continue  # no pre-action baseline: nothing to diff
            walls = self._scope_walls(records, a["scope"],
                                      float(a.get("appliedTs", 0)))
            if len(walls) < max(1, self._guard_window):
                continue
            op50 = percentile(walls, 0.50)
            op99 = percentile(walls, 0.99)
            ch50 = (bp50 - op50) / bp50
            ch99 = (bp99 - op99) / bp99 if bp99 > 0 else 0.0
            observed = {"p50": round(op50, 6), "p99": round(op99, 6),
                        "windowQueries": len(walls),
                        "changeP50": round(ch50, 4),
                        "changeP99": round(ch99, 4)}
            if min(ch50, ch99) < -self._revert_threshold:
                self._revert(
                    a, why=(f"guardrail: post-action p50/p99 regressed "
                            f"past {self._revert_threshold:.0%}"),
                    observed=observed)
            else:
                a["state"] = "accepted"
                a["acceptedTs"] = time.time()
                a.setdefault("evidence", {})["accepted"] = observed

    # -- fault injection (site:tuning) --------------------------------------

    def _maybe_inject_harmful(self) -> int:
        """The ``site:tuning:N`` leg: at the scheduled tick, apply a
        deliberately HARMFUL synthetic action — a concurrency clamp
        whose recorded baseline is epsilon, so ANY observed wall reads
        as a regression and the guardrail must revert it. Returns the
        number of actions it spent from the tick budget."""
        from spark_rapids_tpu.retry import get_fault_injector
        inj = get_fault_injector(self._conf)
        if inj is None or not inj.on_tuning_tick():
            return 0
        scope = next(iter(self._sig_sql), None) or "0" * 40
        try:
            old = self._admission.signature_limit(scope) \
                if self._admission is not None else None
            act = self._new_action(
                "limitConcurrency", scope, KNOB_SIGNATURE_CONCURRENCY,
                old, 1,
                {"injected": True,
                 "why": "site:tuning fault — synthetic harmful action "
                        "for guardrail testing",
                 "baseline": {"p50": 1e-9, "p99": 1e-9}})
            self._apply(act)
            return 1
        except Exception:
            return 0

    # -- history scoring ----------------------------------------------------

    def _newest_record(self, records: List[Dict[str, Any]],
                       digest: str) -> Dict[str, Any]:
        for r in reversed(records):
            if r.get("signature") == digest and \
                    r.get("status") == STATUS_FINISHED and \
                    not r.get("resultCacheHit"):
                return r
        return {}

    def _scan_and_apply(self, records: List[Dict[str, Any]],
                        budget: int) -> None:
        """Score the history (doctor batch scan + SLO evaluation) and
        apply up to ``budget`` new actions for verdicts the catalog
        maps; scopes that already carry a live action of the same kind
        are skipped (convergence, not oscillation)."""
        from spark_rapids_tpu.telemetry.doctor import scan_signatures
        from spark_rapids_tpu.telemetry.history import \
            signature_aggregates
        aggs = signature_aggregates(records)
        try:
            scans = scan_signatures(self._dir, top=16)
        except Exception:
            scans = []
        for d in scans:
            if budget <= 0:
                return
            if not d.get("regressed"):
                continue
            digest = d.get("signatureFull")
            if not digest:
                continue
            agg = aggs.get(digest) or {}
            baseline = {"p50": (d.get("baseline") or {}).get(
                "wallP50", agg.get("wallP50", 0.0)),
                "p99": agg.get("wallP99", 0.0)}
            verdict = d.get("verdict")
            if verdict == "compileStorm" and \
                    not self._active("prewarmCaches", digest):
                act = self._new_action(
                    "prewarmCaches", digest, KNOB_PREWARM, False, True,
                    {"verdict": verdict, "baseline": baseline,
                     "slowdown": d.get("slowdown")})
                self._apply(act)
                budget -= 1
            elif verdict == "retrySpill":
                if not self._active("limitConcurrency", digest) \
                        and budget > 0:
                    old = self._admission.signature_limit(digest) \
                        if self._admission is not None else None
                    new = 2 if old is None else max(1, int(old) - 1)
                    act = self._new_action(
                        "limitConcurrency", digest,
                        KNOB_SIGNATURE_CONCURRENCY, old, new,
                        {"verdict": verdict, "baseline": baseline,
                         "slowdown": d.get("slowdown"),
                         "retryRate": agg.get("retryRate")})
                    self._apply(act)
                    budget -= 1
                ooc_key = ACTION_CATALOG["seedOutOfCore"]["knob"]
                cur = self._get_conf(ooc_key) \
                    if self._get_conf is not None else None
                if budget > 0 and self._set_conf is not None and \
                        not self._active("seedOutOfCore", digest) and \
                        str(cur).lower() != "true":
                    act = self._new_action(
                        "seedOutOfCore", digest, ooc_key,
                        cur, "true",
                        {"verdict": verdict, "baseline": baseline,
                         "slowdown": d.get("slowdown")})
                    self._apply(act)
                    budget -= 1
            elif verdict == "kernelFallback" and \
                    self._set_conf is not None:
                rec = self._newest_record(records, digest)
                by_name = rec.get("kernelFallbacksByName") or {}
                allowed = ACTION_CATALOG["kernelFallback"]["knobs"]
                for name, n in sorted(by_name.items(),
                                      key=lambda kv: (-kv[1], kv[0])):
                    key = f"spark.rapids.sql.kernel.{name}.enabled"
                    if key not in allowed or budget <= 0 or \
                            self._active("kernelFallback", digest):
                        continue
                    cur = self._get_conf(key) \
                        if self._get_conf is not None else None
                    if str(cur).lower() == "false":
                        continue  # already off
                    act = self._new_action(
                        "kernelFallback", digest, key, cur, "false",
                        {"verdict": verdict, "baseline": baseline,
                         "kernel": name, "fallbacks": int(n),
                         "rebaseline": True})
                    self._apply(act)
                    budget -= 1
        # SLO burn -> tenant weight shift
        if self._slo is None or budget <= 0:
            return
        try:
            slo = self._slo.evaluate()
        except Exception:
            slo = {}
        for tenant, st in sorted(slo.items()):
            if budget <= 0:
                return
            if st.get("burnRatio", 0.0) < 0.5 or \
                    st.get("windowQueries", 0) < 3:
                continue
            scope = f"tenant:{tenant}"
            if self._active("tenantWeight", scope) or \
                    self._admission is None:
                continue
            old = self._admission.tenant_weight(tenant)
            walls = self._scope_walls(records, scope, 0.0)
            from spark_rapids_tpu.lifecycle import percentile
            act = self._new_action(
                "tenantWeight", scope, KNOB_TENANT_WEIGHT,
                old, float(old) * 1.5,
                {"verdict": "sloBurn", "slo": st,
                 "baseline": {
                     "p50": round(percentile(walls, 0.50), 6),
                     "p99": round(percentile(walls, 0.99), 6)}})
            self._apply(act)
            budget -= 1

    # -- inspection ---------------------------------------------------------

    def actions(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._state["actions"]]

    def stats(self) -> Dict[str, Any]:
        """The server-stats `tuning` section (the Prometheus renderer
        exports these as srt_tuning_* families)."""
        with self._lock:
            acts = self._state["actions"]
            by_name: Dict[str, int] = {}
            for a in acts:
                by_name[a.get("action", "?")] = \
                    by_name.get(a.get("action", "?"), 0) + 1
            return {
                "enabled": True,
                "epoch": int(self._state.get("epoch", 0)),
                "ticks": self.ticks,
                "actionsApplied": self.actions_applied,
                "actionsReverted": self.actions_reverted,
                "actionsByName": by_name,
                "activeActions": sum(
                    1 for a in acts
                    if a.get("state") in ("applied", "accepted")),
                "pinnedActions": sum(1 for a in acts
                                     if a.get("pinned")),
                "prewarmedSignatures": len(self._state["prewarm"]),
                "prewarmReplayed": self.prewarm_replayed,
                "lastScanTs": self.last_scan_ts,
            }
