"""Live telemetry for the serving tier (docs/observability.md "Live
telemetry").

PRs 5/6 built *post-hoc* observability: per-query trace files and
profile artifacts you opt into before running. A long-lived multi-tenant
QueryServer needs the opposite — telemetry that is on by default, cheap
enough to never turn off, and able to reconstruct what just happened
after the fact. Four coordinated pieces:

- **flight recorder** (ring.py): ``spark.rapids.sql.trace.mode=ring``
  keeps the last N spans/instants/counter samples per thread in a
  fixed-size lock-free ring behind the existing Tracer; ``dump_ring``
  writes the standard Chrome-trace JSON so ``tools trace`` /
  ``tools hotspots`` work unchanged on dumps;
- **trigger engine** (triggers.py): declarative slow-query / retry /
  HBM-watermark / queue-saturation triggers that emit rate-limited
  *slow-query bundles* (ring dump + profile artifact + server stats +
  the triggering condition) into ``spark.rapids.sql.telemetry.dir``;
- **metrics endpoint** (prometheus.py): the QueryServer's ``metrics``
  protocol verb and the ``tools serve --metrics-port`` HTTP twin export
  the process metric registries + server stats in Prometheus text
  format, fed by a registry-delta aggregator whose counters stay
  monotone across plan lifetimes; ``tools top`` renders a live
  per-tenant terminal view over the same stats;
- **regression tracking** (bench_diff.py): ``tools bench-diff`` diffs
  two bench JSON outputs (headline walls + detail legs) against
  configurable thresholds with a machine-readable verdict and a
  nonzero exit on regression;
- **query history** (history.py): the persistent, bounded JSONL store
  of one record per finished query — the cross-run memory behind
  server warm-start (watchdog p99 + quarantine streaks survive
  restarts), per-tenant SLO burn tracking (``srt_slo_*`` families +
  the ``sloBurn`` trigger), ``tools history`` trends, and the
  ``tools doctor`` auto-diagnosis (doctor.py) that names WHY a query
  was slow against its signature's historical baseline.
"""

from spark_rapids_tpu.telemetry.ring import RingTrace, dump_ring  # noqa: F401
from spark_rapids_tpu.telemetry import triggers  # noqa: F401
