"""`tools top <port>`: a live terminal view over a running QueryServer
(docs/observability.md "Live telemetry").

Polls the server's ``stats`` verb on an interval and renders a
refreshing table of tenants x {QPS, p50/p99 latency, queue wait, live
HBM, in-flight, rejected} above a global admission/cache line — the
`nvidia-smi`-shaped answer to "what is this server doing right now".
Per-tenant QPS is computed from the admitted-count delta between two
polls (the first frame shows lifetime averages)."""

from __future__ import annotations

import sys
import time
from typing import Dict, Optional


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20),
                      ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n}B"


def format_top(stats: Dict, prev: Optional[Dict] = None,
               interval: float = 0.0) -> str:
    """One rendered frame from a server ``stats`` dict (pure function —
    the CLI loop and the tests share it)."""
    adm = stats.get("admission", {})
    hbm = stats.get("tenantsHBM", {})
    lines = [
        f"spark-rapids-tpu serve {stats.get('host', '?')}:"
        f"{stats.get('port', '?')}  up {stats.get('uptimeSeconds', 0):.0f}s"
        f"  ok {stats.get('queriesOk', 0)}  err {stats.get('queriesErr', 0)}"
        f"  qps {stats.get('qps', 0):.2f}",
        f"admission: {adm.get('inFlight', 0)} in flight, "
        f"{adm.get('queued', 0)} queued "
        f"(max {adm.get('maxConcurrentQueries', '?')}/"
        f"{adm.get('maxQueued', '?')}), "
        f"{adm.get('admitted', 0)} admitted, "
        f"{adm.get('rejected', 0)} rejected, "
        f"{adm.get('throttledWaits', 0)} fair-share waits",
    ]
    # result/subplan cache hit rates (docs/caching.md): line present
    # only when the server runs with a cache enabled
    cache = stats.get("cache") or {}

    def _rate(cs: Dict) -> str:
        probes = cs.get("hits", 0) + cs.get("misses", 0)
        pct = 100.0 * cs.get("hits", 0) / probes if probes else 0.0
        return (f"{cs.get('hits', 0)}/{probes} hits ({pct:.0f}%), "
                f"{cs.get('entries', 0)} entries "
                f"{_fmt_bytes(cs.get('bytes', 0))}")

    if cache:
        parts = []
        if cache.get("result") is not None:
            parts.append(f"result {_rate(cache['result'])}")
        if cache.get("subplan") is not None:
            parts.append(f"subplan {_rate(cache['subplan'])}")
        lines.append("cache: " + "; ".join(parts))
    lines += [
        "",
        f"{'tenant':16s} {'qps':>7s} {'p50ms':>8s} {'p99ms':>8s} "
        f"{'waitP99':>8s} {'liveHBM':>9s} {'inFlt':>5s} {'rej':>5s}",
    ]
    prev_tenants = (prev or {}).get("admission", {}).get("tenants", {})
    uptime = max(1e-9, float(stats.get("uptimeSeconds", 0)) or 1e-9)
    tenants = adm.get("tenants", {})
    for name in sorted(set(tenants) | set(hbm)):
        t = tenants.get(name, {})
        lat = t.get("latencyMs", {})
        wait = t.get("queueWaitMs", {})
        admitted = t.get("admitted", 0)
        if prev is not None and interval > 0:
            qps = (admitted
                   - prev_tenants.get(name, {}).get("admitted", 0)) \
                / interval
        else:
            qps = admitted / uptime
        live = hbm.get(name, {}).get("liveBytes", 0)
        lines.append(
            f"{name[:16]:16s} {qps:7.2f} "
            f"{lat.get('p50', 0):8.1f} {lat.get('p99', 0):8.1f} "
            f"{wait.get('p99', 0):8.1f} {_fmt_bytes(live):>9s} "
            f"{t.get('inFlight', 0):5d} {t.get('rejected', 0):5d}")
    if not tenants and not hbm:
        lines.append("(no tenants yet)")
    return "\n".join(lines)


def run_top(port: int, host: str = "127.0.0.1", interval: float = 2.0,
            iterations: int = 0, once: bool = False) -> int:
    """The CLI loop: ``iterations`` frames (0 = until interrupted);
    ``once`` renders exactly one frame (scripting sugar for
    ``--once``). Returns 0; a server that goes away MID-POLL (drained,
    restarted, crashed) is a clean exit — message + code 0, never a
    raw socket traceback — while an initial connect failure stays an
    error (code 1)."""
    from spark_rapids_tpu.serve import ServeClient
    if once:
        iterations = 1
    try:
        client = ServeClient(port, host=host)
    except OSError as e:
        print(f"cannot connect to {host}:{port}: {e}")
        return 1
    n = 0
    prev = None
    try:
        while True:
            try:
                stats = client.stats()
            except Exception as e:  # noqa: BLE001 - reported cleanly
                # mid-poll disappearance is the server's normal end of
                # life from a watcher's point of view: exit clean
                print(f"server at {host}:{port} went away: {e}")
                return 0
            frame = format_top(stats, prev=prev,
                               interval=interval if prev else 0.0)
            if n and sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")  # clear + home
            print(frame, flush=True)
            prev = stats
            n += 1
            if iterations and n >= iterations:
                return 0
            time.sleep(max(0.1, interval))
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()
