"""Persistent query-history store: the serving tier's cross-run
performance memory (docs/observability.md "Query history").

PR 12/13 made the server observable *live* — but every reservoir the
watchdog, the quarantine, and the stats surface keep dies with the
process, so a restart is a cold start that cannot tell "stuck" from
"first time". The reference's whole retrospective tier (the
qualification/profiling tools) mines *persisted* Spark event logs
across runs; this module is that durability layer for our own engine:

- **one compact record per finished query** (``HISTORY_FIELD_CATALOG``
  is the schema; the tpu-lint ``history-field`` rule pins record
  construction to it), appended at query close from
  ``session.execute_plan`` (every terminal status it sees) and from the
  query server (terminal outcomes the session never starts, e.g.
  cancelled while still queued);
- **crash-safe bounded storage**: JSONL segments
  (``history-<ms>-<pid>-<seq>.jsonl``) rotated at a fraction of
  ``telemetry.history.maxBytes`` and compacted whole-segment-at-a-time
  by total size and ``telemetry.history.maxAgeDays`` — a record is one
  line, a torn tail line is skipped by the reader, and compaction never
  truncates mid-record;
- **read API**: :func:`read_records` (filtering by age/tenant/
  signature) and :func:`signature_aggregates` (count, p50/p99, trend
  slope, retry/fallback rates) — the substrate for ``tools history``,
  ``tools doctor`` (telemetry/doctor.py), warm-start, and SLO burn;
- **warm-start** (:func:`warm_start`): at server start, replay the
  history into the lifecycle layer — per-signature wall reservoirs and
  consecutive-failure streaks — so the stuck-query watchdog and the
  poison-query quarantine work from query one after a restart;
- **SLO burn** (:class:`SloTracker`): per-tenant p99 objectives
  (``serve.slo.p99Ms[.<tenant>]``) evaluated over the history window,
  exported as ``srt_slo_*`` Prometheus families and fired as a
  rate-limited ``sloBurn`` bundle through the trigger engine.

Appending is one lock + one line write + flush; everything heavier
(compaction file deletes) is amortized and never under a query's
hot-path lock. History writes never raise — observability must not
take down execution.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from spark_rapids_tpu.conf import (SERVE_QUARANTINE_THRESHOLD,
                                   SERVE_SLO_P99_MS, SERVE_SLO_WINDOW,
                                   TELEMETRY_DIR,
                                   TELEMETRY_HISTORY_DIR,
                                   TELEMETRY_HISTORY_MAX_AGE_DAYS,
                                   TELEMETRY_HISTORY_MAX_BYTES,
                                   TELEMETRY_HISTORY_WARM_START,
                                   TELEMETRY_MIN_INTERVAL_S)

HISTORY_VERSION = 1

# terminal statuses a record may carry (the event log's `status` field
# uses the same vocabulary, so history and event logs agree on query
# outcomes by construction)
STATUS_FINISHED = "finished"
STATUS_CANCELLED = "cancelled"
STATUS_TIMED_OUT = "timed-out"
STATUS_QUARANTINED = "quarantined"
STATUS_FAILED = "failed"
HISTORY_STATUSES = (STATUS_FINISHED, STATUS_CANCELLED, STATUS_TIMED_OUT,
                    STATUS_QUARANTINED, STATUS_FAILED)

# control-plane statuses: TuningController audit records (an applied
# action / a guardrail or manual rollback). They live in the SAME
# store as query records — the audit trail rides the store's
# durability and compaction — but they are NOT query outcomes:
# aggregates, SLO windows, doctor baselines, and warm-start replay all
# exclude them, the same discipline as cache-served records
# (docs/tuning.md).
STATUS_TUNING = "tuning"
STATUS_REVERT = "revert"
TUNING_STATUSES = (STATUS_TUNING, STATUS_REVERT)

# The record schema. Every field a record construction site in this
# module writes MUST be a key here (tpu-lint `history-field`), and the
# generated observability doc renders this table — the store's on-disk
# vocabulary can never drift from the documentation.
HISTORY_FIELD_CATALOG: Dict[str, str] = {
    "version": "record format version (currently 1)",
    "ts": "unix wall-clock seconds at record append (query close)",
    "queryId": "process query id (int) or the wire queryId (string)",
    "tenant": "serving tenant id (absent for untenanted sessions)",
    "signature": "plan-cache signature digest of the query shape "
                 "(plan_cache.signature_digest — the lifecycle "
                 "layer's key; absent when the plan cache is off or "
                 "planning never resolved one)",
    "status": "terminal status: finished / cancelled / timed-out / "
              "quarantined / failed",
    "reason": "cancellation reason (cancel/deadline/disconnect/"
              "watchdog/shutdown/injected) when status is cancelled "
              "or timed-out",
    "wallSeconds": "execution wall seconds (admission to terminal "
                   "state; 0 for queries that never started)",
    "queueWaitSeconds": "admission-queue wait seconds (served queries "
                        "only)",
    "outputRows": "result rows (finished queries)",
    "retryCount": "OOM retries accumulated by the query's plan",
    "splitRetryCount": "split-and-retry events accumulated by the "
                       "query's plan",
    "spillBytes": "device bytes spilled by the query's plan",
    "kernelDispatches": "Pallas kernel dispatches "
                        "(sum of kernelDispatchCount.*)",
    "kernelFallbacks": "Pallas kernel oracle fallbacks "
                       "(sum of kernelFallbacks.*)",
    "kernelFallbacksByName": "per-kernel oracle fallback counts "
                             "(nonzero kernelFallbacks.<name> entries; "
                             "present only when any fired) — the "
                             "doctor's kernelFallback verdict names "
                             "the culprit kernel(s) from these",
    "jitMisses": "compile-cache misses billed to the query's plan "
                 "(compileCacheMisses)",
    "fallbackCoverage": "rewrite device-operator coverage (0..1) from "
                        "the explain report",
    "peakHbmBytes": "device-store pool peak bytes observed at query "
                    "close",
    "profilePath": "this query's profile artifact "
                   "(spark.rapids.sql.profile.*), when written",
    "tracePath": "this query's Chrome-trace file "
                 "(spark.rapids.sql.trace.*), when written",
    "aqeActions": "adaptive replan counters from the executed plan "
                  "(aqeReplans/aqeBroadcastFlip/aqeSkewSplits/"
                  "aqeCoalescedPartitions; nonzero entries only, "
                  "present only when any fired — docs/adaptive.md)",
    "resultCacheHit": "true when the query was served verbatim from "
                      "the serve-tier result cache (docs/caching.md); "
                      "cache-served records are EXCLUDED from doctor "
                      "baselines, SLO windows, warm-start replay, and "
                      "per-signature wall aggregates — a near-zero "
                      "cached wall must not poison a shape's baseline",
    "plannedOutOfCore": "planned out-of-core counters from the "
                        "executed plan (plannedPartitions/"
                        "plannedOutOfCoreEscalations/"
                        "budgetPressurePeak; nonzero entries only, "
                        "present only when the budget oracle engaged "
                        "— docs/out_of_core.md); the doctor uses this "
                        "to classify planned big-input spill as "
                        "biggerInput rather than retrySpill",
    "action": "tuning/revert records: the ACTION_CATALOG action name "
              "(docs/tuning.md)",
    "scope": "tuning/revert records: what the action applied to — a "
             "signature digest or tenant:<id>",
    "knob": "tuning/revert records: the knob the action wrote (a "
            "registered conf key, or an internal knob like "
            "signatureConcurrency / tenantWeight / prewarm)",
    "oldValue": "tuning/revert records: the knob value before the "
                "write (what a revert restores)",
    "newValue": "tuning/revert records: the clamped knob value after "
                "the write",
    "evidence": "tuning/revert records: why — the verdict, baseline "
                "p50/p99, and the observed window that motivated the "
                "action or triggered the rollback",
    "epoch": "tuning/revert records: the controller's monotonic "
             "action id (tools tuning pins/reverts by it)",
}


# ---------------------------------------------------------------------------
# Store
# ---------------------------------------------------------------------------

class HistoryStore:
    """Bounded, crash-safe JSONL store under one directory. Appends are
    serialized by an internal lock; segments rotate at
    ``maxBytes // 4`` (min 64 KiB) and compaction deletes whole
    segments oldest-first by total size, then by age."""

    COMPACT_EVERY = 64  # appends between compaction sweeps
    SEGMENT_FLOOR = 64 << 10  # smallest rotation target (bytes)

    def __init__(self, dir_path: str, max_bytes: int,
                 max_age_days: float):
        self.dir = dir_path
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_days) * 86400.0
        self._lock = threading.Lock()
        self._fh = None
        self._seg_bytes = 0
        self._seq = 0
        self.appended = 0
        self.pruned_segments = 0

    @property
    def segment_target(self) -> int:
        return max(self.SEGMENT_FLOOR, self.max_bytes // 4)

    def _open_segment_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        os.makedirs(self.dir, exist_ok=True)
        self._seq += 1
        name = (f"history-{int(time.time() * 1000):013d}-"
                f"{os.getpid()}-{self._seq:04d}.jsonl")
        self._fh = open(os.path.join(self.dir, name), "a",
                        encoding="utf-8")
        self._seg_bytes = 0

    def append(self, rec: Dict[str, Any]) -> None:
        """Append one record (one JSON line, flushed) and amortize
        compaction. Never raises."""
        try:
            line = json.dumps(rec, default=str) + "\n"
            with self._lock:
                if self._fh is None or \
                        self._seg_bytes + len(line) > self.segment_target:
                    self._open_segment_locked()
                self._fh.write(line)
                self._fh.flush()
                self._seg_bytes += len(line)
                self.appended += 1
                if self.appended % self.COMPACT_EVERY == 0:
                    self._compact_locked()
        except Exception:
            pass  # observability must not take down execution

    def _segments(self) -> List[str]:
        try:
            return sorted(
                os.path.join(self.dir, f) for f in os.listdir(self.dir)
                if f.startswith("history-") and f.endswith(".jsonl"))
        except OSError:
            return []

    def compact(self) -> int:
        """Run one compaction sweep now; returns segments deleted."""
        with self._lock:
            return self._compact_locked()

    def _compact_locked(self) -> int:
        deleted = 0
        active = None
        if self._fh is not None:
            active = os.path.realpath(self._fh.name)
        segs = self._segments()
        sizes = {}
        for p in segs:
            try:
                sizes[p] = os.path.getsize(p)
            except OSError:
                sizes[p] = 0
        total = sum(sizes.values())
        now = time.time()
        for p in segs:
            if os.path.realpath(p) == active:
                continue  # never delete the segment being written
            too_big = self.max_bytes > 0 and total > self.max_bytes
            too_old = self.max_age_s > 0 and \
                (now - _segment_mtime(p)) > self.max_age_s
            if not (too_big or too_old):
                continue
            try:
                os.unlink(p)
                total -= sizes.get(p, 0)
                deleted += 1
                self.pruned_segments += 1
            except OSError:
                pass
        return deleted

    def stats(self) -> Dict[str, Any]:
        segs = self._segments()
        total = 0
        for p in segs:
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return {"dir": self.dir, "segments": len(segs),
                "totalBytes": total, "appended": self.appended,
                "prunedSegments": self.pruned_segments}


def _segment_mtime(path: str) -> float:
    try:
        return os.path.getmtime(path)
    except OSError:
        return time.time()


# one store per directory, process-wide: a restarted QueryServer in the
# same process reuses the writer; two sessions on one dir share it
_STORES: Dict[str, HistoryStore] = {}
_STORES_LOCK = threading.Lock()


def store_for(conf_obj) -> Optional[HistoryStore]:
    """The process HistoryStore for the session's configured
    ``telemetry.history.dir`` (None when unset = history disabled)."""
    if conf_obj is None:
        return None
    dir_path = str(conf_obj.get(TELEMETRY_HISTORY_DIR) or "")
    if not dir_path:
        return None
    key = os.path.realpath(dir_path)
    with _STORES_LOCK:
        store = _STORES.get(key)
        if store is None:
            store = _STORES[key] = HistoryStore(
                dir_path,
                int(conf_obj.get(TELEMETRY_HISTORY_MAX_BYTES)),
                float(conf_obj.get(TELEMETRY_HISTORY_MAX_AGE_DAYS)))
        return store


def reset_history() -> None:
    """Test hook: forget the per-directory writer singletons and the
    warm-start replay markers (on-disk segments are untouched — that
    is the point of the store)."""
    with _STORES_LOCK:
        for s in _STORES.values():
            with s._lock:
                if s._fh is not None:
                    try:
                        s._fh.close()
                    except OSError:
                        pass
                    s._fh = None
        _STORES.clear()
    with _WARM_LOCK:
        _WARM_DONE.clear()


# ---------------------------------------------------------------------------
# Record construction (the write path)
# ---------------------------------------------------------------------------

def _plan_counters(physical) -> Dict[str, Any]:
    """The per-query counter deltas from the executed plan's registries
    (the registries ARE the delta — same contract as the trigger
    engine's query-end hook)."""
    if physical is None:
        return {}
    from spark_rapids_tpu.metrics import registry_snapshot
    vals = registry_snapshot(plans=[physical])["metrics"]
    out = {
        "retryCount": int(vals.get("retryCount", 0)),
        "splitRetryCount": int(vals.get("splitRetryCount", 0)),
        "spillBytes": int(vals.get("spillBytes", 0)),
        "jitMisses": int(vals.get("compileCacheMisses", 0)),
        "kernelDispatches": sum(
            v for k, v in vals.items()
            if k.startswith("kernelDispatchCount.")),
        "kernelFallbacks": sum(
            v for k, v in vals.items()
            if k.startswith("kernelFallbacks.")),
    }
    by_name = {k.split(".", 1)[1]: int(v) for k, v in vals.items()
               if k.startswith("kernelFallbacks.") and v}
    if by_name:
        out["kernelFallbacksByName"] = by_name
    poc = {k: int(vals[k]) for k in ("plannedPartitions",
                                     "plannedOutOfCoreEscalations",
                                     "budgetPressurePeak")
           if vals.get(k)}
    if poc:
        out["plannedOutOfCore"] = poc
    return out


def _aqe_actions(physical) -> Dict[str, int]:
    """Adaptive replan counters from the executed plan (nonzero
    entries only), so ``tools doctor`` can attribute a wall change
    between two runs of ONE signature — adaptive and unadaptive runs
    share signatures by the plan_signature exclusion — to an AQE
    decision delta instead of a shape change (docs/adaptive.md)."""
    if physical is None:
        return {}
    from spark_rapids_tpu.metrics import registry_snapshot
    vals = registry_snapshot(plans=[physical])["metrics"]
    return {k: int(vals[k])
            for k in ("aqeReplans", "aqeBroadcastFlip",
                      "aqeSkewSplits", "aqeCoalescedPartitions")
            if vals.get(k)}


def build_record(*, status: str, reason: Optional[str] = None,
                 signature: Optional[str] = None,
                 tenant: Optional[str] = None,
                 query_id=None, wall_s: float = 0.0,
                 queue_wait_s: float = 0.0, rows: int = 0,
                 physical=None, report=None,
                 profile_path: Optional[str] = None,
                 trace_path: Optional[str] = None,
                 result_cache_hit: bool = False) -> Dict[str, Any]:
    """One history record. Every key written here must be a
    HISTORY_FIELD_CATALOG entry (tpu-lint ``history-field``)."""
    from spark_rapids_tpu import memory
    rec: Dict[str, Any] = {
        "version": HISTORY_VERSION,
        "ts": time.time(),
        "status": status,
        "wallSeconds": round(float(wall_s), 6),
        "queueWaitSeconds": round(float(queue_wait_s), 6),
        "outputRows": int(rows),
    }
    if query_id is not None:
        rec["queryId"] = query_id
    if tenant:
        rec["tenant"] = tenant
    if signature:
        rec["signature"] = signature
    if reason:
        rec["reason"] = reason
    for k, v in _plan_counters(physical).items():
        rec[k] = v
    acts = _aqe_actions(physical)
    if acts:
        rec["aqeActions"] = acts
    if report is not None:
        try:
            rec["fallbackCoverage"] = round(
                float(report.summary().get("coverage", 1.0)), 4)
        except Exception:
            pass
    store = memory._STORE
    if store is not None:
        try:
            rec["peakHbmBytes"] = int(
                store.stats().get("peakDeviceBytes", 0))
        except Exception:
            pass
    if profile_path:
        rec["profilePath"] = profile_path
    if trace_path:
        rec["tracePath"] = trace_path
    if result_cache_hit:
        rec["resultCacheHit"] = True
    return rec


def build_tuning_record(*, status: str, action: str, scope: str,
                        knob: str, old_value, new_value,
                        evidence: Dict[str, Any], epoch: int,
                        tenant: Optional[str] = None,
                        signature: Optional[str] = None
                        ) -> Dict[str, Any]:
    """One TuningController audit record (status ``tuning`` or
    ``revert``). Lives in history.py so the ``history-field`` lint
    rule pins its fields to HISTORY_FIELD_CATALOG like every other
    record construction site."""
    rec: Dict[str, Any] = {
        "version": HISTORY_VERSION,
        "ts": time.time(),
        "status": status,
        "action": action,
        "scope": scope,
        "knob": knob,
        "oldValue": old_value,
        "newValue": new_value,
        "evidence": evidence,
        "epoch": int(epoch),
    }
    if tenant:
        rec["tenant"] = tenant
    if signature:
        rec["signature"] = signature
    return rec


def record_query_close(conf_obj, **kwargs) -> None:
    """Append one query-close record when history is configured; the
    session's and the server's shared write hook. Never raises."""
    try:
        store = store_for(conf_obj)
        if store is None:
            return
        store.append(build_record(**kwargs))
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Read API
# ---------------------------------------------------------------------------

def read_records(path: str, since: Optional[float] = None,
                 tenant: Optional[str] = None,
                 signature: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
    """Load history records from a directory (every history-*.jsonl,
    chronological) or one file. Torn/corrupt lines (a crash mid-append)
    are skipped; older records are normalized (``status`` defaults to
    finished, ``version`` to 1). ``since`` is a unix-seconds lower
    bound on ``ts``."""
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("history-") and f.endswith(".jsonl"))
    else:
        files = [path]
    out: List[Dict[str, Any]] = []
    for fp in files:
        if since is not None:
            # a segment's mtime is its LAST append: when even that is
            # older than the bound, every record inside is too — skip
            # the parse entirely (the SLO tracker's windowed reads
            # must not re-parse the whole store every scrape)
            try:
                if os.path.getmtime(fp) < since:
                    continue
            except OSError:
                continue
        try:
            with open(fp, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            continue  # compacted away under the reader
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line: crash-safety contract
            if not isinstance(rec, dict):
                continue
            rec.setdefault("version", 1)
            rec.setdefault("status", STATUS_FINISHED)
            if since is not None and float(rec.get("ts", 0)) < since:
                continue
            if tenant is not None and rec.get("tenant") != tenant:
                continue
            if signature is not None and \
                    rec.get("signature") != signature:
                continue
            out.append(rec)
    out.sort(key=lambda r: float(r.get("ts", 0)))
    return out


def _percentile(samples: List[float], q: float) -> float:
    from spark_rapids_tpu.lifecycle import percentile
    return percentile(samples, q)


def trend_slope(records: List[Dict[str, Any]]) -> float:
    """Least-squares slope of wallSeconds over ts, in seconds of wall
    per HOUR of history — a positive slope means the shape is getting
    slower run over run (0 below 2 samples)."""
    pts = [(float(r.get("ts", 0)), float(r.get("wallSeconds", 0)))
           for r in records]
    if len(pts) < 2:
        return 0.0
    t0 = pts[0][0]
    xs = [t - t0 for t, _ in pts]
    ys = [w for _, w in pts]
    n = len(pts)
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0:
        return 0.0
    slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
    return slope * 3600.0


def signature_aggregates(records: List[Dict[str, Any]]
                         ) -> Dict[str, Dict[str, Any]]:
    """Per-signature aggregates over a record list: count, wall
    p50/p99, trend slope, retry/fallback rates, status histogram, and
    the tenants that ran the shape. Finished records drive the latency
    numbers; every terminal status counts in the histogram."""
    by_sig: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("status") in TUNING_STATUSES:
            # controller audit records carry the signature they acted
            # on but are not query outcomes: counting them would make
            # the aggregates differ with tuning on vs off
            continue
        sig = r.get("signature")
        if sig:
            by_sig.setdefault(sig, []).append(r)
    out: Dict[str, Dict[str, Any]] = {}
    for sig, recs in by_sig.items():
        fin = [r for r in recs if r.get("status") == STATUS_FINISHED]
        # cache-served queries count in the histogram but never drive
        # the latency numbers: a near-zero cached wall would crater a
        # shape's p50/p99 and trend slope (docs/caching.md)
        fin = [r for r in fin if not r.get("resultCacheHit")]
        walls = [float(r.get("wallSeconds", 0)) for r in fin]
        statuses: Dict[str, int] = {}
        tenants = set()
        for r in recs:
            statuses[r.get("status", STATUS_FINISHED)] = \
                statuses.get(r.get("status", STATUS_FINISHED), 0) + 1
            if r.get("tenant"):
                tenants.add(r["tenant"])
        retries = sum(1 for r in fin
                      if (r.get("retryCount", 0)
                          + r.get("splitRetryCount", 0)) > 0)
        fallbacks = sum(1 for r in fin
                        if r.get("kernelFallbacks", 0) > 0)
        out[sig] = {
            "count": len(recs),
            "finished": len(fin),
            "wallP50": round(_percentile(walls, 0.50), 6),
            "wallP99": round(_percentile(walls, 0.99), 6),
            "trendSlopePerHour": round(trend_slope(fin), 6),
            "retryRate": round(retries / len(fin), 4) if fin else 0.0,
            "fallbackRate": round(fallbacks / len(fin), 4) if fin
            else 0.0,
            "statuses": statuses,
            "tenants": sorted(tenants),
        }
    return out


def format_history(records: List[Dict[str, Any]], top: int = 30) -> str:
    """The `tools history` table: per-signature rows ranked by query
    count, plus a per-tenant rollup (docs/observability.md)."""
    lines = ["=== TPU Query History ===",
             f"{len(records)} records", ""]
    if not records:
        lines.append("no history records found")
        return "\n".join(lines)
    aggs = signature_aggregates(records)
    lines.append(
        f"  {'signature':14s} {'tenants':14s} {'n':>5s} {'ok':>5s} "
        f"{'p50_s':>8s} {'p99_s':>8s} {'trend/h':>9s} {'retry%':>7s} "
        f"{'fb%':>5s}  statuses")
    ranked = sorted(aggs.items(), key=lambda kv: -kv[1]["count"])
    for sig, a in ranked[:top]:
        sts = ",".join(f"{k}:{v}" for k, v in sorted(a["statuses"].items()))
        tns = ",".join(a["tenants"])[:14] or "-"
        lines.append(
            f"  {sig_digest(sig):14s} {tns:14s} {a['count']:5d} "
            f"{a['finished']:5d} {a['wallP50']:8.3f} "
            f"{a['wallP99']:8.3f} {a['trendSlopePerHour']:+9.4f} "
            f"{a['retryRate']:7.1%} {a['fallbackRate']:5.0%}  {sts}")
    # per-tenant rollup over finished records
    by_tenant: Dict[str, List[float]] = {}
    for r in records:
        if r.get("status") == STATUS_FINISHED:
            by_tenant.setdefault(r.get("tenant") or "-", []).append(
                float(r.get("wallSeconds", 0)))
    lines += ["", f"  {'tenant':14s} {'queries':>8s} {'p50_s':>8s} "
              f"{'p99_s':>8s}"]
    for t, walls in sorted(by_tenant.items()):
        lines.append(f"  {t:14s} {len(walls):8d} "
                     f"{_percentile(walls, 0.5):8.3f} "
                     f"{_percentile(walls, 0.99):8.3f}")
    return "\n".join(lines)


def sig_digest(signature: str) -> str:
    """Short display form of a signature. Records normally carry the
    40-hex ``plan_cache.signature_digest`` already — show its prefix;
    anything else (a raw plan string in a hand-built record) is hashed
    down to the same shape."""
    import hashlib
    import re
    if re.fullmatch(r"[0-9a-f]{12,64}", signature):
        return signature[:12]
    return hashlib.sha1(signature.encode()).hexdigest()[:12]


def find_record(records: List[Dict[str, Any]], selector: str
                ) -> Optional[Dict[str, Any]]:
    """Resolve a `tools doctor` selector against a record list: a
    queryId (exact match on either id form), a signature digest
    (sig_digest prefix), or a signature prefix — newest match wins."""
    sel = str(selector)
    for r in reversed(records):
        if str(r.get("queryId")) == sel:
            return r
    for r in reversed(records):
        if r.get("status") in TUNING_STATUSES:
            continue  # audit records are not diagnosable queries
        sig = r.get("signature")
        if sig and (sig_digest(sig).startswith(sel)
                    or sig.startswith(sel)):
            return r
    return None


# ---------------------------------------------------------------------------
# Warm-start (docs/observability.md "Query history")
# ---------------------------------------------------------------------------

# most recent history records replayed at warm-start: the lifecycle
# reservoirs are bounded anyway; replaying an unbounded store would
# only cost startup time
_WARM_START_CAP = 10_000

# dirs already replayed into the CURRENT lifecycle generation: a
# second server start in one process must not replay the same records
# on top of live streaks (that would double-count failures toward the
# quarantine threshold); a lifecycle reset (the restart simulation)
# bumps the generation and re-enables replay
_WARM_LOCK = threading.Lock()
_WARM_DONE: Dict[str, int] = {}


def warm_start(conf_obj) -> Dict[str, Any]:
    """Seed the lifecycle layer from the history store: finished
    records feed ``lifecycle.record_wall`` (the watchdog's p99
    source) and clear failure streaks; failed records replay
    ``record_runtime_failure`` so a signature that crossed the
    quarantine threshold before the restart is blacklisted from query
    one. Cancelled/timed-out/quarantined records never count — the
    same rules as the live paths. Returns a summary for the server
    stats/log."""
    out = {"enabled": False, "records": 0, "walls": 0,
           "failures": 0, "quarantined": 0, "alreadyWarm": False}
    if conf_obj is None:
        return out
    dir_path = str(conf_obj.get(TELEMETRY_HISTORY_DIR) or "")
    if not dir_path or not bool(
            conf_obj.get(TELEMETRY_HISTORY_WARM_START)):
        return out
    if not os.path.isdir(dir_path):
        out["enabled"] = True
        return out
    from spark_rapids_tpu import lifecycle as LC
    gen = LC.lifecycle_generation()
    key = os.path.realpath(dir_path)
    with _WARM_LOCK:
        if _WARM_DONE.get(key) == gen:
            # this store already seeded the CURRENT lifecycle state:
            # replaying again would double-count failure streaks
            out["enabled"] = True
            out["alreadyWarm"] = True
            return out
        _WARM_DONE[key] = gen
    thr = int(conf_obj.get(SERVE_QUARANTINE_THRESHOLD))
    records = read_records(dir_path)[-_WARM_START_CAP:]
    out["enabled"] = True
    out["records"] = len(records)
    for rec in records:  # chronological: streaks replay in order
        if rec.get("status") in TUNING_STATUSES:
            continue  # controller audit rows never seed lifecycle
        sig = rec.get("signature")
        if not sig:
            continue
        status = rec.get("status")
        if status == STATUS_FINISHED:
            if not rec.get("resultCacheHit"):
                # a cache-served wall is not an execution wall: seeding
                # the watchdog's p99 history with near-zero values
                # would make every real run look stuck
                LC.record_wall(sig, float(rec.get("wallSeconds", 0.0)))
                out["walls"] += 1
            if thr > 0:
                LC.record_success(sig)
        elif status == STATUS_FAILED and thr > 0:
            out["failures"] += 1
            if LC.record_runtime_failure(sig, thr):
                out["quarantined"] += 1
    return out


# ---------------------------------------------------------------------------
# SLO burn tracking (docs/observability.md "SLO tracking")
# ---------------------------------------------------------------------------

_SLO_PREFIX = "spark.rapids.sql.serve.slo.p99Ms."
_SLO_CACHE_S = 1.0  # evaluate() result cache (scrapes are frequent)


class SloTracker:
    """Per-tenant latency objectives evaluated over the history
    window. The server embeds one; ``stats()`` exposes the evaluation
    and the Prometheus renderer exports it as ``srt_slo_*`` families.
    A tenant whose observed p99 exceeds its objective fires a
    rate-limited ``sloBurn`` bundle through the trigger engine."""

    def __init__(self, conf_obj):
        self._conf = conf_obj
        self._dir = str(conf_obj.get(TELEMETRY_HISTORY_DIR) or "")
        self._window_s = float(conf_obj.get(SERVE_SLO_WINDOW))
        self._base_ms = int(conf_obj.get(SERVE_SLO_P99_MS))
        self._overrides: Dict[str, int] = {}
        for k, v in conf_obj.settings.items():
            if str(k).startswith(_SLO_PREFIX):
                try:
                    self._overrides[str(k)[len(_SLO_PREFIX):]] = \
                        max(0, int(v))
                except (TypeError, ValueError):
                    pass
        self._lock = threading.Lock()
        self._cached_at = 0.0
        self._cached: Dict[str, Dict[str, Any]] = {}

    @property
    def enabled(self) -> bool:
        return bool(self._dir) and (
            self._base_ms > 0 or any(self._overrides.values()))

    def objective_ms(self, tenant: str) -> int:
        return self._overrides.get(tenant, self._base_ms)

    def evaluate(self, max_age_s: float = _SLO_CACHE_S
                 ) -> Dict[str, Dict[str, Any]]:
        """Per-tenant SLO state over the window: objective, observed
        p99, window query count, violations (queries over the
        objective), and burn ratio (violations / count). Cached for
        ``max_age_s`` so a scrape storm doesn't re-read the store."""
        if not self.enabled:
            return {}
        now = time.monotonic()
        with self._lock:
            # validity is the timestamp, NOT the payload: an empty
            # evaluation (SLO armed, no tenanted records yet) must
            # cache too, or every scrape re-reads the store
            if self._cached_at and now - self._cached_at < max_age_s:
                return self._cached
        since = time.time() - self._window_s
        by_tenant: Dict[str, List[float]] = {}
        for rec in read_records(self._dir, since=since):
            if rec.get("status") != STATUS_FINISHED:
                # non-query statuses — including the controller's
                # tuning/revert audit records — never enter the window
                continue
            if rec.get("resultCacheHit"):
                # cache-served queries are excluded from the SLO
                # window: near-zero cached walls would mask a real
                # latency burn behind a high hit rate
                continue
            t = rec.get("tenant")
            if not t:
                continue
            by_tenant.setdefault(t, []).append(
                float(rec.get("wallSeconds", 0.0)) * 1e3)
        out: Dict[str, Dict[str, Any]] = {}
        tenants = set(by_tenant) | {
            t for t, v in self._overrides.items() if v > 0}
        for t in sorted(tenants):
            obj = self.objective_ms(t)
            if obj <= 0:
                continue
            walls_ms = by_tenant.get(t, [])
            violations = sum(1 for w in walls_ms if w > obj)
            out[t] = {
                "objectiveP99Ms": obj,
                "observedP99Ms": round(
                    _percentile(walls_ms, 0.99), 3),
                "windowQueries": len(walls_ms),
                "violations": violations,
                "burnRatio": round(violations / len(walls_ms), 4)
                if walls_ms else 0.0,
            }
        with self._lock:
            self._cached_at = now
            self._cached = out
        return out

    def on_query_close(self, tenant: Optional[str]) -> None:
        """Query-close evaluation point (the server calls this after
        the finished record lands): when the tenant's observed p99
        over the window exceeds its objective, fire a rate-limited
        ``sloBurn`` bundle through the trigger engine."""
        if not tenant or not self.enabled:
            return
        obj = self.objective_ms(tenant)
        if obj <= 0:
            return
        state = self.evaluate().get(tenant)
        if state is None or state["observedP99Ms"] <= obj:
            return
        from spark_rapids_tpu.telemetry import triggers as _triggers
        eng = _triggers.engine()
        eng._ensure_worker()
        eng._maybe_fire(
            "sloBurn",
            {"tenant": tenant, **state,
             "windowSeconds": self._window_s},
            out_dir=str(self._conf.get(TELEMETRY_DIR)),
            min_interval=float(
                self._conf.get(TELEMETRY_MIN_INTERVAL_S)))
