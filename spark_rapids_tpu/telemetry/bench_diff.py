"""`tools bench-diff <a> <b|dir>`: regression tracking across bench
rounds (docs/observability.md "Live telemetry").

The repo accumulates one bench JSON per round (BENCH_r01.json ...);
without a differ the trajectory is loose files a human eyeballs. This
module turns it into an enforced curve: diff the headline rows/s and
the detail legs (device walls, decode overlap, kernel A/B, serving QPS,
tracing/profiling overheads) between two bench outputs against
configurable thresholds, emit a machine-readable verdict, and exit
nonzero on regression — bench.py runs it against the previous round as
part of every bench, and CI can gate on it.

Check semantics: ``a`` is the baseline (older), ``b`` the candidate
(newer). A *gating* check regresses when the candidate is worse than
the baseline by more than the relative threshold in the metric's bad
direction; *informational* checks (CPU-engine walls, retry counters —
environment/workload shaped) report their change but never trip the
verdict.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_THRESHOLD = 0.10

# (dot path into the bench JSON, direction, gating?, label)
# direction: "higher" = bigger is better (throughput), "lower" =
# smaller is better (walls, overhead ratios)
CHECKS: List[Tuple[str, str, bool, str]] = [
    ("value", "higher", True, "headline q1 rows/s"),
    ("detail.device_wall_s", "lower", True, "q1 device wall"),
    ("detail.tpcds_q3.device_wall_s", "lower", True, "q3 device wall"),
    ("detail.cpu_engine_wall_s", "lower", False, "q1 CPU-engine wall"),
    ("detail.fusion.q1_fusion_speedup", "higher", True,
     "q1 fusion speedup"),
    ("detail.decode.ab.pipelineSpeedup", "higher", True,
     "scan pipeline speedup"),
    ("detail.decode.ab.deviceDecodeSpeedup", "higher", True,
     "device-decode speedup"),
    ("detail.trace.scanOverlap.overlapRatio", "higher", True,
     "scan overlap ratio"),
    ("detail.trace.tracingOverhead", "lower", True,
     "file-tracing overhead"),
    ("detail.profile.profilingOverhead", "lower", True,
     "profiling overhead"),
    ("detail.kernels.wallSpeedup", "higher", True,
     "kernel-tier wall speedup"),
    ("detail.kernels.aggDrainSpeedup", "higher", True,
     "q1 agg-drain speedup"),
    ("detail.kernels.decodeFused.wallSpeedup", "higher", True,
     "fused-decode wall speedup (fused vs chain)"),
    ("detail.kernels.decodeFused.fused.programsPerBatch", "lower", True,
     "fused-decode programs per batch"),
    ("detail.kernels.autotune.warmSweeps", "lower", True,
     "autotune warm-start sweeps (zero when the table holds)"),
    ("detail.kernels.autotune.coldTotal_s", "lower", False,
     "autotune cold-sweep leg wall"),
    ("detail.serving.concurrency.c1.qps", "higher", True,
     "serving QPS @ c=1"),
    ("detail.serving.concurrency.c4.qps", "higher", True,
     "serving QPS @ c=4"),
    ("detail.serving.concurrency.c16.qps", "higher", True,
     "serving QPS @ c=16"),
    ("detail.telemetry.ringOverhead", "lower", True,
     "ring-recorder overhead"),
    ("detail.lifecycle.cancelLatency.p50_s", "lower", False,
     "cancel latency p50"),
    ("detail.lifecycle.cancelLatency.p99_s", "lower", False,
     "cancel latency p99"),
    ("detail.lifecycle.drain.drain_s", "lower", False,
     "graceful-drain wall with in-flight queries"),
    ("detail.lifecycle.quarantine.failFastMs", "lower", False,
     "quarantine fail-fast latency"),
    ("detail.robustness.legs.oomEveryN.retryCount", "lower", False,
     "retries under injected OOM"),
    ("detail.robustness.legs.oomEveryN.slowdown_vs_clean", "lower",
     False, "injected-OOM slowdown"),
    # planned out-of-core (docs/out_of_core.md): the gate is the
    # 1.0/0.0 indicator — raw retryCount can't gate through the
    # va==0 short-circuit below, so bench.py derives the boolean
    ("detail.outOfCore.plannedPathClean", "higher", True,
     "planned out-of-core path stayed retry-free"),
    ("detail.outOfCore.legs.budget10x.slowdown_vs_clean", "lower",
     False, "10x-over-budget slowdown"),
    ("detail.outOfCore.legs.budget10x.plannedPartitions", "lower",
     False, "10x-over-budget planned partitions"),
    ("detail.outOfCore.legs.budget10x.retryCount", "lower", False,
     "10x-over-budget retries (0 on the planned path)"),
    ("detail.adaptive.skew.speedup", "higher", True,
     "skewed-join adaptive speedup"),
    ("detail.adaptive.coalesce.dispatchDelta", "higher", False,
     "AQE coalesce dispatch savings"),
    ("detail.adaptive.batchFusion.qpsSpeedup", "higher", False,
     "same-signature batch-fusion QPS speedup"),
    ("detail.resultCache.replay.warmQps", "higher", True,
     "dashboard-replay warm QPS @ c=16"),
    ("detail.resultCache.replay.qpsSpeedup", "higher", True,
     "result-cache replay QPS speedup (warm vs cold)"),
    ("detail.resultCache.replay.hitRate", "higher", True,
     "result-cache replay hit rate"),
    ("detail.resultCache.subplan.buildSpeedup", "higher", False,
     "subplan-cache join build-time speedup"),
    ("detail.history.appendOverhead", "lower", False,
     "query-history append overhead"),
    ("detail.history.doctor.roundTripMs", "lower", False,
     "tools doctor round-trip latency"),
    ("detail.history.doctor.stormWall_s", "lower", False,
     "forced retry-storm wall (doctor leg)"),
    ("detail.tuning.prewarm.hitOnRestart", "higher", False,
     "tuning pre-warm plan-cache hit on restart"),
    ("detail.tuning.prewarm.restartSpeedup", "higher", False,
     "tuning pre-warm first-request restart speedup"),
    ("detail.tuning.kernelFallback.flipped", "higher", False,
     "tuning kernel-fallback conf flip applied"),
    ("detail.tuning.guard.autoReverted", "higher", False,
     "tuning guardrail auto-revert of the injected harmful action"),
]


def _resolve(doc: Any, dotted: str) -> Optional[float]:
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def load_bench(path: str) -> Dict:
    """One bench result from any of the shapes it ships in: the bench
    output object itself, a harness wrapper holding it under
    ``parsed`` (or as a JSON line inside ``tail``/stdout text — the
    BENCH_r0*.json layout), or a log whose last JSON line carries a
    ``metric`` field."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if "metric" in doc:
            return doc
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed
        tail = doc.get("tail")
        if isinstance(tail, str):
            text = tail
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "metric" in cand:
            return cand
    raise ValueError(f"no bench JSON object found in {path}")


def latest_bench_file(dir_path: str,
                      exclude: Optional[str] = None) -> Optional[str]:
    """The newest BENCH_r*.json in ``dir_path`` by round-name order
    (BENCH_r05 > BENCH_r04), excluding ``exclude`` when given."""
    files = sorted(glob.glob(os.path.join(dir_path, "BENCH_r*.json")))
    if exclude is not None:
        ex = os.path.realpath(exclude)
        files = [f for f in files if os.path.realpath(f) != ex]
    return files[-1] if files else None


def bench_diff(a, b, threshold: float = DEFAULT_THRESHOLD) -> Dict:
    """Diff two bench outputs (paths or already-loaded dicts); returns
    the machine-readable report: ``verdict`` is ``"regression"`` iff
    any gating check worsened beyond ``threshold`` (relative)."""
    a_doc = load_bench(a) if isinstance(a, str) else a
    b_doc = load_bench(b) if isinstance(b, str) else b
    checks: List[Dict] = []
    regressed: List[str] = []
    improved: List[str] = []
    missing: List[str] = []
    for path, direction, gating, label in CHECKS:
        va, vb = _resolve(a_doc, path), _resolve(b_doc, path)
        if va is None or vb is None:
            missing.append(path)
            continue
        if va == 0:
            change = 0.0
        elif direction == "higher":
            change = (vb - va) / abs(va)   # + = better
        else:
            change = (va - vb) / abs(va)   # + = better (smaller wall)
        is_reg = gating and change < -threshold
        entry = {
            "path": path, "label": label, "direction": direction,
            "gating": gating, "a": va, "b": vb,
            "change": round(change, 4), "regressed": is_reg,
        }
        checks.append(entry)
        if is_reg:
            regressed.append(path)
        elif change > threshold:
            improved.append(path)
    return {
        "verdict": "regression" if regressed else "ok",
        "threshold": threshold,
        "a": a if isinstance(a, str) else "<inline>",
        "b": b if isinstance(b, str) else "<inline>",
        "regressed": regressed,
        "improved": improved,
        "missing": missing,
        "checks": checks,
    }


def format_diff(report: Dict) -> str:
    lines = ["=== TPU Bench Diff ===",
             f"baseline:  {report['a']}",
             f"candidate: {report['b']}",
             f"threshold: {report['threshold']:.0%} relative "
             f"(gating checks only)", ""]
    lines.append(f"  {'check':32s} {'baseline':>12s} {'candidate':>12s} "
                 f"{'change':>8s}")
    for c in report["checks"]:
        flag = "REGRESSED" if c["regressed"] else (
            "improved" if c["change"] > report["threshold"] else "")
        gate = "" if c["gating"] else " (info)"
        lines.append(
            f"  {c['label']:32s} {c['a']:12.4f} {c['b']:12.4f} "
            f"{c['change']:+8.1%} {flag}{gate}")
    if report["missing"]:
        lines += ["", f"not comparable ({len(report['missing'])} "
                  f"checks missing a side): "
                  + ", ".join(report["missing"])]
    lines += ["", f"verdict: {report['verdict'].upper()}"]
    return "\n".join(lines)
