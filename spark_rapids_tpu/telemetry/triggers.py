"""Declarative dump/snapshot triggers over the live telemetry state
(docs/observability.md "Live telemetry").

The flight recorder answers "what just happened" only if something
dumps it at the right moment. This engine watches four conditions at
the places they become true —

- **slowQuery**    query wall over ``telemetry.slowQueryMs``
                   (evaluated at query end, session.execute_plan);
- **retryCount** / **kernelFallbacks**  per-query metric deltas over
                   their thresholds (same evaluation point — the
                   executed plan's registries ARE the delta);
- **retryStorm**   more than ``telemetry.retryStormThreshold`` OOM
                   retries in a 60 s window (evaluated at retry time,
                   retry.py);
- **hbmWatermark** device-store occupancy over
                   ``telemetry.hbmWatermark`` x budget (evaluated at
                   every store transition, memory.py);
- **queueSaturation**  admission-queue depth over
                   ``telemetry.queueWatermark`` x maxQueued (evaluated
                   at every enqueue, serve/scheduler.py)

(The lifecycle watchdog's ``stuckQuery`` and the SLO tracker's
``sloBurn`` firings ride the same engine — lifecycle.py and
telemetry/history.py call ``_maybe_fire`` with their own conditions.)

— and emits a *slow-query bundle* per firing: one JSON under
``spark.rapids.sql.telemetry.dir`` tying together the flight-recorder
dump (a standard Chrome-trace file ``tools trace`` loads), the query's
profile artifact path when profiling is on, a server stats snapshot
when a QueryServer registered itself, the device-store stats, and the
triggering condition. Firing is rate-limited PER TRIGGER
(``telemetry.triggerMinIntervalS``) so a storm cannot flood the disk,
and bundle IO runs on a dedicated daemon thread so no query/store/
admission path ever blocks on a file write.

Hot-path cost when disabled: the store/admission/retry hooks are one
module-global boolean check; the query-end hook reads three conf
values per query.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from spark_rapids_tpu.conf import (TELEMETRY_DIR,
                                   TELEMETRY_HBM_WATERMARK,
                                   TELEMETRY_KERNEL_FALLBACK_THRESHOLD,
                                   TELEMETRY_MAX_BUNDLE_BYTES,
                                   TELEMETRY_MAX_BUNDLES,
                                   TELEMETRY_MIN_INTERVAL_S,
                                   TELEMETRY_QUEUE_WATERMARK,
                                   TELEMETRY_RETRY_COUNT_THRESHOLD,
                                   TELEMETRY_RETRY_STORM_THRESHOLD,
                                   TELEMETRY_SLOW_QUERY_MS)

BUNDLE_VERSION = 1
_RETRY_WINDOW_S = 60.0


class TriggerEngine:
    """Process-wide trigger state. One instance (module singleton);
    every mutation is under ``_lock`` except the armed fast-path
    check."""

    def __init__(self):
        self._lock = threading.Lock()
        # armed = any session explicitly configured a telemetry conf;
        # the store/admission/retry hooks read this WITHOUT the lock
        # (stale reads only delay arming by one event)
        self.armed = False
        self._dir = str(TELEMETRY_DIR.default)
        self._min_interval = float(TELEMETRY_MIN_INTERVAL_S.default)
        self._hbm_watermark = 0.0
        self._queue_watermark = 0.0
        self._retry_storm = 0
        self._retry_times: deque = deque()
        self._last_fire: Dict[str, float] = {}
        self.fired: Dict[str, int] = {}
        self.rate_limited: Dict[str, int] = {}
        self.bundle_paths: list = []
        # artifact retention (satellite of the query-history PR):
        # bundles + ring dumps in telemetry.dir are pruned oldest-first
        # by the bundle WORKER after each write — never under a
        # hot-path lock
        self._max_bundles = int(TELEMETRY_MAX_BUNDLES.default)
        self._max_bundle_bytes = int(TELEMETRY_MAX_BUNDLE_BYTES.default)
        self.pruned = 0
        self._seq = 0
        self._pending = 0
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._stats_provider: Optional[Callable[[], Dict]] = None

    # -- configuration -----------------------------------------------------

    def configure(self, conf_obj) -> None:
        """Arm the conf-less hooks (store occupancy, admission depth,
        retry storm) from a session's settings. Only a session that
        EXPLICITLY sets a ``spark.rapids.sql.telemetry.*`` key arms or
        re-arms the engine — default sessions never disarm a configured
        one."""
        if conf_obj is None or not any(
                str(k).startswith("spark.rapids.sql.telemetry.")
                for k in conf_obj.settings):
            return
        with self._lock:
            self._dir = str(conf_obj.get(TELEMETRY_DIR))
            self._min_interval = float(
                conf_obj.get(TELEMETRY_MIN_INTERVAL_S))
            self._hbm_watermark = float(
                conf_obj.get(TELEMETRY_HBM_WATERMARK))
            self._queue_watermark = float(
                conf_obj.get(TELEMETRY_QUEUE_WATERMARK))
            self._retry_storm = int(
                conf_obj.get(TELEMETRY_RETRY_STORM_THRESHOLD))
            self._max_bundles = int(
                conf_obj.get(TELEMETRY_MAX_BUNDLES))
            self._max_bundle_bytes = int(
                conf_obj.get(TELEMETRY_MAX_BUNDLE_BYTES))
            self.armed = True
        # arming implies firings may come from under the store /
        # admission locks, where the worker must already exist
        self._ensure_worker()

    def _ensure_worker(self) -> None:
        """Start the bundle-writer thread if it is not running. Called
        only from contexts that hold no engine-external locks."""
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain_queue, name="srt-telemetry",
                    daemon=True)
                self._worker.start()

    def set_stats_provider(self, fn: Optional[Callable[[], Dict]]
                           ) -> None:
        with self._lock:
            self._stats_provider = fn

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "armed": self.armed,
                "fired": dict(self.fired),
                "rateLimited": dict(self.rate_limited),
                "pruned": self.pruned,
                "bundles": list(self.bundle_paths),
            }

    def reset(self) -> None:
        """Test hook: drop counters, rate-limit state and arming."""
        self.drain(timeout=5.0)
        with self._lock:
            self.armed = False
            self._hbm_watermark = self._queue_watermark = 0.0
            self._retry_storm = 0
            self._retry_times.clear()
            self._last_fire.clear()
            self.fired.clear()
            self.rate_limited.clear()
            self.bundle_paths.clear()
            self.pruned = 0
            self._max_bundles = int(TELEMETRY_MAX_BUNDLES.default)
            self._max_bundle_bytes = int(
                TELEMETRY_MAX_BUNDLE_BYTES.default)
            self._stats_provider = None

    # -- firing ------------------------------------------------------------

    def _maybe_fire(self, trigger: str, condition: Dict[str, Any],
                    out_dir: Optional[str] = None,
                    min_interval: Optional[float] = None,
                    profile_path: Optional[str] = None) -> bool:
        """Rate-limit check + enqueue for the bundle worker; returns
        True when the firing was accepted (a bundle WILL be written)."""
        now = time.monotonic()
        with self._lock:
            interval = (min_interval if min_interval is not None
                        else self._min_interval)
            last = self._last_fire.get(trigger)
            if last is not None and now - last < interval:
                self.rate_limited[trigger] = \
                    self.rate_limited.get(trigger, 0) + 1
                return False
            self._last_fire[trigger] = now
            self.fired[trigger] = self.fired.get(trigger, 0) + 1
            self._seq += 1
            seq = self._seq
            self._pending += 1
            d = out_dir if out_dir is not None else self._dir
        # NOTE: no thread start here — the store/admission hooks call
        # this under DeviceStore._lock / AdmissionController._cv, and
        # Thread.start() blocks until the child is scheduled. The
        # worker is started by configure()/on_query_end()/drain(),
        # which always run before (or can flush) any armed firing.
        from spark_rapids_tpu import trace as _trace
        _trace.instant("telemetryTrigger", trigger=trigger)
        self._queue.put({"trigger": trigger, "condition": condition,
                         "dir": d, "seq": seq,
                         "profile": profile_path,
                         "wallTs": time.time()})
        return True

    def _drain_queue(self) -> None:
        while True:
            item = self._queue.get()
            try:
                self._write_bundle(item)
            except Exception:
                pass  # observability must not take down execution
            finally:
                with self._lock:
                    self._pending -= 1

    def _write_bundle(self, item: Dict[str, Any]) -> None:
        from spark_rapids_tpu import memory
        from spark_rapids_tpu.telemetry.ring import dump_ring
        out_dir = item["dir"]
        os.makedirs(out_dir, exist_ok=True)
        with self._lock:
            provider = self._stats_provider
        server_stats = None
        if provider is not None:
            try:
                server_stats = provider()
            except Exception:
                server_stats = {"error": "stats provider failed"}
        store = memory._STORE
        bundle = {
            "version": BUNDLE_VERSION,
            "trigger": item["trigger"],
            "condition": item["condition"],
            "ts": item["wallTs"],
            "pid": os.getpid(),
            "ringDump": dump_ring(out_dir),
            "profile": item.get("profile"),
            "serverStats": server_stats,
            "storeStats": store.stats() if store is not None else None,
        }
        path = os.path.join(
            out_dir,
            f"bundle-{os.getpid()}-{item['seq']:05d}-"
            f"{item['trigger']}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)
        with self._lock:
            self.bundle_paths.append(path)
            del self.bundle_paths[:-64]
        # retention sweep (telemetry.maxBundles / maxBundleBytes):
        # runs HERE on the worker thread, after the write, so the
        # hot-path hooks never pay for directory listing or unlinks
        self._prune_artifacts(out_dir)

    def _prune_artifacts(self, out_dir: str) -> None:
        """Prune telemetry artifacts (trigger bundles + flight-recorder
        dumps) oldest-first until the directory fits the configured
        count/byte bounds. Never raises."""
        with self._lock:
            max_bundles = self._max_bundles
            max_bytes = self._max_bundle_bytes
        if max_bundles <= 0 and max_bytes <= 0:
            return
        try:
            files = [
                os.path.join(out_dir, f) for f in os.listdir(out_dir)
                if f.endswith(".json")
                and (f.startswith("bundle-")
                     or f.startswith("trace-ring-"))]
            stats = []
            for p in files:
                try:
                    st = os.stat(p)
                    stats.append((st.st_mtime, p, st.st_size))
                except OSError:
                    continue
            stats.sort()
            total = sum(s for _, _, s in stats)
            pruned = 0
            while stats and (
                    (max_bundles > 0 and len(stats) > max_bundles)
                    or (max_bytes > 0 and total > max_bytes)):
                _, p, size = stats.pop(0)
                try:
                    os.unlink(p)
                    pruned += 1
                    total -= size
                except OSError:
                    total -= size
            if pruned:
                with self._lock:
                    self.pruned += pruned
                    self.bundle_paths[:] = [
                        p for p in self.bundle_paths
                        if os.path.exists(p)]
        except Exception:
            pass  # observability must not take down execution

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until every accepted firing has its bundle on disk
        (tests/bench call this before reading telemetry.dir)."""
        self._ensure_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return True
            time.sleep(0.005)
        return False

    # -- evaluation points -------------------------------------------------

    def on_query_end(self, conf_obj, wall_s: float, plan=None,
                     tenant: Optional[str] = None,
                     query_id: Optional[int] = None,
                     profile_path: Optional[str] = None) -> None:
        """Query-close evaluation: latency + per-query metric deltas
        (the executed plan's registries are this query's deltas by
        construction)."""
        if conf_obj is None:
            return
        slow_ms = int(conf_obj.get(TELEMETRY_SLOW_QUERY_MS))
        retry_thr = int(conf_obj.get(TELEMETRY_RETRY_COUNT_THRESHOLD))
        fb_thr = int(conf_obj.get(TELEMETRY_KERNEL_FALLBACK_THRESHOLD))
        if slow_ms <= 0 and retry_thr <= 0 and fb_thr <= 0:
            return
        self._ensure_worker()
        out_dir = str(conf_obj.get(TELEMETRY_DIR))
        interval = float(conf_obj.get(TELEMETRY_MIN_INTERVAL_S))
        base = {"tenant": tenant, "queryId": query_id,
                "wallMs": round(wall_s * 1e3, 3)}
        if slow_ms > 0 and wall_s * 1e3 > slow_ms:
            self._maybe_fire(
                "slowQuery", {**base, "slowQueryMs": slow_ms},
                out_dir=out_dir, min_interval=interval,
                profile_path=profile_path)
        if plan is not None and (retry_thr > 0 or fb_thr > 0):
            from spark_rapids_tpu.metrics import registry_snapshot
            vals = registry_snapshot(plans=[plan])["metrics"]
            retries = vals.get("retryCount", 0) \
                + vals.get("splitRetryCount", 0)
            if retry_thr > 0 and retries > retry_thr:
                self._maybe_fire(
                    "retryCount",
                    {**base, "retryCount": retries,
                     "threshold": retry_thr},
                    out_dir=out_dir, min_interval=interval,
                    profile_path=profile_path)
            fallbacks = sum(v for k, v in vals.items()
                            if k.startswith("kernelFallbacks."))
            if fb_thr > 0 and fallbacks > fb_thr:
                self._maybe_fire(
                    "kernelFallbacks",
                    {**base, "kernelFallbacks": fallbacks,
                     "threshold": fb_thr},
                    out_dir=out_dir, min_interval=interval,
                    profile_path=profile_path)

    def on_store_sample(self, device_bytes: int, budget: int) -> None:
        """Store-transition evaluation (called by the DeviceStore under
        its lock — this method only enqueues, never does IO)."""
        wm = self._hbm_watermark
        if wm <= 0 or budget <= 0:
            return
        frac = device_bytes / budget
        if frac > wm:
            self._maybe_fire("hbmWatermark",
                             {"deviceBytes": device_bytes,
                              "budget": budget,
                              "occupancy": round(frac, 4),
                              "watermark": wm})

    def on_admission(self, queued: int, max_queued: int) -> None:
        """Enqueue-time evaluation (called by the admission controller
        under its condition lock — enqueue only, no IO)."""
        wm = self._queue_watermark
        if wm <= 0 or max_queued <= 0:
            return
        frac = queued / max_queued
        if frac > wm:
            self._maybe_fire("queueSaturation",
                             {"queued": queued,
                              "maxQueued": max_queued,
                              "saturation": round(frac, 4),
                              "watermark": wm})

    def on_retry(self) -> None:
        """Retry-time evaluation: a sliding 60 s window of OOM-retry
        events; over the threshold, the storm is visible WHILE it is
        happening, not at the next query end."""
        thr = self._retry_storm
        if thr <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self._retry_times.append(now)
            while self._retry_times and \
                    self._retry_times[0] < now - _RETRY_WINDOW_S:
                self._retry_times.popleft()
            n = len(self._retry_times)
        if n > thr:
            self._maybe_fire("retryStorm",
                             {"retriesInWindow": n,
                              "windowSeconds": _RETRY_WINDOW_S,
                              "threshold": thr})


_ENGINE = TriggerEngine()


def engine() -> TriggerEngine:
    return _ENGINE


def configure(conf_obj) -> None:
    _ENGINE.configure(conf_obj)


def set_stats_provider(fn) -> None:
    _ENGINE.set_stats_provider(fn)


def on_query_end(conf_obj, wall_s: float, plan=None, tenant=None,
                 query_id=None, profile_path=None) -> None:
    _ENGINE.on_query_end(conf_obj, wall_s, plan=plan, tenant=tenant,
                         query_id=query_id, profile_path=profile_path)


def on_store_sample(device_bytes: int, budget: int) -> None:
    if _ENGINE.armed:
        _ENGINE.on_store_sample(device_bytes, budget)


def on_admission(queued: int, max_queued: int) -> None:
    if _ENGINE.armed:
        _ENGINE.on_admission(queued, max_queued)


def on_retry() -> None:
    if _ENGINE.armed:
        _ENGINE.on_retry()
