"""The flight recorder: a fixed-size lock-free ring buffer behind the
existing Tracer (``spark.rapids.sql.trace.mode=ring``).

The recorder is a drop-in span sink for the trace hooks: it exposes
exactly the ``QueryTrace`` recording surface (``add``/``mark``/
``count``/``_thread``), so every instrumented choke point — metric
timer mirrors, dispatch spans, store transitions, retry markers, JIT
compiles — records into it with the SAME one-``None``-check hot path.
Storage differs: instead of unbounded per-query lists, each thread owns
a ``collections.deque(maxlen=N)`` (append is atomic under the GIL and
O(1) with eviction built in), so memory is bounded at roughly
``threads x ringSpans`` records no matter how long the process serves.

``dump_ring`` snapshots the rings and writes the standard Chrome-trace
JSON (``trace-ring-<pid>-<seq>.json``), so Perfetto, ``tools trace``
and ``tools hotspots`` work unchanged on dumps — that is what a
slow-query bundle embeds (triggers.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from spark_rapids_tpu.trace import (QueryTrace, _clean,
                                    write_chrome_trace)


class RingTrace(QueryTrace):
    """Process-lifetime span sink with per-thread bounded rings.

    Unlike a ``QueryTrace`` (one query, cleared at end), a ``RingTrace``
    is installed once and shared by every query; ``trace.end_query``
    leaves only a ``queryEnd`` marker. The hot path takes no lock:
    per-thread rings are created with ``dict.setdefault`` (atomic) and
    appended with ``deque.append`` (atomic, evicts the oldest record
    when full)."""

    __slots__ = ("capacity", "_span_rings", "_instant_rings",
                 "_counter_ring", "queries_begun", "dropped_snapshots",
                 "_dump_lock", "_dump_seq")

    is_ring = True

    def __init__(self, capacity: int, tenant: Optional[str] = None):
        super().__init__(0, tenant=tenant)
        self.capacity = max(16, int(capacity))
        self._span_rings: Dict[int, deque] = {}
        self._instant_rings: Dict[int, deque] = {}
        self._counter_ring: deque = deque(maxlen=self.capacity)
        self.queries_begun = 0
        self.dropped_snapshots = 0
        self._dump_lock = threading.Lock()
        self._dump_seq = 0

    # -- recording (the QueryTrace surface, lock-free) ---------------------

    def _ring(self, rings: Dict[int, deque], ident: int) -> deque:
        r = rings.get(ident)
        if r is None:
            r = rings.setdefault(ident, deque(maxlen=self.capacity))
        return r

    def add(self, kind: str, t0: int, t1: int, batch=None, chip=None,
            **attrs) -> None:
        ident = self._thread()
        self._ring(self._span_rings, ident).append(
            (kind, t0, t1, ident, batch, chip, _clean(attrs)))

    def mark(self, kind: str, **attrs) -> None:
        ident = self._thread()
        self._ring(self._instant_rings, ident).append(
            (kind, time.perf_counter_ns(), ident, _clean(attrs)))

    def count(self, series: str, value) -> None:
        self._counter_ring.append((series, time.perf_counter_ns(),
                                   value))

    # -- snapshot + dump ---------------------------------------------------

    def _copy_live(self, container) -> list:
        # writers mutate concurrently: deque appends (and dict inserts
        # from a thread's FIRST record) never invalidate existing
        # elements but CAN raise "mutated during iteration" — retry a
        # few times, then accept a tiny loss rather than lose the
        # whole dump (the busy-server moment is exactly when a dump
        # matters)
        for _ in range(8):
            try:
                return list(container)
            except RuntimeError:
                continue
        self.dropped_snapshots += 1
        return []

    def snapshot(self) -> QueryTrace:
        """A plain ``QueryTrace`` holding a point-in-time copy of every
        ring (writers keep recording concurrently), ready for
        ``write_chrome_trace``."""
        qt = QueryTrace.__new__(QueryTrace)
        qt.query_id = self.queries_begun
        qt.tenant = self.tenant
        qt.t0 = self.t0
        qt.wall_t0 = self.wall_t0
        qt.spans = [s for ident in sorted(self._copy_live(
                        self._span_rings))
                    for s in self._copy_live(
                        self._span_rings.get(ident, ()))]
        qt.instants = [i for ident in sorted(self._copy_live(
                           self._instant_rings))
                       for i in self._copy_live(
                           self._instant_rings.get(ident, ()))]
        qt.counters = self._copy_live(self._counter_ring)
        qt._thread_names = dict(
            (k, self._thread_names.get(k, str(k)))
            for k in self._copy_live(self._thread_names))
        return qt

    def record_counts(self) -> Dict[str, int]:
        return {
            "spans": sum(len(r) for r in self._span_rings.values()),
            "instants": sum(len(r)
                            for r in self._instant_rings.values()),
            "counters": len(self._counter_ring),
            "threads": len(self._span_rings),
            "capacityPerThread": self.capacity,
            "queriesBegun": self.queries_begun,
        }

    def dump(self, out_dir: str) -> str:
        """Write the current ring contents as one Chrome-trace file
        (``trace-ring-<pid>-<seq>.json``) under ``out_dir`` and return
        its path — the `trace-` prefix keeps ``tools trace <dir>`` /
        ``tools hotspots <dir>`` working on dump directories."""
        snap = self.snapshot()
        with self._dump_lock:
            self._dump_seq += 1
            seq = self._dump_seq
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"trace-ring-{os.getpid()}-{seq:05d}.json")
        write_chrome_trace(path, snap)
        return path


def dump_ring(out_dir: str) -> Optional[str]:
    """Dump the installed flight recorder (None when ring mode is not
    active) — the trigger engine's and the CLI's entry point."""
    from spark_rapids_tpu import trace as _trace
    qt = _trace.ring_active()
    if qt is None:
        return None
    try:
        return qt.dump(out_dir)
    except Exception:
        return None  # observability must not take down execution
