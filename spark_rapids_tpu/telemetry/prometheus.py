"""Prometheus text exposition of the process metric registries and the
QueryServer stats (docs/observability.md "Live telemetry").

Two kinds of families:

- **engine metrics** — every metric key the registries carry, exported
  as ``srt_<snake_case>`` (prefix families like
  ``kernelFallbacks.groupbyHash`` become one family with a ``key``
  label; ``*Time`` metrics convert ns -> seconds with a
  ``_seconds_total`` suffix). HELP text comes from
  ``metrics.describe_metric`` — a key that does not resolve is NOT
  exported (it is counted in ``srt_undescribed_metric_keys``, asserted
  zero by tier-1), so the endpoint cannot drift from the documented
  metric tables.
- **server families** — admission/tenant/cache/store/trigger gauges and
  counters with names and HELP from :data:`SERVER_FAMILY_HELP`; the
  tpu-lint ``prom-family`` rule checks every emitted literal name
  against that table, and the generated observability doc renders the
  same table, so names can't drift either.

Scrapes go through a **registry-delta aggregator**: per-live-registry
snapshots are cached and re-read only when the registry's summed
mutation counter changed, and a registry that is garbage-collected with
its plan folds its last snapshot into a retired base — counters stay
MONOTONE across plan lifetimes (a Prometheus `rate()` works), and a
scrape costs O(changed registries), not O(every metric ever created).
"""

from __future__ import annotations

import re
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

# name -> (prom type, help). Every literal family name emitted below
# MUST be a key here (tpu-lint `prom-family`); the observability doc's
# Prometheus table is generated from this dict.
SERVER_FAMILY_HELP: Dict[str, Tuple[str, str]] = {
    "srt_queries_ok_total": ("counter", "queries served successfully"),
    "srt_queries_err_total": ("counter", "queries that failed"),
    "srt_queries_cancelled_total": (
        "counter", "queries that terminated cancelled (cancel verb, "
                   "deadline, disconnect, watchdog, or drain)"),
    "srt_queries_quarantined_total": (
        "counter", "queries failed fast by the poison-query "
                   "quarantine"),
    "srt_uptime_seconds": ("gauge", "server uptime in seconds"),
    "srt_qps": ("gauge", "successful queries per second since server "
                         "start"),
    "srt_admission_in_flight": ("gauge", "queries executing right now"),
    "srt_admission_queued": ("gauge", "queries waiting for admission"),
    "srt_admission_admitted_total": ("counter",
                                     "queries admitted to execute"),
    "srt_admission_rejected_total": ("counter",
                                     "queries rejected (queue full or "
                                     "shutdown)"),
    "srt_admission_throttled_waits_total": (
        "counter", "admissions delayed by the fair-share HBM throttle"),
    "srt_tenant_admitted_total": ("counter",
                                  "queries admitted per tenant"),
    "srt_tenant_rejected_total": ("counter",
                                  "queries rejected per tenant"),
    "srt_tenant_in_flight": ("gauge", "queries executing per tenant"),
    "srt_tenant_queue_wait_ms": ("gauge",
                                 "admission queue wait quantiles per "
                                 "tenant (ms)"),
    "srt_tenant_latency_ms": ("gauge",
                              "end-to-end latency quantiles per "
                              "tenant (ms)"),
    "srt_tenant_hbm_live_bytes": ("gauge",
                                  "live device-store bytes per tenant"),
    "srt_tenant_hbm_peak_bytes": ("gauge",
                                  "peak device-store bytes per tenant"),
    "srt_tenant_hbm_spill_bytes_total": (
        "counter", "device bytes spilled from the tenant's working "
                   "set"),
    "srt_jit_cache_hits_total": ("counter",
                                 "compile-cache hits per cache"),
    "srt_jit_cache_misses_total": ("counter",
                                   "compile-cache misses per cache"),
    "srt_jit_cache_evictions_total": ("counter",
                                      "compile-cache evictions per "
                                      "cache"),
    "srt_jit_cache_contention_total": (
        "counter", "threads that blocked on another thread's "
                   "in-progress compile"),
    "srt_jit_cache_size": ("gauge", "entries live per compile cache"),
    "srt_store_device_bytes": ("gauge",
                               "device-store live HBM bytes"),
    "srt_store_peak_device_bytes": ("gauge",
                                    "device-store peak HBM bytes"),
    "srt_store_host_bytes": ("gauge", "device-store host-tier bytes"),
    "srt_store_spill_count_total": ("counter",
                                    "device->host store demotions"),
    "srt_store_spilled_device_bytes_total": (
        "counter", "HBM bytes demoted device->host"),
    "srt_store_disk_files_live": ("gauge",
                                  "disk-tier spill files believed "
                                  "live"),
    "srt_telemetry_triggers_fired_total": (
        "counter", "telemetry trigger firings per trigger"),
    "srt_telemetry_triggers_rate_limited_total": (
        "counter", "trigger firings suppressed by the per-trigger "
                   "rate limit"),
    "srt_telemetry_bundles_pruned_total": (
        "counter", "telemetry artifacts (bundles + ring dumps) "
                   "pruned by the maxBundles/maxBundleBytes "
                   "retention"),
    "srt_slo_objective_p99_ms": (
        "gauge", "per-tenant SLO p99 objective in ms "
                 "(serve.slo.p99Ms[.<tenant>])"),
    "srt_slo_observed_p99_ms": (
        "gauge", "observed p99 wall in ms over the SLO window per "
                 "tenant (query history)"),
    "srt_slo_window_queries": (
        "gauge", "finished queries inside the SLO window per tenant"),
    "srt_slo_window_violations": (
        "gauge", "queries over the tenant's SLO objective inside the "
                 "window"),
    "srt_slo_burn_ratio": (
        "gauge", "fraction of the tenant's window queries over its "
                 "SLO objective"),
    "srt_tuning_ticks_total": (
        "counter", "TuningController scan ticks run (start-of-server "
                   "scan included; docs/tuning.md)"),
    "srt_tuning_actions_total": (
        "counter", "tuning actions applied, labeled by ACTION_CATALOG "
                   "action name"),
    "srt_tuning_reverts_total": (
        "counter", "tuning actions rolled back (guardrail "
                   "auto-reverts + operator reverts via tools "
                   "tuning)"),
    "srt_tuning_active_actions": (
        "gauge", "actions currently in effect (state applied or "
                 "accepted)"),
    "srt_tuning_pinned_actions": (
        "gauge", "actions pinned by the operator (exempt from the "
                 "guardrail's auto-revert)"),
    "srt_tuning_prewarmed_signatures": (
        "gauge", "signatures in the pre-warm ledger (plan templates "
                 "replayed at server start and protected from LRU "
                 "eviction)"),
    "srt_undescribed_metric_keys": (
        "gauge", "registry metric keys that did not resolve via "
                 "describe_metric and were NOT exported (must be 0)"),
    "srt_aqe_batch_fused_queries_total": (
        "counter", "queries served out of same-signature fused "
                   "batches of size >= 2 (docs/adaptive.md)"),
    "srt_aqe_batch_fusion_batches_total": (
        "counter", "fused batches of size >= 2 executed under one "
                   "admission slot"),
    "srt_cache_result_hits_total": (
        "counter", "queries served verbatim from the result cache "
                   "(zero device work; docs/caching.md)"),
    "srt_cache_result_misses_total": (
        "counter", "result-cache probes that fell through to "
                   "execution"),
    "srt_cache_result_entries": (
        "gauge", "result-cache entries resident"),
    "srt_cache_result_bytes": (
        "gauge", "Arrow IPC payload bytes held by the result cache"),
    "srt_cache_result_invalidations_total": (
        "counter", "result-cache entries dropped because an input "
                   "file fingerprint or the view generation changed"),
    "srt_cache_result_evictions_total": (
        "counter", "result-cache entries evicted by the LRU bounds"),
    "srt_cache_subplan_hits_total": (
        "counter", "join build tables reused from the subplan cache "
                   "(docs/caching.md)"),
    "srt_cache_subplan_misses_total": (
        "counter", "subplan-cache probes that fell through to a "
                   "build"),
    "srt_cache_subplan_entries": (
        "gauge", "device-resident build tables held by the subplan "
                 "cache"),
    "srt_cache_subplan_bytes": (
        "gauge", "HBM bytes held by cached build tables (evict-first "
                 "under pool pressure)"),
    "srt_cache_subplan_invalidations_total": (
        "counter", "cached build tables dropped because an input "
                   "file fingerprint changed"),
    "srt_cache_subplan_evictions_total": (
        "counter", "cached build tables evicted (LRU bounds or "
                   "device-pool pressure drop)"),
}


# ---------------------------------------------------------------------------
# Registry-delta aggregator
# ---------------------------------------------------------------------------

class RegistryAggregator:
    """Monotone totals over every MetricRegistry the process ever
    created: ``metrics.retired_totals()`` (each registry's FINAL
    values, folded in by a metrics.py finalizer when the registry is
    garbage-collected with its plan — a query completing between two
    scrapes still counts) plus the live registries, whose snapshots are
    cached and re-read only when their summed metric-mutation counters
    changed."""

    def __init__(self):
        self._lock = threading.Lock()
        # id(registry) -> [version_sum, snapshot]; dropped at GC (the
        # dead registry's contribution moves to the retired base)
        self._cache: Dict[int, List] = {}
        self._finalized: set = set()

    def _drop(self, rid: int) -> None:
        # finalize path: runs at arbitrary allocation points, so no
        # locks — dict.pop / set.discard are atomic under the GIL
        self._cache.pop(rid, None)
        self._finalized.discard(rid)

    @staticmethod
    def _read(reg) -> Optional[Tuple[int, Dict[str, int]]]:
        """(version sum, snapshot) of one registry; None when a
        concurrent create() mutated the metric dict mid-read (the
        caller reuses the cached snapshot — next scrape catches up)."""
        for _ in range(4):
            try:
                vsum = 0
                snap: Dict[str, int] = {}
                for k, m in reg.metrics.items():
                    vsum += m.version
                    snap[k] = m.value
                return vsum + len(snap), snap
            except RuntimeError:
                continue
        return None

    def scrape(self) -> Tuple[Dict[str, int], int]:
        """(folded totals per metric key — sums for counters, max for
        watermark metrics — and the count of changed registries re-read
        this scrape)."""
        from spark_rapids_tpu.metrics import (fold_metric,
                                              live_registries,
                                              retired_totals)
        regs = live_registries()
        changed = 0
        with self._lock:
            totals = retired_totals()
            for reg in regs:
                rid = id(reg)
                entry = self._cache.get(rid)
                if entry is None:
                    entry = [-1, {}]
                    self._cache[rid] = entry
                    if rid not in self._finalized:
                        self._finalized.add(rid)
                        weakref.finalize(reg, self._drop, rid)
                got = self._read(reg)
                if got is not None and got[0] != entry[0]:
                    entry[0], entry[1] = got
                    changed += 1
                for k, v in entry[1].items():
                    fold_metric(totals, k, v)
        return totals, changed


_AGG = RegistryAggregator()


def aggregator() -> RegistryAggregator:
    return _AGG


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

_SNAKE_RE = re.compile(r"([a-z0-9])([A-Z])")


def prom_name(key: str) -> str:
    """camelCase metric base -> srt_snake_case."""
    s = _SNAKE_RE.sub(r"\1_\2", key).lower()
    return "srt_" + re.sub(r"[^a-z0-9_]", "_", s)


def engine_family(key: str) -> Tuple[str, Optional[Tuple[str, str]],
                                     bool, bool]:
    """(family name, optional (label, value), is_seconds, is_gauge)
    for one registry metric key. Prefix-family members
    (``base.member``) share one family with a ``key`` label; watermark
    metrics are gauges (max-folded by the aggregator), everything else
    a ``_total`` counter."""
    from spark_rapids_tpu.metrics import is_watermark_metric
    base, dot, rest = key.partition(".")
    label = ("key", rest) if dot else None
    seconds = base.endswith(("Time", "time"))
    name = prom_name(base)
    if seconds:
        name += "_seconds"
    gauge = is_watermark_metric(base)
    if not gauge:
        name += "_total"
    return name, label, seconds, gauge


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class _Out:
    """Family-grouped exposition builder: HELP/TYPE once per family,
    samples in emission order."""

    def __init__(self):
        self._fams: "Dict[str, List[str]]" = {}
        self._meta: Dict[str, Tuple[str, str]] = {}

    def family(self, name: str, ftype: str, help_text: str) -> None:
        self._meta.setdefault(name, (ftype, help_text))
        self._fams.setdefault(name, [])

    def sample(self, name: str, value, labels: Dict[str, Any] = None
               ) -> None:
        lab = ""
        if labels:
            lab = "{" + ",".join(
                f'{k}="{_escape(v)}"'
                for k, v in sorted(labels.items())) + "}"
        if isinstance(value, float):
            sval = repr(round(value, 9))
        else:
            sval = str(int(value))
        self._fams.setdefault(name, []).append(f"{name}{lab} {sval}")

    def text(self) -> str:
        lines: List[str] = []
        for name in sorted(self._fams):
            ftype, help_text = self._meta.get(name, ("untyped", ""))
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {ftype}")
            lines.extend(self._fams[name])
        return "\n".join(lines) + "\n"


def _emit_server(out: "_Out", name: str, value,
                 labels: Dict[str, Any] = None) -> None:
    ftype, help_text = SERVER_FAMILY_HELP[name]
    out.family(name, ftype, help_text)
    out.sample(name, value, labels)


def render_prometheus(server_stats: Optional[Dict] = None) -> str:
    """The full exposition: engine registry totals + store/jit-cache/
    trigger process families + (when given) the QueryServer's
    admission/tenant stats."""
    from spark_rapids_tpu import memory
    from spark_rapids_tpu.jit_cache import cache_stats
    from spark_rapids_tpu.metrics import describe_metric
    from spark_rapids_tpu.telemetry import triggers as _triggers
    out = _Out()

    totals, _changed = _AGG.scrape()
    undescribed = 0
    for key in sorted(totals):
        desc = describe_metric(key)
        if desc is None:
            undescribed += 1
            continue
        name, label, seconds, gauge = engine_family(key)
        out.family(name, "gauge" if gauge else "counter", desc)
        value = totals[key] / 1e9 if seconds else totals[key]
        out.sample(name, float(value) if seconds else value,
                   dict([label]) if label else None)
    _emit_server(out, "srt_undescribed_metric_keys", undescribed)

    store = memory._STORE
    if store is not None:
        st = store.stats()
        _emit_server(out, "srt_store_device_bytes", st["deviceBytes"])
        _emit_server(out, "srt_store_peak_device_bytes",
                     st["peakDeviceBytes"])
        _emit_server(out, "srt_store_host_bytes", st["hostBytes"])
        _emit_server(out, "srt_store_spill_count_total",
                     st["spillCount"])
        _emit_server(out, "srt_store_spilled_device_bytes_total",
                     st["spilledDeviceBytes"])
        _emit_server(out, "srt_store_disk_files_live",
                     st["diskFilesLive"])
        for tenant, ts in store.tenant_stats().items():
            lab = {"tenant": tenant}
            _emit_server(out, "srt_tenant_hbm_live_bytes",
                         ts["liveBytes"], lab)
            _emit_server(out, "srt_tenant_hbm_peak_bytes",
                         ts["peakBytes"], lab)
            _emit_server(out, "srt_tenant_hbm_spill_bytes_total",
                         ts["spillBytes"], lab)

    for cache, cs in sorted(cache_stats().items()):
        lab = {"cache": cache}
        _emit_server(out, "srt_jit_cache_hits_total", cs["hits"], lab)
        _emit_server(out, "srt_jit_cache_misses_total", cs["misses"],
                     lab)
        _emit_server(out, "srt_jit_cache_evictions_total",
                     cs["evictions"], lab)
        _emit_server(out, "srt_jit_cache_contention_total",
                     cs["contention"], lab)
        _emit_server(out, "srt_jit_cache_size", cs["size"], lab)

    tstats = _triggers.engine().stats()
    for trig, n in sorted(tstats["fired"].items()):
        _emit_server(out, "srt_telemetry_triggers_fired_total", n,
                     {"trigger": trig})
    for trig, n in sorted(tstats["rateLimited"].items()):
        _emit_server(out, "srt_telemetry_triggers_rate_limited_total",
                     n, {"trigger": trig})
    _emit_server(out, "srt_telemetry_bundles_pruned_total",
                 tstats.get("pruned", 0))

    if server_stats:
        _emit_server(out, "srt_queries_ok_total",
                     server_stats.get("queriesOk", 0))
        _emit_server(out, "srt_queries_err_total",
                     server_stats.get("queriesErr", 0))
        _emit_server(out, "srt_queries_cancelled_total",
                     server_stats.get("queriesCancelled", 0))
        _emit_server(out, "srt_queries_quarantined_total",
                     server_stats.get("lifecycle", {})
                     .get("queriesQuarantined", 0))
        _emit_server(out, "srt_uptime_seconds",
                     float(server_stats.get("uptimeSeconds", 0.0)))
        _emit_server(out, "srt_qps",
                     float(server_stats.get("qps", 0.0)))
        adm = server_stats.get("admission", {})
        _emit_server(out, "srt_admission_in_flight",
                     adm.get("inFlight", 0))
        _emit_server(out, "srt_admission_queued", adm.get("queued", 0))
        _emit_server(out, "srt_admission_admitted_total",
                     adm.get("admitted", 0))
        _emit_server(out, "srt_admission_rejected_total",
                     adm.get("rejected", 0))
        _emit_server(out, "srt_admission_throttled_waits_total",
                     adm.get("throttledWaits", 0))
        for tenant, ts in sorted(adm.get("tenants", {}).items()):
            lab = {"tenant": tenant}
            _emit_server(out, "srt_tenant_admitted_total",
                         ts.get("admitted", 0), lab)
            _emit_server(out, "srt_tenant_rejected_total",
                         ts.get("rejected", 0), lab)
            _emit_server(out, "srt_tenant_in_flight",
                         ts.get("inFlight", 0), lab)
            for q, v in ts.get("queueWaitMs", {}).items():
                _emit_server(out, "srt_tenant_queue_wait_ms",
                             float(v), {**lab, "quantile": q})
            for q, v in ts.get("latencyMs", {}).items():
                if q == "count":
                    continue
                _emit_server(out, "srt_tenant_latency_ms", float(v),
                             {**lab, "quantile": q})
        # same-signature batch fusion (docs/adaptive.md): present only
        # when the server runs with batchFusion.enabled
        bf = server_stats.get("batchFusion")
        if bf:
            _emit_server(out, "srt_aqe_batch_fused_queries_total",
                         bf.get("fusedQueries", 0))
            _emit_server(out, "srt_aqe_batch_fusion_batches_total",
                         bf.get("fusedBatches", 0))
        # result + subplan caches (docs/caching.md): present only when
        # the server runs with resultCache/subplanCache enabled
        cache = server_stats.get("cache") or {}
        rc = cache.get("result")
        if rc:
            _emit_server(out, "srt_cache_result_hits_total",
                         rc.get("hits", 0))
            _emit_server(out, "srt_cache_result_misses_total",
                         rc.get("misses", 0))
            _emit_server(out, "srt_cache_result_entries",
                         rc.get("entries", 0))
            _emit_server(out, "srt_cache_result_bytes",
                         rc.get("bytes", 0))
            _emit_server(out, "srt_cache_result_invalidations_total",
                         rc.get("invalidations", 0))
            _emit_server(out, "srt_cache_result_evictions_total",
                         rc.get("evictions", 0))
        sp = cache.get("subplan")
        if sp:
            _emit_server(out, "srt_cache_subplan_hits_total",
                         sp.get("hits", 0))
            _emit_server(out, "srt_cache_subplan_misses_total",
                         sp.get("misses", 0))
            _emit_server(out, "srt_cache_subplan_entries",
                         sp.get("entries", 0))
            _emit_server(out, "srt_cache_subplan_bytes",
                         sp.get("bytes", 0))
            _emit_server(out, "srt_cache_subplan_invalidations_total",
                         sp.get("invalidations", 0))
            _emit_server(out, "srt_cache_subplan_evictions_total",
                         sp.get("evictions", 0))
        # SLO burn tracking over the query history (docs/
        # observability.md "SLO tracking"): per-tenant objective vs
        # observed p99 over the window, gauges because the window
        # slides
        for tenant, slo in sorted(
                (server_stats.get("slo") or {}).items()):
            lab = {"tenant": tenant}
            _emit_server(out, "srt_slo_objective_p99_ms",
                         float(slo.get("objectiveP99Ms", 0)), lab)
            _emit_server(out, "srt_slo_observed_p99_ms",
                         float(slo.get("observedP99Ms", 0.0)), lab)
            _emit_server(out, "srt_slo_window_queries",
                         slo.get("windowQueries", 0), lab)
            _emit_server(out, "srt_slo_window_violations",
                         slo.get("violations", 0), lab)
            _emit_server(out, "srt_slo_burn_ratio",
                         float(slo.get("burnRatio", 0.0)), lab)
        # feedback control (docs/tuning.md): present only when the
        # server runs with serve.tuning.enabled
        tun = server_stats.get("tuning")
        if tun:
            _emit_server(out, "srt_tuning_ticks_total",
                         tun.get("ticks", 0))
            for action, n in sorted(
                    (tun.get("actionsByName") or {}).items()):
                _emit_server(out, "srt_tuning_actions_total", n,
                             {"action": action})
            _emit_server(out, "srt_tuning_reverts_total",
                         tun.get("actionsReverted", 0))
            _emit_server(out, "srt_tuning_active_actions",
                         tun.get("activeActions", 0))
            _emit_server(out, "srt_tuning_pinned_actions",
                         tun.get("pinnedActions", 0))
            _emit_server(out, "srt_tuning_prewarmed_signatures",
                         tun.get("prewarmedSignatures", 0))
    return out.text()


# ---------------------------------------------------------------------------
# HTTP twin (`tools serve --metrics-port`)
# ---------------------------------------------------------------------------

def serve_http_metrics(render_fn, port: int, host: str = "127.0.0.1"):
    """Serve ``GET /metrics`` (Prometheus text via ``render_fn``) on a
    daemon thread; returns the httpd (``.shutdown()`` +
    ``.server_close()`` to stop). ``render_fn`` is called per request
    so scrapes always see current state."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
            if path in ("/metrics", "/"):
                try:
                    body = render_fn().encode("utf-8")
                    ctype = "text/plain; version=0.0.4"
                    code = 200
                except Exception as e:  # pragma: no cover - defensive
                    body = _json.dumps({"error": str(e)}).encode()
                    ctype = "application/json"
                    code = 500
            else:
                body = b"not found (try /metrics)\n"
                ctype = "text/plain"
                code = 404
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever,
                         name="srt-metrics-http", daemon=True)
    t.start()
    return httpd
