"""`tools doctor`: automated "why is this query slow" diagnosis over
the query-history store (docs/observability.md "tools doctor").

A slow query's history record, profile artifact, and trace file carry
everything a human would grep for; this module does the grep. Given a
queryId or signature selector it:

1. resolves the target record in the history store;
2. builds the signature's **historical baseline** from the other
   finished records of the same shape (wall p50/p99, mean queue wait,
   retry/fallback/jit-miss rates, mean rows, mean per-stage times from
   their profile artifacts);
3. diffs the target's **per-stage self-times** against that baseline,
   stage by stage (profile-artifact time metrics aggregated by stage
   key — ``retryBlockTime`` -> ``retryBlock`` — with the trace file's
   exclusive self-times as corroborating evidence when present);
4. scores the **verdict taxonomy** below and emits a ranked verdict
   with concrete evidence lines.

The taxonomy (VERDICT_CLASSES renders into the generated doc):
queue-wait vs compile-storm vs retry/spill vs kernel-fallback vs
scan-bound vs genuinely-bigger-input, with ``unknown`` when nothing
diverges enough to blame.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu.telemetry.history import (STATUS_FINISHED,
                                                find_record,
                                                read_records,
                                                sig_digest)

# verdict class -> what it means (the generated observability doc
# renders this table; the doctor's `verdict` field is one of the keys)
VERDICT_CLASSES: Dict[str, str] = {
    "queueWait": "the query spent its time waiting for admission, not "
                 "executing — the server was saturated, not the query "
                 "slow",
    "compileStorm": "jit-cache misses well above the signature's "
                    "baseline — compilation (cold caches, capacity "
                    "eviction, or a shape flip) dominated the wall",
    "retrySpill": "OOM retry / split-retry / spill activity above "
                  "baseline — the retryBlock recovery wall (spill + "
                  "backoff) stretched the query",
    "kernelFallback": "Pallas kernel calls fell back to the XLA-op "
                      "oracle composition above baseline — check "
                      "kernel confs / tableSlots",
    "scanBound": "scan-side stages (decode, prefetch, upload) diverge "
                 "from baseline — input IO/decode got slower, not the "
                 "compute",
    "biggerInput": "the query genuinely processed more data than its "
                   "baseline runs (rows well above baseline, stages "
                   "scaled roughly uniformly)",
    "skewedShuffle": "a materialized exchange in the profile artifact "
                     "is heavily skewed (max partition well above the "
                     "median) — one partition serializes the stage; "
                     "check the aqeActions field / "
                     "spark.rapids.sql.adaptive.skewFactor "
                     "(docs/adaptive.md)",
    "unknown": "no stage or counter diverges enough from the "
               "signature's baseline to name a cause",
}

# stage-name fragments whose divergence indicates a scan-bound /
# compile-bound query (matched as substrings — the profile vocabulary
# is metric stems like `decode`, the trace vocabulary span names like
# `FileScan.decodeTime` / `scanPrefetch`)
_SCAN_FRAGMENTS = ("decode", "scanPrefetch", "uploadAhead",
                   "copyToDevice", "readFileRange")
_COMPILE_FRAGMENTS = ("compile",)


def _profile_stage_times(profile_path: str) -> Dict[str, float]:
    """Per-stage self-times (seconds) from one profile artifact: every
    time metric on every plan node (fused constituents included),
    aggregated by stage key — the metric name with its ``Time`` suffix
    dropped, so ``retryBlockTime`` contributes to stage
    ``retryBlock``."""
    import json
    out: Dict[str, float] = {}
    try:
        with open(profile_path, encoding="utf-8") as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return out

    def add(entry: Dict[str, Any]) -> None:
        for k, v in (entry.get("metrics") or {}).items():
            if not v or not k.endswith(("Time", "time")):
                continue
            stage = k[:-4]
            # metric-mirror names are bare (opTime on every exec);
            # keep them bare so stages aggregate across operators
            out[stage] = out.get(stage, 0.0) + float(v) / 1e9

    def walk(entry: Dict[str, Any]) -> None:
        add(entry)
        for fe in entry.get("fused", []):
            add(fe)
        for c in entry.get("children", []):
            walk(c)

    plan = prof.get("plan")
    if isinstance(plan, dict):
        walk(plan)
    return out


def _profile_exchange_skew(profile_path: str) -> Dict[str, Any]:
    """The WORST exchange-partition skew in one profile artifact:
    max/median partition-byte ratio over every plan node that recorded
    the exchange-stat metrics ``_materialize`` captures
    (docs/adaptive.md). Empty dict when the artifact is unreadable or
    no exchange materialized."""
    import json
    try:
        with open(profile_path, encoding="utf-8") as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return {}
    worst: Dict[str, Any] = {}

    def visit(entry: Dict[str, Any]) -> None:
        m = entry.get("metrics") or {}
        mx = float(m.get("exchangeMaxPartitionBytes", 0))
        med = float(m.get("exchangeMedianPartitionBytes", 0))
        if mx > 0 and med > 0:
            ratio = mx / med
            if ratio > worst.get("ratio", 0.0):
                worst.update({
                    "ratio": round(ratio, 2),
                    "maxBytes": int(mx),
                    "medianBytes": int(med),
                    "node": entry.get("op") or "exchange"})
        for fe in entry.get("fused", []):
            visit(fe)
        for c in entry.get("children", []):
            visit(c)

    plan = prof.get("plan")
    if isinstance(plan, dict):
        visit(plan)
    return worst


def _trace_self_times(trace_path: str) -> Dict[str, float]:
    """Exclusive self-times (seconds) per span family from one trace
    file — corroborating evidence next to the profile-based stage
    diff."""
    try:
        from spark_rapids_tpu.tools import exclusive_times
        from spark_rapids_tpu.trace import load_trace
        spans = load_trace(trace_path)["spans"]
        return {name: d["exclusive"] / 1e6
                for name, d in exclusive_times(spans).items()}
    except Exception:
        return {}


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _record_stage_times(rec: Dict[str, Any],
                        use_trace: bool) -> Dict[str, float]:
    """One record's per-stage times from its artifacts: EXCLUSIVE
    self-times per span family when traces are the chosen source
    (nested spans — retryBlock inside operator timers — subtracted, so
    the divergent stage is attributable), profile time metrics
    otherwise."""
    if use_trace:
        tp = rec.get("tracePath")
        if tp and os.path.exists(str(tp)):
            return _trace_self_times(str(tp))
        return {}
    pp = rec.get("profilePath")
    if pp and os.path.exists(str(pp)):
        return _profile_stage_times(str(pp))
    return {}


def _pick_stage_source(target: Dict[str, Any],
                       base: List[Dict[str, Any]]) -> bool:
    """True = use traces. Traces win when the target AND at least one
    baseline record still have trace files on disk (both sides must
    speak one stage vocabulary for the diff to mean anything)."""
    def has_trace(r) -> bool:
        tp = r.get("tracePath")
        return bool(tp) and os.path.exists(str(tp))
    return has_trace(target) and any(has_trace(r) for r in base)


def _baseline(records: List[Dict[str, Any]],
              target: Dict[str, Any]) -> Dict[str, Any]:
    """Aggregate the signature's OTHER finished records into the
    comparison baseline (counter means + mean per-stage times from
    whichever of their artifacts still exist on disk)."""
    from spark_rapids_tpu.lifecycle import percentile
    # an unsignatured target (plan cache off) gets an EMPTY baseline:
    # matching None == None would aggregate unrelated query shapes
    # into a meaningless comparison
    sig = target.get("signature")
    base = [r for r in records
            if sig and r is not target
            and r.get("status") == STATUS_FINISHED
            and r.get("signature") == sig
            # cache-served records carry near-zero walls and no device
            # work — aggregating them would make every real execution
            # look like a regression (docs/caching.md)
            and not r.get("resultCacheHit")]
    walls = [float(r.get("wallSeconds", 0)) for r in base]
    use_trace = _pick_stage_source(target, base)
    stage_sets: List[Dict[str, float]] = []
    for r in base:
        st = _record_stage_times(r, use_trace)
        if st:
            stage_sets.append(st)
    stages: Dict[str, float] = {}
    if stage_sets:
        keys = set()
        for s in stage_sets:
            keys.update(s)
        for k in keys:
            stages[k] = _mean([s.get(k, 0.0) for s in stage_sets])
    return {
        "useTrace": use_trace,
        "count": len(base),
        "wallP50": percentile(walls, 0.50),
        "wallP99": percentile(walls, 0.99),
        "queueWaitMean": _mean(
            [float(r.get("queueWaitSeconds", 0)) for r in base]),
        "retriesMean": _mean(
            [float(r.get("retryCount", 0)
                   + r.get("splitRetryCount", 0)) for r in base]),
        "spillBytesMean": _mean(
            [float(r.get("spillBytes", 0)) for r in base]),
        "fallbacksMean": _mean(
            [float(r.get("kernelFallbacks", 0)) for r in base]),
        "jitMissesMean": _mean(
            [float(r.get("jitMisses", 0)) for r in base]),
        "rowsMean": _mean(
            [float(r.get("outputRows", 0)) for r in base]),
        "stages": stages,
        "stagedRuns": len(stage_sets),
    }


def _stage_diff(target_stages: Dict[str, float],
                base_stages: Dict[str, float]
                ) -> List[Dict[str, float]]:
    keys = set(target_stages) | set(base_stages)
    rows = []
    for k in keys:
        t = target_stages.get(k, 0.0)
        b = base_stages.get(k, 0.0)
        rows.append({"stage": k, "targetS": round(t, 4),
                     "baselineS": round(b, 4),
                     "deltaS": round(t - b, 4)})
    rows.sort(key=lambda r: -r["deltaS"])
    return rows


def diagnose(history_dir: str, selector: str) -> Dict[str, Any]:
    """Run the full diagnosis; returns the machine-readable report
    (``format_diagnosis`` renders it). ``error`` is set when the
    selector does not resolve."""
    records = read_records(history_dir)
    target = find_record(records, selector)
    if target is None:
        return {"error": f"no history record matches {selector!r} "
                         f"in {history_dir}"}
    return diagnose_record(records, target)


def diagnose_record(records: List[Dict[str, Any]],
                    target: Dict[str, Any]) -> Dict[str, Any]:
    """Diagnose one already-resolved record against an already-loaded
    record list — the store is read ONCE however many signatures the
    batch scan walks."""
    sig = target.get("signature")
    base = _baseline(records, target)

    wall = float(target.get("wallSeconds", 0))
    queue_wait = float(target.get("queueWaitSeconds", 0))
    retries = float(target.get("retryCount", 0)
                    + target.get("splitRetryCount", 0))
    spill = float(target.get("spillBytes", 0))
    fallbacks = float(target.get("kernelFallbacks", 0))
    jit_misses = float(target.get("jitMisses", 0))
    rows = float(target.get("outputRows", 0))

    target_stages = _record_stage_times(target, base["useTrace"])
    diff = _stage_diff(target_stages, base["stages"]) \
        if target_stages else []
    divergent = diff[0]["stage"] if diff and diff[0]["deltaS"] > 0 \
        else None
    # a stage can only "explain the regression" when there IS one: the
    # target must be meaningfully slower than its baseline p50, or the
    # share denominators would divide run-to-run jitter by epsilon and
    # confidently blame a stage on a perfectly normal run
    wall_delta = wall - base["wallP50"]
    regressed = base["count"] > 0 and base["wallP50"] > 0 and \
        wall_delta > max(0.01, 0.05 * base["wallP50"])

    def stage_share(fragments: Tuple[str, ...]) -> float:
        """Fraction of the wall regression explained by stages whose
        name contains one of the fragments (substring match bridges
        the profile-metric and trace-span vocabularies); 0 when the
        query did not regress against its baseline."""
        if not regressed:
            return 0.0
        d = sum(r["deltaS"] for r in diff
                if r["deltaS"] > 0 and any(
                    f.lower() in r["stage"].lower()
                    for f in fragments))
        return min(1.0, d / wall_delta)

    verdicts: List[Dict[str, Any]] = []

    def verdict(cls: str, score: float, evidence: List[str]) -> None:
        if score > 0:
            verdicts.append({"class": cls, "score": round(score, 4),
                             "evidence": evidence})

    # queue-wait: the time went to admission, not execution
    total = wall + queue_wait
    qfrac = queue_wait / total if total > 0 else 0.0
    if qfrac > 0.4 and queue_wait > 2 * max(base["queueWaitMean"],
                                            1e-3):
        verdict("queueWait", qfrac, [
            f"queue wait {queue_wait:.3f}s is {qfrac:.0%} of the "
            f"request (baseline mean {base['queueWaitMean']:.3f}s)"])

    # compile-storm: jit misses well over baseline
    if jit_misses > max(2 * base["jitMissesMean"], base["jitMissesMean"]
                        + 2) and jit_misses > 0:
        verdict("compileStorm",
                0.5 + 0.5 * stage_share(_COMPILE_FRAGMENTS), [
                    f"jit-cache misses {jit_misses:.0f} vs baseline "
                    f"mean {base['jitMissesMean']:.1f}"])

    # retry/spill: retries or spill bytes over baseline; the
    # retryBlock stage divergence is the smoking gun
    if retries > base["retriesMean"] + 0.5 or \
            spill > 2 * max(base["spillBytesMean"], 1.0):
        share = stage_share(("retryBlock",))
        ev = [f"retries {retries:.0f} vs baseline mean "
              f"{base['retriesMean']:.1f}; spill "
              f"{spill:.0f}B vs mean {base['spillBytesMean']:.0f}B"]
        for r in diff:
            if r["stage"] == "retryBlock" and r["deltaS"] > 0:
                ev.append(
                    f"retryBlock self-time {r['targetS']:.3f}s vs "
                    f"baseline {r['baselineS']:.3f}s "
                    f"(+{r['deltaS']:.3f}s — the divergent stage)")
        score = 0.5 + 0.5 * share
        poc = target.get("plannedOutOfCore") or {}
        if poc.get("plannedPartitions") and \
                retries <= base["retriesMean"] + 0.5:
            # spill without retries under an engaged budget oracle is
            # PLANNED out-of-core activity, not thrash — rank this
            # verdict below biggerInput (docs/out_of_core.md)
            score *= 0.3
            ev.append(
                f"spill was planned out-of-core activity "
                f"(plannedPartitions="
                f"{poc['plannedPartitions']:.0f}, retries stayed at "
                f"baseline) — not retry thrash")
        elif retries > max(2.0, 2 * base["retriesMean"] + 1.0):
            ev.append(
                "repeated retry storm — set "
                "spark.rapids.sql.memory.deviceBudgetBytes and "
                "spark.rapids.sql.outOfCore.enabled so joins/aggs "
                "partition up front instead of riding the "
                "spill-and-retry loop (docs/out_of_core.md)")
        verdict("retrySpill", score, ev)

    # kernel-fallback: the oracle ride, with the culprit kernel(s)
    # named from the record's per-kernel counters so the operator
    # checks ONE conf instead of the whole kernel tier
    if fallbacks > base["fallbacksMean"] + 0.5:
        ev = [f"kernel fallbacks {fallbacks:.0f} vs baseline mean "
              f"{base['fallbacksMean']:.1f} — check kernel confs / "
              f"tableSlots"]
        by_name = target.get("kernelFallbacksByName") or {}
        for name, n in sorted(by_name.items(),
                              key=lambda kv: (-kv[1], kv[0])):
            ev.append(f"{name}: {n:.0f} fallback(s) — check "
                      f"spark.rapids.sql.kernel.{name}.enabled "
                      f"and its tuning confs")
        verdict("kernelFallback", 0.4, ev)

    # scan-bound: scan-side stages own the regression
    scan_share = stage_share(_SCAN_FRAGMENTS)
    if scan_share > 0.4:
        verdict("scanBound", scan_share, [
            f"scan stages explain {scan_share:.0%} of the wall "
            f"regression"])

    # skewed-shuffle: one exchange partition dwarfs the median in the
    # target's profile artifact — that partition serializes the stage
    # regardless of baseline comparisons (the stats come straight from
    # the _materialize capture, docs/adaptive.md)
    pp = target.get("profilePath")
    skew = _profile_exchange_skew(str(pp)) \
        if pp and os.path.exists(str(pp)) else {}
    if skew.get("ratio", 0.0) >= 4.0:
        ev = [f"{skew['node']}: max partition {skew['maxBytes']}B is "
              f"{skew['ratio']:.1f}x the median "
              f"({skew['medianBytes']}B)"]
        acts = target.get("aqeActions") or {}
        if acts.get("aqeSkewSplits"):
            ev.append(f"AQE already split it "
                      f"(aqeSkewSplits={acts['aqeSkewSplits']}) — "
                      f"the ratio is pre-split")
        elif acts:
            ev.append(f"aqeActions={acts} (no skew split fired — "
                      f"check adaptive.skewFactor)")
        else:
            ev.append("no aqeActions on record — check "
                      "spark.rapids.sql.adaptive.enabled/skewFactor")
        verdict("skewedShuffle",
                min(1.0, 0.3 + skew["ratio"] / 40.0), ev)

    # genuinely-bigger-input: rows well over baseline, stages
    # scaled roughly uniformly (no single stage owns the regression)
    if base["rowsMean"] > 0 and rows > 1.5 * base["rowsMean"]:
        uniform = 1.0
        if diff and regressed:
            top = max((r["deltaS"] for r in diff), default=0.0)
            uniform = 1.0 - min(1.0, max(0.0, top / wall_delta - 0.5))
        ev = [f"output rows {rows:.0f} vs baseline mean "
              f"{base['rowsMean']:.0f}"]
        score = 0.3 + 0.4 * uniform
        poc = target.get("plannedOutOfCore") or {}
        if poc.get("plannedPartitions"):
            # the budget oracle engaged: the run paid a planned
            # partition pass for a working set over budget — direct
            # evidence the input genuinely grew (docs/out_of_core.md)
            score = min(1.0, score + 0.3)
            ev.append(
                f"planned out-of-core engaged (plannedPartitions="
                f"{poc['plannedPartitions']:.0f}, "
                f"budgetPressurePeak="
                f"{poc.get('budgetPressurePeak', 0):.0f}) — the "
                f"working set outgrew the device budget")
        verdict("biggerInput", score, ev)

    verdicts.sort(key=lambda v: -v["score"])
    return {
        "queryId": target.get("queryId"),
        "signature": sig_digest(sig) if sig else None,
        "status": target.get("status"),
        "tenant": target.get("tenant"),
        "wallSeconds": wall,
        "queueWaitSeconds": queue_wait,
        "baseline": {k: (round(v, 6) if isinstance(v, float) else v)
                     for k, v in base.items() if k != "stages"},
        "slowdown": round(wall / base["wallP50"], 4)
        if base["wallP50"] > 0 else None,
        "regressed": regressed,
        "stageDiff": diff[:12],
        "divergentStage": divergent,
        "exchangeSkew": skew,
        "aqeActions": target.get("aqeActions") or {},
        "traceSelfTimes": _trace_self_times(target["tracePath"])
        if target.get("tracePath")
        and os.path.exists(str(target.get("tracePath"))) else {},
        "verdicts": verdicts,
        "verdict": verdicts[0]["class"] if verdicts else "unknown",
    }


def scan_signatures(history_dir: str, top: int = 10
                    ) -> List[Dict[str, Any]]:
    """Batch doctor (`tools doctor --all`; the TuningController's scan
    loop runs the same walk): diagnose the NEWEST executed finished
    record of every signature in the store against that signature's
    baseline and rank regressed shapes worst-first (regressed before
    not, then by slowdown). One store read covers the whole scan."""
    records = read_records(history_dir)
    newest: Dict[str, Dict[str, Any]] = {}
    for r in records:  # chronological — the last write wins
        sig = r.get("signature")
        if not sig or r.get("status") != STATUS_FINISHED \
                or r.get("resultCacheHit"):
            continue
        newest[sig] = r
    scans: List[Dict[str, Any]] = []
    for sig, rec in newest.items():
        d = diagnose_record(records, rec)
        d["signatureFull"] = sig
        scans.append(d)
    scans.sort(key=lambda d: (not d.get("regressed"),
                              -(d.get("slowdown") or 0.0),
                              d.get("signature") or ""))
    return scans[:max(1, int(top))]


def format_scan(scans: List[Dict[str, Any]]) -> str:
    """The `tools doctor --all` table: one row per scanned signature,
    worst regression first."""
    lines = ["=== TPU Query Doctor (batch scan) ===",
             f"{len(scans)} signature(s) scanned", ""]
    if not scans:
        lines.append("no finished signatured records found")
        return "\n".join(lines)
    lines.append(
        f"  {'signature':14s} {'tenant':10s} {'verdict':14s} "
        f"{'x p50':>7s} {'wall_s':>8s} {'base_p50':>9s}  "
        f"divergent stage")
    for d in scans:
        slow = d.get("slowdown")
        b = d.get("baseline", {})
        mark = " <-- regressed" if d.get("regressed") else ""
        lines.append(
            f"  {d.get('signature') or '-':14s} "
            f"{(d.get('tenant') or '-'):10s} "
            f"{d.get('verdict'):14s} "
            f"{(f'{slow:.2f}' if slow else '-'):>7s} "
            f"{d.get('wallSeconds', 0):8.3f} "
            f"{b.get('wallP50', 0):9.3f}  "
            f"{d.get('divergentStage') or '-'}{mark}")
    return "\n".join(lines)


def format_diagnosis(d: Dict[str, Any]) -> str:
    if d.get("error"):
        return f"doctor: {d['error']}"
    lines = ["=== TPU Query Doctor ===",
             f"query {d.get('queryId')} "
             f"(signature {d.get('signature')}, "
             f"tenant {d.get('tenant') or '-'}): "
             f"status {d.get('status')}, "
             f"{d.get('wallSeconds', 0):.3f}s wall, "
             f"{d.get('queueWaitSeconds', 0):.3f}s queued"]
    b = d.get("baseline", {})
    lines.append(
        f"baseline: {b.get('count', 0)} finished runs, "
        f"p50 {b.get('wallP50', 0):.3f}s, p99 {b.get('wallP99', 0):.3f}s"
        + (f"  (this run: {d['slowdown']:.2f}x p50)"
           if d.get("slowdown") else ""))
    lines.append(f"verdict: {d.get('verdict')} — "
                 f"{VERDICT_CLASSES.get(d.get('verdict'), '')}")
    for v in d.get("verdicts", []):
        lines.append(f"  [{v['score']:.2f}] {v['class']}")
        for ev in v["evidence"]:
            lines.append(f"         {ev}")
    diff = d.get("stageDiff", [])
    if diff:
        lines += ["", "stage-by-stage vs the signature baseline "
                  "(profile self-times, seconds):",
                  f"  {'stage':28s} {'this run':>9s} {'baseline':>9s} "
                  f"{'delta':>9s}"]
        for r in diff:
            mark = "  <-- divergent" \
                if r["stage"] == d.get("divergentStage") else ""
            lines.append(f"  {r['stage']:28s} {r['targetS']:9.3f} "
                         f"{r['baselineS']:9.3f} "
                         f"{r['deltaS']:+9.3f}{mark}")
    return "\n".join(lines)
