"""Cross-process shuffle leg v0 over the SRTB serialized-batch format.

The host-staged / DCN skeleton (RapidsShuffleInternalManagerBase.scala:76
writer-side, GpuColumnarBatchSerializer.scala:50 format role): map tasks
write each output partition as SRTB blocks to a SHARED directory
(`map{m}_part{p}.srtb` + a commit marker, the shuffle-file contract of
Spark's sort shuffle), and reduce tasks — in ANY process — read every
map's block for their partition. Atomicity comes from write-to-temp +
rename; the compression codec (`spark.rapids.shuffle.compression.codec`)
rides the SRTB header, so readers need no out-of-band config.

`spark.rapids.shuffle.mode=external` routes every device exchange
through this leg (serialize after the device split, deserialize +
re-upload on the reduce side). In one process that is a loopback through
the filesystem — deliberately: it is the transport-correctness skeleton
a true multi-host DCN backend plugs into, testable without hardware
(SURVEY.md §2.3 TPU mapping note; the tests drive a REAL second
process over the same directory).
"""

from __future__ import annotations

import os
import tempfile
import uuid
from typing import List, Optional

from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.columnar.serde import (deserialize_batch,
                                             serialize_batch)


def write_map_output(shuffle_dir: str, map_id: str,
                     parts: List[List[HostBatch]],
                     codec: str = "none") -> None:
    """Persist one map task's output: one SRTB file per non-empty
    partition, committed atomically (temp + rename) so concurrent
    readers never observe torn files."""
    os.makedirs(shuffle_dir, exist_ok=True)
    for pid, batches in enumerate(parts):
        batches = [b for b in batches if b.num_rows]
        if not batches:
            continue
        payload = b"".join(
            len(blk).to_bytes(4, "little") + blk
            for blk in (serialize_batch(b, codec) for b in batches))
        final = os.path.join(shuffle_dir, f"map{map_id}_part{pid}.srtb")
        tmp = final + f".tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, final)
    marker = os.path.join(shuffle_dir, f"map{map_id}.done")
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        f.write("ok")
    os.replace(tmp, marker)


def map_outputs_done(shuffle_dir: str) -> List[str]:
    """Committed map ids in the directory."""
    if not os.path.isdir(shuffle_dir):
        return []
    return sorted(f[3:-5] for f in os.listdir(shuffle_dir)
                  if f.startswith("map") and f.endswith(".done"))


def read_partition(shuffle_dir: str, pid: int,
                   map_ids: Optional[List[str]] = None
                   ) -> List[HostBatch]:
    """Every committed map's blocks for partition ``pid`` (the
    RapidsCachingReader remote-fetch role, filesystem transport)."""
    out: List[HostBatch] = []
    for mid in (map_ids if map_ids is not None
                else map_outputs_done(shuffle_dir)):
        path = os.path.join(shuffle_dir, f"map{mid}_part{pid}.srtb")
        if not os.path.exists(path):
            continue
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            ln = int.from_bytes(data[off:off + 4], "little")
            off += 4
            out.append(deserialize_batch(data[off:off + ln]))
            off += ln
    return out


def new_shuffle_dir(base: Optional[str] = None) -> str:
    root = base or os.path.join(tempfile.gettempdir(), "srt-shuffle")
    os.makedirs(root, exist_ok=True)
    return tempfile.mkdtemp(prefix="exch-", dir=root)
