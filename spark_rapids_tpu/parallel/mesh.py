"""Device-mesh management (the GpuDeviceManager + heartbeat-topology
analogue, GpuDeviceManager.scala:36, RapidsShuffleHeartbeatManager.scala:50).

The reference discovers shuffle peers through a driver-RPC heartbeat; on
TPU the runtime already knows the topology — ``jax.devices()`` — so the
"transport bootstrap" collapses to building a 1-D ``jax.sharding.Mesh``
over the chips and remembering it for the exchange operators.  A session
activates a mesh once (executor-plugin init in the reference); operators
consult ``get_active_mesh()`` and take the in-process path when no mesh is
active or it has a single device.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# The one mesh axis a SQL exchange needs: every chip is a shuffle peer.
# (Trainer-style tp/pp axes have no analogue in a columnar SQL engine; the
# reference likewise has a flat peer topology.)
SHUFFLE_AXIS = "shuffle"

_lock = threading.Lock()
_active: Optional[Mesh] = None
# chips demoted after dispatch failures (docs/robustness.md degradation
# ladder): the healthy mesh excludes them, so scans, stages, and
# exchanges re-plan on the survivors instead of failing the query
_failed_chips: set = set()
_healthy_cache: Optional[tuple] = None  # (key, mesh)


def build_mesh(n_devices: Optional[int] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` chips (all by default)."""
    import numpy as np
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} present")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHUFFLE_AXIS,))


def set_active_mesh(mesh: Optional[Mesh]) -> None:
    global _active, _healthy_cache
    with _lock:
        _active = mesh
        # a (re)activated topology starts fully healthy: degradation is
        # a per-activation view, like the reference's heartbeat registry
        _failed_chips.clear()
        _healthy_cache = None


def mark_chip_failed(chip_id: int) -> bool:
    """Demote one chip after a dispatch failure. Returns False when the
    chip was already demoted. Degrade loops decide retry-vs-reraise
    against a ``failed_chips()`` snapshot taken BEFORE their attempt
    (a failure on a chip demoted before the attempt began means the
    failure is elsewhere; losing a demotion race mid-attempt does not),
    and use this return value only to keep degradedChips exact."""
    global _healthy_cache
    with _lock:
        if chip_id in _failed_chips:
            return False
        _failed_chips.add(chip_id)
        _healthy_cache = None
        return True


def failed_chips() -> frozenset:
    with _lock:
        return frozenset(_failed_chips)


def degraded_chip_count() -> int:
    with _lock:
        return len(_failed_chips)


def healthy_mesh() -> Optional[Mesh]:
    """The active mesh restricted to chips that have not failed; the
    full active mesh while everything is healthy, None when no mesh is
    active or at most one chip survives (single-chip execution then
    takes the normal non-mesh paths)."""
    global _healthy_cache
    with _lock:
        m = _active
        if m is None:
            return None
        if not _failed_chips:
            return m
        key = (mesh_key(m), frozenset(_failed_chips))
        if _healthy_cache is not None and _healthy_cache[0] == key:
            return _healthy_cache[1]
        devs = [d for d in m.devices.flat if d.id not in _failed_chips]
        healthy = build_mesh(devices=devs) if len(devs) >= 2 else None
        _healthy_cache = (key, healthy)
        return healthy


def get_active_mesh() -> Optional[Mesh]:
    return _active


def mesh_size(mesh: Optional[Mesh] = None) -> int:
    m = mesh if mesh is not None else _active
    return 1 if m is None else m.shape[SHUFFLE_AXIS]


@contextlib.contextmanager
def active_mesh(mesh: Mesh) -> Iterator[Mesh]:
    """Scoped activation (tests; a long-lived session calls set_active_mesh
    once at startup like RapidsExecutorPlugin.init)."""
    prev = get_active_mesh()
    set_active_mesh(mesh)
    try:
        yield mesh
    finally:
        set_active_mesh(prev)


# ---------------------------------------------------------------------------
# Served-query collective serialization (docs/multichip.md)
#
# Two concurrent XLA CPU collectives over ONE device set deadlock at
# rendezvous — the PR 13 soak-documented limit of the mesh path under
# the server. Until the runtime grows per-query collective isolation
# (ROADMAP item 3's prerequisite), served sessions serialize their mesh
# collective sections behind this per-process mutex
# (spark.rapids.sql.multichip.serializeServedQueries, default on): only
# the collective dispatch is exclusive — staging, scans and
# non-collective stages of other queries keep running — and waiting
# queries re-check their CancelToken every bounded slice, so a
# cancelled/timed-out query never parks on the mutex.
# ---------------------------------------------------------------------------

_COLLECTIVE_MUTEX = threading.RLock()


@contextlib.contextmanager
def collective_section(conf) -> Iterator[None]:
    """Scoped mesh-collective exclusion. A no-op for non-served
    sessions (a single user cannot race itself into the rendezvous
    deadlock) and when ``serializeServedQueries`` is off; reentrant on
    one thread, so nested sections compose."""
    from spark_rapids_tpu.conf import (MULTICHIP_SERIALIZE_SERVED,
                                       SERVE_TENANT_ID)
    if conf is None or not str(conf.get(SERVE_TENANT_ID)) \
            or not bool(conf.get(MULTICHIP_SERIALIZE_SERVED)):
        yield
        return
    from spark_rapids_tpu import lifecycle as LC
    while not _COLLECTIVE_MUTEX.acquire(timeout=0.05):
        # bounded slices: cancellation reaches a queued mesh query
        LC.checkpoint("meshMutex")
    try:
        yield
    finally:
        _COLLECTIVE_MUTEX.release()


def mesh_scan_devices(conf) -> list:
    """Devices for the mesh-sharded scan: the active mesh's chips when
    ``spark.rapids.sql.multichip.scan.enabled`` is on AND a multi-device
    mesh is active, else ``[]`` (single-chip behavior unchanged). The
    scan, the row-to-columnar upload, and the exchange all consult this
    one gate so the whole pipeline flips together."""
    m = healthy_mesh()  # degraded chips never receive scan streams
    if m is None or mesh_size(m) <= 1:
        return []
    from spark_rapids_tpu.conf import MULTICHIP_SCAN_ENABLED
    if not bool(conf.get(MULTICHIP_SCAN_ENABLED)):
        return []
    return list(m.devices.flat)


def record_chip_dispatch(metrics, batch) -> None:
    """Per-chip dispatch attribution (bench ``detail.multichip``): when
    a mesh is active, also count this program dispatch against the chip
    the batch is resident on, so the bench can show every chip doing
    scan/stage work (the per-executor task counters of the reference's
    Spark UI)."""
    if _active is None:
        return
    from spark_rapids_tpu import metrics as M
    from spark_rapids_tpu.columnar.device import batch_device
    d = batch_device(batch)
    if d is not None:
        metrics.create(f"{M.DISPATCH_COUNT}.chip{d.id}",
                       M.MODERATE).add(1)


def mesh_key(mesh: Mesh) -> tuple:
    """Value-based cache key for compiled per-mesh programs (two Mesh
    objects over the same devices share executables; id()-keyed caches
    would retain every Mesh ever built)."""
    return (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)


def shard_leading(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding placing a stacked array's leading axis across the mesh."""
    return NamedSharding(
        mesh, PartitionSpec(SHUFFLE_AXIS, *([None] * (ndim - 1))))
