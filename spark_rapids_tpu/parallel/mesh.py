"""Device-mesh management (the GpuDeviceManager + heartbeat-topology
analogue, GpuDeviceManager.scala:36, RapidsShuffleHeartbeatManager.scala:50).

The reference discovers shuffle peers through a driver-RPC heartbeat; on
TPU the runtime already knows the topology — ``jax.devices()`` — so the
"transport bootstrap" collapses to building a 1-D ``jax.sharding.Mesh``
over the chips and remembering it for the exchange operators.  A session
activates a mesh once (executor-plugin init in the reference); operators
consult ``get_active_mesh()`` and take the in-process path when no mesh is
active or it has a single device.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# The one mesh axis a SQL exchange needs: every chip is a shuffle peer.
# (Trainer-style tp/pp axes have no analogue in a columnar SQL engine; the
# reference likewise has a flat peer topology.)
SHUFFLE_AXIS = "shuffle"

_lock = threading.Lock()
_active: Optional[Mesh] = None


def build_mesh(n_devices: Optional[int] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` chips (all by default)."""
    import numpy as np
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} present")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHUFFLE_AXIS,))


def set_active_mesh(mesh: Optional[Mesh]) -> None:
    global _active
    with _lock:
        _active = mesh


def get_active_mesh() -> Optional[Mesh]:
    return _active


def mesh_size(mesh: Optional[Mesh] = None) -> int:
    m = mesh if mesh is not None else _active
    return 1 if m is None else m.shape[SHUFFLE_AXIS]


@contextlib.contextmanager
def active_mesh(mesh: Mesh) -> Iterator[Mesh]:
    """Scoped activation (tests; a long-lived session calls set_active_mesh
    once at startup like RapidsExecutorPlugin.init)."""
    prev = get_active_mesh()
    set_active_mesh(mesh)
    try:
        yield mesh
    finally:
        set_active_mesh(prev)


def mesh_scan_devices(conf) -> list:
    """Devices for the mesh-sharded scan: the active mesh's chips when
    ``spark.rapids.sql.multichip.scan.enabled`` is on AND a multi-device
    mesh is active, else ``[]`` (single-chip behavior unchanged). The
    scan, the row-to-columnar upload, and the exchange all consult this
    one gate so the whole pipeline flips together."""
    m = get_active_mesh()
    if m is None or mesh_size(m) <= 1:
        return []
    from spark_rapids_tpu.conf import MULTICHIP_SCAN_ENABLED
    if not bool(conf.get(MULTICHIP_SCAN_ENABLED)):
        return []
    return list(m.devices.flat)


def record_chip_dispatch(metrics, batch) -> None:
    """Per-chip dispatch attribution (bench ``detail.multichip``): when
    a mesh is active, also count this program dispatch against the chip
    the batch is resident on, so the bench can show every chip doing
    scan/stage work (the per-executor task counters of the reference's
    Spark UI)."""
    if _active is None:
        return
    from spark_rapids_tpu import metrics as M
    from spark_rapids_tpu.columnar.device import batch_device
    d = batch_device(batch)
    if d is not None:
        metrics.create(f"{M.DISPATCH_COUNT}.chip{d.id}",
                       M.MODERATE).add(1)


def mesh_key(mesh: Mesh) -> tuple:
    """Value-based cache key for compiled per-mesh programs (two Mesh
    objects over the same devices share executables; id()-keyed caches
    would retain every Mesh ever built)."""
    return (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)


def shard_leading(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding placing a stacked array's leading axis across the mesh."""
    return NamedSharding(
        mesh, PartitionSpec(SHUFFLE_AXIS, *([None] * (ndim - 1))))
