"""Fused multi-chip aggregate step: the framework's "training step".

The canonical distributed SQL pipeline — scan-local partial aggregation,
hash exchange, final aggregation (SURVEY.md §3.3/§3.4) — expressed as ONE
``shard_map`` program jitted over the mesh, so XLA schedules the ICI
collective together with the segment kernels.  This is what the driver's
``dryrun_multichip`` compiles, and the strongest perf shape the framework
has: zero host round-trips between the partial agg, the shuffle, and the
final agg.

Reference counterpart: GpuHashAggregateExec(partial) ->
GpuShuffleExchangeExec -> GpuHashAggregateExec(final), three operators
bridged by the UCX transport; here the whole pipeline is one XLA program.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax >= 0.4.35 re-exports shard_map at the top level
    from jax import shard_map
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map

from spark_rapids_tpu.columnar.device import DeviceColumn
from spark_rapids_tpu.ops import groupby as G
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.parallel.ici import all_to_all_rows
from spark_rapids_tpu.parallel.mesh import SHUFFLE_AXIS
from spark_rapids_tpu.sql import types as T

# bounded LRU like every other structural jit cache: mesh step programs
# count in cache_stats() (bench detail.jitCaches) instead of living in
# an invisible module dict
from spark_rapids_tpu.jit_cache import JitCache

_STEP_CACHE = JitCache("meshStep")


def sum_count_step(mesh: Mesh) -> Callable:
    """groupBy(key).agg(sum(val), count(val)) over the mesh.

    Inputs (stacked, leading axis = chip): ``keys`` int64[n, cap],
    ``vals`` int64[n, cap], ``active`` bool[n, cap].  Output per chip:
    final (keys, sums, counts, out_active) for the key-groups that chip
    owns (murmur3(key) % n_dev).
    """
    from spark_rapids_tpu.parallel.mesh import mesh_key
    n_dev = mesh.shape[SHUFFLE_AXIS]
    key = (mesh_key(mesh), "sum_count", G.kernel_salt())

    def per_shard(keys, vals, active):
        keys, vals, active = keys[0], vals[0], active[0]
        cap = active.shape[0]
        kc = DeviceColumn(T.LongT, keys, active)
        vc = DeviceColumn(T.LongT, vals, active)
        # local partial aggregation (segment kernel)
        seg = G.build_segments([kc], active,
                               payload=(keys, vals, active))
        keys_s, vals_s, act_s = seg.payload
        vc_s = DeviceColumn(T.LongT, vals_s, act_s)
        psum = G.seg_sum(seg, vc_s, T.LongT, null_when_empty=True)
        pcnt = G.seg_count(seg, vc_s)
        # results live at segment-END rows (scatter-free layout)
        pact = seg.out_active
        pkeys = jnp.where(pact, keys_s, jnp.int64(0))
        # route partial rows by bit-exact Spark murmur3 of the key
        kcol = DeviceColumn(T.LongT, pkeys, pact)
        hv = hashing.murmur3_columns([kcol], cap, 42)
        dest = jnp.mod(hv.astype(jnp.int64), n_dev).astype(jnp.int32)
        recv, recv_act = all_to_all_rows(
            [pkeys, psum.data, psum.validity, pcnt.data], pact, dest, n_dev)
        rkeys = recv[0].reshape(n_dev * cap)
        rsum = recv[1].reshape(n_dev * cap)
        rsum_valid = recv[2].reshape(n_dev * cap)
        rcnt = recv[3].reshape(n_dev * cap)
        ract = recv_act.reshape(n_dev * cap)
        # final merge: segment-sum the partial buffers per key
        fkc = DeviceColumn(T.LongT, rkeys, ract)
        fseg = G.build_segments(
            [fkc], ract,
            payload=(rkeys, rsum, rsum_valid & ract, rcnt, ract))
        rkeys_s, rsum_s, rsumv_s, rcnt_s, ract_s = fseg.payload
        fsum = G.seg_sum(fseg, DeviceColumn(T.LongT, rsum_s, rsumv_s),
                         T.LongT, null_when_empty=True)
        fcnt = G.seg_sum(fseg, DeviceColumn(T.LongT, rcnt_s, ract_s),
                         T.LongT, null_when_empty=False)
        fact = fseg.out_active
        fkeys = jnp.where(fact, rkeys_s, jnp.int64(0))
        add = lambda a: a[None]
        return (add(fkeys), add(fsum.data), add(fcnt.data), add(fact))

    def build():
        sm = shard_map(per_shard, mesh=mesh,
                       in_specs=(P(SHUFFLE_AXIS), P(SHUFFLE_AXIS),
                                 P(SHUFFLE_AXIS)),
                       out_specs=(P(SHUFFLE_AXIS),) * 4)
        return jax.jit(sm)

    # single-flight get_or_build (not raw get/put): two concurrent
    # queries racing the first mesh-step compile would otherwise both
    # trace+jit the program (docs/serving.md thread-safety audit)
    fn, _ = _STEP_CACHE.get_or_build(key, build)
    return fn
