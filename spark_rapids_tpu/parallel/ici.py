"""ICI all-to-all shuffle: the device-resident exchange transport.

Reference counterpart: the UCX P2P shuffle (UCX.scala:68,
UCXShuffleTransport.scala:47) whose writer keeps partition batches in the
device store and serves them peer-to-peer
(RapidsShuffleInternalManagerBase.scala:76).  The TPU-native design
replaces the whole client/server/bounce-buffer machinery with ONE compiled
XLA program per exchange shape:

  1. every chip evaluates the partition-key expressions and the bit-exact
     Spark murmur3 on its resident rows (same kernel as the single-chip
     path, so placement is identical to CPU Spark),
  2. rows are compacted into per-destination send blocks
     (``contiguousSplit`` analogue, a fixed-shape argsort-gather),
  3. a single ``jax.lax.all_to_all`` moves all blocks chip-to-chip over
     ICI,
  4. each chip lands the blocks for the partitions it owns
     (partition p lives on chip ``p % n_dev``).

Static shapes throughout: send blocks are input-capacity sized (worst
case: every row picks one destination), so the collective's shape is
data-independent and XLA compiles it once per capacity bucket.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 re-exports shard_map at the top level
    from jax import shard_map
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map

from spark_rapids_tpu.columnar.device import (
    AnyDeviceColumn, DeviceBatch, DeviceColumn, DeviceStringColumn,
    make_column)
from spark_rapids_tpu.parallel.mesh import SHUFFLE_AXIS, shard_leading
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import types as T


# ---------------------------------------------------------------------------
# Row-block all-to-all primitive (shared by the exchange and the fused
# multi-chip aggregate step)
# ---------------------------------------------------------------------------

def all_to_all_rows(arrs: Sequence[jax.Array], active: jax.Array,
                    dest: jax.Array, n_dev: int,
                    block_cap: Optional[int] = None
                    ) -> Tuple[List[jax.Array], jax.Array]:
    """Inside a shard_map program: route each active row to chip
    ``dest[i]``.  Returns per-source received blocks
    (``[n_src, block, ...]`` per array) plus the received active mask
    ``[n_src, block]``.  Padding rows are zeroed for determinism.

    ``block_cap`` sizes each per-destination send block. The default
    (full local capacity) is worst-case safe but stages n_dev x cap per
    chip; callers that size-exchange first (mesh_exchange does) pass
    the bucketed MAX rows any (src, dest) pair actually ships, keeping
    ICI staging occupancy-proportional on real pod slices."""
    cap = active.shape[0]
    block = cap if block_cap is None else min(block_cap, cap)
    send_leaves: List[List[jax.Array]] = [[] for _ in arrs]
    send_act = []
    for d in range(n_dev):
        m = active & (dest == d)
        order = jnp.argsort(~m, stable=True)[:block]
        new_act = jnp.arange(block) < jnp.sum(m)
        for i, a in enumerate(arrs):
            g = a[order]
            if a.ndim == 2:
                g = jnp.where(new_act[:, None], g, 0)
            else:
                g = jnp.where(new_act, g, jnp.zeros((), dtype=g.dtype))
            send_leaves[i].append(g)
        send_act.append(new_act)
    recv = []
    for leaves in send_leaves:
        stacked = jnp.stack(leaves)  # [n_dest, cap, ...]
        recv.append(jax.lax.all_to_all(stacked, SHUFFLE_AXIS, 0, 0))
    recv_act = jax.lax.all_to_all(jnp.stack(send_act), SHUFFLE_AXIS, 0, 0)
    return recv, recv_act


# ---------------------------------------------------------------------------
# Exchange program cache
# ---------------------------------------------------------------------------

# bounded LRU like every other structural jit cache: mesh programs show
# up in compileCacheHits/Misses and the bench's detail.jitCaches
from spark_rapids_tpu.jit_cache import JitCache, mirror_to_metrics

_EXCHANGE_CACHE = JitCache("iciExchange")


def _build_exchange(mesh: Mesh, exprs: Tuple[E.Expression, ...],
                    n_parts: int,
                    block_cap: Optional[int] = None) -> Callable:
    """One shard_map program: eval keys -> murmur3 pids -> route rows."""
    from spark_rapids_tpu.ops import exprs as X
    from spark_rapids_tpu.ops import hashing
    n_dev = mesh.shape[SHUFFLE_AXIS]

    def per_shard(cols, active, lit_vals):
        # leaves arrive as [1, cap, ...]; squeeze the shard axis
        cols = jax.tree_util.tree_map(lambda a: a[0], cols)
        active = active[0]
        pids = hashing.traced_partition_ids(exprs, cols, active, lit_vals,
                                            n_parts)
        dest = jnp.mod(pids, n_dev)
        flat, treedef = jax.tree_util.tree_flatten(cols)
        recv, recv_act = all_to_all_rows(flat + [pids], active, dest,
                                         n_dev, block_cap)
        recv_cols = jax.tree_util.tree_unflatten(treedef, recv[:-1])
        recv_pids = recv[-1]
        # re-add the shard axis for the out_specs
        add = lambda a: a[None]
        return (jax.tree_util.tree_map(add, recv_cols), add(recv_pids),
                add(recv_act))

    sm = shard_map(per_shard, mesh=mesh,
                   in_specs=(P(SHUFFLE_AXIS), P(SHUFFLE_AXIS), P()),
                   out_specs=(P(SHUFFLE_AXIS), P(SHUFFLE_AXIS),
                              P(SHUFFLE_AXIS)))
    return jax.jit(sm)


def exchange_fn(mesh: Mesh, exprs: Sequence[E.Expression],
                n_parts: int, block_cap: Optional[int] = None,
                metrics=None) -> Callable:
    from spark_rapids_tpu.ops import exprs as X
    from spark_rapids_tpu.parallel.mesh import mesh_key
    key = (mesh_key(mesh), tuple(X.expr_key(e) for e in exprs), n_parts,
           block_cap)
    fn, was_miss = _EXCHANGE_CACHE.get_or_build(
        key, lambda: _build_exchange(mesh, tuple(exprs), n_parts,
                                     block_cap))
    if metrics is not None:
        mirror_to_metrics(_EXCHANGE_CACHE, metrics, was_miss)
    return fn


def _dest_counts_fn(mesh: Mesh, exprs: Tuple[E.Expression, ...],
                    n_parts: int, metrics=None) -> Callable:
    """Tiny shard_map program: per-chip [n_dev] counts of rows headed to
    each destination — the size-exchange phase that lets the real
    exchange stage occupancy-proportional send blocks (the
    bounce-buffer-sizing handshake of the reference's UCX transport,
    reduced to one collective-free counting pass)."""
    from spark_rapids_tpu.ops import exprs as X
    from spark_rapids_tpu.ops import hashing
    from spark_rapids_tpu.parallel.mesh import mesh_key
    key = (mesh_key(mesh), tuple(X.expr_key(e) for e in exprs), n_parts,
           "counts")
    n_dev = mesh.shape[SHUFFLE_AXIS]

    def build():
        def per_shard(cols, active, lit_vals):
            cols = jax.tree_util.tree_map(lambda a: a[0], cols)
            active = active[0]
            pids = hashing.traced_partition_ids(exprs, cols, active,
                                                lit_vals, n_parts)
            dest = jnp.mod(pids, n_dev)
            counts = jnp.stack([
                jnp.sum(active & (dest == d)) for d in range(n_dev)])
            return counts[None]

        sm = shard_map(per_shard, mesh=mesh,
                       in_specs=(P(SHUFFLE_AXIS), P(SHUFFLE_AXIS), P()),
                       out_specs=P(SHUFFLE_AXIS))
        return jax.jit(sm)

    fn, was_miss = _EXCHANGE_CACHE.get_or_build(key, build)
    if metrics is not None:
        mirror_to_metrics(_EXCHANGE_CACHE, metrics, was_miss)
    return fn


# ---------------------------------------------------------------------------
# Batch stacking / unstacking glue (host-orchestrated, device-resident)
# ---------------------------------------------------------------------------

def _pad_column(c: AnyDeviceColumn, cap: int, char_cap: Optional[int]
                ) -> AnyDeviceColumn:
    if isinstance(c, DeviceStringColumn):
        chars = c.chars
        if char_cap is not None and c.char_cap < char_cap:
            chars = jnp.pad(chars, ((0, 0), (0, char_cap - c.char_cap)))
        pad = cap - c.capacity
        if pad:
            chars = jnp.pad(chars, ((0, pad), (0, 0)))
            return DeviceStringColumn(c.dtype, chars,
                                      jnp.pad(c.lengths, (0, pad)),
                                      jnp.pad(c.validity, (0, pad)))
        return DeviceStringColumn(c.dtype, chars, c.lengths, c.validity)
    pad = cap - c.capacity
    if pad:
        return DeviceColumn(c.dtype, jnp.pad(c.data, (0, pad)),
                            jnp.pad(c.validity, (0, pad)))
    return c


def pad_batch(b: DeviceBatch, cap: int,
              char_caps: Sequence[Optional[int]]) -> DeviceBatch:
    cols = [_pad_column(c, cap, cc) for c, cc in zip(b.columns, char_caps)]
    pad = cap - b.capacity
    active = jnp.pad(b.active, (0, pad)) if pad else b.active
    return DeviceBatch(b.schema, cols, active, b._num_rows)


def stack_batches(slots: Sequence[DeviceBatch], mesh: Mesh):
    from spark_rapids_tpu import trace as _trace
    with _trace.span("meshStack", slots=len(slots)):
        return _stack_batches(slots, mesh)


def _stack_batches(slots: Sequence[DeviceBatch], mesh: Mesh):
    """Pad each per-chip batch to the common bucketed capacity ON ITS
    CHIP, then assemble global arrays sharded over the mesh's shuffle
    axis directly from the per-device shards
    (``jax.make_array_from_single_device_arrays``) — the chip-resident
    handoff: a slot already living on its chip contributes its buffers
    in place, with no gather to one device and no host round trip.
    Slots produced elsewhere (chip 0, host uploads) are device_put
    (device-to-device) onto their mesh position first."""
    from spark_rapids_tpu.columnar.device import (batch_device,
                                                  batch_to_device,
                                                  bucket_capacity,
                                                  bucket_char_cap)
    schema = slots[0].schema
    cap = bucket_capacity(max(b.capacity for b in slots))
    char_caps: List[Optional[int]] = []
    for ci, f in enumerate(schema.fields):
        if isinstance(slots[0].columns[ci], DeviceStringColumn):
            char_caps.append(bucket_char_cap(
                max(b.columns[ci].char_cap for b in slots)))
        else:
            char_caps.append(None)
    padded = []
    for b, d in zip(slots, mesh.devices.flat):
        cur = batch_device(b)
        if cur is None or cur.id != d.id:
            b = batch_to_device(b, d)
        padded.append(pad_batch(b, cap, char_caps))
    stacked_cols = jax.tree_util.tree_map(
        lambda *xs: _assemble_sharded(xs, mesh),
        padded[0].columns, *[p.columns for p in padded[1:]])
    stacked_active = _assemble_sharded([p.active for p in padded], mesh)
    return stacked_cols, stacked_active, schema, cap


def _assemble_sharded(xs: Sequence[jax.Array], mesh: Mesh) -> jax.Array:
    """Global [n_dev, ...] array built from one resident shard per chip
    — no data movement (each ``x[None]`` stays committed to x's chip)."""
    shape = (len(xs),) + tuple(xs[0].shape)
    return jax.make_array_from_single_device_arrays(
        shape, shard_leading(mesh, len(shape)), [x[None] for x in xs])


def mesh_exchange(slots: Sequence[DeviceBatch],
                  bound_exprs: Sequence[E.Expression], n_parts: int,
                  mesh: Mesh, metrics=None) -> List[List[DeviceBatch]]:
    """Run the ICI exchange: one input batch per chip -> per-partition
    output batches (partition p owned by chip p % n_dev).  Returns
    ``out[pid] -> [DeviceBatch]`` like the in-process exchange."""
    from spark_rapids_tpu.ops import exprs as X
    import numpy as np
    from spark_rapids_tpu.columnar.device import bucket_capacity
    n_dev = mesh.shape[SHUFFLE_AXIS]
    assert len(slots) == n_dev, (len(slots), n_dev)
    stacked_cols, stacked_active, schema, cap = stack_batches(slots, mesh)
    lit_vals = X.literal_values(list(bound_exprs))
    # size exchange: per-(src, dest) row counts (tiny [n_dev, n_dev]
    # fetch) size the send blocks proportionally to real occupancy —
    # without it every block is worst-case cap and staging grows
    # n_dev x cap per chip (VERDICT r3 weak #6)
    from spark_rapids_tpu import trace as _trace
    with _trace.span("meshSizeExchange"):
        counts = np.asarray(_dest_counts_fn(
            mesh, tuple(bound_exprs), n_parts, metrics)(
            stacked_cols, stacked_active, lit_vals))
    if metrics is not None:
        # cross-chip padding overhead: rows staged for the collective
        # beyond the active ones (slots pad to the global max bucket)
        metrics.create("meshPadWaste").add(
            n_dev * cap - int(counts.sum()))
    block_cap = min(cap, bucket_capacity(max(1, int(counts.max()))))
    fn = exchange_fn(mesh, bound_exprs, n_parts, block_cap, metrics)
    with _trace.span("meshExchange", nDev=n_dev, blockCap=block_cap):
        recv_cols, recv_pids, recv_act = fn(stacked_cols, stacked_active,
                                            lit_vals)
    # recv leaves: [n_dev(owner), n_src, block, ...]; land each owner
    # chip's block through the shared sort-split (one counts sync per
    # chip, no per-partition round trips)
    from spark_rapids_tpu.exec.exchange import split_by_pid
    out: List[List[DeviceBatch]] = [[] for _ in range(n_parts)]
    for d in range(n_dev):
        flat_cols: List[AnyDeviceColumn] = []
        for c in recv_cols:
            arrs = [a[d].reshape((n_dev * block_cap,) + a.shape[3:])
                    for a in c.arrays()]
            flat_cols.append(make_column(c.dtype, arrs))
        pids_d = recv_pids[d].reshape(n_dev * block_cap)
        act_d = recv_act[d].reshape(n_dev * block_cap)
        landed = DeviceBatch(schema, flat_cols, act_d, None)
        for pid, part in enumerate(split_by_pid(landed, pids_d, n_parts)):
            if part is not None:
                out[pid].append(part)
    return out
