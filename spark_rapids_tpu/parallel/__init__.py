"""Multi-chip execution over a jax device mesh.

This package is the TPU-native replacement for the reference's first-class
shuffle transport (shuffle-plugin UCX stack, SURVEY.md §2.3): instead of
Active Messages + bounce buffers + GPUDirect RDMA, hash-partitioned
exchanges ride the ICI as a single XLA ``all_to_all`` collective inside a
``shard_map`` program, and batches stay HBM-resident on their owning chip
(the RapidsShuffleInternalManagerBase.scala:76 design goal, reached with
collectives instead of P2P transfers).
"""

from spark_rapids_tpu.parallel.mesh import (SHUFFLE_AXIS, active_mesh,
                                            build_mesh, get_active_mesh,
                                            set_active_mesh)

__all__ = ["SHUFFLE_AXIS", "active_mesh", "build_mesh", "get_active_mesh",
           "set_active_mesh"]
