"""Operator metrics (GpuMetric, GpuExec.scala:17-103 twin).

Three verbosity levels (ESSENTIAL/MODERATE/DEBUG) gated by
``spark.rapids.sql.metrics.level``; each Tpu exec owns a named metric map
surfaced by ``TpuExec.metrics``. Timers are wall-clock nanoseconds.

Every ``timed``/``timed_wall`` scope also mirrors its interval into the
active span tracer (spark_rapids_tpu/trace.py) as a span named
``<owner>.<metric>`` — the trace, the event log, and the profiler read
the SAME measurement, so the three can never disagree
(docs/observability.md). When tracing is off the mirror is a single
module-global None check.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional

from spark_rapids_tpu import trace as _trace

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVELS = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

# canonical metric names (GpuMetric object in GpuExec.scala)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
PEAK_DEVICE_MEMORY = "peakDeviceMemory"
SPILL_BYTES = "spillBytes"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
JOIN_TIME = "joinTime"
CONCAT_TIME = "concatTime"
PARTITION_TIME = "partitionTime"
COPY_TO_DEVICE_TIME = "copyToDeviceTime"
PACK_TIME = "packBatchTime"  # host-side staging half of an upload
COPY_FROM_DEVICE_TIME = "copyFromDeviceTime"
# stage-fusion metrics (TpuFusedStageExec + prelude-absorbing aggs)
DISPATCH_COUNT = "dispatchCount"        # device programs dispatched
STAGE_COMPILE_TIME = "stageCompileTime"  # first-call build+compile wall
FUSED_OPS = "fusedOps"                  # operators collapsed into a stage
COMPILE_CACHE_HITS = "compileCacheHits"
COMPILE_CACHE_MISSES = "compileCacheMisses"
# retry framework metrics (spark_rapids_tpu/retry.py, docs/robustness.md)
RETRY_COUNT = "retryCount"                # OOM retries that re-attempted
SPLIT_RETRY_COUNT = "splitRetryCount"     # input batches split in half
RETRY_BLOCK_TIME = "retryBlockTime"       # spill+backoff wall inside retries
SPILL_BYTES_ON_RETRY = "spillBytesOnRetry"  # HBM freed by retry spills
DEGRADED_CHIPS = "degradedChips"          # mesh chips demoted after failure
IO_RETRY_COUNT = "ioRetryCount"           # transient reader IO retries
DEVICE_DECODE_OOM_FALLBACKS = "deviceDecodeOomFallbacks"  # encoded-upload
#   OOMs that fell back to the pyarrow host decode for that batch
# planned out-of-core family (docs/out_of_core.md): the budget
# oracle's planning decisions, distinct from the reactive retry
# counters above
PLANNED_PARTITIONS = "plannedPartitions"  # spill-backed partitions planned
BUDGET_PRESSURE_PEAK = "budgetPressurePeak"  # worst estimate/share ratio
PLANNED_OOC_ESCALATIONS = "plannedOutOfCoreEscalations"  # re-plans


# ---------------------------------------------------------------------------
# Central metric description table (docs/tools/profile single source of
# truth). EVERY metric any exec registers — constants above AND the
# ad-hoc keys created inline — must have an entry here (exact key) or
# match a prefix in METRIC_PREFIX_DESCRIPTIONS (dynamic families like
# per-chip counters). tests/test_profile.py lints this against the
# registries of executed plans, so profile/docs/bench can never
# disagree on names.
# ---------------------------------------------------------------------------

METRIC_DESCRIPTIONS: Dict[str, str] = {
    NUM_OUTPUT_ROWS: "rows emitted by the operator",
    NUM_OUTPUT_BATCHES: "device batches emitted",
    NUM_INPUT_ROWS: "rows consumed",
    NUM_INPUT_BATCHES: "batches consumed",
    OP_TIME: "operator wall time (ns)",
    SEMAPHORE_WAIT_TIME: "wall blocked on the device semaphore (ns)",
    PEAK_DEVICE_MEMORY: "peak HBM bytes this operator held live in the "
                        "device store (owner-attributed accounting)",
    SPILL_BYTES: "HBM bytes of this operator's batches demoted "
                 "device->host by the store",
    SORT_TIME: "device sort wall (ns)",
    AGG_TIME: "aggregation update/merge wall (ns)",
    JOIN_TIME: "join probe/gather wall (ns)",
    CONCAT_TIME: "device batch concat wall (ns)",
    PARTITION_TIME: "exchange partition-split wall (ns)",
    COPY_TO_DEVICE_TIME: "host->HBM upload wall (ns)",
    PACK_TIME: "host-side upload staging wall (ns; overlaps transfer)",
    COPY_FROM_DEVICE_TIME: "HBM->host download wall (ns)",
    DISPATCH_COUNT: "device programs dispatched",
    STAGE_COMPILE_TIME: "first-call trace+XLA-compile wall (ns)",
    FUSED_OPS: "operators collapsed into this fused stage",
    COMPILE_CACHE_HITS: "jit-cache hits for this exec's programs",
    COMPILE_CACHE_MISSES: "jit-cache misses (compiles) for this exec",
    RETRY_COUNT: "OOM retries that re-attempted the operation",
    SPLIT_RETRY_COUNT: "input batches split in half after OOM",
    RETRY_BLOCK_TIME: "spill+backoff wall inside OOM retries (ns; also "
                      "counted inside the enclosing operator timer)",
    SPILL_BYTES_ON_RETRY: "HBM freed by retry spills",
    DEGRADED_CHIPS: "mesh chips demoted after persistent failure",
    IO_RETRY_COUNT: "transient reader IO retries",
    DEVICE_DECODE_OOM_FALLBACKS: "encoded uploads that fell back to the "
                                 "pyarrow host decode after OOM",
    PLANNED_PARTITIONS: "spill-backed partitions the out-of-core "
                        "budget oracle planned up front "
                        "(docs/out_of_core.md)",
    BUDGET_PRESSURE_PEAK: "worst working-set estimate observed at "
                          "planning, as bytes per 100 bytes of budget "
                          "share (>100 = the planned out-of-core tier "
                          "engaged)",
    PLANNED_OOC_ESCALATIONS: "planned out-of-core partition plans "
                             "escalated (re-partitioned at a doubled "
                             "modulus) after a partition still "
                             "overflowed its budget share",
    # ad-hoc keys registered inline by individual operators
    "pipelineDrainTime": "wall where the partial agg drained the async "
                         "upstream pipeline (interval union)",
    "pythonEvalTime": "python worker-pool UDF evaluation wall (ns)",
    "externalShuffleWriteTime": "external-shuffle serialize+write wall",
    "externalShuffleReadTime": "external-shuffle read+re-upload wall",
    "externalShuffleBytes": "bytes shipped through the external shuffle",
    "broadcastBuilds": "broadcast build-side materializations",
    "subplanCacheHits": "join build tables reused from the subplan "
                        "cache instead of rebuilt (docs/caching.md)",
    "numIciExchanges": "all-to-all exchanges run over the ICI mesh",
    "aqeCoalescedPartitions": "tiny exchange partitions coalesced by AQE",
    "aqeBroadcastFlip": "shuffled joins flipped to broadcast at runtime",
    "aqeReplans": "adaptive runtime replans applied over measured "
                  "exchange stats (docs/adaptive.md)",
    "aqeSkewSplits": "skewed exchange partitions split by the adaptive "
                     "skew-join rewrite",
    "exchangeTotalBytes": "materialized exchange output bytes (all "
                          "partitions)",
    "exchangeMaxPartitionBytes": "largest materialized exchange "
                                 "partition",
    "exchangeMedianPartitionBytes": "median non-empty materialized "
                                    "exchange partition",
    "fkFastPathJoins": "joins taking the unique-build-key fast path",
    "meshPadWaste": "staged-minus-active rows padded by mesh stacking",
    # scan-side keys (CpuFileScanExec; kept here so the profile tree and
    # docs can annotate the whole plan, not only Tpu* nodes)
    "decodeTime": "host parquet/file decode wall (interval union)",
    "convertTime": "arrow->HostBatch conversion wall",
    "deviceDecodeTime": "host-side half of the device decode path "
                        "(IO, page headers, decode plans)",
    "deviceDecodedBatches": "scan batches decoded on device",
    "deviceDecodePrograms": "logical decode-stage programs billed per "
                            "device-decoded batch (1 when the fused "
                            "kernel ran; the XLA chain's stage count "
                            "otherwise — docs/kernels.md)",
    "deviceFallbackUnits": "scan units that fell back to host decode",
    "deviceFallbackColumns": "columns that fell back to host decode",
    # scan pipeline (docs/scan.md): producer-thread prefetch + bounded
    # upload-ahead ring in TpuRowToColumnarExec
    "scanPrefetchTime": "scan producer-thread read+pack wall "
                        "(interval union; overlaps device compute)",
    "uploadAheadBatches": "scan batches whose raw-chunk upload was "
                          "issued ahead of the consuming stage",
    "prefetchRingShrinks": "upload-ahead rings drained after OOM on a "
                           "prefetched upload",
}

# dynamic metric families: any key starting with one of these prefixes
# is described by the entry (per-chip counters, per-encoding counts)
METRIC_PREFIX_DESCRIPTIONS: Dict[str, str] = {
    "dispatchCount.chip": "device programs dispatched on chip <N>",
    "meshScanUnits.chip": "scan units assigned to chip <N>'s stream",
    "deviceDecodedValues.": "values decoded on device per encoding",
    "kernelDispatchCount.": "device programs dispatched through the "
                            "named Pallas kernel (docs/kernels.md)",
    "kernelFallbacks.": "kernel-path calls that fell back to the "
                        "XLA-op oracle composition (lowering/compile "
                        "failure or hash-table overflow)",
    "hostDecodedValues.": "values host-decoded (fallback columns) per "
                          "encoding",
}


def describe_metric(name: str) -> Optional[str]:
    """Description for a metric key, resolving dynamic per-chip /
    per-encoding families by prefix; None for an unknown key (the lint
    test fails on those)."""
    d = METRIC_DESCRIPTIONS.get(name)
    if d is not None:
        return d
    for prefix, desc in METRIC_PREFIX_DESCRIPTIONS.items():
        if name.startswith(prefix):
            return desc
    return None


@dataclass
class TpuMetric:
    """Thread-safe counter: task threads (taskParallelism/shuffle pools)
    update the same operator's metrics concurrently."""

    name: str
    level: int = MODERATE
    value: int = 0
    # mutation counter (one int += under the already-held lock): the
    # telemetry endpoint's registry-delta aggregator sums versions per
    # registry to decide whether a cached snapshot is still current, so
    # a scrape re-reads only registries that actually changed
    # (telemetry/prometheus.py)
    version: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    # wall-union timer state (timed_wall): overlapping intervals from
    # concurrent threads count once
    _active: int = field(default=0, repr=False, compare=False)
    _wall_start: int = field(default=0, repr=False, compare=False)

    def add(self, v: int) -> None:
        with self._lock:
            self.value += int(v)
            self.version += 1

    def set_max(self, v: int) -> None:
        with self._lock:
            self.value = max(self.value, int(v))
            self.version += 1

    def enter_wall(self) -> None:
        with self._lock:
            if self._active == 0:
                self._wall_start = time.perf_counter_ns()
            self._active += 1

    def exit_wall(self) -> None:
        with self._lock:
            self._active -= 1
            if self._active == 0:
                self.value += time.perf_counter_ns() - self._wall_start
                self.version += 1


# every live registry, for registry_snapshot(); weak so plans release
# their metrics with themselves
_REGISTRIES: "weakref.WeakSet[MetricRegistry]" = weakref.WeakSet()

# process-LIFETIME totals: when a registry is garbage-collected with
# its plan, its final values fold in here (the finalizer holds the
# inner metrics dict, which needs no access to the dead registry), so
# the telemetry endpoint's counters stay monotone across plan
# lifetimes — a query that completed between two scrapes still counts
# (telemetry/prometheus.py layers live registries on top of this base)
_RETIRED_LOCK = threading.Lock()
_RETIRED_TOTALS: Dict[str, int] = {}
# finalizers run at arbitrary allocation points (possibly while a
# reader holds _RETIRED_LOCK on the same thread), so they must not
# lock: the handoff is an atomic deque append, drained by readers
_RETIRED_QUEUE: deque = deque()


def _retire_metrics(metrics_dict: Dict[str, "TpuMetric"]) -> None:
    _RETIRED_QUEUE.append(metrics_dict)


def is_watermark_metric(name: str) -> bool:
    """True for high-watermark (``set_max``-style) metrics: they fold
    across registries by MAX, not sum — 10k dead per-plan peaks summed
    would dwarf the pool budget and mean nothing (the telemetry
    endpoint exports these as gauges)."""
    return "peak" in name.lower()


def fold_metric(totals: Dict[str, int], name: str, value: int) -> None:
    """Fold one registry's value into cross-registry totals with the
    right semantics (max for watermarks, sum otherwise)."""
    if is_watermark_metric(name):
        totals[name] = max(totals.get(name, 0), value)
    else:
        totals[name] = totals.get(name, 0) + value


def retired_totals() -> Dict[str, int]:
    """Folded final values of every garbage-collected registry."""
    with _RETIRED_LOCK:
        while True:
            try:
                md = _RETIRED_QUEUE.popleft()
            except IndexError:
                break
            for k, m in md.items():
                fold_metric(_RETIRED_TOTALS, k, m.value)
        return dict(_RETIRED_TOTALS)

# registry epoch: process-wide counters (the weak set above, the device
# store peaks) otherwise bleed one bench leg's numbers into the next
# leg's snapshot. Each registry stamps the epoch current at its
# creation; begin_epoch() + registry_snapshot(epoch=...) scope a
# process-wide snapshot to registries created since.
_EPOCH = 0


def begin_epoch() -> int:
    """Start a new registry epoch and return it. Bench detail legs call
    this (plus DeviceStore.reset_peaks) at leg start so process-wide
    snapshots cover only the leg's own plans."""
    global _EPOCH
    _EPOCH += 1
    return _EPOCH


def current_epoch() -> int:
    return _EPOCH


class MetricRegistry:
    """Per-exec metric map; creation is gated by the configured level so
    disabled metrics cost a no-op (the reference wraps them in NoopMetric).
    ``owner`` labels this registry's spans in the trace (the exec class
    name)."""

    def __init__(self, conf_level: str = "MODERATE", owner: str = ""):
        self.enabled_level = _LEVELS.get(conf_level.upper(), MODERATE)
        self.metrics: Dict[str, TpuMetric] = {}
        self.owner = owner
        self.epoch = _EPOCH
        self._lock = threading.Lock()
        _REGISTRIES.add(self)
        weakref.finalize(self, _retire_metrics, self.metrics)

    def clone_empty(self) -> "MetricRegistry":
        """A fresh registry with the same level/owner and the same
        PRE-CREATED (all-zero) metric names, for plan-cache clones: a
        cached template's registries are never updated (the template is
        never executed), so copying the names reproduces exactly the
        event-log-v2 pre-creation contract (numOutputRows: 0 present)."""
        r = MetricRegistry.__new__(MetricRegistry)
        r.enabled_level = self.enabled_level
        r.metrics = {}
        r.owner = self.owner
        r.epoch = _EPOCH
        r._lock = threading.Lock()
        _REGISTRIES.add(r)
        weakref.finalize(r, _retire_metrics, r.metrics)
        for k, m in self.metrics.items():
            r.create(k, m.level)
        return r

    def create(self, name: str, level: int = MODERATE) -> TpuMetric:
        with self._lock:  # check-then-set must be atomic across tasks
            m = self.metrics.get(name)
            if m is None:
                m = TpuMetric(name, level)
                if level <= self.enabled_level:
                    self.metrics[name] = m
            return m

    def __getitem__(self, name: str) -> TpuMetric:
        return self.metrics.get(name) or TpuMetric(name)

    def value(self, name: str) -> int:
        m = self.metrics.get(name)
        return m.value if m else 0

    def _span_kind(self, name: str) -> str:
        return f"{self.owner}.{name}" if self.owner else name

    @contextlib.contextmanager
    def timed(self, name: str, level: int = MODERATE,
              **attrs) -> Iterator[None]:
        m = self.create(name, level)
        qt = _trace._ACTIVE
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            t1 = time.perf_counter_ns()
            m.add(t1 - t0)
            if qt is not None:
                qt.add(self._span_kind(name), t0, t1, **attrs)

    @contextlib.contextmanager
    def timed_wall(self, name: str, level: int = MODERATE,
                   **attrs) -> Iterator[None]:
        """Union-of-intervals timer: when N pool threads run the same
        phase concurrently, the metric advances by WALL time, not by N
        stacked thread-times, so a stage breakdown sums against the
        query wall sensibly (round-5 issue: q1's drain metric read
        11.6s against a 5.4s wall). The mirrored trace span is this
        THREAD's interval — the trace shows per-thread lanes, the
        metric their union."""
        m = self.create(name, level)
        qt = _trace._ACTIVE
        t0 = time.perf_counter_ns()
        m.enter_wall()
        try:
            yield
        finally:
            m.exit_wall()
            if qt is not None:
                qt.add(self._span_kind(name), t0,
                       time.perf_counter_ns(), **attrs)

    def snapshot(self) -> Dict[str, int]:
        return {k: m.value for k, m in self.metrics.items()}


def live_registries() -> list:
    """Every live MetricRegistry in the process (a stable list copy of
    the weak set) — the telemetry aggregator's iteration surface."""
    return list(_REGISTRIES)


def registry_snapshot(plans=None, epoch: Optional[int] = None
                      ) -> Dict[str, Any]:
    """Every metric as ONE dict: ``{"metrics": {name: summed value},
    "jitCaches": {cache: stats}}``. With ``plans`` given (captured
    physical plans), only their registries contribute — fused-stage
    constituents and children included — which is the bench's scraping
    shape; with None, every live registry in the process contributes
    (cross-query totals). ``epoch`` scopes the process-wide form to
    registries created at or after a ``begin_epoch()`` stamp, so bench
    detail legs stop inheriting earlier legs' registries."""
    vals: Dict[str, int] = {}

    def add_reg(ms) -> None:
        for k, v in ms.snapshot().items():
            vals[k] = vals.get(k, 0) + v

    if plans is None:
        for ms in list(_REGISTRIES):
            if epoch is not None and getattr(ms, "epoch", 0) < epoch:
                continue
            add_reg(ms)
    else:
        def walk(p) -> None:
            ms = getattr(p, "metrics", None)
            if ms is not None:
                add_reg(ms)
            for op in getattr(p, "fused_ops", []):
                fm = getattr(op, "metrics", None)
                if fm is not None:
                    add_reg(fm)
            for c in getattr(p, "children", []):
                walk(c)
        for plan in plans or []:
            walk(plan)
    from spark_rapids_tpu.jit_cache import cache_stats
    return {"metrics": vals, "jitCaches": cache_stats()}


def sum_plan_metrics(plans, prefix: str) -> Dict[str, int]:
    """Sum every metric whose key starts with ``prefix`` across captured
    physical plans, fused-stage constituents included. Per-chip counters
    (``dispatchCount.chip3``, ``meshScanUnits.chip0``) are dynamic keys,
    so callers aggregate by prefix (bench ``detail.multichip``, the
    multichip tests)."""
    out: Dict[str, int] = {}

    def add(p) -> None:
        ms = getattr(p, "metrics", None)
        if ms is None:
            return
        for k, v in ms.snapshot().items():
            if k.startswith(prefix):
                out[k] = out.get(k, 0) + v

    def walk(p) -> None:
        add(p)
        for op in getattr(p, "fused_ops", []):
            add(op)
        for c in getattr(p, "children", []):
            walk(c)

    for plan in plans or []:
        walk(plan)
    return out
