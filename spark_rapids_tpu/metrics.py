"""Operator metrics (GpuMetric, GpuExec.scala:17-103 twin).

Three verbosity levels (ESSENTIAL/MODERATE/DEBUG) gated by
``spark.rapids.sql.metrics.level``; each Tpu exec owns a named metric map
surfaced by ``TpuExec.metrics``. Timers are wall-clock nanoseconds.

Every ``timed``/``timed_wall`` scope also mirrors its interval into the
active span tracer (spark_rapids_tpu/trace.py) as a span named
``<owner>.<metric>`` — the trace, the event log, and the profiler read
the SAME measurement, so the three can never disagree
(docs/observability.md). When tracing is off the mirror is a single
module-global None check.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator

from spark_rapids_tpu import trace as _trace

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVELS = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

# canonical metric names (GpuMetric object in GpuExec.scala)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
PEAK_DEVICE_MEMORY = "peakDeviceMemory"
SPILL_BYTES = "spillBytes"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
JOIN_TIME = "joinTime"
CONCAT_TIME = "concatTime"
PARTITION_TIME = "partitionTime"
COPY_TO_DEVICE_TIME = "copyToDeviceTime"
PACK_TIME = "packBatchTime"  # host-side staging half of an upload
COPY_FROM_DEVICE_TIME = "copyFromDeviceTime"
# stage-fusion metrics (TpuFusedStageExec + prelude-absorbing aggs)
DISPATCH_COUNT = "dispatchCount"        # device programs dispatched
STAGE_COMPILE_TIME = "stageCompileTime"  # first-call build+compile wall
FUSED_OPS = "fusedOps"                  # operators collapsed into a stage
COMPILE_CACHE_HITS = "compileCacheHits"
COMPILE_CACHE_MISSES = "compileCacheMisses"
# retry framework metrics (spark_rapids_tpu/retry.py, docs/robustness.md)
RETRY_COUNT = "retryCount"                # OOM retries that re-attempted
SPLIT_RETRY_COUNT = "splitRetryCount"     # input batches split in half
RETRY_BLOCK_TIME = "retryBlockTime"       # spill+backoff wall inside retries
SPILL_BYTES_ON_RETRY = "spillBytesOnRetry"  # HBM freed by retry spills
DEGRADED_CHIPS = "degradedChips"          # mesh chips demoted after failure
IO_RETRY_COUNT = "ioRetryCount"           # transient reader IO retries
DEVICE_DECODE_OOM_FALLBACKS = "deviceDecodeOomFallbacks"  # encoded-upload
#   OOMs that fell back to the pyarrow host decode for that batch


@dataclass
class TpuMetric:
    """Thread-safe counter: task threads (taskParallelism/shuffle pools)
    update the same operator's metrics concurrently."""

    name: str
    level: int = MODERATE
    value: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    # wall-union timer state (timed_wall): overlapping intervals from
    # concurrent threads count once
    _active: int = field(default=0, repr=False, compare=False)
    _wall_start: int = field(default=0, repr=False, compare=False)

    def add(self, v: int) -> None:
        with self._lock:
            self.value += int(v)

    def set_max(self, v: int) -> None:
        with self._lock:
            self.value = max(self.value, int(v))

    def enter_wall(self) -> None:
        with self._lock:
            if self._active == 0:
                self._wall_start = time.perf_counter_ns()
            self._active += 1

    def exit_wall(self) -> None:
        with self._lock:
            self._active -= 1
            if self._active == 0:
                self.value += time.perf_counter_ns() - self._wall_start


# every live registry, for registry_snapshot(); weak so plans release
# their metrics with themselves
_REGISTRIES: "weakref.WeakSet[MetricRegistry]" = weakref.WeakSet()


class MetricRegistry:
    """Per-exec metric map; creation is gated by the configured level so
    disabled metrics cost a no-op (the reference wraps them in NoopMetric).
    ``owner`` labels this registry's spans in the trace (the exec class
    name)."""

    def __init__(self, conf_level: str = "MODERATE", owner: str = ""):
        self.enabled_level = _LEVELS.get(conf_level.upper(), MODERATE)
        self.metrics: Dict[str, TpuMetric] = {}
        self.owner = owner
        self._lock = threading.Lock()
        _REGISTRIES.add(self)

    def create(self, name: str, level: int = MODERATE) -> TpuMetric:
        with self._lock:  # check-then-set must be atomic across tasks
            m = self.metrics.get(name)
            if m is None:
                m = TpuMetric(name, level)
                if level <= self.enabled_level:
                    self.metrics[name] = m
            return m

    def __getitem__(self, name: str) -> TpuMetric:
        return self.metrics.get(name) or TpuMetric(name)

    def value(self, name: str) -> int:
        m = self.metrics.get(name)
        return m.value if m else 0

    def _span_kind(self, name: str) -> str:
        return f"{self.owner}.{name}" if self.owner else name

    @contextlib.contextmanager
    def timed(self, name: str, level: int = MODERATE,
              **attrs) -> Iterator[None]:
        m = self.create(name, level)
        qt = _trace._ACTIVE
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            t1 = time.perf_counter_ns()
            m.add(t1 - t0)
            if qt is not None:
                qt.add(self._span_kind(name), t0, t1, **attrs)

    @contextlib.contextmanager
    def timed_wall(self, name: str, level: int = MODERATE,
                   **attrs) -> Iterator[None]:
        """Union-of-intervals timer: when N pool threads run the same
        phase concurrently, the metric advances by WALL time, not by N
        stacked thread-times, so a stage breakdown sums against the
        query wall sensibly (round-5 issue: q1's drain metric read
        11.6s against a 5.4s wall). The mirrored trace span is this
        THREAD's interval — the trace shows per-thread lanes, the
        metric their union."""
        m = self.create(name, level)
        qt = _trace._ACTIVE
        t0 = time.perf_counter_ns()
        m.enter_wall()
        try:
            yield
        finally:
            m.exit_wall()
            if qt is not None:
                qt.add(self._span_kind(name), t0,
                       time.perf_counter_ns(), **attrs)

    def snapshot(self) -> Dict[str, int]:
        return {k: m.value for k, m in self.metrics.items()}


def registry_snapshot(plans=None) -> Dict[str, Any]:
    """Every metric as ONE dict: ``{"metrics": {name: summed value},
    "jitCaches": {cache: stats}}``. With ``plans`` given (captured
    physical plans), only their registries contribute — fused-stage
    constituents and children included — which is the bench's scraping
    shape; with None, every live registry in the process contributes
    (cross-query totals)."""
    vals: Dict[str, int] = {}

    def add_reg(ms) -> None:
        for k, v in ms.snapshot().items():
            vals[k] = vals.get(k, 0) + v

    if plans is None:
        for ms in list(_REGISTRIES):
            add_reg(ms)
    else:
        def walk(p) -> None:
            ms = getattr(p, "metrics", None)
            if ms is not None:
                add_reg(ms)
            for op in getattr(p, "fused_ops", []):
                fm = getattr(op, "metrics", None)
                if fm is not None:
                    add_reg(fm)
            for c in getattr(p, "children", []):
                walk(c)
        for plan in plans or []:
            walk(plan)
    from spark_rapids_tpu.jit_cache import cache_stats
    return {"metrics": vals, "jitCaches": cache_stats()}


def sum_plan_metrics(plans, prefix: str) -> Dict[str, int]:
    """Sum every metric whose key starts with ``prefix`` across captured
    physical plans, fused-stage constituents included. Per-chip counters
    (``dispatchCount.chip3``, ``meshScanUnits.chip0``) are dynamic keys,
    so callers aggregate by prefix (bench ``detail.multichip``, the
    multichip tests)."""
    out: Dict[str, int] = {}

    def add(p) -> None:
        ms = getattr(p, "metrics", None)
        if ms is None:
            return
        for k, v in ms.snapshot().items():
            if k.startswith(prefix):
                out[k] = out.get(k, 0) + v

    def walk(p) -> None:
        add(p)
        for op in getattr(p, "fused_ops", []):
            add(op)
        for c in getattr(p, "children", []):
            walk(c)

    for plan in plans or []:
        walk(plan)
    return out
