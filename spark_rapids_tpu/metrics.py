"""Operator metrics (GpuMetric, GpuExec.scala:17-103 twin).

Three verbosity levels (ESSENTIAL/MODERATE/DEBUG) gated by
``spark.rapids.sql.metrics.level``; each Tpu exec owns a named metric map
surfaced by ``TpuExec.metrics``. Timers are wall-clock nanoseconds.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVELS = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

# canonical metric names (GpuMetric object in GpuExec.scala)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
PEAK_DEVICE_MEMORY = "peakDeviceMemory"
SPILL_BYTES = "spillBytes"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
JOIN_TIME = "joinTime"
CONCAT_TIME = "concatTime"
PARTITION_TIME = "partitionTime"
COPY_TO_DEVICE_TIME = "copyToDeviceTime"
PACK_TIME = "packBatchTime"  # host-side staging half of an upload
COPY_FROM_DEVICE_TIME = "copyFromDeviceTime"
# stage-fusion metrics (TpuFusedStageExec + prelude-absorbing aggs)
DISPATCH_COUNT = "dispatchCount"        # device programs dispatched
STAGE_COMPILE_TIME = "stageCompileTime"  # first-call build+compile wall
FUSED_OPS = "fusedOps"                  # operators collapsed into a stage
COMPILE_CACHE_HITS = "compileCacheHits"
COMPILE_CACHE_MISSES = "compileCacheMisses"
# retry framework metrics (spark_rapids_tpu/retry.py, docs/robustness.md)
RETRY_COUNT = "retryCount"                # OOM retries that re-attempted
SPLIT_RETRY_COUNT = "splitRetryCount"     # input batches split in half
RETRY_BLOCK_TIME = "retryBlockTime"       # spill+backoff wall inside retries
SPILL_BYTES_ON_RETRY = "spillBytesOnRetry"  # HBM freed by retry spills
DEGRADED_CHIPS = "degradedChips"          # mesh chips demoted after failure
IO_RETRY_COUNT = "ioRetryCount"           # transient reader IO retries
DEVICE_DECODE_OOM_FALLBACKS = "deviceDecodeOomFallbacks"  # encoded-upload
#   OOMs that fell back to the pyarrow host decode for that batch


@dataclass
class TpuMetric:
    """Thread-safe counter: task threads (taskParallelism/shuffle pools)
    update the same operator's metrics concurrently."""

    name: str
    level: int = MODERATE
    value: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    # wall-union timer state (timed_wall): overlapping intervals from
    # concurrent threads count once
    _active: int = field(default=0, repr=False, compare=False)
    _wall_start: int = field(default=0, repr=False, compare=False)

    def add(self, v: int) -> None:
        with self._lock:
            self.value += int(v)

    def set_max(self, v: int) -> None:
        with self._lock:
            self.value = max(self.value, int(v))

    def enter_wall(self) -> None:
        with self._lock:
            if self._active == 0:
                self._wall_start = time.perf_counter_ns()
            self._active += 1

    def exit_wall(self) -> None:
        with self._lock:
            self._active -= 1
            if self._active == 0:
                self.value += time.perf_counter_ns() - self._wall_start


class MetricRegistry:
    """Per-exec metric map; creation is gated by the configured level so
    disabled metrics cost a no-op (the reference wraps them in NoopMetric)."""

    def __init__(self, conf_level: str = "MODERATE"):
        self.enabled_level = _LEVELS.get(conf_level.upper(), MODERATE)
        self.metrics: Dict[str, TpuMetric] = {}
        self._lock = threading.Lock()

    def create(self, name: str, level: int = MODERATE) -> TpuMetric:
        with self._lock:  # check-then-set must be atomic across tasks
            m = self.metrics.get(name)
            if m is None:
                m = TpuMetric(name, level)
                if level <= self.enabled_level:
                    self.metrics[name] = m
            return m

    def __getitem__(self, name: str) -> TpuMetric:
        return self.metrics.get(name) or TpuMetric(name)

    def value(self, name: str) -> int:
        m = self.metrics.get(name)
        return m.value if m else 0

    @contextlib.contextmanager
    def timed(self, name: str, level: int = MODERATE) -> Iterator[None]:
        m = self.create(name, level)
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            m.add(time.perf_counter_ns() - t0)

    @contextlib.contextmanager
    def timed_wall(self, name: str, level: int = MODERATE
                   ) -> Iterator[None]:
        """Union-of-intervals timer: when N pool threads run the same
        phase concurrently, the metric advances by WALL time, not by N
        stacked thread-times, so a stage breakdown sums against the
        query wall sensibly (round-5 issue: q1's drain metric read
        11.6s against a 5.4s wall)."""
        m = self.create(name, level)
        m.enter_wall()
        try:
            yield
        finally:
            m.exit_wall()

    def snapshot(self) -> Dict[str, int]:
        return {k: m.value for k, m in self.metrics.items()}


def sum_plan_metrics(plans, prefix: str) -> Dict[str, int]:
    """Sum every metric whose key starts with ``prefix`` across captured
    physical plans, fused-stage constituents included. Per-chip counters
    (``dispatchCount.chip3``, ``meshScanUnits.chip0``) are dynamic keys,
    so callers aggregate by prefix (bench ``detail.multichip``, the
    multichip tests)."""
    out: Dict[str, int] = {}

    def add(p) -> None:
        ms = getattr(p, "metrics", None)
        if ms is None:
            return
        for k, v in ms.snapshot().items():
            if k.startswith(prefix):
                out[k] = out.get(k, 0) + v

    def walk(p) -> None:
        add(p)
        for op in getattr(p, "fused_ops", []):
            add(op)
        for c in getattr(p, "children", []):
            walk(c)

    for plan in plans or []:
        walk(plan)
    return out
