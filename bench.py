#!/usr/bin/env python
"""Driver benchmark: TPC-H q1 at SF1, from Parquet files.

BASELINE.md's first target config: ``parquet scan -> filter -> groupBy
aggregate, single host``. A seeded SF1 ``lineitem`` (6,001,215 rows —
the TPC-H SF1 cardinality) is generated ONCE into ``.bench-data/`` and
written as Parquet through the engine's own writer; the timed query is
the full q1 — scan, date filter, arithmetic projections, 2-key groupBy
with 8 aggregates, orderBy — run through ``spark.sql`` on this engine's
CPU path (the stand-in for "CPU Spark", which the reference's 3x-7x /
"4x typical" claim is measured against, /root/reference/docs/FAQ.md:
104-105) and on the TPU path with every operator force-placed on device.

Prints ONE JSON line:
  {"metric": ..., "value": rows/s on device, "unit": "rows/s",
   "vs_baseline": device_speedup_over_cpu / 4.0}

so vs_baseline >= 1.0 means matching the reference's typical published
speedup on its own terms. Correctness is asserted before timing: with
the real decimal(15,2) money columns (round 4), every aggregate is
exact integer arithmetic, so ALL columns must match bit-for-bit —
no float tolerance carve-out applies to q1 anymore.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Multichip leg on emulated devices: BENCH_MULTICHIP_DEVICES=8 forces N
# virtual CPU devices (same emulation tests/conftest.py uses) so the
# detail.multichip section can run without TPU hardware. Must be set
# BEFORE the first jax import; on real multi-chip backends leave unset.
_mc_emu = int(os.environ.get("BENCH_MULTICHIP_DEVICES", "0"))
if _mc_emu > 1:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_mc_emu}"
        ).strip()

import numpy as np  # noqa: E402

SF1_ROWS = 6_001_215
N_ROWS = int(os.environ.get("BENCH_ROWS", SF1_ROWS))
N_PARTITIONS = 8
REFERENCE_TYPICAL_SPEEDUP = 4.0
DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", f"lineitem_dec_{N_ROWS}")

Q1 = """
SELECT
    l_returnflag,
    l_linestatus,
    sum(l_quantity) AS sum_qty,
    sum(l_extendedprice) AS sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
    avg(l_quantity) AS avg_qty,
    avg(l_extendedprice) AS avg_price,
    avg(l_discount) AS avg_disc,
    count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= date '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


def make_lineitem():
    """Seeded SF1-shaped lineitem with the REAL TPC-H schema: the money
    columns are decimal(15,2) (dbgen 4.2.2.13 domains), generated as
    unscaled int64 directly."""
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.sql import types as T

    DEC = T.DecimalType(15, 2)
    rng = np.random.default_rng(20260730)
    n = N_ROWS
    quantity = rng.integers(1, 51, n) * 100          # 1.00 .. 50.00
    extendedprice = rng.integers(90100, 10494951, n)  # 901.00..104949.50
    discount = rng.integers(0, 11, n)                 # 0.00 .. 0.10
    tax = rng.integers(0, 9, n)                       # 0.00 .. 0.08
    returnflag = np.array(["A", "N", "R"], dtype=object)[
        rng.integers(0, 3, n)]
    linestatus = np.array(["O", "F"], dtype=object)[rng.integers(0, 2, n)]
    # 1992-01-02 .. 1998-12-01 as days since epoch
    lo = (np.datetime64("1992-01-02") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1998-12-01") - np.datetime64("1970-01-01")).astype(int)
    shipdate = rng.integers(lo, hi + 1, n).astype(np.int32)
    schema = T.StructType([
        T.StructField("l_quantity", DEC),
        T.StructField("l_extendedprice", DEC),
        T.StructField("l_discount", DEC),
        T.StructField("l_tax", DEC),
        T.StructField("l_returnflag", T.StringT),
        T.StructField("l_linestatus", T.StringT),
        T.StructField("l_shipdate", T.DateT),
    ])
    cols = [HostColumn.all_valid(c, f.data_type)
            for c, f in zip([quantity, extendedprice, discount, tax,
                             returnflag, linestatus, shipdate],
                            schema.fields)]
    return HostBatch(schema, cols, n)


def ensure_data(spark) -> str:
    marker = os.path.join(DATA_DIR, "_SUCCESS.bench")
    if os.path.exists(marker):
        return DATA_DIR
    if os.path.exists(DATA_DIR):
        shutil.rmtree(DATA_DIR)
    batch = make_lineitem()
    df = spark.createDataFrame(batch, num_partitions=N_PARTITIONS)
    df.write.mode("overwrite").parquet(DATA_DIR)
    with open(marker, "w") as f:
        f.write("ok\n")
    return DATA_DIR


def build_query(spark):
    spark.read.parquet(DATA_DIR).createOrReplaceTempView("lineitem")
    return spark.sql(Q1)


def run_once(q):
    t0 = time.perf_counter()
    rows = q.collect()
    return time.perf_counter() - t0, rows


def assert_rows_match(cpu_rows, tpu_rows):
    assert len(cpu_rows) == len(tpu_rows), \
        (len(cpu_rows), len(tpu_rows))
    for rc, rt in zip(cpu_rows, tpu_rows):
        for vc, vt in zip(rc, rt):
            if isinstance(vc, float):
                assert vt == vc or abs(vt - vc) <= 1e-9 * max(
                    abs(vc), abs(vt)), (vc, vt)
            else:
                assert vc == vt, (vc, vt)


TPCDS_Q3 = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
FROM store_sales
JOIN date_dim ON d_date_sk = ss_sold_date_sk
JOIN item ON ss_item_sk = i_item_sk
WHERE i_manufact_id = 128 AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, brand_id
LIMIT 100
"""

TPCDS_ROWS = int(os.environ.get("BENCH_TPCDS_ROWS", 2_000_000))
TPCDS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench-data", f"tpcds_{TPCDS_ROWS}")


def ensure_tpcds_data(spark) -> None:
    """Synthetic TPC-DS star-schema slice for q3 (BASELINE config 2):
    store_sales fact + item/date_dim dimensions, decimal money."""
    marker = os.path.join(TPCDS_DIR, "_SUCCESS.bench")
    if os.path.exists(marker):
        return
    if os.path.exists(TPCDS_DIR):
        shutil.rmtree(TPCDS_DIR)
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.sql import types as T
    rng = np.random.default_rng(20260731)
    DEC = T.DecimalType(7, 2)

    n_item = 20_000
    item = HostBatch(T.StructType([
        T.StructField("i_item_sk", T.LongT),
        T.StructField("i_brand_id", T.IntegerT),
        T.StructField("i_brand", T.StringT),
        T.StructField("i_manufact_id", T.IntegerT),
    ]), [
        HostColumn.all_valid(np.arange(1, n_item + 1), T.LongT),
        HostColumn.all_valid(
            rng.integers(1, 1000, n_item).astype(np.int32), T.IntegerT),
        HostColumn.all_valid(np.array(
            [f"brand#{i % 997:03d}" for i in range(n_item)],
            dtype=object), T.StringT),
        HostColumn.all_valid(
            rng.integers(1, 1001, n_item).astype(np.int32), T.IntegerT),
    ], n_item)

    n_date = 73_049
    date_dim = HostBatch(T.StructType([
        T.StructField("d_date_sk", T.LongT),
        T.StructField("d_year", T.IntegerT),
        T.StructField("d_moy", T.IntegerT),
    ]), [
        HostColumn.all_valid(np.arange(1, n_date + 1), T.LongT),
        HostColumn.all_valid(
            (1998 + (np.arange(n_date) // 365) % 7).astype(np.int32),
            T.IntegerT),
        HostColumn.all_valid(
            (1 + (np.arange(n_date) // 30) % 12).astype(np.int32),
            T.IntegerT),
    ], n_date)

    n = TPCDS_ROWS
    store_sales = HostBatch(T.StructType([
        T.StructField("ss_sold_date_sk", T.LongT),
        T.StructField("ss_item_sk", T.LongT),
        T.StructField("ss_ext_sales_price", DEC),
    ]), [
        HostColumn.all_valid(rng.integers(1, n_date + 1, n), T.LongT),
        HostColumn.all_valid(rng.integers(1, n_item + 1, n), T.LongT),
        HostColumn.all_valid(rng.integers(100, 1_000_000, n), DEC),
    ], n)

    for name, batch, parts in (("item", item, 1), ("date_dim", date_dim, 1),
                               ("store_sales", store_sales, 8)):
        spark.createDataFrame(batch, num_partitions=parts).write \
            .mode("overwrite").parquet(os.path.join(TPCDS_DIR, name))
    with open(marker, "w") as f:
        f.write("ok\n")


def run_tpcds_q3(spark, capture=False):
    for name in ("item", "date_dim", "store_sales"):
        spark.read.parquet(os.path.join(TPCDS_DIR, name)) \
            .createOrReplaceTempView(name)
    q = spark.sql(TPCDS_Q3)
    run_once(q)  # warm
    times, rows, stages, decode = [], None, None, None
    for i in range(2):
        if capture and i == 1:
            spark.start_capture()
        dt, rows = run_once(q)
        times.append(dt)
    if capture:
        plans = spark.get_captured_plans()
        stages = stage_breakdown(plans)
        decode = decode_breakdown(plans)
    return min(times), rows, stages, decode


def stage_breakdown(plans) -> dict:
    """Aggregate per-operator time metrics from the captured physical
    plan of the LAST timed run (VERDICT r3 weak #10: publish where the
    wall time goes, not just its total). Fused stages fan their metrics
    back to their constituent execs, so the breakdown keeps the same
    per-operator stage keys whether or not fusion is enabled."""
    out: dict = {}

    def visit(p):
        ms = getattr(p, "metrics", None)
        if ms is None:
            return
        name = p.simple_string().split()[0]
        for k, v in ms.snapshot().items():
            if "Time" in k and v:
                key = f"{name}.{k}"
                out[key] = round(out.get(key, 0.0) + v / 1e9, 3)

    def walk(p):
        visit(p)
        for op in getattr(p, "fused_ops", []):
            visit(op)  # shallow: child links point back into the chain
        for c in p.children:
            walk(c)

    for plan in plans or []:
        walk(plan)
    return out


def collect_counters(plans, names) -> dict:
    """Named metric counters across every exec of the captured plans —
    one registry_snapshot call (metrics.py owns the walk; fused
    constituents included)."""
    from spark_rapids_tpu.metrics import registry_snapshot
    snap = registry_snapshot(plans)["metrics"]
    return {n: snap.get(n, 0) for n in names}


def decode_breakdown(plans) -> dict:
    """Per-encoding scan decode attribution: host decodeTime vs
    deviceDecodeTime (the host-side IO/plan half of the device path),
    how many values each Parquet encoding contributed on DEVICE vs the
    per-column HOST fallbacks, and the scan pipeline's prefetch /
    upload-ahead counters (docs/scan.md)."""
    out = {"hostDecodeTime_s": 0.0, "deviceDecodeTime_s": 0.0,
           "scanPrefetchTime_s": 0.0, "deviceDecodedBatches": 0,
           "deviceFallbackUnits": 0, "deviceFallbackColumns": 0,
           "uploadAheadBatches": 0, "prefetchRingShrinks": 0,
           "valuesByEncoding": {}, "hostValuesByEncoding": {}}

    def walk(p):
        name = type(p).__name__
        if name == "CpuFileScanExec":
            snap = p.metrics.snapshot()
            out["hostDecodeTime_s"] = round(
                out["hostDecodeTime_s"] + snap.get("decodeTime", 0) / 1e9,
                3)
            out["deviceDecodeTime_s"] = round(
                out["deviceDecodeTime_s"]
                + snap.get("deviceDecodeTime", 0) / 1e9, 3)
            for k in ("deviceDecodedBatches", "deviceFallbackUnits",
                      "deviceFallbackColumns"):
                out[k] += snap.get(k, 0)
            for k, v in snap.items():
                if k.startswith("deviceDecodedValues."):
                    enc = k.split(".", 1)[1]
                    out["valuesByEncoding"][enc] = \
                        out["valuesByEncoding"].get(enc, 0) + v
                elif k.startswith("hostDecodedValues."):
                    enc = k.split(".", 1)[1]
                    out["hostValuesByEncoding"][enc] = \
                        out["hostValuesByEncoding"].get(enc, 0) + v
        elif name == "TpuRowToColumnarExec":
            snap = p.metrics.snapshot()
            out["scanPrefetchTime_s"] = round(
                out["scanPrefetchTime_s"]
                + snap.get("scanPrefetchTime", 0) / 1e9, 3)
            out["uploadAheadBatches"] += snap.get("uploadAheadBatches", 0)
            out["prefetchRingShrinks"] += snap.get(
                "prefetchRingShrinks", 0)
        for c in p.children:
            walk(c)

    for plan in plans or []:
        walk(plan)
    return out


def fresh_leg() -> int:
    """Scope a detail leg's process-wide observability state: start a
    new metric-registry epoch and re-base the device store's pool +
    per-owner peak watermarks, so each leg's snapshot/profile reports
    its OWN run instead of inheriting earlier legs' registries and
    high-watermarks."""
    from spark_rapids_tpu import memory
    from spark_rapids_tpu.metrics import begin_epoch
    memory.reset_store_peaks()
    return begin_epoch()


TPU_CONF = {
    "spark.rapids.sql.enabled": "true",
    "spark.rapids.sql.test.forceDevice": "true",  # fail on any fallback
    "spark.rapids.sql.variableFloatAgg.enabled": "true",
    # TPU executes f64 via emulation (not bit-identical rounding);
    # q1's double arithmetic opts in exactly like the reference's
    # .incompat() ops, and the result assert holds doubles to 1e-9
    "spark.rapids.sql.incompatibleOps.enabled": "true",
    # overlap per-task host round trips with device compute
    "spark.rapids.sql.taskParallelism": "4",
    "spark.rapids.sql.concurrentGpuTasks": "4",
    # device parquet decode + the async scan pipeline are ON BY
    # DEFAULT (ISSUE 9); the bench runs the stock configuration and
    # detail.decode A/B-measures the host-decode / unpipelined legs
}

DEVICE_DECODE_CONF = \
    "spark.rapids.sql.format.parquet.deviceDecode.enabled"
MAX_IN_FLIGHT_CONF = \
    "spark.rapids.sql.format.parquet.deviceDecode.maxInFlight"

_COUNTERS = ("dispatchCount", "stageCompileTime", "fusedOps")


def run_tpu(fusion_enabled: bool) -> dict:
    """One full TPU pass (q1 warm + 3 timed, q3) with stage fusion on
    or off — the fused-vs-unfused comparison runs in the SAME bench
    invocation so the walls are directly comparable."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    fresh_leg()
    conf = dict(TPU_CONF)
    conf["spark.rapids.sql.stageFusion.enabled"] = str(
        fusion_enabled).lower()
    tpu = TpuSparkSession(conf)
    q_tpu = build_query(tpu)
    tpu.start_capture()
    run_once(q_tpu)  # jit compile warm-up
    warm_counters = collect_counters(tpu.get_captured_plans(), _COUNTERS)
    times, rows = [], None
    for i in range(3):
        if i == 2:
            tpu.start_capture()
        dt, rows = run_once(q_tpu)
        times.append(dt)
    captured = tpu.get_captured_plans()
    counters = collect_counters(captured, _COUNTERS)
    out = {
        "wall_s": round(min(times), 4),
        "rows": rows,
        "stages": stage_breakdown(captured),
        "decode": decode_breakdown(captured),
        "dispatchCount": counters["dispatchCount"],
        "fusedOps": counters["fusedOps"],
        "stageCompileTime_s": round(
            warm_counters["stageCompileTime"] / 1e9, 3),
    }
    q3_t, q3_rows, q3_stages, q3_decode = run_tpcds_q3(tpu, capture=True)
    out["q3"] = {"wall_s": round(q3_t, 4), "rows": q3_rows,
                 "stages": q3_stages, "decode": q3_decode}
    tpu.stop()
    return out


def run_decode_ab(pipelined_wall: float, cpu_rows) -> dict:
    """detail.decode A/B legs (like detail.fusion): q1 with the HOST
    decode (deviceDecode off) and with device decode but the scan
    pipeline fully synchronous (maxInFlight=0), against the default
    pipelined wall — so the device-decode win and the pipeline win are
    separately attributable. Both legs assert bit-identical rows."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    out = {"pipelined_wall_s": round(pipelined_wall, 4)}
    for name, extra in (("hostDecode", {DEVICE_DECODE_CONF: "false"}),
                        ("unpipelined", {MAX_IN_FLIGHT_CONF: "0"})):
        fresh_leg()
        conf = dict(TPU_CONF)
        conf.update(extra)
        tpu = TpuSparkSession(conf)
        try:
            q = build_query(tpu)
            run_once(q)  # warm
            times, rows = [], None
            for i in range(2):
                if i == 1:
                    tpu.start_capture()
                dt, rows = run_once(q)
                times.append(dt)
            assert_rows_match(cpu_rows, rows)
            out[name] = {
                "wall_s": round(min(times), 4),
                "decode": decode_breakdown(tpu.get_captured_plans()),
            }
        finally:
            tpu.stop()
    out["pipelineSpeedup"] = round(
        out["unpipelined"]["wall_s"] / pipelined_wall, 4)
    out["deviceDecodeSpeedup"] = round(
        out["hostDecode"]["wall_s"] / pipelined_wall, 4)
    return out


def run_multichip(single_chip_wall: float, cpu_rows) -> dict:
    """q1 end-to-end with shuffle.mode=ici over every visible device:
    the mesh-sharded scan runs one reader stream per chip, fused stages
    execute on each chip's resident batches, and the exchange consumes
    them without a host gather (docs/multichip.md). Skips gracefully
    when fewer than 2 devices are visible. The mesh size honors
    spark.rapids.shuffle.ici.devices (0 = all visible)."""
    import jax
    n_vis = len(jax.devices())
    if n_vis < 2:
        return {"skipped": True,
                "reason": f"{n_vis} device visible (need >= 2; set "
                          "BENCH_MULTICHIP_DEVICES=8 to emulate)"}
    from spark_rapids_tpu.sql.session import TpuSparkSession
    fresh_leg()
    conf = dict(TPU_CONF)
    conf["spark.rapids.shuffle.mode"] = "ici"
    # 0 = all visible devices (resolved by the session's mesh wiring)
    conf["spark.rapids.shuffle.ici.devices"] = os.environ.get(
        "BENCH_ICI_DEVICES", "0")
    tpu = TpuSparkSession(conf)
    try:
        from spark_rapids_tpu.parallel.mesh import get_active_mesh, mesh_size
        n_chips = mesh_size(get_active_mesh())
        q = build_query(tpu)
        run_once(q)  # jit compile warm-up
        times, rows = [], None
        for i in range(2):
            if i == 1:
                tpu.start_capture()
            dt, rows = run_once(q)
            times.append(dt)
        from spark_rapids_tpu.metrics import sum_plan_metrics
        captured = tpu.get_captured_plans()
        assert_rows_match(cpu_rows, rows)
        wall = min(times)
        dispatch = sum_plan_metrics(captured, "dispatchCount.chip")
        units = sum_plan_metrics(captured, "meshScanUnits.chip")
        pad = sum_plan_metrics(captured, "meshPadWaste")
        return {
            "skipped": False,
            "n_chips": n_chips,
            "wall_s": round(wall, 4),
            "single_chip_wall_s": round(single_chip_wall, 4),
            "speedup_vs_single_chip": round(single_chip_wall / wall, 4),
            "perChipDispatchCount": dispatch,
            "chipsDispatching": sum(1 for v in dispatch.values() if v),
            "scanUnitsPerChip": units,
            "meshPadWaste": pad.get("meshPadWaste", 0),
        }
    finally:
        tpu.stop()


_ROBUSTNESS_COUNTERS = ("retryCount", "splitRetryCount",
                        "spillBytesOnRetry", "retryBlockTime",
                        "ioRetryCount", "degradedChips",
                        "prefetchRingShrinks", "uploadAheadBatches",
                        "deviceDecodeOomFallbacks")


def run_robustness(clean_wall: float, cpu_rows) -> dict:
    """q1 under deterministic fault injection (docs/robustness.md): one
    leg per failure mode — every-Nth OOM (retry), split-OOM (split-and-
    retry), and a persistently failing mesh chip (graceful degradation)
    — asserting bit-identical results and reporting the retry/split/
    spill counters plus the degraded-mode walls against the clean wall.
    Skips gracefully when injection is off (BENCH_INJECT=0)."""
    if os.environ.get("BENCH_INJECT", "1").lower() in ("0", "false",
                                                       "off"):
        return {"skipped": True, "reason": "injection off (BENCH_INJECT=0)"}
    from spark_rapids_tpu import retry as RT
    from spark_rapids_tpu.sql.session import TpuSparkSession
    legs = [
        ("oomEveryN", {"spark.rapids.sql.test.injectOOM": "5"}, {}),
        ("splitOom", {"spark.rapids.sql.test.injectOOM": "split:7"}, {}),
        # OOM targeted at the scan pipeline's prefetched uploads: the
        # in-flight ring must SHRINK (drain + synchronous retry), not
        # deadlock, under with_retry spills (docs/scan.md)
        ("prefetchOom",
         {"spark.rapids.sql.test.injectOOM": "site:upload:3"}, {}),
    ]
    import jax
    if len(jax.devices()) >= 2:
        legs.append(("chipFailure",
                     {"spark.rapids.sql.test.injectChipFailure":
                      str(jax.devices()[0].id)},
                     {"spark.rapids.shuffle.mode": "ici"}))
    out = {"skipped": False, "clean_wall_s": round(clean_wall, 4),
           "legs": {}}
    for name, inject, extra in legs:
        RT.reset_fault_injection()
        fresh_leg()
        conf = dict(TPU_CONF)
        conf.update(inject)
        conf.update(extra)
        tpu = TpuSparkSession(conf)
        try:
            q = build_query(tpu)
            # capture BOTH runs: one-time events (chip degradation
            # happens once per session) land in the warm run, while the
            # second run's wall is the degraded-mode steady state
            tpu.start_capture()
            run_once(q)
            RT.reset_fault_injection()
            dt, rows = run_once(q)
            assert_rows_match(cpu_rows, rows)
            counters = collect_counters(tpu.get_captured_plans(),
                                        _ROBUSTNESS_COUNTERS)
            inj = RT.get_fault_injector(tpu.conf_obj)
            out["legs"][name] = {
                "wall_s": round(dt, 4),
                "slowdown_vs_clean": round(dt / clean_wall, 4),
                "retryCount": counters["retryCount"],
                "splitRetryCount": counters["splitRetryCount"],
                "spillBytesOnRetry": counters["spillBytesOnRetry"],
                "retryBlockTime_s": round(
                    counters["retryBlockTime"] / 1e9, 4),
                "degradedChips": counters["degradedChips"],
                "prefetchRingShrinks": counters["prefetchRingShrinks"],
                "deviceDecodeOomFallbacks":
                    counters["deviceDecodeOomFallbacks"],
                "injected": inj.stats() if inj is not None else {},
            }
        finally:
            tpu.stop()
    RT.reset_fault_injection()
    return out


_OOC_COUNTERS = ("retryCount", "splitRetryCount", "plannedPartitions",
                 "plannedOutOfCoreEscalations", "budgetPressurePeak")


def run_out_of_core(clean_wall: float, cpu_rows) -> dict:
    """detail.outOfCore (docs/out_of_core.md): q1 with the planning
    budget pinned at 1x / 4x / 10x UNDER the clean run's peak HBM, so
    the planned partitioned tier absorbs the pressure. The acceptance
    number is plannedPathClean: 1.0 means every over-budget leg stayed
    bit-identical with retryCount == 0 and splitRetryCount == 0 — the
    degradation ladder never fell past its first two rungs."""
    from spark_rapids_tpu import retry as RT
    from spark_rapids_tpu.memory import get_device_store
    from spark_rapids_tpu.sql.session import TpuSparkSession

    # probe: the clean run's peak HBM is the working-set estimate the
    # over-budget legs divide down from
    fresh_leg()
    tpu = TpuSparkSession(dict(TPU_CONF))
    try:
        q = build_query(tpu)
        run_once(q)
        peak = int(get_device_store(tpu.conf_obj)
                   .stats()["peakDeviceBytes"])
    finally:
        tpu.stop()
    if peak <= 0:
        return {"skipped": True,
                "reason": f"clean peakDeviceBytes={peak}: no working "
                          f"set to budget against"}

    out = {"skipped": False, "clean_wall_s": round(clean_wall, 4),
           "workingSetBytes": peak, "legs": {}}
    clean_path = True
    for name, divisor in (("budget1x", 1), ("budget4x", 4),
                          ("budget10x", 10)):
        RT.reset_fault_injection()
        fresh_leg()
        conf = dict(TPU_CONF)
        budget = max(1, peak // divisor)
        conf["spark.rapids.sql.memory.deviceBudgetBytes"] = str(budget)
        tpu = TpuSparkSession(conf)
        try:
            q = build_query(tpu)
            run_once(q)  # warm: compiles at this budget's plan shape
            tpu.start_capture()
            dt, rows = run_once(q)
            assert_rows_match(cpu_rows, rows)
            counters = collect_counters(tpu.get_captured_plans(),
                                        _OOC_COUNTERS)
            store_peak = int(get_device_store(tpu.conf_obj)
                             .stats()["peakDeviceBytes"])
            retried = (counters["retryCount"]
                       + counters["splitRetryCount"]) > 0
            clean_path = clean_path and not retried
            out["legs"][name] = {
                "wall_s": round(dt, 4),
                "slowdown_vs_clean": round(dt / clean_wall, 4),
                "budgetBytes": budget,
                "peakDeviceBytes": store_peak,
                "retryCount": counters["retryCount"],
                "splitRetryCount": counters["splitRetryCount"],
                "plannedPartitions": counters["plannedPartitions"],
                "plannedOutOfCoreEscalations":
                    counters["plannedOutOfCoreEscalations"],
                "budgetPressurePeak": counters["budgetPressurePeak"],
            }
        finally:
            tpu.stop()
    out["plannedPathClean"] = 1.0 if clean_path else 0.0
    return out


def run_trace(clean_wall: float, cpu_rows) -> dict:
    """q1 with span tracing on (docs/observability.md): emits one
    Chrome-trace file per run under .bench-data/traces, reports the
    per-chip occupancy + critical-path breakdown from the last run's
    trace, and measures the tracing overhead against the untraced
    wall (budget: <= 15% on the smoke input, tests/test_trace.py)."""
    import glob

    from spark_rapids_tpu import trace as TR
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.tools import analyze_trace
    tdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", "traces")
    shutil.rmtree(tdir, ignore_errors=True)
    TR.reset_tracing()
    fresh_leg()
    conf = dict(TPU_CONF)
    conf["spark.rapids.sql.trace.enabled"] = "true"
    conf["spark.rapids.sql.trace.dir"] = tdir
    tpu = TpuSparkSession(conf)
    try:
        q = build_query(tpu)
        run_once(q)  # jit compile warm-up
        times, rows = [], None
        for i in range(2):
            if i == 1:
                tpu.start_capture()
            dt, rows = run_once(q)
            times.append(dt)
        assert_rows_match(cpu_rows, rows)
        wall = min(times)
        files = sorted(glob.glob(os.path.join(tdir, "trace-*.json")))
        analysis = analyze_trace(files[-1]) if files else {}
        cp = analysis.get("criticalPath_s", {})
        # decode-overlap ratio (ISSUE 9 acceptance): how much of the
        # scan's wall (host decode plan + prefetch threads) hid under
        # device compute — 1.0 means the scan never held the critical
        # path, and FileScan.decodeTime off the critical path is the
        # flip's proof
        dec = decode_breakdown(tpu.get_captured_plans())
        scan_total = (dec["hostDecodeTime_s"] + dec["deviceDecodeTime_s"]
                      + dec["scanPrefetchTime_s"])
        scan_critical = sum(v for k, v in cp.items() if k in (
            "FileScan.decodeTime", "FileScan.deviceDecodeTime",
            "scanPrefetch", "uploadAhead"))
        overlap = {
            "scanTotal_s": round(scan_total, 4),
            "scanOnCriticalPath_s": round(scan_critical, 4),
            "overlapRatio": round(
                max(0.0, 1.0 - scan_critical / scan_total), 4)
            if scan_total > 0 else 1.0,
            "decodeTimeOnCriticalPath":
                "FileScan.decodeTime" in cp,
        }
        return {
            "skipped": False,
            "wall_s": round(wall, 4),
            "untraced_wall_s": round(clean_wall, 4),
            "tracingOverhead": round(wall / clean_wall, 4),
            "traceFiles": len(files),
            "spanCount": analysis.get("spanCount", 0),
            "criticalPath_s": cp,
            "criticalPathIdle_s": analysis.get("criticalPathIdle_s", 0),
            "occupancy": analysis.get("occupancy", {}),
            "topSpans": analysis.get("topSpans", []),
            "scanOverlap": overlap,
        }
    finally:
        tpu.stop()
        TR.reset_tracing()


def run_profile(clean_wall: float, cpu_rows) -> dict:
    """q1 + q3 with the profile subsystem on (docs/observability.md
    "Reading a query profile"): per-op peak HBM from each query's
    artifact (checked against the pool watermark), explain coverage
    counts, and the measured profiling overhead vs the clean wall
    (acceptance: <= 1.15x on the smoke input)."""
    from spark_rapids_tpu.profile import read_profiles
    from spark_rapids_tpu.sql.session import TpuSparkSession
    pdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", "profiles")
    shutil.rmtree(pdir, ignore_errors=True)
    conf = dict(TPU_CONF)
    # no forceDevice: the explain section should report REAL coverage
    # (a forced-fallback query would abort under forceDevice)
    conf.pop("spark.rapids.sql.test.forceDevice", None)
    conf["spark.rapids.sql.profile.enabled"] = "true"
    conf["spark.rapids.sql.profile.dir"] = pdir

    def leg(run_query, check_rows) -> dict:
        epoch = fresh_leg()
        tpu = TpuSparkSession(conf)
        try:
            wall, rows, path = run_query(tpu)
            if check_rows is not None:
                assert_rows_match(check_rows, rows)
            prof = list(read_profiles(path))[0]
            ops = prof["memory"]["operators"]
            pool = prof["memory"]["pool"]
            ex = prof.get("explain", {})
            # consistency: the pool watermark is bounded by the sum of
            # per-op peaks (acceptance criterion)
            sum_peaks = sum(st["peakBytes"] for st in ops.values())
            assert pool.get("peakDeviceBytes", 0) <= sum_peaks or \
                not ops, (pool, ops)
            # epoch-scoped process-wide snapshot: only THIS leg's
            # registries contribute (the registry-bleed satellite)
            from spark_rapids_tpu.metrics import registry_snapshot
            leg_metrics = registry_snapshot(epoch=epoch)["metrics"]
            return {
                "wall_s": round(wall, 4),
                "perOpPeakHBM": {o: st["peakBytes"]
                                 for o, st in sorted(ops.items())},
                "poolPeakHBM": pool.get("peakDeviceBytes", 0),
                "deviceOps": len(ex.get("deviceOps", [])),
                "fallbacks": len(ex.get("fallbacks", [])),
                "coverage": ex.get("coverage", 1.0),
                "legSpillBytes": leg_metrics.get("spillBytes", 0),
                "legRetryCount": leg_metrics.get("retryCount", 0),
            }
        finally:
            tpu.stop()

    def q1_run(tpu):
        q = build_query(tpu)
        run_once(q)  # warm
        times, rows = [], None
        for _ in range(2):
            dt, rows = run_once(q)
            times.append(dt)
        return min(times), rows, tpu.last_profile_path

    def q3_run(tpu):
        t, rows, _stages, _decode = run_tpcds_q3(tpu)
        return t, rows, tpu.last_profile_path

    q1_leg = leg(q1_run, cpu_rows)
    q3_leg = leg(q3_run, None)
    return {
        "skipped": False,
        "clean_wall_s": round(clean_wall, 4),
        "profilingOverhead": round(q1_leg["wall_s"] / clean_wall, 4),
        "q1": q1_leg,
        "q3": q3_leg,
    }


_KERNEL_NAMES = ("groupbyHash", "joinProbe", "murmur3", "decodeFused")

# the q1 agg-drain span families whose EXCLUSIVE self-time the kernel
# tier targets (ISSUE 11 acceptance: >= 2x on the drain, kernel vs
# oracle): the per-batch aggregation dispatches plus the drain wall
_DRAIN_SPANS = ("TpuHashAggregateExec.dispatch",
                "TpuHashAggregateExec.pipelineDrainTime",
                "pipelineDrainTime")


def run_kernels(clean_wall: float, cpu_rows) -> dict:
    """detail.kernels (docs/kernels.md): per-kernel A/B walls — q1
    with the Pallas kernel tier on (stock conf) vs the XLA-op oracle
    composition (kernel.enabled=false), plus one leg per kernel with
    only that kernel disabled — with the q1 agg-drain EXCLUSIVE
    self-time extracted from each leg's trace (tools.exclusive_times)
    and the kernelDispatchCount/kernelFallbacks counters. Every leg
    asserts bit-identical rows. On backends without native Pallas
    lowering the kernels run in interpreter-mode emulation: the legs
    still measure (the parity/counter story holds) but walls are not
    representative of TPU kernels — `pallasMode` says which."""
    import glob

    from spark_rapids_tpu import device_caps as DC
    from spark_rapids_tpu import trace as TR
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.tools import exclusive_times
    from spark_rapids_tpu.trace import load_trace
    mode = DC.pallas_mode()
    if mode is None:
        return {"skipped": True,
                "reason": "pallas unavailable on this backend"}
    tdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", "kernel-traces")

    def leg(extra, traced=True, runs=2) -> dict:
        shutil.rmtree(tdir, ignore_errors=True)
        TR.reset_tracing()
        fresh_leg()
        conf = dict(TPU_CONF)
        if traced:
            conf["spark.rapids.sql.trace.enabled"] = "true"
            conf["spark.rapids.sql.trace.dir"] = tdir
        conf.update(extra)
        tpu = TpuSparkSession(conf)
        try:
            q = build_query(tpu)
            run_once(q)  # jit compile warm-up
            times, rows = [], None
            for i in range(runs):
                if i == runs - 1:
                    tpu.start_capture()
                dt, rows = run_once(q)
                times.append(dt)
            assert_rows_match(cpu_rows, rows)
            counters = collect_counters(
                tpu.get_captured_plans(),
                tuple(f"kernelDispatchCount.{n}" for n in _KERNEL_NAMES)
                + tuple(f"kernelFallbacks.{n}" for n in _KERNEL_NAMES)
                + ("deviceDecodePrograms", "deviceDecodedBatches"))
            out = {"wall_s": round(min(times), 4),
                   "kernelDispatchCount": {
                       n: counters[f"kernelDispatchCount.{n}"]
                       for n in _KERNEL_NAMES
                       if counters[f"kernelDispatchCount.{n}"]},
                   "kernelFallbacks": {
                       n: counters[f"kernelFallbacks.{n}"]
                       for n in _KERNEL_NAMES
                       if counters[f"kernelFallbacks.{n}"]}}
            if counters["deviceDecodedBatches"]:
                # decode-stage programs billed per device-decoded
                # batch: 1.0 when every batch ran the fused kernel, the
                # XLA chain's stage count otherwise (docs/kernels.md)
                out["decodeProgramsPerBatch"] = round(
                    counters["deviceDecodePrograms"]
                    / counters["deviceDecodedBatches"], 4)
            if traced:
                files = sorted(glob.glob(
                    os.path.join(tdir, "trace-*.json")))
                if files:
                    excl = exclusive_times(
                        load_trace(files[-1])["spans"])
                    out["aggDrainSelf_s"] = round(sum(
                        d["exclusive"] for name, d in excl.items()
                        if name in _DRAIN_SPANS) / 1e6, 4)
            return out
        finally:
            tpu.stop()
            TR.reset_tracing()

    on = leg({})
    off = leg({"spark.rapids.sql.kernel.enabled": "false"})
    per_kernel = {}
    for name in _KERNEL_NAMES:
        per_kernel[name] = leg(
            {f"spark.rapids.sql.kernel.{name}.enabled": "false"},
            traced=False, runs=1)

    def decode_fused_ab() -> dict:
        """Fused single-program decode vs the stock XLA chain at equal
        run counts: the stock ``on`` leg IS the fused leg (decodeFused
        defaults on), so only the chain side runs fresh."""
        chain = leg(
            {"spark.rapids.sql.kernel.decodeFused.enabled": "false"},
            traced=False, runs=2)
        ab = {
            "fused": {
                "wall_s": on["wall_s"],
                "programsPerBatch": on.get("decodeProgramsPerBatch")},
            "chain": {
                "wall_s": chain["wall_s"],
                "programsPerBatch": chain.get(
                    "decodeProgramsPerBatch")},
        }
        if on["wall_s"]:
            ab["wallSpeedup"] = round(
                chain["wall_s"] / on["wall_s"], 4)
        return ab

    def autotune_leg() -> dict:
        """Cold sweep cost vs warm-start zero-cost: a first leg against
        a fresh tuning dir sweeps each (kernel, bucket) once during
        warm-up; after a simulated restart (tables dropped, file kept)
        the second leg must load every winner off disk and perform ZERO
        sweeps. Totals include session build + warm-up, so the sweep
        cost shows up in coldTotal_s vs warmTotal_s."""
        import tempfile

        from spark_rapids_tpu.kernels import autotune as AT
        d = tempfile.mkdtemp(prefix="bench-kernel-autotune-")
        extra = {"spark.rapids.sql.kernel.autotune.enabled": "true",
                 "spark.rapids.sql.kernel.autotune.dir": d}
        try:
            AT.reset_for_tests()
            t0 = time.perf_counter()
            cold = leg(extra, traced=False, runs=1)
            cold_total = time.perf_counter() - t0
            cold_stats = AT.stats()
            AT.reset_for_tests()  # "restart": memory gone, file kept
            t0 = time.perf_counter()
            warm = leg(extra, traced=False, runs=1)
            warm_total = time.perf_counter() - t0
            warm_stats = AT.stats()
            return {
                "coldWall_s": cold["wall_s"],
                "coldTotal_s": round(cold_total, 4),
                "coldSweeps": cold_stats["sweeps"],
                "rejected": cold_stats["rejected"],
                "warmWall_s": warm["wall_s"],
                "warmTotal_s": round(warm_total, 4),
                "warmSweeps": warm_stats["sweeps"],
                "warmLoaded": warm_stats["loaded"],
                "warmHits": warm_stats["hits"],
            }
        finally:
            AT.reset_for_tests()
            shutil.rmtree(d, ignore_errors=True)

    out = {
        "skipped": False,
        "pallasMode": mode,
        "clean_wall_s": round(clean_wall, 4),
        "kernelsOn": on,
        "kernelsOff": off,
        "oneKernelOff": per_kernel,
        "wallSpeedup": round(off["wall_s"] / on["wall_s"], 4),
        "decodeFused": decode_fused_ab(),
        "autotune": autotune_leg(),
    }
    if on.get("aggDrainSelf_s") and off.get("aggDrainSelf_s"):
        out["aggDrainSpeedup"] = round(
            off["aggDrainSelf_s"] / on["aggDrainSelf_s"], 4)
    if mode != "native":
        out["note"] = ("interpret-mode emulation: parity/counters are "
                       "real, walls are not representative of TPU "
                       "kernel performance")
    return out


def run_serving(clean_wall: float, cpu_rows, q3_cpu_rows) -> dict:
    """Mixed q1/q3 workload through the query server
    (docs/serving.md): sustained QPS and p50/p99 latency at
    concurrency 1/4/16, plan-cache and jit-cache hit rates warm vs
    cold, per-tenant queue waits. Results are asserted bit-identical
    to the CPU oracle on every request. Skips gracefully when the
    server cannot bind."""
    import threading

    from spark_rapids_tpu.plan_cache import PLAN_CACHE
    from spark_rapids_tpu.serve import QueryServer, ServeClient
    from spark_rapids_tpu.serve.scheduler import percentile
    fresh_leg()
    conf = dict(TPU_CONF)
    # admission sized for the c=16 leg: queries queue rather than reject
    conf.update({
        "spark.rapids.sql.serve.maxConcurrentQueries": "4",
        "spark.rapids.sql.serve.maxQueued": "64",
        "spark.rapids.sql.serve.maxConcurrentPerTenant": "4",
    })
    try:
        srv = QueryServer(conf).start()
    except OSError as e:
        return {"skipped": True, "reason": f"cannot bind: {e!r}"}
    try:
        srv.register_view("lineitem", DATA_DIR)
        for name in ("item", "date_dim", "store_sales"):
            srv.register_view(name, os.path.join(TPCDS_DIR, name))

        def check(kind, rows):
            assert_rows_match(cpu_rows if kind == "q1" else q3_cpu_rows,
                              rows)

        # cold: first submission of each shape populates plan cache +
        # jit caches through the server path
        cold_stats = {"hits0": PLAN_CACHE.hits,
                      "misses0": PLAN_CACHE.misses}
        t0 = time.perf_counter()
        with ServeClient(srv.port, tenant="warmup") as c:
            b, _ = c.sql(Q1)
            check("q1", [tuple(r) for r in b.rows()])
            b, _ = c.sql(TPCDS_Q3)
            check("q3", [tuple(r) for r in b.rows()])
        cold_s = time.perf_counter() - t0
        cold = {
            "wall_s": round(cold_s, 4),
            "planCacheMisses": PLAN_CACHE.misses - cold_stats["misses0"],
            "planCacheHits": PLAN_CACHE.hits - cold_stats["hits0"],
        }

        legs = {}
        n_queries = int(os.environ.get("BENCH_SERVE_QUERIES", "8"))
        for concurrency in (1, 4, 16):
            h0, m0 = PLAN_CACHE.hits, PLAN_CACHE.misses
            total = max(n_queries, concurrency)
            lat: list = []
            errors: list = []
            lat_lock = threading.Lock()

            def worker(i):
                try:
                    with ServeClient(srv.port,
                                     tenant=f"t{i % 4}") as c:
                        kind = "q1" if i % 2 == 0 else "q3"
                        tq = time.perf_counter()
                        b, _h = c.sql(Q1 if kind == "q1" else TPCDS_Q3)
                        dt = time.perf_counter() - tq
                        check(kind, [tuple(r) for r in b.rows()])
                        with lat_lock:
                            lat.append(dt)
                except Exception as e:  # noqa: BLE001 - reported below
                    errors.append(repr(e))

            t0 = time.perf_counter()
            threads = []
            for i in range(total):
                t = threading.Thread(target=worker, args=(i,))
                t.start()
                threads.append(t)
                # cap live threads at the leg's concurrency
                while sum(1 for x in threads if x.is_alive()) \
                        >= concurrency:
                    time.sleep(0.005)
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if errors:
                legs[f"c{concurrency}"] = {"errors": errors[:3]}
                continue
            hits = PLAN_CACHE.hits - h0
            misses = PLAN_CACHE.misses - m0
            legs[f"c{concurrency}"] = {
                "queries": total,
                "wall_s": round(wall, 4),
                "qps": round(total / wall, 4),
                "latency_s": {
                    "p50": round(percentile(lat, 0.50), 4),
                    "p99": round(percentile(lat, 0.99), 4),
                },
                "planCacheHitRate": round(
                    hits / max(1, hits + misses), 4),
            }
        st = srv.stats()
        jit = st["jitCaches"]
        warm_hit_rates = {
            name: round(s["hits"] / max(1, s["hits"] + s["misses"]), 4)
            for name, s in sorted(jit.items())
            if s["hits"] + s["misses"] > 0}
        return {
            "skipped": False,
            "clean_wall_s": round(clean_wall, 4),
            "cold": cold,
            "concurrency": legs,
            "admission": st["admission"],
            "tenantsHBM": st["tenantsHBM"],
            "jitCacheHitRates": warm_hit_rates,
        }
    finally:
        srv.shutdown()


def run_result_cache(clean_wall: float, cpu_rows, q3_cpu_rows) -> dict:
    """detail.resultCache (docs/caching.md): dashboard-replay QPS at
    c=16 — the same mixed q1/q3 workload replayed against a cache-off
    server (cold: every query executes) and a result-cache server after
    one priming pass per shape (warm: hits serve payload bytes from
    memory) — plus the subplan-cache join build-time delta on repeated
    q3. Every response, cached or executed, is asserted bit-identical
    to the CPU oracle. Skips gracefully when the server cannot bind."""
    import threading

    from spark_rapids_tpu.serve import QueryServer, ServeClient

    def check(kind, rows):
        assert_rows_match(cpu_rows if kind == "q1" else q3_cpu_rows,
                          rows)

    def serve(extra: dict) -> "QueryServer":
        conf = dict(TPU_CONF)
        conf.update({
            "spark.rapids.sql.serve.maxConcurrentQueries": "4",
            "spark.rapids.sql.serve.maxQueued": "64",
            "spark.rapids.sql.serve.maxConcurrentPerTenant": "4",
        })
        conf.update(extra)
        srv = QueryServer(conf).start()
        srv.register_view("lineitem", DATA_DIR)
        for name in ("item", "date_dim", "store_sales"):
            srv.register_view(name, os.path.join(TPCDS_DIR, name))
        return srv

    def replay(port: int, total: int, concurrency: int = 16):
        errors: list = []

        def worker(i):
            try:
                with ServeClient(port, tenant=f"dash{i % 4}") as c:
                    kind = "q1" if i % 2 == 0 else "q3"
                    b, _ = c.sql(Q1 if kind == "q1" else TPCDS_Q3)
                    check(kind, [tuple(r) for r in b.rows()])
            except Exception as e:  # noqa: BLE001 - reported below
                errors.append(repr(e))

        t0 = time.perf_counter()
        threads = []
        for i in range(total):
            t = threading.Thread(target=worker, args=(i,))
            t.start()
            threads.append(t)
            while sum(1 for x in threads if x.is_alive()) \
                    >= concurrency:
                time.sleep(0.005)
        for t in threads:
            t.join()
        return time.perf_counter() - t0, errors

    fresh_leg()
    total = int(os.environ.get("BENCH_REPLAY_QUERIES", "32"))

    # cold side: caches off — every replayed query admits and executes
    try:
        srv = serve({})
    except OSError as e:
        return {"skipped": True, "reason": f"cannot bind: {e!r}"}
    try:
        cold_wall, errors = replay(srv.port, total)
        if errors:
            return {"skipped": True, "reason": errors[:3]}
    finally:
        srv.shutdown()

    # warm side: result cache on — one priming pass per shape, then
    # the identical replay; hits bypass admission and device work
    srv = serve({
        "spark.rapids.sql.resultCache.enabled": "true",
        "spark.rapids.sql.subplanCache.enabled": "true",
    })
    try:
        with ServeClient(srv.port, tenant="prime") as c:
            b, _ = c.sql(Q1)
            check("q1", [tuple(r) for r in b.rows()])
            b, _ = c.sql(TPCDS_Q3)
            check("q3", [tuple(r) for r in b.rows()])
        warm_wall, errors = replay(srv.port, total)
        if errors:
            return {"skipped": True, "reason": errors[:3]}
        rc = srv.stats().get("cache", {}).get("result", {})
    finally:
        srv.shutdown()
    probes = rc.get("hits", 0) + rc.get("misses", 0)
    out = {
        "skipped": False,
        "clean_wall_s": round(clean_wall, 4),
        "replay": {
            "queries": total,
            "coldWall_s": round(cold_wall, 4),
            "coldQps": round(total / cold_wall, 4),
            "warmWall_s": round(warm_wall, 4),
            "warmQps": round(total / warm_wall, 4),
            "qpsSpeedup": round(cold_wall / max(1e-9, warm_wall), 4),
            "hitRate": round(rc.get("hits", 0) / max(1, probes), 4),
            "result": rc,
        },
    }

    # subplan leg: result cache OFF so repeats re-execute, subplan
    # cache ON so the q3 join build tables are reused — the wall delta
    # between the first (building) and best repeated run is the
    # build-time saving
    from spark_rapids_tpu.serve import result_cache as RC
    RC.reset_subplan_cache()
    srv = serve({"spark.rapids.sql.subplanCache.enabled": "true"})
    try:
        walls = []
        with ServeClient(srv.port, tenant="sub") as c:
            for _ in range(3):
                tq = time.perf_counter()
                b, _ = c.sql(TPCDS_Q3)
                walls.append(time.perf_counter() - tq)
                check("q3", [tuple(r) for r in b.rows()])
        sp = srv.stats().get("cache", {}).get("subplan", {})
    finally:
        srv.shutdown()
    out["subplan"] = {
        "buildWall_s": round(walls[0], 4),
        "reuseWall_s": round(min(walls[1:]), 4),
        "buildSpeedup": round(
            walls[0] / max(1e-9, min(walls[1:])), 4),
        "stats": sp,
    }
    return out


def run_lifecycle(clean_wall: float, cpu_rows) -> dict:
    """detail.lifecycle (docs/serving.md "Query lifecycle"): cancel
    latency p50/p99 (cancel verb fired against a running q1; latency =
    cancel send -> status:cancelled on the submitter's wire), a
    deadline leg asserting the cancelled response lands within the
    deadline + one batch interval, graceful-drain wall with in-flight
    queries, and the poison-query quarantine's fail-fast behavior."""
    import threading

    from spark_rapids_tpu import lifecycle as LC
    from spark_rapids_tpu import retry as R
    from spark_rapids_tpu.serve import QueryServer, ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled, ServeError
    from spark_rapids_tpu.serve.scheduler import percentile
    fresh_leg()
    conf = dict(TPU_CONF)
    conf.update({
        "spark.rapids.sql.serve.maxConcurrentQueries": "4",
        "spark.rapids.sql.serve.maxQueued": "16",
        "spark.rapids.sql.serve.maxConcurrentPerTenant": "4",
    })
    try:
        srv = QueryServer(conf).start()
    except OSError as e:
        return {"skipped": True, "reason": f"cannot bind: {e!r}"}
    cancel_lat: list = []
    completed_before_cancel = 0
    deadline_leg = {}
    try:
        srv.register_view("lineitem", DATA_DIR)
        with ServeClient(srv.port, tenant="warm") as c:
            b, _ = c.sql(Q1)
            assert_rows_match(cpu_rows, [tuple(r) for r in b.rows()])

        # -- cancel latency: q1 runs multiple seconds at SF1, so a
        # cancel fired shortly after submit lands mid-execution
        for i in range(5):
            state = {}
            done = threading.Event()

            def submit(qid=f"bench-cancel-{i}"):
                try:
                    with ServeClient(srv.port, tenant="cancelme") as c:
                        c.sql(Q1, query_id=qid)
                        state["outcome"] = "ok"
                except ServeCancelled:
                    state["t_resp"] = time.perf_counter()
                    state["outcome"] = "cancelled"
                except ServeError as e:
                    state["outcome"] = f"error: {e}"
                finally:
                    done.set()

            t = threading.Thread(target=submit)
            t.start()
            time.sleep(0.3)
            t_cancel = time.perf_counter()
            with ServeClient(srv.port) as cc:
                n = cc.cancel(query_id=f"bench-cancel-{i}",
                              tenant="cancelme")
            done.wait(timeout=120)
            t.join(timeout=10)
            if n and state.get("outcome") == "cancelled":
                cancel_lat.append(state["t_resp"] - t_cancel)
            else:
                completed_before_cancel += 1

        # -- deadline: the cancelled response must land within the
        # deadline + one batch interval (acceptance criterion)
        deadline_ms = 400
        t0 = time.perf_counter()
        try:
            with ServeClient(srv.port, tenant="deadline") as c:
                c.sql(Q1, timeout_ms=deadline_ms)
            deadline_leg = {"outcome": "completed under deadline"}
        except ServeCancelled as e:
            resp_ms = (time.perf_counter() - t0) * 1e3
            deadline_leg = {
                "outcome": "cancelled",
                "reason": e.reason,
                "deadlineMs": deadline_ms,
                "responseMs": round(resp_ms, 1),
                # one batch interval of slack: the checkpoint slice is
                # 50ms; generous bound for the verdict flag
                "withinBound": resp_ms <= deadline_ms + 1000,
            }

        # -- graceful drain with in-flight queries
        def drain_worker(i: int) -> None:
            try:
                with ServeClient(srv.port, tenant=f"drain{i}") as c:
                    c.sql(Q1)
            except ServeError:
                pass  # a straggler cancel is a valid drain outcome

        inflight = []
        for i in range(2):
            t = threading.Thread(target=drain_worker, args=(i,))
            t.start()
            inflight.append(t)
        time.sleep(0.3)
        t0 = time.perf_counter()
        drained = srv.shutdown(timeout=120)
        drain_s = time.perf_counter() - t0
        for t in inflight:
            t.join(timeout=30)
        drain_leg = {"drained": drained, "drain_s": round(drain_s, 3)}
    finally:
        srv.shutdown(timeout=10)

    # -- quarantine: a signature that fails K consecutive times fails
    # fast afterwards (fresh server; IO injection makes every scan
    # runtime-fatal quickly and deterministically)
    R.reset_fault_injection()
    LC.reset_lifecycle()
    qconf = dict(TPU_CONF)
    qconf.update({
        "spark.rapids.sql.test.injectIOError": "1:99",
        "spark.rapids.sql.reader.maxRetries": "1",
        "spark.rapids.sql.serve.quarantineThreshold": "2",
    })
    quarantine = {}
    try:
        qsrv = QueryServer(qconf).start()
        try:
            qsrv.register_view("lineitem", DATA_DIR)
            statuses = []
            fail_fast_ms = None
            for i in range(3):
                t0 = time.perf_counter()
                try:
                    with ServeClient(qsrv.port, tenant="poison") as c:
                        c.sql(Q1)
                    statuses.append("ok")
                except ServeError as e:
                    statuses.append(type(e).__name__)
                    if i == 2:
                        fail_fast_ms = round(
                            (time.perf_counter() - t0) * 1e3, 1)
            quarantine = {
                "statuses": statuses,
                "thirdFailedFast": statuses[2:] == ["ServeQuarantined"],
                "failFastMs": fail_fast_ms,
            }
        finally:
            qsrv.shutdown(timeout=30)
    except OSError as e:
        quarantine = {"skipped": True, "reason": f"cannot bind: {e!r}"}
    finally:
        R.reset_fault_injection()
        LC.reset_lifecycle()

    return {
        "skipped": False,
        "clean_wall_s": round(clean_wall, 4),
        "cancelLatency": {
            "samples": len(cancel_lat),
            "completedBeforeCancel": completed_before_cancel,
            "p50_s": round(percentile(cancel_lat, 0.50), 4),
            "p99_s": round(percentile(cancel_lat, 0.99), 4),
        },
        "deadline": deadline_leg,
        "drain": drain_leg,
        "quarantine": quarantine,
    }


def run_telemetry(clean_wall: float, cpu_rows) -> dict:
    """detail.telemetry (docs/observability.md "Live telemetry"): the
    q1 ring-recorder overhead ratio vs trace fully off (budget
    <= 1.05x — INTERLEAVED walls so machine drift can't masquerade as
    recorder overhead), the Prometheus endpoint's scrape latency while
    c=4 queries run, and one forced slow-query bundle round trip (ring
    dump loads in the trace analyzer, bundle names its condition)."""
    import glob
    import threading

    from spark_rapids_tpu import trace as TR
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.telemetry import triggers as TEL
    from spark_rapids_tpu.tools import analyze_trace

    # -- ring-recorder overhead (interleaved best-of) ----------------------
    TR.reset_tracing()
    fresh_leg()
    off = TpuSparkSession(dict(TPU_CONF))
    on = TpuSparkSession({**TPU_CONF,
                          "spark.rapids.sql.trace.enabled": "true",
                          "spark.rapids.sql.trace.mode": "ring"})
    try:
        q_off, q_on = build_query(off), build_query(on)
        run_once(q_off)  # warm (compile caches are process-wide)
        run_once(q_on)
        offs, ons = [], []
        for _ in range(2):
            dt, rows_off = run_once(q_off)
            offs.append(dt)
            dt, rows_on = run_once(q_on)
            ons.append(dt)
        assert_rows_match(cpu_rows, rows_off)
        assert_rows_match(cpu_rows, rows_on)
        ring = TR.ring_active()
        ring_counts = ring.record_counts() if ring is not None else {}
    finally:
        on.stop()
        off.stop()
        TR.reset_tracing()
    out = {
        "skipped": False,
        "clean_wall_s": round(clean_wall, 4),
        "ringWall_s": round(min(ons), 4),
        "offWall_s": round(min(offs), 4),
        "ringOverhead": round(min(ons) / min(offs), 4),
        "ringOverheadBudget": 1.05,
        "ringRecordCounts": ring_counts,
    }

    # -- endpoint scrape under load + forced slow-query bundle -------------
    tdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", "telemetry")
    shutil.rmtree(tdir, ignore_errors=True)
    from spark_rapids_tpu.serve import QueryServer, ServeClient
    TEL.engine().reset()
    conf = dict(TPU_CONF)
    conf.update({
        "spark.rapids.sql.telemetry.dir": tdir,
        # every query is "slow": one forced bundle, then rate-limited
        "spark.rapids.sql.telemetry.slowQueryMs": "1",
        "spark.rapids.sql.telemetry.triggerMinIntervalS": "3600",
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": os.path.join(tdir, "profiles"),
    })
    try:
        srv = QueryServer(conf).start()
    except OSError as e:
        out["endpoint"] = {"skipped": True,
                           "reason": f"cannot bind: {e!r}"}
        return out
    try:
        srv.register_view("lineitem", DATA_DIR)
        stop = threading.Event()
        errors: list = []

        def load_worker(i):
            try:
                with ServeClient(srv.port, tenant=f"t{i % 2}") as c:
                    while not stop.is_set():
                        c.sql(Q1)
            except Exception as e:  # noqa: BLE001 - reported below
                if not stop.is_set():
                    errors.append(repr(e))

        workers = [threading.Thread(target=load_worker, args=(i,))
                   for i in range(4)]
        for w in workers:
            w.start()
        time.sleep(0.5)  # let the first queries land
        scrape_lat = []
        with ServeClient(srv.port, tenant="scraper") as sc:
            for _ in range(20):
                t0 = time.perf_counter()
                text = sc.metrics()
                scrape_lat.append(time.perf_counter() - t0)
        stop.set()
        for w in workers:
            w.join(timeout=120)
        from spark_rapids_tpu.serve.scheduler import percentile
        out["endpoint"] = {
            "scrapes": len(scrape_lat),
            "scrapeLatencyMs": {
                "p50": round(percentile(scrape_lat, 0.50) * 1e3, 3),
                "p99": round(percentile(scrape_lat, 0.99) * 1e3, 3),
            },
            "families": sum(1 for ln in text.splitlines()
                            if ln.startswith("# TYPE ")),
            "loadErrors": errors[:3],
        }
        TEL.engine().drain(timeout=30)
        bundles = sorted(glob.glob(os.path.join(tdir, "bundle-*.json")))
        bundle_leg = {"bundles": len(bundles)}
        if bundles:
            with open(bundles[0]) as f:
                b = json.load(f)
            bundle_leg["trigger"] = b.get("trigger")
            bundle_leg["condition"] = b.get("condition")
            bundle_leg["hasProfile"] = bool(b.get("profile"))
            bundle_leg["hasServerStats"] = bool(b.get("serverStats"))
            ring_dump = b.get("ringDump")
            if ring_dump and os.path.exists(ring_dump):
                analysis = analyze_trace(ring_dump)
                bundle_leg["ringDumpSpans"] = analysis.get(
                    "spanCount", 0)
        out["slowQueryBundle"] = bundle_leg
        out["triggerStats"] = TEL.engine().stats()
        out["triggerStats"].pop("bundles", None)
    finally:
        srv.shutdown()
        TEL.engine().reset()
        TR.reset_tracing()
    return out


def run_history(clean_wall: float, cpu_rows) -> dict:
    """detail.history (docs/observability.md "Query history"): the q1
    history-append overhead ratio (interleaved on/off walls, budget
    <= 1.05x), a doctor round trip on a FORCED slow query (OOM storm
    injected via the process injector while the session conf — and so
    the plan signature — stays identical to the baseline runs), and a
    warm-start leg proving the watchdog p99 is available with ZERO
    fresh samples after a lifecycle reset."""
    from spark_rapids_tpu import lifecycle as LC
    from spark_rapids_tpu import retry as R
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.telemetry import history as H
    from spark_rapids_tpu.telemetry.doctor import diagnose

    hdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", "history")
    shutil.rmtree(hdir, ignore_errors=True)
    H.reset_history()
    LC.reset_lifecycle()
    R.reset_fault_injection()
    fresh_leg()

    # -- append overhead (interleaved best-of; the sessions differ in
    # ONE variable — history.dir — so the ratio measures the append
    # path alone, not profile writing or plan-cache savings) ---------------
    off = TpuSparkSession({
        **TPU_CONF,
        "spark.rapids.sql.planCache.enabled": "true",
    })
    on = TpuSparkSession({
        **TPU_CONF,
        "spark.rapids.sql.planCache.enabled": "true",
        "spark.rapids.sql.telemetry.history.dir": hdir,
    })
    prof_conf = {
        **TPU_CONF,
        "spark.rapids.sql.planCache.enabled": "true",
        "spark.rapids.sql.telemetry.history.dir": hdir,
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": os.path.join(hdir, "profiles"),
        # consulted only when retries happen — harmless on the clean
        # baseline runs, but it must live in the BASELINE conf too so
        # the storm session's plan signature matches
        "spark.rapids.sql.retry.backoffMs": "20",
        "spark.rapids.sql.retry.maxBackoffMs": "200",
    }
    prof = TpuSparkSession(prof_conf)
    try:
        q_off, q_on = build_query(off), build_query(on)
        run_once(q_off)  # warm
        run_once(q_on)
        offs, ons = [], []
        for _ in range(2):
            dt, rows_off = run_once(q_off)
            offs.append(dt)
            dt, rows_on = run_once(q_on)
            ons.append(dt)
        assert_rows_match(cpu_rows, rows_off)
        assert_rows_match(cpu_rows, rows_on)

        # -- doctor round trip on a forced slow query ----------------------
        # baseline runs with profile artifacts (the doctor's stage
        # source), then the storm on a session whose conf adds ONLY
        # the injection schedule — test.inject* keys are excluded from
        # the plan signature, so the storm query diffs against these
        # baselines, exactly the situation `tools doctor` exists for
        q_prof = build_query(prof)
        # 4 baselines + the storm = 5 finished records for this
        # signature, the watchdog's minimum sample count — so the
        # warm-start leg below proves p99 availability
        for _ in range(4):
            _, base_rows = run_once(q_prof)
        assert_rows_match(cpu_rows, base_rows)
        storm_sess = TpuSparkSession({
            **prof_conf,
            "spark.rapids.sql.test.injectOOM": "4:2",
        })
        try:
            q_storm = build_query(storm_sess)
            t0 = time.perf_counter()
            _, storm_rows = run_once(q_storm)
            storm_wall = time.perf_counter() - t0
            assert_rows_match(cpu_rows, storm_rows)
        finally:
            storm_sess.stop()
            R.reset_fault_injection()
        recs = H.read_records(hdir)
        storm = recs[-1]
        t0 = time.perf_counter()
        diag = diagnose(hdir, str(storm.get("queryId")))
        doctor_ms = (time.perf_counter() - t0) * 1e3
        doctor_leg = {
            "records": len(recs),
            "stormWall_s": round(storm_wall, 4),
            "stormRetries": storm.get("retryCount", 0),
            "verdict": diag.get("verdict"),
            "divergentStage": diag.get("divergentStage"),
            "roundTripMs": round(doctor_ms, 1),
        }

        # -- warm-start: watchdog p99 with zero fresh samples --------------
        sig = storm.get("signature")
        LC.reset_lifecycle()  # the "restart"
        assert LC.signature_p99(sig) is None
        ws = H.warm_start(on.conf_obj)
        warm_leg = {
            "summary": ws,
            "p99AvailableWithZeroFreshSamples":
                LC.signature_p99(sig) is not None,
        }
    finally:
        prof.stop()
        on.stop()
        off.stop()
        R.reset_fault_injection()
        LC.reset_lifecycle()
        H.reset_history()
    return {
        "skipped": False,
        "clean_wall_s": round(clean_wall, 4),
        "historyWall_s": round(min(ons), 4),
        "offWall_s": round(min(offs), 4),
        "appendOverhead": round(min(ons) / min(offs), 4),
        "appendOverheadBudget": 1.05,
        "doctor": doctor_leg,
        "warmStart": warm_leg,
    }


def run_tuning(clean_wall: float, cpu_rows) -> dict:
    """detail.tuning (docs/tuning.md): the feedback-control loop end
    to end. A forced compileStorm verdict (synthetic regressed record
    with a jit-miss storm) puts the q1 signature in the pre-warm
    ledger and a server RESTART serves the first request from the
    pre-warmed plan cache; a site:tuning injected harmful action
    auto-reverts within the guard window (visible in the stats, the
    history store, srt_tuning_* and the `tools tuning` table); a
    forced kernelFallback verdict flips the culprit kernel conf
    server-wide with results still bit-identical to the CPU oracle.
    The controller tick interval is parked at 3600s so the LEG drives
    every tick — each phase is deterministic, not timing-dependent."""
    from spark_rapids_tpu import lifecycle as LC
    from spark_rapids_tpu import plan_cache as PC
    from spark_rapids_tpu import retry as R
    from spark_rapids_tpu.plan_cache import PLAN_CACHE
    from spark_rapids_tpu.serve import QueryServer, ServeClient
    from spark_rapids_tpu.telemetry import history as H
    from spark_rapids_tpu.telemetry import tuning as T

    hdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench-data", "tuning")
    shutil.rmtree(hdir, ignore_errors=True)
    H.reset_history()
    R.reset_fault_injection()
    fresh_leg()
    kernel_key = "spark.rapids.sql.kernel.groupbyHash.enabled"
    conf = {
        **TPU_CONF,
        "spark.rapids.sql.planCache.enabled": "true",
        "spark.rapids.sql.telemetry.history.dir": hdir,
        "spark.rapids.sql.serve.tuning.enabled": "true",
        "spark.rapids.sql.serve.tuning.intervalS": "3600",
        "spark.rapids.sql.serve.tuning.guardWindowQueries": "2",
        # the 3rd scan tick applies the synthetic harmful action
        "spark.rapids.sql.test.injectOOM": "site:tuning:3",
    }

    def new_server():
        srv = QueryServer(dict(conf))
        srv.register_view("lineitem", DATA_DIR)
        return srv.start()

    def run_q1(client):
        t0 = time.perf_counter()
        b, _h = client.sql(Q1)
        dt = time.perf_counter() - t0
        assert_rows_match(cpu_rows, [tuple(r) for r in b.rows()])
        return dt

    try:
        srv = new_server()  # tick 1: empty history, no actions
    except OSError as e:
        return {"skipped": True, "reason": f"cannot bind: {e!r}"}
    try:
        # -- learn: q1 records + the sql<->signature pairing ---------------
        with ServeClient(srv.port, tenant="bench") as c:
            cold_first_s = run_q1(c)
            for _ in range(2):
                run_q1(c)
        tun = srv._tuning
        sig = tun.signature_hint(Q1)
        store = H.HistoryStore(hdir, 1 << 30, 14)
        walls = sorted(float(r.get("wallSeconds", 0))
                       for r in H.read_records(hdir)
                       if r.get("signature") == sig)
        p50 = walls[len(walls) // 2]

        # -- forced compileStorm: a synthetic regressed record with a
        # jit-miss storm makes the doctor verdict deterministic ------------
        store.append({"version": 1, "ts": time.time(), "signature": sig,
                      "status": "finished",
                      "wallSeconds": 3 * p50 + 0.05,
                      "queueWaitSeconds": 0.0, "outputRows": 4,
                      "jitMisses": 64})
        tun.tick()  # tick 2: applies prewarmCaches for sig
        prewarmed = sig in (T.load_state(hdir).get("prewarm") or {})

        tun.tick()  # tick 3: site:tuning fires -> harmful clamp on sig
        injected = [a for a in tun.actions()
                    if (a.get("evidence") or {}).get("injected")]
        clamped = srv._admission.signature_limit(sig)

        # guard window: two clean post-action q1 runs, then the judge
        with ServeClient(srv.port, tenant="bench") as c:
            for _ in range(2):
                run_q1(c)
        tun.tick()  # tick 4: guardrail reverts the injected action
        reverted = [a for a in tun.actions()
                    if (a.get("evidence") or {}).get("injected")
                    and a.get("state") == "reverted"]
        guard = {
            "injected": len(injected),
            "clampApplied": clamped == 1,
            "autoReverted": 1.0 if reverted else 0.0,
            "clampCleared": srv._admission.signature_limit(sig) is None,
            "revertVisible": {
                "metrics": "srt_tuning_reverts_total 1"
                           in srv.metrics_text(),
                "history": any(r.get("status") == "revert"
                               for r in H.read_records(hdir)),
                "cli": "reverted" in T.format_tuning(T.load_state(hdir)),
            },
        }

        # -- forced kernelFallback: a synthetic signature whose newest
        # record names the culprit kernel -> server-wide conf flip ---------
        sig2 = "b" * 40
        t0 = time.time()
        for i in range(4):
            store.append({"version": 1, "ts": t0 - 40 + i,
                          "signature": sig2, "status": "finished",
                          "wallSeconds": 0.05,
                          "queueWaitSeconds": 0.0, "outputRows": 4})
        store.append({"version": 1, "ts": t0, "signature": sig2,
                      "status": "finished", "wallSeconds": 0.5,
                      "queueWaitSeconds": 0.0, "outputRows": 4,
                      "kernelFallbacks": 6,
                      "kernelFallbacksByName": {"groupbyHash": 6}})
        tun.tick()  # tick 5: flips kernel_key to false
        flipped = str(tun._get_conf(kernel_key)).lower() == "false"
        with ServeClient(srv.port, tenant="bench") as c:
            flipped_wall = run_q1(c)  # bit-identity holds post-flip
        stats_before_restart = srv.stats().get("tuning") or {}
    finally:
        srv.shutdown()

    # -- restart: persisted actions re-apply, the pre-warm ledger
    # replays, and the FIRST request hits the plan cache ------------------
    PLAN_CACHE.clear()
    LC.reset_lifecycle()
    R.reset_fault_injection()
    try:
        srv = new_server()
        try:
            replayed = srv._tuning.prewarm_replayed
            h0 = PLAN_CACHE.hits
            with ServeClient(srv.port, tenant="bench") as c:
                warm_first_s = run_q1(c)
            hit = PLAN_CACHE.hits - h0
            prewarm_leg = {
                "ledgered": prewarmed,
                "replayed": replayed,
                "hitOnRestart": 1.0 if hit >= 1 else 0.0,
                "firstRequestCold_s": round(cold_first_s, 4),
                "firstRequestWarm_s": round(warm_first_s, 4),
                "restartSpeedup": round(cold_first_s / warm_first_s, 4),
            }
        finally:
            srv.shutdown()
    finally:
        PC.set_prewarm_digests(set())
        PLAN_CACHE.clear()
        LC.reset_lifecycle()
        R.reset_fault_injection()
        H.reset_history()
    return {
        "skipped": False,
        "clean_wall_s": round(clean_wall, 4),
        "prewarm": prewarm_leg,
        "kernelFallback": {
            "flipped": 1.0 if flipped else 0.0,
            "conf": kernel_key,
            "postFlipWall_s": round(flipped_wall, 4),
            "bitIdentical": True,  # run_q1 asserted it
        },
        "guard": guard,
        "controller": stats_before_restart,
    }


def _adaptive_skew_query(spark):
    """A shuffled join with ONE hot key at ~20x the median partition
    (48 base keys spread the other partitions; the right side is small
    but broadcast is disabled in the leg conf, so the skew-split replan
    is the adaptive action under test)."""
    rep = 24
    lk = [100 + (i % 48) for i in range(48 * rep)]
    lk += [7] * (rep * 12 * 20)
    lv = list(range(len(lk)))
    rk = list(range(100, 148)) * 2 + [7, 7]
    rw = [i * 10 for i in range(len(rk))]
    left = spark.createDataFrame({"k": lk, "v": lv}, "k int, v long",
                                 num_partitions=3)
    right = spark.createDataFrame({"k2": rk, "w": rw},
                                  "k2 int, w long", num_partitions=2)
    from spark_rapids_tpu.sql import functions as F
    return (left.join(right, left["k"] == right["k2"], "inner")
            .groupBy("k").agg(F.sum("v").alias("sv"),
                              F.sum("w").alias("sw"),
                              F.count("*").alias("c"))
            .orderBy("k"))


def run_adaptive(clean_wall: float) -> dict:
    """detail.adaptive (docs/adaptive.md): (a) skewed-join wall A/B —
    the adaptive run skew-splits the hot partition and completes clean
    (retryCount == 0) while the unadaptive run of the same shape rides
    an injected OOM storm (the CPU backend's DeviceStore spills instead
    of raising, so the deterministic storm stands in for the monolithic
    hot partition blowing HBM on real hardware, exactly like
    detail.robustness) — both bit-identical to the CPU oracle;
    (b) AQE partition coalescing on a mostly-empty exchange: dispatch
    count adaptive-on vs adaptive-off; (c) same-signature serving: 16
    concurrent same-template queries (distinct literal bindings)
    through the server with batch fusion on vs off under ONE saturated
    admission slot, bit-identical per member."""
    import threading

    from spark_rapids_tpu import retry as RT
    from spark_rapids_tpu.sql.session import TpuSparkSession

    out = {"skipped": False, "clean_wall_s": round(clean_wall, 4)}

    # -- (a) skewed-join wall A/B -------------------------------------
    skew_conf = dict(TPU_CONF)
    skew_conf.update({
        "spark.rapids.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.sql.shuffle.devicePartitions": "4",
        "spark.rapids.sql.batchSizeRows": "512",
        "spark.rapids.sql.retry.backoffMs": "40",
        "spark.rapids.sql.retry.maxBackoffMs": "400",
    })
    cpu = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _, skew_oracle = run_once(_adaptive_skew_query(cpu))
    finally:
        cpu.stop()

    def skew_leg(extra):
        RT.reset_fault_injection()
        fresh_leg()
        conf = dict(skew_conf)
        conf.update(extra)
        spark = TpuSparkSession(conf)
        try:
            q = _adaptive_skew_query(spark)
            run_once(q)  # warm compile caches
            RT.reset_fault_injection()
            spark.start_capture()
            dt, rows = run_once(q)
            assert_rows_match(skew_oracle, rows)
            counters = collect_counters(
                spark.get_captured_plans(),
                ("retryCount", "splitRetryCount", "aqeReplans",
                 "aqeSkewSplits", "aqeBroadcastFlip"))
        finally:
            spark.stop()
            RT.reset_fault_injection()
        return dt, counters

    on_dt, on_c = skew_leg({})
    off_dt, off_c = skew_leg({
        "spark.rapids.sql.adaptive.enabled": "false",
        "spark.rapids.sql.test.injectOOM": "5"})
    assert on_c["retryCount"] == 0, on_c
    assert on_c["aqeSkewSplits"] > 0, on_c
    out["skew"] = {
        "adaptive_wall_s": round(on_dt, 4),
        "unadaptive_wall_s": round(off_dt, 4),
        "speedup": round(off_dt / on_dt, 4),
        "retryCount_adaptive": on_c["retryCount"],
        "retryCount_unadaptive": off_c["retryCount"],
        "aqeSkewSplits": on_c["aqeSkewSplits"],
        "aqeReplans": on_c["aqeReplans"],
    }

    # -- (b) coalesce dispatch delta ----------------------------------
    coalesce_conf = dict(TPU_CONF)
    coalesce_conf.update({
        "spark.rapids.sql.shuffle.devicePartitions": "8",
        "spark.rapids.sql.batchSizeRows": "512",
    })

    def coalesce_query(spark):
        from spark_rapids_tpu.sql import functions as F
        df = spark.createDataFrame(
            {"g": [i % 3 for i in range(3000)],
             "v": list(range(3000))}, "g int, v long",
            num_partitions=4)
        return df.groupBy("g").agg(F.sum("v").alias("sv")) \
                 .orderBy("g")

    def coalesce_leg(extra):
        fresh_leg()
        conf = dict(coalesce_conf)
        conf.update(extra)
        spark = TpuSparkSession(conf)
        try:
            q = coalesce_query(spark)
            run_once(q)
            spark.start_capture()
            dt, rows = run_once(q)
            counters = collect_counters(
                spark.get_captured_plans(),
                ("dispatchCount", "aqeCoalescedPartitions"))
        finally:
            spark.stop()
        return dt, rows, counters

    c_on_dt, c_on_rows, c_on = coalesce_leg({})
    c_off_dt, c_off_rows, c_off = coalesce_leg(
        {"spark.rapids.sql.adaptive.enabled": "false"})
    assert_rows_match(c_off_rows, c_on_rows)
    out["coalesce"] = {
        "adaptive_wall_s": round(c_on_dt, 4),
        "unadaptive_wall_s": round(c_off_dt, 4),
        "dispatchCount_adaptive": c_on["dispatchCount"],
        "dispatchCount_unadaptive": c_off["dispatchCount"],
        "dispatchDelta": c_off["dispatchCount"] - c_on["dispatchCount"],
        "aqeCoalescedPartitions": c_on["aqeCoalescedPartitions"],
    }

    # -- (c) same-signature batch fusion QPS A/B ----------------------
    from spark_rapids_tpu.serve import QueryServer, ServeClient

    def variant(i):
        return ("SELECT l_returnflag, count(*) AS c, "
                "sum(l_quantity) AS sq FROM lineitem "
                f"WHERE l_quantity > {i}00 "
                "GROUP BY l_returnflag ORDER BY l_returnflag")

    def fusion_leg(enabled):
        fresh_leg()
        conf = dict(TPU_CONF)
        conf.update({
            "spark.rapids.sql.serve.maxConcurrentQueries": "1",
            "spark.rapids.sql.serve.maxQueued": "64",
            "spark.rapids.sql.serve.maxConcurrentPerTenant": "32",
            "spark.rapids.sql.serve.batchFusion.enabled":
                "true" if enabled else "false",
            "spark.rapids.sql.serve.batchFusion.windowMs": "50",
            "spark.rapids.sql.serve.batchFusion.maxBatch": "16",
        })
        try:
            srv = QueryServer(conf).start()
        except OSError as e:
            return None, {"skipped": True,
                          "reason": f"cannot bind: {e!r}"}
        results: dict = {}
        errors: list = []
        try:
            srv.register_view("lineitem", DATA_DIR)
            with ServeClient(srv.port, tenant="warmup") as c:
                for i in range(4):
                    results[f"warm{i}"] = c.collect(variant(i))

            def worker(i):
                try:
                    with ServeClient(srv.port,
                                     tenant=f"t{i % 4}") as c:
                        results[i] = c.collect(variant(i % 4))
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))

            t0 = time.perf_counter()
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if errors:
                return None, {"errors": errors[:3]}
            for i in range(16):
                assert results[i] == results[f"warm{i % 4}"], (
                    f"fusion={enabled}: member {i} diverged")
            st = srv.stats()
            leg = {"wall_s": round(wall, 4),
                   "qps": round(16 / wall, 4)}
            if enabled:
                leg["batchFusion"] = st.get("batchFusion", {})
            return results, leg
        finally:
            srv.shutdown()

    r_off, leg_off = fusion_leg(False)
    r_on, leg_on = fusion_leg(True)
    fusion = {"off": leg_off, "on": leg_on}
    if r_on is not None and r_off is not None:
        for i in range(16):
            assert r_on[i] == r_off[i], (
                f"fusion on/off diverged on member {i}")
        fusion["qpsSpeedup"] = round(
            leg_on["qps"] / leg_off["qps"], 4)
    out["batchFusion"] = fusion
    return out


def run_bench_diff(current: dict) -> dict:
    """Regression tracking: diff THIS run's output against the newest
    BENCH_r0*.json in the repo (docs/observability.md 'Live
    telemetry'); the machine verdict rides in the bench JSON so the
    round trajectory is an enforced curve, not loose files."""
    from spark_rapids_tpu.telemetry.bench_diff import (bench_diff,
                                                      latest_bench_file)
    prev = latest_bench_file(os.path.dirname(os.path.abspath(__file__)))
    if prev is None:
        return {"skipped": True, "reason": "no previous BENCH_r*.json"}
    report = bench_diff(prev, current)
    return {
        "skipped": False,
        "baseline": os.path.basename(prev),
        "verdict": report["verdict"],
        "regressed": report["regressed"],
        "improved": report["improved"],
        "compared": len(report["checks"]),
        "notComparable": len(report["missing"]),
    }


def main():
    from spark_rapids_tpu.metrics import registry_snapshot
    from spark_rapids_tpu.sql.session import TpuSparkSession

    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    ensure_data(gen)
    ensure_tpcds_data(gen)
    gen.stop()

    cpu = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    q_cpu = build_query(cpu)
    run_once(q_cpu)  # warm (footer caches, numpy paths)
    cpu_times, cpu_rows = [], None
    for _ in range(3):
        dt, cpu_rows = run_once(q_cpu)
        cpu_times.append(dt)
    q3_cpu_t, q3_cpu_rows, _, _ = run_tpcds_q3(cpu)
    cpu.stop()

    # unfused FIRST (its compile misses don't warm fused-stage
    # programs; the fused pass compiles its own)
    unfused = run_tpu(fusion_enabled=False)
    fused = run_tpu(fusion_enabled=True)

    assert_rows_match(cpu_rows, fused["rows"])
    assert_rows_match(cpu_rows, unfused["rows"])
    assert_rows_match(q3_cpu_rows, fused["q3"]["rows"])
    assert_rows_match(q3_cpu_rows, unfused["q3"]["rows"])

    # decode A/B legs (host decode / unpipelined), fault-isolated like
    # every other detail leg
    try:
        decode_ab = run_decode_ab(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        decode_ab = {"skipped": True,
                     "reason": f"decode A/B leg failed: {e!r}"}

    # AFTER the primary asserts, and fault-isolated: a multichip-leg
    # failure must not discard the measured single-chip results
    try:
        multichip = run_multichip(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        multichip = {"skipped": True,
                     "reason": f"multichip leg failed: {e!r}"}

    # robustness sweep, equally fault-isolated
    try:
        robustness = run_robustness(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        robustness = {"skipped": True,
                      "reason": f"robustness leg failed: {e!r}"}

    # planned out-of-core sweep (docs/out_of_core.md): 1x/4x/10x over
    # budget, gated on the planned path staying retry-free
    try:
        out_of_core_leg = run_out_of_core(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        out_of_core_leg = {"skipped": True,
                           "reason": f"out-of-core leg failed: {e!r}"}

    # span-tracing leg (docs/observability.md), equally fault-isolated
    try:
        trace_leg = run_trace(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        trace_leg = {"skipped": True,
                     "reason": f"trace leg failed: {e!r}"}

    # query-profile leg (per-op peak HBM + explain coverage)
    try:
        profile_leg = run_profile(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        profile_leg = {"skipped": True,
                       "reason": f"profile leg failed: {e!r}"}

    # Pallas kernel tier A/B (docs/kernels.md), equally fault-isolated
    try:
        kernels_leg = run_kernels(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        kernels_leg = {"skipped": True,
                       "reason": f"kernels leg failed: {e!r}"}

    # serving leg (docs/serving.md): QPS/latency through the query
    # server at concurrency 1/4/16, equally fault-isolated
    try:
        serving = run_serving(fused["wall_s"], cpu_rows, q3_cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        serving = {"skipped": True,
                   "reason": f"serving leg failed: {e!r}"}

    # live-telemetry leg (docs/observability.md "Live telemetry"):
    # ring-recorder overhead, endpoint scrape-under-load latency, one
    # forced slow-query bundle round trip — equally fault-isolated
    try:
        telemetry_leg = run_telemetry(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        telemetry_leg = {"skipped": True,
                         "reason": f"telemetry leg failed: {e!r}"}

    # query-lifecycle leg (docs/serving.md "Query lifecycle"): cancel
    # latency, deadline bound, drain wall, quarantine fail-fast
    try:
        lifecycle_leg = run_lifecycle(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        lifecycle_leg = {"skipped": True,
                         "reason": f"lifecycle leg failed: {e!r}"}

    # query-history leg (docs/observability.md "Query history"):
    # append overhead, doctor round trip on a forced slow query,
    # warm-start watchdog availability — equally fault-isolated
    try:
        history_leg = run_history(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        history_leg = {"skipped": True,
                       "reason": f"history leg failed: {e!r}"}

    # self-tuning leg (docs/tuning.md): forced compileStorm pre-warm
    # hit on restart, forced kernelFallback conf flip, injected
    # harmful action auto-reverted by the guardrail
    try:
        tuning_leg = run_tuning(fused["wall_s"], cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        tuning_leg = {"skipped": True,
                      "reason": f"tuning leg failed: {e!r}"}

    # adaptive-execution leg (docs/adaptive.md): skewed-join replan
    # A/B, coalesce dispatch delta, same-signature batch-fusion QPS
    try:
        adaptive_leg = run_adaptive(fused["wall_s"])
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        adaptive_leg = {"skipped": True,
                        "reason": f"adaptive leg failed: {e!r}"}

    # result + subplan cache leg (docs/caching.md): dashboard-replay
    # warm-vs-cold QPS at c=16, hit rates, join build reuse delta
    try:
        result_cache_leg = run_result_cache(fused["wall_s"], cpu_rows,
                                            q3_cpu_rows)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        result_cache_leg = {"skipped": True,
                            "reason": f"result-cache leg failed: {e!r}"}

    cpu_t = min(cpu_times)
    tpu_t = fused["wall_s"]
    q3_tpu_t = fused["q3"]["wall_s"]
    speedup = cpu_t / tpu_t
    result = {
        "metric": "tpch_q1_sf1_parquet",
        "value": round(N_ROWS / tpu_t, 1),
        "unit": "rows/s",
        "vs_baseline": round(speedup / REFERENCE_TYPICAL_SPEEDUP, 4),
        "detail": {
            "device_wall_s": round(tpu_t, 4),
            "cpu_engine_wall_s": round(cpu_t, 4),
            "speedup_vs_cpu_engine": round(speedup, 4),
            "backend": __import__("jax").default_backend(),
            "rows": N_ROWS,
            "stages": fused["stages"],
            "decode": {**fused["decode"], "ab": decode_ab,
                       "overlap": trace_leg.get("scanOverlap")},
            "fusion": {
                "q1_fused_wall_s": fused["wall_s"],
                "q1_unfused_wall_s": unfused["wall_s"],
                "q1_fusion_speedup": round(
                    unfused["wall_s"] / fused["wall_s"], 4),
                "q3_fused_wall_s": fused["q3"]["wall_s"],
                "q3_unfused_wall_s": unfused["q3"]["wall_s"],
                "q3_fusion_speedup": round(
                    unfused["q3"]["wall_s"] / fused["q3"]["wall_s"], 4),
                "dispatchCount_fused": fused["dispatchCount"],
                "dispatchCount_unfused": unfused["dispatchCount"],
                "fusedOps": fused["fusedOps"],
                "stageCompileTime_s": fused["stageCompileTime_s"],
                "unfused_stages": unfused["stages"],
            },
            "multichip": multichip,
            "robustness": robustness,
            "outOfCore": out_of_core_leg,
            "trace": trace_leg,
            "profile": profile_leg,
            "kernels": kernels_leg,
            "serving": serving,
            "telemetry": telemetry_leg,
            "lifecycle": lifecycle_leg,
            "history": history_leg,
            "tuning": tuning_leg,
            "adaptive": adaptive_leg,
            "resultCache": result_cache_leg,
            "jitCaches": registry_snapshot()["jitCaches"],
            "tpcds_q3": {
                "device_wall_s": round(q3_tpu_t, 4),
                "cpu_engine_wall_s": round(q3_cpu_t, 4),
                "speedup_vs_cpu_engine": round(q3_cpu_t / q3_tpu_t, 4),
                "rows": TPCDS_ROWS,
                "stages": fused["q3"]["stages"],
                "decode": fused["q3"]["decode"],
            },
        },
    }
    # regression verdict vs the previous round rides IN the output
    # (fault-isolated: a differ failure must not discard the results)
    try:
        telemetry_leg["benchDiff"] = run_bench_diff(result)
    except Exception as e:  # noqa: BLE001 - reported, not swallowed
        telemetry_leg["benchDiff"] = {
            "skipped": True, "reason": f"bench-diff failed: {e!r}"}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
