#!/usr/bin/env python
"""Driver benchmark: scan -> filter -> project -> groupBy aggregate.

Measures the flagship device pipeline (the TPC-H q1 shape from BASELINE.md's
first config: wide scan, predicate filter, arithmetic projection, grouped
sum/count/min/max) at 10M rows, against this engine's own CPU path — the
stand-in for "CPU Spark" that the reference's 3x-7x / "4x typical" claim is
measured against (/root/reference/docs/FAQ.md:104-105).

Prints ONE JSON line:
  {"metric": ..., "value": rows/s on device, "unit": "rows/s",
   "vs_baseline": device_speedup_over_cpu / 4.0}

so vs_baseline >= 1.0 means matching the reference's typical published
speedup on its own terms. Correctness is asserted before timing: results
must be bit-identical between sessions, and the device run must place every
operator on the TPU (spark.rapids.test.forceDevice).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

N_ROWS = 10_000_000
N_KEYS = 1_000
N_PARTITIONS = 8
REFERENCE_TYPICAL_SPEEDUP = 4.0


def make_batch():
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.sql import types as T

    rng = np.random.default_rng(42)
    k = rng.integers(0, N_KEYS, N_ROWS).astype(np.int64)
    v1 = rng.integers(-1_000, 100_000, N_ROWS).astype(np.int64)
    v2 = rng.integers(0, 1_000_000, N_ROWS).astype(np.int64)
    schema = T.StructType([
        T.StructField("k", T.LongT),
        T.StructField("v1", T.LongT),
        T.StructField("v2", T.LongT),
    ])
    return HostBatch(schema, [
        HostColumn.all_valid(k, T.LongT),
        HostColumn.all_valid(v1, T.LongT),
        HostColumn.all_valid(v2, T.LongT),
    ], N_ROWS)


def build_query(spark, batch):
    from spark_rapids_tpu.sql import functions as F

    df = spark.createDataFrame(batch, num_partitions=N_PARTITIONS)
    return (df
            .filter(F.col("v1") >= 0)
            .withColumn("v3", F.col("v1") * F.lit(2) + F.col("v2"))
            .groupBy("k")
            .agg(F.sum("v1").alias("s1"),
                 F.sum("v3").alias("s3"),
                 F.count("v1").alias("c"),
                 F.min("v2").alias("lo"),
                 F.max("v2").alias("hi")))


def run_once(q):
    t0 = time.perf_counter()
    rows = q.collect()
    return time.perf_counter() - t0, rows


def canon(rows):
    return sorted(tuple(r) for r in rows)


def main():
    from spark_rapids_tpu.sql.session import TpuSparkSession

    batch = make_batch()

    cpu = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    q_cpu = build_query(cpu, batch)
    # warm (allocator, numpy paths), then best-of-3
    run_once(q_cpu)
    cpu_times, cpu_rows = [], None
    for _ in range(3):
        dt, cpu_rows = run_once(q_cpu)
        cpu_times.append(dt)
    cpu.stop()

    tpu = TpuSparkSession({
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.test.forceDevice": "true",  # fail on any fallback
        # overlap per-task host round trips with device compute
        "spark.rapids.sql.taskParallelism": "4",
    })
    q_tpu = build_query(tpu, batch)
    run_once(q_tpu)  # jit compile warm-up
    tpu_times, tpu_rows = [], None
    for _ in range(3):
        dt, tpu_rows = run_once(q_tpu)
        tpu_times.append(dt)
    tpu.stop()

    assert canon(cpu_rows) == canon(tpu_rows), \
        "device results diverge from CPU engine"

    cpu_t = min(cpu_times)
    tpu_t = min(tpu_times)
    speedup = cpu_t / tpu_t
    print(json.dumps({
        "metric": "scan_filter_project_groupby_agg_10M",
        "value": round(N_ROWS / tpu_t, 1),
        "unit": "rows/s",
        "vs_baseline": round(speedup / REFERENCE_TYPICAL_SPEEDUP, 4),
        "detail": {
            "device_wall_s": round(tpu_t, 4),
            "cpu_engine_wall_s": round(cpu_t, 4),
            "speedup_vs_cpu_engine": round(speedup, 4),
            "backend": __import__("jax").default_backend(),
            "rows": N_ROWS,
        },
    }))


if __name__ == "__main__":
    main()
