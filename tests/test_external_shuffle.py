"""Cross-process shuffle leg v0 (round-5): SRTB-serialized partitions
over a shared directory (RapidsShuffleInternalManagerBase.scala:76 +
GpuColumnarBatchSerializer.scala:50 roles). A REAL second process writes
the map outputs; this process reads them back — the DCN/host-staged
transport skeleton, testable without multi-host hardware."""

import os
import subprocess
import sys
import textwrap

from spark_rapids_tpu.sql import functions as F

from tests.harness import assert_tpu_and_cpu_equal_collect

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_shuffle_roundtrip(tmp_path):
    """Process A partitions rows by the engine's hash partitioning and
    writes SRTB files (zstd codec); THIS process reads each partition
    back and verifies the union matches exactly and every row landed in
    its murmur3 partition."""
    sdir = str(tmp_path / "shuffle")
    writer = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
        from spark_rapids_tpu.parallel import external_shuffle as XS
        from spark_rapids_tpu.sql import expressions as E
        from spark_rapids_tpu.sql import physical as P
        from spark_rapids_tpu.sql import types as T
        rng = np.random.default_rng(7)
        n = 5000
        schema = T.StructType([T.StructField("k", T.LongT),
                               T.StructField("s", T.StringT)])
        k = rng.integers(0, 1000, n)
        s = np.array([f"v{{i % 37}}" for i in range(n)], dtype=object)
        batch = HostBatch(schema, [HostColumn.all_valid(k, T.LongT),
                                   HostColumn.all_valid(s, T.StringT)], n)
        part = P.HashPartitioning([E.AttributeReference("k", T.LongT)], 4)
        bound = [E.BoundReference(0, T.LongT, True)]
        pids = part.partition_ids(batch, bound)
        parts = [[batch.take(np.nonzero(pids == p)[0])] for p in range(4)]
        XS.write_map_output({sdir!r}, "A", parts, codec="zstd")
        print("WROTE", sum(p[0].num_rows for p in parts))
    """)
    r = subprocess.run([sys.executable, "-c", writer],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "WROTE 5000" in r.stdout

    # reduce side (THIS process): read every partition, verify placement
    # and exact content
    import numpy as np

    from spark_rapids_tpu.parallel import external_shuffle as XS
    from spark_rapids_tpu.sql import expressions as E
    from spark_rapids_tpu.sql import physical as P
    from spark_rapids_tpu.sql import types as T
    assert XS.map_outputs_done(sdir) == ["A"]
    got = []
    bound = [E.BoundReference(0, T.LongT, True)]
    for pid in range(4):
        for hb in XS.read_partition(sdir, pid):
            pids = P.HashPartitioning(
                [E.AttributeReference("k", T.LongT)], 4
            ).partition_ids(hb, bound)
            assert (pids == pid).all(), f"row in wrong partition {pid}"
            got.extend(zip(hb.columns[0].data.tolist(),
                           hb.columns[1].data.tolist()))
    rng = np.random.default_rng(7)
    n = 5000
    k = rng.integers(0, 1000, n)
    expect = sorted(zip(k.tolist(),
                        [f"v{i % 37}" for i in range(n)]))
    assert sorted(got) == expect


def test_external_shuffle_mode_dual_session():
    """shuffle.mode=external routes every device exchange through the
    SRTB filesystem leg; results stay bit-identical and the codec is
    exercised (externalShuffleBytes metric present)."""
    def q(s):
        df = s.createDataFrame(
            {"k": [i % 23 for i in range(3000)],
             "v": list(range(3000))}, "k int, v long", num_partitions=3)
        return df.groupBy("k").agg(F.sum("v").alias("sv"),
                                   F.count("v").alias("cv")).orderBy("k")
    assert_tpu_and_cpu_equal_collect(
        q,
        conf={"spark.rapids.shuffle.mode": "external",
              "spark.rapids.shuffle.compression.codec": "zstd"},
        expect_execs=["TpuExchange", "TpuHashAggregate"])
