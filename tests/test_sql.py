"""spark.sql() / selectExpr / string-filter tests (the Catalyst-parser
role; dual-session equality like every other surface).
"""

import pytest

from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (DoubleGen, IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, gen_batch)
from tests.harness import assert_tpu_and_cpu_equal_collect


def _with_views(s):
    df = s.createDataFrame(
        gen_batch([("k", SmallIntGen()), ("v", LongGen()),
                   ("s", KeyStringGen())], 400, 17), num_partitions=3)
    df.createOrReplaceTempView("t")
    dim = s.createDataFrame(
        gen_batch([("k2", SmallIntGen()), ("w", IntegerGen())], 80, 18),
        num_partitions=2)
    dim.createOrReplaceTempView("dim")
    return s


@pytest.mark.parametrize("q", [
    "SELECT k, v FROM t WHERE v > 0 AND k IS NOT NULL",
    "SELECT k + 1 AS k1, v * 2 AS v2 FROM t",
    "SELECT DISTINCT k FROM t",
    "SELECT * FROM t WHERE s LIKE 'k%' OR v BETWEEN 0 AND 100",
    "SELECT k, CASE WHEN v > 0 THEN 'pos' WHEN v < 0 THEN 'neg' "
    "ELSE 'zero' END AS sign FROM t",
    "SELECT CAST(v AS int) AS vi, upper(s) AS u FROM t",
    "SELECT k FROM t WHERE k IN (1, 2, 3)",
    "SELECT s, sum(v) AS sv, count(*) AS c, min(v) AS mn FROM t "
    "GROUP BY s",
    "SELECT k, sum(v) AS sv FROM t GROUP BY k HAVING count(*) > 5",
    "SELECT k, v FROM t ORDER BY v DESC, k ASC NULLS LAST LIMIT 25",
    "SELECT t.k, t.v, dim.w FROM t JOIN dim ON t.k = dim.k2",
    "SELECT t.k FROM t LEFT JOIN dim ON t.k = dim.k2 WHERE dim.w IS NULL",
    "SELECT a.k, a.sv FROM (SELECT k, sum(v) AS sv FROM t GROUP BY k) a "
    "WHERE a.sv > 0",
    "SELECT k FROM t WHERE v > 0 UNION ALL SELECT k2 FROM dim",
    "SELECT k, v, row_number() OVER (PARTITION BY k ORDER BY v) AS rn "
    "FROM t",
    "SELECT count(DISTINCT k) AS dk FROM t",
    "SELECT sum(v) AS total FROM t",
])
def test_sql_queries_dual_engine(q):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _with_views(s).sql(q), require_device=False)


def test_sql_exact_values():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        s.createDataFrame({"k": [1, 1, 2], "v": [10, 20, 5]},
                          "k int, v int").createOrReplaceTempView("x")
        got = s.sql("SELECT k, sum(v) AS sv FROM x GROUP BY k "
                    "ORDER BY k").collect()
        assert [(r.k, r.sv) for r in got] == [(1, 30), (2, 5)]
        one = s.sql("SELECT max(v) AS m, count(*) AS c FROM x").collect()
        assert [(one[0].m, one[0].c)] == [(20, 3)]
    finally:
        s.stop()


def test_select_expr_and_string_filter():
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            gen_batch([("a", IntegerGen()), ("b", LongGen())], 300, 19))
        .selectExpr("a + b AS ab", "abs(a) AS aa", "a % 7 AS am")
        .filter("ab IS NOT NULL AND am > 1"),
        require_device=False)


def test_sql_window_in_text():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _with_views(s).sql(
            "SELECT k, v, sum(v) OVER (PARTITION BY k ORDER BY v) AS rs, "
            "lag(v, 1) OVER (PARTITION BY k ORDER BY v) AS lg FROM t"),
        require_device=False)


def test_sql_syntax_error():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        s.createDataFrame({"a": [1]}, "a int").createOrReplaceTempView("z")
        with pytest.raises(Exception):
            s.sql("SELECT FROM WHERE")
        with pytest.raises(Exception):
            s.sql("SELECT a FROM z trailing junk here ,")
    finally:
        s.stop()


def test_sql_distinct_before_order_limit():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        s.createDataFrame({"x": [1, 1, 1, 2, 3]},
                          "x int").createOrReplaceTempView("d")
        got = sorted(r.x for r in s.sql(
            "SELECT DISTINCT x FROM d LIMIT 2").collect())
        assert len(got) == 2 and set(got) <= {1, 2, 3}
        ordered = [r.x for r in s.sql(
            "SELECT DISTINCT x FROM d ORDER BY x DESC").collect()]
        assert ordered == [3, 2, 1]
    finally:
        s.stop()


def test_sql_sum_distinct():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        s.createDataFrame({"x": [5, 5, 3], "k": [1, 1, 1]},
                          "x int, k int").createOrReplaceTempView("sd")
        got = s.sql("SELECT sum(DISTINCT x) AS sx FROM sd").collect()
        assert got[0].sx == 8
        got2 = s.sql("SELECT k, count(DISTINCT x) AS cx FROM sd "
                     "GROUP BY k").collect()
        assert [(r.k, r.cx) for r in got2] == [(1, 2)]
    finally:
        s.stop()


def test_multiple_distinct_over_different_columns_rejected():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        s.createDataFrame({"a": [1, 1], "b": [1, 2]},
                          "a int, b int").createOrReplaceTempView("md")
        with pytest.raises(NotImplementedError):
            s.sql("SELECT count(DISTINCT a) AS ca, count(DISTINCT b) AS cb "
                  "FROM md").collect()
    finally:
        s.stop()


def test_qualified_column_resolution():
    """`t.col` references resolve against relation aliases (Catalyst
    SubqueryAlias role): join conditions, self-joins with aliases, and
    struct-field fallback — all dual-session (sql/logical.py resolve)."""
    from harness import assert_tpu_and_cpu_equal_collect

    def q(spark):
        fact = spark.createDataFrame(
            {"k": [1, 2, 3, 2, None], "v": [10, 20, 30, 40, 50]},
            "k int, v int")
        dim = spark.createDataFrame(
            {"k": [1, 2, 3], "name": ["a", "b", "c"]},
            "k int, name string")
        fact.createOrReplaceTempView("fact")
        dim.createOrReplaceTempView("dim")
        return spark.sql(
            "SELECT fact.k, dim.name, v FROM fact "
            "JOIN dim ON fact.k = dim.k ORDER BY v")
    assert_tpu_and_cpu_equal_collect(q)

    def self_join(spark):
        t = spark.createDataFrame({"k": [1, 1, 2], "v": [5, 7, 9]},
                                  "k int, v int")
        t.createOrReplaceTempView("t")
        return spark.sql("SELECT a.v, b.v FROM t a JOIN t b "
                         "ON a.k = b.k WHERE a.v < b.v")
    assert_tpu_and_cpu_equal_collect(self_join)


def test_struct_field_dot_access_sql():
    """`s.f` falls back to struct-field extraction when no qualifier
    matches, and the output column is named after the field."""
    from harness import assert_tpu_and_cpu_equal_collect

    def q(spark):
        t = spark.createDataFrame(
            {"s": [{"x": 1, "y": "p"}, {"x": 2, "y": "q"}, None]},
            "s struct<x:int,y:string>")
        t.createOrReplaceTempView("ts")
        return spark.sql("SELECT s.x FROM ts WHERE s.y = 'q'")
    assert_tpu_and_cpu_equal_collect(q)


def test_ambiguous_unqualified_still_errors():
    import pytest
    from spark_rapids_tpu.sql.session import TpuSparkSession
    sp = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        a = sp.createDataFrame({"k": [1]}, "k int")
        b = sp.createDataFrame({"k": [1]}, "k int")
        a.createOrReplaceTempView("a")
        b.createOrReplaceTempView("b")
        with pytest.raises(KeyError):
            sp.sql("SELECT k FROM a JOIN b ON a.k = b.k").collect()
    finally:
        sp.stop()


def test_scalar_subquery():
    """Uncorrelated (SELECT ...) in expression position materializes to
    a literal before planning (Catalyst ScalarSubquery role); empty
    subqueries yield NULL and multi-row subqueries raise."""
    from harness import assert_tpu_and_cpu_equal_collect

    def q(spark):
        t = spark.createDataFrame({"k": [1, 2, 3, 4],
                                   "v": [10, 20, 30, 40]}, "k int, v int")
        t.createOrReplaceTempView("tsq")
        return spark.sql("SELECT k, v - (SELECT avg(v) FROM tsq) d "
                         "FROM tsq WHERE v > (SELECT min(v) FROM tsq) "
                         "ORDER BY k")
    assert_tpu_and_cpu_equal_collect(q, approx=True)

    import pytest
    sp = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        t = sp.createDataFrame({"v": [1, 2]}, "v int")
        t.createOrReplaceTempView("tsq2")
        with pytest.raises(ValueError, match="more than one row"):
            sp.sql("SELECT (SELECT v FROM tsq2) FROM tsq2").collect()
        r = sp.sql("SELECT (SELECT max(v) FROM tsq2 WHERE v > 99) m "
                   "FROM tsq2 LIMIT 1").collect()
        assert r[0][0] is None
    finally:
        sp.stop()
