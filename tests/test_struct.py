"""Struct type support end-to-end (round-5): DeviceStructColumn as
column-of-columns (complexTypeCreator.scala / complexTypeExtractors.scala
/ GpuColumnVector.java nested-handling roles). Struct columns ride
scan -> project (create/extract) -> exchange -> sort -> collect on
device; structs with nested fields tag back to CPU."""

import decimal

import pytest

from spark_rapids_tpu.sql import functions as F

from tests.datagen import IntegerGen, StringGen, gen_batch
from tests.harness import assert_tpu_and_cpu_equal_collect


def _write_struct_data(s, path):
    import os
    if os.path.exists(path):
        return
    df = s.createDataFrame(
        gen_batch([("a", IntegerGen(nullable=True)),
                   ("s", StringGen(nullable=True))], 400, 13),
        num_partitions=2)
    df = df.select(F.struct(F.col("a"), F.col("s")).alias("st"),
                   F.col("a").alias("k"))
    df.write.mode("overwrite").parquet(path)


def test_struct_scan_project_exchange_collect(tmp_path):
    path = str(tmp_path / "structs")

    def q(s):
        _write_struct_data(s, path)
        df = s.read.parquet(path)
        return (df.select(F.col("st").getField("a").alias("fa"),
                          F.col("st").getField("s").alias("fs"),
                          F.struct(F.col("k"),
                                   F.col("st").getField("a")).alias("g"),
                          F.col("k"))
                .repartition(3).orderBy("k", "fa", "fs"))
    assert_tpu_and_cpu_equal_collect(
        q, expect_execs=["TpuProject", "TpuExchange", "TpuSort"])


def test_struct_create_extract_with_decimal():
    def q(s):
        df = s.createDataFrame(
            {"a": [1, None, 3, 4],
             "d": [decimal.Decimal("1.25"), None,
                   decimal.Decimal("-7.50"), decimal.Decimal("0.00")]},
            "a int, d decimal(25,2)")
        st = F.struct(F.col("a"), F.col("d")).alias("st")
        return (df.select(st, F.col("a"))
                .select(F.col("st").getField("d").alias("fd"),
                        F.col("st").getField("a").alias("fa"))
                .orderBy("fa"))
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuProject"])


def test_struct_in_filter_and_groupby_passthrough():
    """Structs pass through filters; aggregations on struct GROUPING
    keys tag to CPU (is_device_agg nested-key rule)."""
    def q(s):
        df = s.createDataFrame(
            {"a": list(range(100)), "b": [i % 5 for i in range(100)]},
            "a int, b int")
        return (df.select(F.struct(F.col("b")).alias("st"), "a", "b")
                .filter(F.col("a") > 10)
                .select("b", F.col("st").getField("b").alias("fb"))
                .orderBy("b", "fb"))
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuFilter"])


def test_nested_struct_falls_back():
    from tests.harness import assert_tpu_fallback_collect

    def q(s):
        df = s.createDataFrame({"a": [1, 2, 3]}, "a int")
        inner = F.struct(F.col("a"))
        return df.select(F.struct(inner.alias("i")).alias("o"), "a") \
            .repartition(2)
    assert_tpu_fallback_collect(q, fallback_exec="CpuShuffleExchangeExec")


def test_time_window_tumbling_device_groupby():
    """window(ts, '10 minutes') -> struct<start,end> groups ON DEVICE:
    struct grouping keys ride field-wise equality words and the struct
    murmur3 fold matches CPU bit-for-bit (TimeWindow rule +
    HashExpression struct semantics)."""
    import datetime
    import random
    random.seed(1)
    base = datetime.datetime(2024, 5, 1)
    rows = {"ts": [base + datetime.timedelta(
                seconds=random.randint(0, 86400)) for _ in range(500)],
            "v": list(range(500))}

    def q(s):
        df = s.createDataFrame(rows, "ts timestamp, v long")
        return (df.groupBy(F.window("ts", "10 minutes").alias("w"))
                .agg(F.sum("v").alias("sv"), F.count("v").alias("c"))
                .orderBy(F.col("sv")))
    assert_tpu_and_cpu_equal_collect(
        q, expect_execs=["TpuHashAggregate", "TpuExchange"])


def test_struct_groupby_key_device():
    def q(s):
        df = s.createDataFrame(
            {"a": [1, 2, 1, None, 2, 1], "b": ["x", "y", "x", "x", "y",
                                               None],
             "v": [1, 2, 3, 4, 5, 6]}, "a int, b string, v long")
        return (df.select(F.struct(F.col("a"), F.col("b")).alias("k"),
                          "v")
                .groupBy("k").agg(F.sum("v").alias("sv"))
                .orderBy("sv"))
    assert_tpu_and_cpu_equal_collect(
        q, expect_execs=["TpuHashAggregate", "TpuExchange"])
