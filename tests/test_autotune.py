"""Persistent kernel autotuner corpus (docs/kernels.md "Autotuner"):
sweep-once semantics, crash-safe table persistence (restart
round-trip, torn lines, last-entry-wins), oracle rejection of broken
candidates, the read-only default, stats surfacing through
``cache_stats()``, and interpret-mode parity of the tiled groupbyHash
builder the tuner selects candidates for."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import jit_cache as JC
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.kernels import autotune as AT
from spark_rapids_tpu.kernels import groupby_hash as GK
from spark_rapids_tpu.metrics import registry_snapshot
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.session import TpuSparkSession


@pytest.fixture(autouse=True)
def _fresh_autotuner():
    AT.reset_for_tests()
    yield
    AT.reset_for_tests()


def _conf(dir_, enabled=True, budget_ms=60000):
    return TpuConf({
        "spark.rapids.sql.kernel.autotune.enabled":
            str(bool(enabled)).lower(),
        "spark.rapids.sql.kernel.autotune.dir": str(dir_),
        "spark.rapids.sql.kernel.autotune.budgetMs": str(budget_ms),
    })


def _table_path(dir_):
    return os.path.join(str(dir_), "kernel-autotune.jsonl")


# ---------------------------------------------------------------------------
# sweep-once + persistence
# ---------------------------------------------------------------------------

def test_read_only_when_disabled(tmp_path):
    p, tuned = AT.params_for(_conf(tmp_path, enabled=False),
                             "decodeFused", 2048)
    assert (p, tuned) == ({}, False)
    assert AT.stats()["sweeps"] == 0
    assert not os.path.exists(_table_path(tmp_path))


def test_sweep_once_then_warm_hits(tmp_path):
    conf = _conf(tmp_path)
    p1, t1 = AT.params_for(conf, "decodeFused", 2048)
    assert AT.stats()["sweeps"] == 1
    p2, t2 = AT.params_for(conf, "decodeFused", 2048)
    assert (p2, t2) == (p1, t1)
    s = AT.stats()
    assert s["sweeps"] == 1 and s["hits"] == 1
    with open(_table_path(tmp_path)) as f:
        lines = [json.loads(x) for x in f if x.strip()]
    assert len(lines) == 1
    e = lines[0]
    assert e["kernel"] == "decodeFused" and e["bucket"] == 2048
    assert e["device"] == AT._device_kind()


def test_restart_roundtrip_zero_resweeps(tmp_path):
    conf = _conf(tmp_path)
    p1, t1 = AT.params_for(conf, "decodeFused", 2048)
    assert AT.stats()["sweeps"] == 1
    AT.reset_for_tests()  # process restart: memory gone, file kept
    p2, t2 = AT.params_for(conf, "decodeFused", 2048)
    s = AT.stats()
    assert s["sweeps"] == 0, "warm start must never re-sweep"
    assert s["loaded"] >= 1 and s["hits"] == 1
    assert (p2, t2) == (p1, t1)


def test_torn_lines_skipped_and_counted(tmp_path):
    good = {"kernel": "decodeFused", "bucket": 2048,
            "device": AT._device_kind(),
            "params": {"charChunk": 2048}, "applied": True}
    with open(_table_path(tmp_path), "w") as f:
        f.write('{"kernel": "decodeFused", "bucket": 2048\n')  # torn
        f.write("not json at all\n")
        f.write(json.dumps(good) + "\n")
    # disabled = read-only: the recorded winner still applies
    p, tuned = AT.params_for(_conf(tmp_path, enabled=False),
                             "decodeFused", 2048)
    assert (p, tuned) == ({"charChunk": 2048}, True)
    s = AT.stats()
    assert s["torn"] == 2 and s["sweeps"] == 0 and s["loaded"] == 1


def test_last_entry_per_key_wins(tmp_path):
    base = {"kernel": "decodeFused", "bucket": 2048,
            "device": AT._device_kind(), "applied": True}
    with open(_table_path(tmp_path), "w") as f:
        f.write(json.dumps({**base,
                            "params": {"charChunk": 2048}}) + "\n")
        f.write(json.dumps({**base,
                            "params": {"charChunk": 8192}}) + "\n")
    p, tuned = AT.params_for(_conf(tmp_path, enabled=False),
                             "decodeFused", 2048)
    assert (p, tuned) == ({"charChunk": 8192}, True)


def test_unwritable_dir_degrades_to_memory(tmp_path):
    blocker = os.path.join(str(tmp_path), "blocker")
    with open(blocker, "w") as f:
        f.write("x")
    conf = _conf(os.path.join(blocker, "sub"))  # makedirs must fail
    p1, _ = AT.params_for(conf, "decodeFused", 2048)
    assert AT.stats()["sweeps"] == 1
    # in-memory entry still serves warm lookups this process life...
    AT.params_for(conf, "decodeFused", 2048)
    assert AT.stats()["hits"] == 1
    # ...but a restart finds nothing persisted and sweeps again
    AT.reset_for_tests()
    AT.params_for(conf, "decodeFused", 2048)
    assert AT.stats()["sweeps"] == 1 and AT.stats()["loaded"] == 0


# ---------------------------------------------------------------------------
# candidate validation
# ---------------------------------------------------------------------------

def test_broken_candidate_rejected_never_wins(tmp_path, monkeypatch):
    def fake(kernel, cap, params):
        if params.get("charChunk") == 2048:
            return False, 0.0  # fastest but WRONG: must never win
        return (True, 10.0) if not params else (True, 20.0)
    monkeypatch.setattr(AT, "_run_candidate", fake)
    p, tuned = AT.params_for(_conf(tmp_path), "decodeFused", 4096)
    assert (p, tuned) == ({}, False)  # default won; sweep remembered
    s = AT.stats()
    assert s["rejected"] == 1 and s["sweeps"] == 1
    # re-lookup is a warm hit, not a re-sweep of the losing sweep
    AT.params_for(_conf(tmp_path), "decodeFused", 4096)
    assert AT.stats()["hits"] == 1 and AT.stats()["sweeps"] == 1


def test_winning_candidate_applied(tmp_path, monkeypatch):
    def fake(kernel, cap, params):
        return True, (1.0 if params.get("charChunk") == 8192 else 50.0)
    monkeypatch.setattr(AT, "_run_candidate", fake)
    p, tuned = AT.params_for(_conf(tmp_path), "decodeFused", 4096)
    assert (p, tuned) == ({"charChunk": 8192}, True)
    AT.reset_for_tests()  # the winner survives restart
    p2, t2 = AT.params_for(_conf(tmp_path, enabled=False),
                           "decodeFused", 4096)
    assert (p2, t2) == ({"charChunk": 8192}, True)


def test_budget_bounds_sweep_but_default_always_runs(tmp_path,
                                                     monkeypatch):
    ran = []

    def fake(kernel, cap, params):
        ran.append(dict(params))
        import time
        time.sleep(0.01)  # make the budget clock move
        return True, 10.0
    monkeypatch.setattr(AT, "_run_candidate", fake)
    p, tuned = AT.params_for(_conf(tmp_path, budget_ms=0),
                             "decodeFused", 2048)
    assert ran == [{}]  # budget 0: only the mandatory default baseline
    assert (p, tuned) == ({}, False)
    assert AT.stats()["sweeps"] == 1  # partial sweep still recorded


def test_decode_fused_probe_oracle():
    # the real decodeFused oracle: chunked char gather is byte-equal
    for cand in AT._GRIDS["decodeFused"]:
        assert AT._run_candidate("decodeFused", 2048, cand)[0], cand
    assert AT._run_candidate("noSuchKernel", 2048, {})[0] is False


# ---------------------------------------------------------------------------
# tiled groupbyHash builder: candidate parity vs the numpy oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("params", [
    {},
    {"blockRows": 128, "laneGroups": 2},
    {"slotsMult": 2},
])
def test_tiled_groupby_candidates_bit_exact(params):
    assert GK.autotune_probe(params), params


# ---------------------------------------------------------------------------
# stats plumbing
# ---------------------------------------------------------------------------

def test_stats_provider_in_cache_stats(tmp_path):
    AT.params_for(_conf(tmp_path), "decodeFused", 2048)
    cs = JC.cache_stats()
    assert "kernelAutotune" in cs
    e = cs["kernelAutotune"]
    # the Prometheus renderer reads these keys unconditionally
    for k in ("size", "capacity", "hits", "misses", "evictions",
              "contention"):
        assert k in e, k
    assert e["misses"] == 1 and e["size"] == 1


def test_broken_stats_provider_is_isolated():
    JC.register_stats_provider("_boomProvider", lambda: 1 // 0)
    try:
        cs = JC.cache_stats()
        assert "kernelAutotune" in cs
        assert "_boomProvider" not in cs
    finally:
        JC._EXTRA_STATS.pop("_boomProvider", None)


# ---------------------------------------------------------------------------
# end-to-end: the engine sweeps once and stays bit-identical
# ---------------------------------------------------------------------------

def _groupy_batch(n=4000, ngroups=7, seed=9):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, ngroups, n)
    vals = rng.integers(-1000, 1000, n)
    vv = rng.random(n) >= 0.1
    return HostBatch(T.StructType([
        T.StructField("k", T.LongT),
        T.StructField("v", T.LongT),
    ]), [HostColumn.all_valid(keys, T.LongT),
         HostColumn(T.LongT, vals, vv).normalized()], n)


def _run(conf, sql):
    s = TpuSparkSession(dict(conf))
    try:
        s.createDataFrame(_groupy_batch()) \
            .createOrReplaceTempView("t")
        s.start_capture()
        out = s.sql(sql)._execute().to_pydict()
        return out, s.get_captured_plans()
    finally:
        s.stop()


def test_engine_sweep_bit_identical_and_warm_restart(tmp_path):
    sql = ("SELECT k, sum(v), count(v), min(v), max(v) FROM t "
           "GROUP BY k ORDER BY k")
    cpu, _ = _run({"spark.rapids.sql.enabled": "false"}, sql)
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.test.forceDevice": "true",
            "spark.rapids.sql.kernel.autotune.enabled": "true",
            "spark.rapids.sql.kernel.autotune.dir": str(tmp_path),
            # budget 0: sweeps validate only the default candidate —
            # keeps this test fast while exercising the full engine
            # path (params_for at dispatch, recorded table, restart)
            "spark.rapids.sql.kernel.autotune.budgetMs": "0"}
    tuned_out, plans = _run(conf, sql)
    assert cpu == tuned_out
    snap = registry_snapshot(plans)["metrics"]
    assert snap.get("kernelDispatchCount.groupbyHash", 0) >= 1
    assert snap.get("kernelFallbacks.groupbyHash", 0) == 0
    assert AT.stats()["sweeps"] >= 1
    assert os.path.exists(_table_path(tmp_path))
    AT.reset_for_tests()  # restart: the table warm-starts the server
    warm_out, _ = _run(conf, sql)
    assert cpu == warm_out
    assert AT.stats()["sweeps"] == 0
