"""Device parquet decode parity corpus (ISSUE 1 tentpole).

Every test asserts the device-decode path (raw page upload + XLA
decode, io/device_decode.py) produces results BIT-IDENTICAL to the
pyarrow host decode over files with controlled encodings: PLAIN,
RLE_DICTIONARY, dictionary-overflow (mixed encodings in one chunk),
nulls at page boundaries, multi-page chunks — plus the per-column
fallback for unsupported encodings, and unit tests of the ops/rle.py
kernels against numpy oracles.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.sql.session import TpuSparkSession

DEV_CONF = "spark.rapids.sql.format.parquet.deviceDecode.enabled"


def _collect(path, device_decode: bool, extra_conf=None, sql=None):
    """Read ``path`` through the engine with a device op above the scan
    (so TpuRowToColumnarExec is the scan's consumer) and return
    (pydict, scan_metrics)."""
    conf = {"spark.rapids.sql.enabled": "true",
            DEV_CONF: str(device_decode).lower()}
    conf.update(extra_conf or {})
    spark = TpuSparkSession(conf)
    try:
        spark.read.parquet(path).createOrReplaceTempView("t")
        df = spark.sql(sql or "SELECT * FROM t")
        spark.start_capture()
        out = df._execute().to_pydict()
        # whole-plan metric snapshot: the scan's decode counters plus
        # the R2C transition's pipeline counters (uploadAheadBatches,
        # prefetchRingShrinks) ride the same dict
        from spark_rapids_tpu.metrics import registry_snapshot
        metrics = registry_snapshot(spark.get_captured_plans())["metrics"]
        return out, metrics
    finally:
        spark.stop()


def _assert_parity(path, expect_device=True, expect_fallback_cols=0,
                   sql=None):
    host, _m0 = _collect(path, False, sql=sql)
    dev, m = _collect(path, True, sql=sql)
    assert list(host) == list(dev)
    for k in host:
        assert host[k] == dev[k], (
            f"column {k} differs: {host[k][:5]} vs {dev[k][:5]}")
    if expect_device:
        assert m.get("deviceDecodedBatches", 0) >= 1, m
    assert m.get("deviceFallbackColumns", 0) == expect_fallback_cols, m
    return m


def _write(tmp_path, tbl, name="t.parquet", **kw):
    path = os.path.join(str(tmp_path), name)
    pq.write_table(tbl, path, **kw)
    return path


def _mixed_table(n=4000, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    null_every = 7 if with_nulls else 0

    def maybe_null(vals):
        if not null_every:
            return list(vals)
        return [None if i % null_every == 0 else v
                for i, v in enumerate(vals)]

    return pa.table({
        "i64": pa.array(maybe_null(rng.integers(-(1 << 40), 1 << 40, n)
                                   .tolist()), type=pa.int64()),
        "i32": pa.array(maybe_null(rng.integers(-(1 << 30), 1 << 30, n)
                                   .tolist()), type=pa.int32()),
        "f32": pa.array(maybe_null(
            rng.random(n).astype("float32").tolist()), type=pa.float32()),
        "dec": pa.array(maybe_null(rng.integers(-10**9, 10**9, n)
                                   .tolist()), type=pa.decimal128(15, 2)),
        "s": pa.array([None if null_every and i % null_every == 3
                       else f"word{i % 11}" for i in range(n)]),
        "d": pa.array(maybe_null(rng.integers(1000, 20000, n)
                                 .astype("int32").tolist()),
                      type=pa.date32()),
        "b": pa.array(maybe_null((rng.integers(0, 2, n) > 0).tolist()),
                      type=pa.bool_()),
    })


# -- parity corpus ---------------------------------------------------------

def test_plain_encoding_parity(tmp_path):
    tbl = _mixed_table(with_nulls=False).drop_columns(["s"])
    path = _write(tmp_path, tbl, use_dictionary=False)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.PLAIN", 0) > 0, m


def test_rle_dictionary_parity(tmp_path):
    n = 4000
    rng = np.random.default_rng(1)
    tbl = pa.table({
        "i": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        "s": pa.array([f"cat{int(v)}" for v in rng.integers(0, 20, n)]),
        "dec": pa.array(rng.integers(0, 100, n).tolist(),
                        type=pa.decimal128(9, 2)),
    })
    path = _write(tmp_path, tbl)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.RLE_DICTIONARY", 0) > 0, m


def test_nulls_at_page_boundaries(tmp_path):
    # tiny pages + null runs that straddle page boundaries: the
    # definition-level runs then split/lean across pages
    n = 6000
    vals = [None if (i // 50) % 2 == 0 else i * 3 for i in range(n)]
    svals = [None if (i // 37) % 3 == 1 else f"s{i % 5}"
             for i in range(n)]
    tbl = pa.table({"v": pa.array(vals, type=pa.int64()),
                    "s": pa.array(svals)})
    path = _write(tmp_path, tbl, data_page_size=512)
    _assert_parity(path)


def test_multi_page_chunks_dict_overflow(tmp_path):
    # small dict limit + small pages: the writer starts RLE_DICTIONARY,
    # overflows, and finishes the SAME chunk with PLAIN pages
    n = 30_000
    rng = np.random.default_rng(2)
    tbl = pa.table({"x": pa.array(rng.integers(0, 1 << 40, n),
                                  type=pa.int64())})
    path = _write(tmp_path, tbl, dictionary_pagesize_limit=20_000,
                  data_page_size=4096)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.PLAIN", 0) > 0, m
    assert m.get("deviceDecodedValues.RLE_DICTIONARY", 0) > 0, m


def test_mixed_types_with_nulls_snappy(tmp_path):
    path = _write(tmp_path, _mixed_table(), compression="snappy",
                  data_page_size=8192)
    _assert_parity(path)


def test_zstd_compression(tmp_path):
    path = _write(tmp_path, _mixed_table(seed=3), compression="zstd")
    _assert_parity(path)


def test_decimal128_flba(tmp_path):
    n = 2000
    rng = np.random.default_rng(4)
    big = [None if i % 11 == 0 else
           int(rng.integers(-10**9, 10**9)) * 10**10 + i
           for i in range(n)]
    tbl = pa.table({"d": pa.array(big, type=pa.decimal128(25, 2))})
    path = _write(tmp_path, tbl)
    _assert_parity(path)


def test_timestamp_micros(tmp_path):
    n = 1500
    rng = np.random.default_rng(5)
    us = rng.integers(0, 2_000_000_000_000_000, n)
    tbl = pa.table({"ts": pa.array(us, type=pa.timestamp("us"))})
    path = _write(tmp_path, tbl, use_dictionary=False)
    _assert_parity(path)


def test_multi_row_group_aggregate(tmp_path):
    n = 20_000
    rng = np.random.default_rng(6)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 9, n), type=pa.int32()),
        "v": pa.array(rng.integers(0, 10**6, n).tolist(),
                      type=pa.decimal128(12, 2)),
    })
    path = _write(tmp_path, tbl, row_group_size=3000)
    _assert_parity(
        path, sql="SELECT k, sum(v) s, count(*) c FROM t "
                  "GROUP BY k ORDER BY k")


# -- full encoding matrix (ISSUE 9 tentpole) -------------------------------

def test_delta_binary_packed_device_decode(tmp_path):
    # DELTA_BINARY_PACKED int64/int32: miniblock runs decoded on device
    # + segmented prefix-sum reconstruction, vs the pyarrow oracle
    n = 30_000
    rng = np.random.default_rng(7)
    tbl = pa.table({
        "i64": pa.array(rng.integers(-(1 << 50), 1 << 50, n),
                        type=pa.int64()),
        "i32": pa.array(rng.integers(-(1 << 30), 1 << 30, n)
                        .astype("int32"), type=pa.int32()),
        "sorted": pa.array(np.cumsum(rng.integers(0, 9, n)),
                           type=pa.int64()),
    })
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding="DELTA_BINARY_PACKED",
                  data_page_size=8192)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.DELTA_BINARY_PACKED", 0) >= 3 * n, m


def test_delta_binary_packed_nulls_and_page_boundaries(tmp_path):
    n = 9000
    vals = [None if (i // 41) % 3 == 0 else (i * 7919) % (1 << 40) - 17
            for i in range(n)]
    tbl = pa.table({"v": pa.array(vals, type=pa.int64())})
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding="DELTA_BINARY_PACKED",
                  data_page_size=1024)
    _assert_parity(path)


def test_delta_decimal_int_physical(tmp_path):
    # decimal with INT32/INT64 physical storage rides the delta path
    n = 4000
    rng = np.random.default_rng(17)
    tbl = pa.table({
        "d": pa.array(rng.integers(0, 10**6, n).tolist(),
                      type=pa.decimal128(9, 2)),
    })
    import pyarrow.parquet as _pq
    path = os.path.join(str(tmp_path), "d.parquet")
    try:
        _pq.write_table(tbl, path, use_dictionary=False,
                        store_decimal_as_integer=True,
                        column_encoding="DELTA_BINARY_PACKED")
    except (OSError, TypeError) as e:
        pytest.skip(f"writer cannot emit delta decimal: {e}")
    enc = _pq.ParquetFile(path).metadata.row_group(0).column(0).encodings
    if "DELTA_BINARY_PACKED" not in enc:
        pytest.skip(f"writer did not emit delta for decimal: {enc}")
    _assert_parity(path, sql="SELECT sum(d) s, count(*) c FROM t")


def test_plain_byte_array_device_decode(tmp_path):
    # PLAIN string pages: host extracts lengths only; the offsets
    # column is a device segmented prefix-sum, the bytes a gather
    n = 2500
    tbl = pa.table({
        "s": pa.array([f"value-{i}" for i in range(n)]),
        "i": pa.array(np.arange(n), type=pa.int64()),
    })
    path = _write(tmp_path, tbl, use_dictionary=False)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.PLAIN", 0) >= 2 * n, m


def test_plain_strings_empty_and_nulls_at_page_boundaries(tmp_path):
    # empty strings, nulls straddling tiny pages, variable lengths
    n = 6000
    vals = []
    for i in range(n):
        if (i // 37) % 3 == 1:
            vals.append(None)
        elif i % 11 == 0:
            vals.append("")
        else:
            vals.append("x" * (i % 23) + f"#{i}")
    tbl = pa.table({"s": pa.array(vals)})
    path = _write(tmp_path, tbl, use_dictionary=False,
                  data_page_size=512)
    _assert_parity(path)


def test_string_dict_overflow_to_plain_mid_chunk(tmp_path):
    # the writer starts RLE_DICTIONARY, overflows the dict-page limit,
    # and finishes the SAME chunk with PLAIN byte-array pages: both
    # lanes decode on device, selected per page
    n = 12_000
    rng = np.random.default_rng(13)
    vals = [f"prefix-{int(v)}-suffix" for v in rng.integers(0, 6000, n)]
    tbl = pa.table({"s": pa.array(vals)})
    path = _write(tmp_path, tbl, dictionary_pagesize_limit=8_000,
                  data_page_size=4096)
    import pyarrow.parquet as _pq
    encs = _pq.ParquetFile(path).metadata.row_group(0).column(0).encodings
    m = _assert_parity(path)
    if "PLAIN" in encs:  # overflow really happened
        assert m.get("deviceDecodedValues.PLAIN", 0) > 0, (encs, m)
        assert m.get("deviceDecodedValues.RLE_DICTIONARY", 0) > 0, m


def test_binary_plain_device_decode(tmp_path):
    n = 1500
    rng = np.random.default_rng(14)
    vals = [rng.bytes(int(rng.integers(0, 19))) for _ in range(n)]
    tbl = pa.table({"b": pa.array(vals, type=pa.binary()),
                    "k": pa.array(np.arange(n) % 7, type=pa.int64())})
    path = _write(tmp_path, tbl, use_dictionary=False)
    _assert_parity(path, sql="SELECT k, count(b) c FROM t GROUP BY k "
                             "ORDER BY k")


def test_delta_length_byte_array(tmp_path):
    n = 5000
    vals = ["" if i % 13 == 0 else
            None if i % 17 == 0 else f"dl-{i % 97}-{'y' * (i % 9)}"
            for i in range(n)]
    tbl = pa.table({"s": pa.array(vals),
                    "i": pa.array(np.arange(n), type=pa.int64())})
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding={"s": "DELTA_LENGTH_BYTE_ARRAY",
                                   "i": "PLAIN"},
                  data_page_size=2048)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.DELTA_LENGTH_BYTE_ARRAY", 0) > 0, m


def test_byte_stream_split_float_and_int(tmp_path):
    n = 4000
    rng = np.random.default_rng(15)
    cols = {
        "f": pa.array(rng.random(n).astype("float32"),
                      type=pa.float32()),
        "i64": pa.array(rng.integers(-(1 << 50), 1 << 50, n),
                        type=pa.int64()),
        "i32": pa.array(rng.integers(-(1 << 30), 1 << 30, n)
                        .astype("int32"), type=pa.int32()),
    }
    tbl = pa.table(cols)
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding="BYTE_STREAM_SPLIT")
    m = _assert_parity(path, sql="SELECT i64, i32 FROM t")
    assert m.get("deviceDecodedValues.BYTE_STREAM_SPLIT", 0) >= 2 * n, m


def test_byte_stream_split_double_matches_backend(tmp_path):
    from spark_rapids_tpu.device_caps import f64_bitcast_exact
    n = 2000
    rng = np.random.default_rng(16)
    tbl = pa.table({"d": pa.array(rng.random(n), type=pa.float64())})
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding="BYTE_STREAM_SPLIT")
    expect_fb = 0 if f64_bitcast_exact() else 1
    _assert_parity(path, expect_device=expect_fb == 0,
                   expect_fallback_cols=expect_fb,
                   sql="SELECT d FROM t WHERE d >= 0")


def test_data_page_v2(tmp_path):
    # v2 pages: uncompressed level section, RLE boolean values
    tbl = _mixed_table(n=3000, seed=18)
    path = _write(tmp_path, tbl, data_page_version="2.0",
                  data_page_size=2048)
    _assert_parity(path)


# -- fallback behavior -----------------------------------------------------

def test_unsupported_encoding_falls_back_per_column(tmp_path):
    # DELTA_BYTE_ARRAY (prefix/suffix strings) is genuinely
    # unsupported: that column host-decodes, the sibling stays on
    # device, and the host fallback is visible per encoding
    n = 3000
    tbl = pa.table({
        "dba": pa.array([f"prefix-common-{i}" for i in range(n)]),
        "ok": pa.array(np.arange(n), type=pa.int64()),
    })
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding={"dba": "DELTA_BYTE_ARRAY",
                                   "ok": "PLAIN"})
    m = _assert_parity(path, expect_fallback_cols=1)
    # the supported sibling column still decoded on device
    assert m.get("deviceDecodedValues.PLAIN", 0) >= n, m
    assert m.get("hostDecodedValues.DELTA_BYTE_ARRAY", 0) >= n, m


def test_per_encoding_enable_confs(tmp_path):
    # each deviceDecode.<enc>.enabled=false turns exactly that lane
    # into a per-column host fallback, bit-identical either way
    n = 2000
    rng = np.random.default_rng(19)
    tbl = pa.table({
        "s": pa.array([f"v{i}" for i in range(n)]),
        "d": pa.array(rng.integers(0, 10**6, n), type=pa.int64()),
        "b": pa.array(rng.random(n).astype("float32"),
                      type=pa.float32()),
    })
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding={"s": "PLAIN",
                                   "d": "DELTA_BINARY_PACKED",
                                   "b": "BYTE_STREAM_SPLIT"})
    base = "spark.rapids.sql.format.parquet.deviceDecode."
    for key, col in ((base + "byteArray.enabled", "s"),
                     (base + "delta.enabled", "d"),
                     (base + "byteStreamSplit.enabled", "b")):
        host, _ = _collect(path, False)
        dev, m = _collect(path, True, {key: "false"})
        assert host == dev, (key, col)
        assert m.get("deviceFallbackColumns", 0) >= 1, (key, m)


def test_double_fallback_matches_backend(tmp_path):
    from spark_rapids_tpu.device_caps import f64_bitcast_exact
    n = 2000
    rng = np.random.default_rng(8)
    tbl = pa.table({"f": pa.array(rng.random(n), type=pa.float64())})
    path = _write(tmp_path, tbl, use_dictionary=False)
    expect_fb = 0 if f64_bitcast_exact() else 1
    _assert_parity(path, expect_device=expect_fb == 0,
                   expect_fallback_cols=expect_fb,
                   sql="SELECT f FROM t WHERE f >= 0")


def test_cpu_consumer_never_sees_encoded_batches(tmp_path):
    # rapids disabled: the same conf key must be inert — the scan's
    # emit_encoded gate only opens under a TpuRowToColumnarExec
    path = _write(tmp_path, _mixed_table(n=500, seed=9))
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false",
                             DEV_CONF: "true"})
    try:
        out = spark.read.parquet(path)._execute().to_pydict()
        assert len(out["i64"]) == 500
    finally:
        spark.stop()


def test_partitioned_dataset_device_decode(tmp_path):
    base = str(tmp_path / "part")
    for g in (1, 2):
        os.makedirs(f"{base}/g={g}", exist_ok=True)
        n = 800
        tbl = pa.table({
            "v": pa.array(np.arange(n) * g, type=pa.int64()),
            "s": pa.array([f"p{g}x{i % 3}" for i in range(n)]),
        })
        pq.write_table(tbl, f"{base}/g={g}/part-0.parquet")
    _assert_parity(base,
                   sql="SELECT g, count(*) c, sum(v) s FROM t "
                       "GROUP BY g ORDER BY g")


def test_reader_type_multithreaded_device_decode(tmp_path):
    base = str(tmp_path / "many")
    os.makedirs(base, exist_ok=True)
    rng = np.random.default_rng(10)
    for i in range(6):
        n = 2000
        tbl = pa.table({
            "v": pa.array(rng.integers(0, 10**6, n).tolist(),
                          type=pa.decimal128(10, 2)),
            "k": pa.array(rng.integers(0, 5, n), type=pa.int32()),
        })
        pq.write_table(tbl, f"{base}/f{i}.parquet")
    for rt in ("PERFILE", "MULTITHREADED"):
        host, _ = _collect(
            base, False,
            {"spark.rapids.sql.format.parquet.reader.type": rt},
            sql="SELECT k, sum(v) s FROM t GROUP BY k ORDER BY k")
        dev, m = _collect(
            base, True,
            {"spark.rapids.sql.format.parquet.reader.type": rt},
            sql="SELECT k, sum(v) s FROM t GROUP BY k ORDER BY k")
        assert host == dev
        assert m.get("deviceDecodedBatches", 0) >= 1, (rt, m)


# -- scan pipeline (async read->decode->compute, docs/scan.md) -------------

MAXIF_CONF = "spark.rapids.sql.format.parquet.deviceDecode.maxInFlight"


def _write_q1_shaped(tmp_path, n=24_000):
    """A lineitem-shaped dataset (decimal money, low-cardinality
    strings, dates) across several row groups — the bench smoke's
    schema at corpus scale."""
    rng = np.random.default_rng(20)
    tbl = pa.table({
        "qty": pa.array(rng.integers(100, 5100, n).tolist(),
                        type=pa.decimal128(15, 2)),
        "price": pa.array(rng.integers(90100, 10494951, n).tolist(),
                          type=pa.decimal128(15, 2)),
        "flag": pa.array([("A", "N", "R")[int(v)]
                          for v in rng.integers(0, 3, n)]),
        "status": pa.array([("O", "F")[int(v)]
                            for v in rng.integers(0, 2, n)]),
        "ship": pa.array(rng.integers(8000, 10500, n).astype("int32"),
                         type=pa.date32()),
    })
    path = os.path.join(str(tmp_path), "lineitem.parquet")
    pq.write_table(tbl, path, row_group_size=4000)
    return path


Q1_SHAPED_SQL = ("SELECT flag, status, sum(qty) sq, sum(price) sp, "
                 "count(*) c FROM t WHERE ship <= date '1998-09-02' "
                 "GROUP BY flag, status ORDER BY flag, status")


def _plan_metrics(spark):
    from spark_rapids_tpu.metrics import registry_snapshot
    return registry_snapshot(spark.get_captured_plans())["metrics"]


def test_q1_shaped_bit_identical_across_decode_and_pipeline(tmp_path):
    # the acceptance sweep: device decode on/off x pipeline depth
    # 0 (sync) / 1 (prefetch only) / 3 (upload-ahead) all bit-identical
    path = _write_q1_shaped(tmp_path)
    want, _ = _collect(path, False, sql=Q1_SHAPED_SQL)
    for depth in ("0", "1", "3"):
        got, m = _collect(path, True, {MAXIF_CONF: depth},
                          sql=Q1_SHAPED_SQL)
        assert got == want, depth
        assert m.get("deviceDecodedBatches", 0) >= 1, (depth, m)
        assert m.get("deviceFallbackColumns", 0) == 0, (depth, m)


def test_pipelined_scan_metrics_and_spans(tmp_path):
    # default depth: uploads are issued ahead, the producer thread's
    # prefetch wall is interval-union (never exceeds the query wall)
    path = _write_q1_shaped(tmp_path)
    from spark_rapids_tpu.sql.session import TpuSparkSession
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                             DEV_CONF: "true"})
    try:
        import time
        spark.read.parquet(path).createOrReplaceTempView("t")
        df = spark.sql(Q1_SHAPED_SQL)
        spark.start_capture()
        t0 = time.perf_counter_ns()
        df._execute()
        wall = time.perf_counter_ns() - t0
        m = _plan_metrics(spark)
        assert m.get("uploadAheadBatches", 0) >= 1, m
        assert m.get("scanPrefetchTime", 0) > 0, m
        # the timed_wall audit: prefetch threads must not re-introduce
        # the PR 1 decodeTime > wall over-count
        assert m["scanPrefetchTime"] <= wall, (m["scanPrefetchTime"],
                                               wall)
        assert m.get("deviceDecodeTime", 0) <= wall, m
    finally:
        spark.stop()


@pytest.mark.fault
def test_pipelined_scan_injected_io_error_cancels_cleanly(tmp_path):
    # an IO error that exhausts reader retries must surface as the
    # query error (not hang the ring), and the next query on a clean
    # injector must succeed — prefetch state drained
    from spark_rapids_tpu import retry as R
    path = _write_q1_shaped(tmp_path)
    R.reset_fault_injection()
    try:
        with pytest.raises(Exception) as ei:
            _collect(path, True,
                     {"spark.rapids.sql.test.injectIOError": "1:99",
                      "spark.rapids.sql.reader.maxRetries": "1"},
                     sql=Q1_SHAPED_SQL)
        assert "injected IO error" in str(ei.value)
    finally:
        R.reset_fault_injection()
    want, _ = _collect(path, False, sql=Q1_SHAPED_SQL)
    got, _ = _collect(path, True, sql=Q1_SHAPED_SQL)
    assert got == want


@pytest.mark.fault
def test_oom_during_prefetched_upload_shrinks_ring(tmp_path):
    # site:upload:N targets exactly the prefetched raw-chunk uploads:
    # the in-flight ring must SHRINK (drain + synchronous retry), not
    # deadlock, and results stay bit-identical
    from spark_rapids_tpu import retry as R
    path = _write_q1_shaped(tmp_path)
    want, _ = _collect(path, False, sql=Q1_SHAPED_SQL)
    R.reset_fault_injection()
    try:
        got, m = _collect(
            path, True,
            {"spark.rapids.sql.test.injectOOM": "site:upload:2"},
            sql=Q1_SHAPED_SQL)
    finally:
        R.reset_fault_injection()
    assert got == want
    assert m.get("prefetchRingShrinks", 0) >= 1, m


def test_site_scoped_injection_grammar():
    from spark_rapids_tpu.retry import FaultInjector, TpuRetryOOM
    inj = FaultInjector(oom_spec="site:upload:2")
    inj.on_alloc()          # untagged: never counts
    inj.on_alloc("other")   # other site: never counts
    inj.on_alloc("upload")  # 1st upload event
    with pytest.raises(TpuRetryOOM):
        inj.on_alloc("upload")  # 2nd fires
    assert inj.oom_injected == 1
    assert FaultInjector(oom_spec="site:upload:split:3")._oom.split


# -- kernel unit tests (ops/rle.py against numpy oracles) ------------------

def _hybrid_stream(values: np.ndarray, width: int):
    """Encode values as one parquet RLE/bit-packed hybrid stream and
    parse it back with the host-side planner, returning the pieces the
    device kernel consumes."""
    from spark_rapids_tpu.io.device_decode import (RunTable,
                                                   _parse_hybrid_runs)
    out = bytearray()
    i, n = 0, len(values)
    while i < n:
        run = 1
        while i + run < n and values[i + run] == values[i]:
            run += 1
        if run >= 8:
            out += _uvarint(run << 1)
            out += int(values[i]).to_bytes((width + 7) // 8, "little")
            i += run
        else:
            j = min(n, i + 8)
            group = list(values[i:j]) + [0] * (8 - (j - i))
            out += _uvarint((1 << 1) | 1)
            bits = 0
            for k, v in enumerate(group):
                bits |= int(v) << (k * width)
            out += bits.to_bytes(width, "little")
            i = j
    runs = RunTable()
    _parse_hybrid_runs(bytes(out), 0, len(out), width, n, 0, 0, runs)
    return np.frombuffer(bytes(out), dtype=np.uint8), runs


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def test_hybrid_lookup_kernel_matches_oracle():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    rng = np.random.default_rng(11)
    for width in (1, 3, 7, 12, 20):
        vals = rng.integers(0, 1 << width, 300)
        vals[40:200] = vals[40]  # force an RLE run
        payload, runs = _hybrid_stream(vals, width)
        words = np.zeros((len(payload) + 3) // 4 * 4, dtype=np.uint8)
        words[:len(payload)] = payload
        bytes_all = R.bytes_of_words(jnp.asarray(words.view(np.int32)))
        arrs = [jnp.asarray(a) for a in runs.arrays(
            max(8, 1 << (len(runs) - 1).bit_length()))]
        pos = jnp.arange(len(vals), dtype=jnp.int64)
        got = np.asarray(R.hybrid_lookup(bytes_all, pos, *arrs))
        assert np.array_equal(got, vals), f"width={width}"


def test_fixed_width_kernels_match_oracle():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    rng = np.random.default_rng(12)
    raw = rng.integers(0, 256, 256).astype(np.uint8)
    words = raw.view(np.int32)
    bytes_all = R.bytes_of_words(jnp.asarray(words))
    # little-endian int64/int32
    offs = np.arange(0, 128, 8, dtype=np.int64)
    got = np.asarray(R.read_le(bytes_all, jnp.asarray(offs), 8))
    assert np.array_equal(got, raw[:128].view(np.int64))
    # big-endian signed (decimal FLBA)
    for w in (3, 7):
        offs = np.arange(0, 10 * w, w, dtype=np.int64)
        got = np.asarray(R.read_be_signed(bytes_all, jnp.asarray(offs), w))
        want = [int.from_bytes(raw[o:o + w].tobytes(), "big", signed=True)
                for o in offs]
        assert got.tolist() == want, f"w={w}"
    # big-endian limbs (decimal128 FLBA)
    w = 13
    offs = np.arange(0, 5 * w, w, dtype=np.int64)
    hi, lo = R.read_be_limbs(bytes_all, jnp.asarray(offs), w)
    for k, o in enumerate(offs):
        full = int.from_bytes(raw[o:o + w].tobytes(), "big", signed=True)
        assert int(hi[k]) == full >> 64
        assert int(lo[k]) & ((1 << 64) - 1) == full & ((1 << 64) - 1)


def test_dense_ranks_kernel():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    v = np.array([True, False, True, True, False, True])
    got = np.asarray(R.dense_ranks(jnp.asarray(v)))
    assert got.tolist() == [0, 0, 1, 2, 2, 3]


def _bytes_arr(payload: bytes):
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    words = np.zeros((len(payload) + 3) // 4 * 4, dtype=np.uint8)
    words[:len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    return R.bytes_of_words(jnp.asarray(words.view(np.int32)))


def test_read_packed64_wide_widths():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    rng = np.random.default_rng(21)
    for width in (33, 47, 63, 64):
        vals = [int(v) for v in
                rng.integers(0, 1 << 62, 40)] if width < 64 else \
            [int(v) for v in rng.integers(-(1 << 62), 1 << 62, 40)]
        vals = [v & ((1 << width) - 1) for v in vals]
        bits = 0
        for k, v in enumerate(vals):
            bits |= v << (k * width)
        payload = bits.to_bytes((len(vals) * width + 7) // 8 + 8,
                                "little")
        ba = _bytes_arr(payload)
        off = jnp.asarray(np.arange(len(vals), dtype=np.int64) * width)
        w = jnp.full(len(vals), width, dtype=jnp.int64)
        got = np.asarray(R.read_packed64(ba, off, w)).astype(np.uint64)
        want = np.array(vals, dtype=np.uint64)
        assert np.array_equal(got, want), f"width={width}"


def test_delta_host_decoder_matches_pyarrow(tmp_path):
    # the host DELTA decoder (used for DELTA_LENGTH lengths) against
    # pyarrow's own decode of a DELTA_BINARY_PACKED file
    import pyarrow.parquet as _pq

    from spark_rapids_tpu.io.device_decode import (_delta_decode_host,
                                                   parse_page_header)
    rng = np.random.default_rng(22)
    n = 5000
    vals = rng.integers(-(1 << 45), 1 << 45, n)
    tbl = pa.table({"v": pa.array(vals, type=pa.int64())})
    path = os.path.join(str(tmp_path), "d.parquet")
    _pq.write_table(tbl, path, use_dictionary=False,
                    column_encoding="DELTA_BINARY_PACKED",
                    compression="NONE")
    meta = _pq.ParquetFile(path).metadata.row_group(0).column(0)
    with open(path, "rb") as f:
        f.seek(meta.data_page_offset)
        raw = f.read(meta.total_compressed_size)
    decoded = []
    pos = 0
    while pos < len(raw) and len(decoded) < n:
        hdr, body_off = parse_page_header(raw, pos)
        csize = hdr.get(3, 0)
        body = raw[body_off:body_off + csize]
        pos = body_off + csize
        if hdr.get(1) != 0:
            continue
        # optional column: skip the length-prefixed def-level section
        dl_len = int.from_bytes(body[0:4], "little")
        val_off = 4 + dl_len
        got, _end = _delta_decode_host(body, val_off, len(body))
        decoded.extend(got.tolist())
    assert decoded == vals.tolist()


def test_plain_str_lengths_oracle():
    from spark_rapids_tpu.io.device_decode import _plain_str_lengths
    rng = np.random.default_rng(23)
    vals = [b"x" * int(rng.integers(0, 37)) for _ in range(500)]
    body = b"".join(len(v).to_bytes(4, "little") + v for v in vals)
    lens = _plain_str_lengths(body, 0, len(body), len(vals))
    assert lens.tolist() == [len(v) for v in vals]


def test_gather_chars_and_seg_cumsum_kernels():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    data = b"heyworldabc!"
    ba = _bytes_arr(data)
    starts = jnp.asarray(np.array([0, 3, 8], dtype=np.int64))
    lens = jnp.asarray(np.array([3, 5, 4], dtype=np.int32))
    out = np.asarray(R.gather_chars(ba, starts, lens, 8))
    assert bytes(out[0][:3]) == b"hey" and out[0][3:].tolist() == [0] * 5
    assert bytes(out[1][:5]) == b"world"
    assert bytes(out[2][:4]) == b"abc!"
    # segmented exclusive cumsum: two segments starting at lanes 0, 3
    contrib = jnp.asarray(np.array([2, 3, 4, 10, 20, 30],
                                   dtype=np.int64))
    seg = jnp.asarray(np.array([0, 0, 0, 3, 3, 3], dtype=np.int64))
    got = np.asarray(R.seg_excl_cumsum(contrib, seg))
    assert got.tolist() == [0, 2, 5, 0, 10, 30]


def test_read_bss_kernel():
    from spark_rapids_tpu.ops import rle as R
    import jax.numpy as jnp
    rng = np.random.default_rng(24)
    vals = rng.integers(-(1 << 60), 1 << 60, 17)
    raw = vals.astype("<i8").tobytes()
    # split the byte planes the BYTE_STREAM_SPLIT way
    planes = b"".join(raw[j::8] for j in range(8))
    ba = _bytes_arr(planes)
    n = len(vals)
    base = jnp.zeros(n, dtype=jnp.int64)
    stride = jnp.full(n, n, dtype=jnp.int64)
    local = jnp.asarray(np.arange(n, dtype=np.int64))
    got = np.asarray(R.read_bss(ba, base, stride, local, 8))
    assert got.tolist() == vals.tolist()


# -- fused decode kernel (one Pallas program per batch, docs/kernels.md) ----

FUSED_OFF = {"spark.rapids.sql.kernel.decodeFused.enabled": "false"}


def _fused_vs_chain(path):
    """Host oracle vs fused-kernel decode vs XLA-chain decode over one
    file; all three must be bit-identical. Returns (fused metrics,
    chain metrics)."""
    host, _ = _collect(path, False)
    fused, mf = _collect(path, True)
    chain, mc = _collect(path, True, extra_conf=FUSED_OFF)
    assert list(host) == list(fused) == list(chain)
    for k in host:
        assert host[k] == fused[k], f"fused decode differs on {k}"
        assert host[k] == chain[k], f"chain decode differs on {k}"
    return mf, mc


def test_fused_decode_single_program_per_batch(tmp_path):
    path = _write(tmp_path, _mixed_table())
    mf, mc = _fused_vs_chain(path)
    assert mf.get("kernelDispatchCount.decodeFused", 0) >= 1, mf
    assert mf.get("kernelFallbacks.decodeFused", 0) == 0, mf
    # the whole fused claim: ONE logical program per decoded batch
    assert mf["deviceDecodedBatches"] >= 1
    assert mf["deviceDecodePrograms"] == mf["deviceDecodedBatches"], mf
    # the chain leg bills its real multi-stage program count
    assert mc.get("kernelDispatchCount.decodeFused", 0) == 0, mc
    assert mc["deviceDecodePrograms"] > mc["deviceDecodedBatches"], mc


@pytest.mark.parametrize("case", ["plain", "dict", "page_nulls",
                                  "dict_overflow"])
def test_fused_decode_parity_matrix(tmp_path, case):
    # the PR 8/9 encoding corpus re-run explicitly as fused-vs-chain
    # A/B: dictionary and PLAIN lanes, nulls straddling tiny pages,
    # and mid-chunk dict overflow all decode bit-identically in ONE
    # program with zero fallbacks
    if case == "plain":
        tbl = _mixed_table(with_nulls=False)
        path = _write(tmp_path, tbl, use_dictionary=False)
    elif case == "dict":
        path = _write(tmp_path, _mixed_table())
    elif case == "page_nulls":
        n = 6000
        vals = [None if (i // 50) % 2 == 0 else i * 3 for i in range(n)]
        svals = [None if (i // 37) % 3 == 1 else f"s{i % 5}"
                 for i in range(n)]
        tbl = pa.table({"v": pa.array(vals, type=pa.int64()),
                        "s": pa.array(svals)})
        path = _write(tmp_path, tbl, data_page_size=512)
    else:
        n = 12_000
        rng = np.random.default_rng(13)
        vals = [f"prefix-{int(v)}-suffix"
                for v in rng.integers(0, 6000, n)]
        tbl = pa.table({"s": pa.array(vals)})
        path = _write(tmp_path, tbl, dictionary_pagesize_limit=8_000,
                      data_page_size=4096)
    mf, _mc = _fused_vs_chain(path)
    assert mf.get("kernelFallbacks.decodeFused", 0) == 0, mf
    assert mf.get("kernelDispatchCount.decodeFused", 0) >= 1, mf


def test_fused_decode_injected_failure_falls_back_bit_identical(
        tmp_path):
    from spark_rapids_tpu import kernels as KR
    path = _write(tmp_path, _mixed_table())
    host, _ = _collect(path, False)
    KR.inject_failure("decodeFused")
    try:
        dev, m = _collect(path, True)
    finally:
        KR.inject_failure("decodeFused", on=False)
        KR.clear_poison()
    for k in host:
        assert host[k] == dev[k], f"fallback decode differs on {k}"
    assert m.get("kernelFallbacks.decodeFused", 0) >= 1, m
    # fallbacks billed at the chain's program count, not the fused 1
    assert m["deviceDecodePrograms"] > m["deviceDecodedBatches"], m


def test_fused_decode_host_only_layout_uses_chain(tmp_path):
    # a file whose every column host-falls-back (DELTA_BYTE_ARRAY is
    # genuinely unsupported) has no device entries: nothing to fuse,
    # no decodeFused fallback billed, parity still holds
    n = 500
    tbl = pa.table({"dba": pa.array([f"prefix-common-{i}"
                                     for i in range(n)])})
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding={"dba": "DELTA_BYTE_ARRAY"})
    host, _ = _collect(path, False)
    dev, m = _collect(path, True)
    for k in host:
        assert host[k] == dev[k]
    assert m.get("kernelFallbacks.decodeFused", 0) == 0, m
