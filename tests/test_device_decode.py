"""Device parquet decode parity corpus (ISSUE 1 tentpole).

Every test asserts the device-decode path (raw page upload + XLA
decode, io/device_decode.py) produces results BIT-IDENTICAL to the
pyarrow host decode over files with controlled encodings: PLAIN,
RLE_DICTIONARY, dictionary-overflow (mixed encodings in one chunk),
nulls at page boundaries, multi-page chunks — plus the per-column
fallback for unsupported encodings, and unit tests of the ops/rle.py
kernels against numpy oracles.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.sql.session import TpuSparkSession

DEV_CONF = "spark.rapids.sql.format.parquet.deviceDecode.enabled"


def _collect(path, device_decode: bool, extra_conf=None, sql=None):
    """Read ``path`` through the engine with a device op above the scan
    (so TpuRowToColumnarExec is the scan's consumer) and return
    (pydict, scan_metrics)."""
    conf = {"spark.rapids.sql.enabled": "true",
            DEV_CONF: str(device_decode).lower()}
    conf.update(extra_conf or {})
    spark = TpuSparkSession(conf)
    try:
        spark.read.parquet(path).createOrReplaceTempView("t")
        df = spark.sql(sql or "SELECT * FROM t")
        spark.start_capture()
        out = df._execute().to_pydict()
        scan_metrics = {}
        for plan in spark.get_captured_plans():
            stack = [plan]
            while stack:
                p = stack.pop()
                if type(p).__name__ == "CpuFileScanExec":
                    for k, v in p.metrics.snapshot().items():
                        scan_metrics[k] = scan_metrics.get(k, 0) + v
                stack.extend(p.children)
        return out, scan_metrics
    finally:
        spark.stop()


def _assert_parity(path, expect_device=True, expect_fallback_cols=0,
                   sql=None):
    host, _m0 = _collect(path, False, sql=sql)
    dev, m = _collect(path, True, sql=sql)
    assert list(host) == list(dev)
    for k in host:
        assert host[k] == dev[k], (
            f"column {k} differs: {host[k][:5]} vs {dev[k][:5]}")
    if expect_device:
        assert m.get("deviceDecodedBatches", 0) >= 1, m
    assert m.get("deviceFallbackColumns", 0) == expect_fallback_cols, m
    return m


def _write(tmp_path, tbl, name="t.parquet", **kw):
    path = os.path.join(str(tmp_path), name)
    pq.write_table(tbl, path, **kw)
    return path


def _mixed_table(n=4000, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    null_every = 7 if with_nulls else 0

    def maybe_null(vals):
        if not null_every:
            return list(vals)
        return [None if i % null_every == 0 else v
                for i, v in enumerate(vals)]

    return pa.table({
        "i64": pa.array(maybe_null(rng.integers(-(1 << 40), 1 << 40, n)
                                   .tolist()), type=pa.int64()),
        "i32": pa.array(maybe_null(rng.integers(-(1 << 30), 1 << 30, n)
                                   .tolist()), type=pa.int32()),
        "f32": pa.array(maybe_null(
            rng.random(n).astype("float32").tolist()), type=pa.float32()),
        "dec": pa.array(maybe_null(rng.integers(-10**9, 10**9, n)
                                   .tolist()), type=pa.decimal128(15, 2)),
        "s": pa.array([None if null_every and i % null_every == 3
                       else f"word{i % 11}" for i in range(n)]),
        "d": pa.array(maybe_null(rng.integers(1000, 20000, n)
                                 .astype("int32").tolist()),
                      type=pa.date32()),
        "b": pa.array(maybe_null((rng.integers(0, 2, n) > 0).tolist()),
                      type=pa.bool_()),
    })


# -- parity corpus ---------------------------------------------------------

def test_plain_encoding_parity(tmp_path):
    tbl = _mixed_table(with_nulls=False).drop_columns(["s"])
    path = _write(tmp_path, tbl, use_dictionary=False)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.PLAIN", 0) > 0, m


def test_rle_dictionary_parity(tmp_path):
    n = 4000
    rng = np.random.default_rng(1)
    tbl = pa.table({
        "i": pa.array(rng.integers(0, 50, n), type=pa.int64()),
        "s": pa.array([f"cat{int(v)}" for v in rng.integers(0, 20, n)]),
        "dec": pa.array(rng.integers(0, 100, n).tolist(),
                        type=pa.decimal128(9, 2)),
    })
    path = _write(tmp_path, tbl)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.RLE_DICTIONARY", 0) > 0, m


def test_nulls_at_page_boundaries(tmp_path):
    # tiny pages + null runs that straddle page boundaries: the
    # definition-level runs then split/lean across pages
    n = 6000
    vals = [None if (i // 50) % 2 == 0 else i * 3 for i in range(n)]
    svals = [None if (i // 37) % 3 == 1 else f"s{i % 5}"
             for i in range(n)]
    tbl = pa.table({"v": pa.array(vals, type=pa.int64()),
                    "s": pa.array(svals)})
    path = _write(tmp_path, tbl, data_page_size=512)
    _assert_parity(path)


def test_multi_page_chunks_dict_overflow(tmp_path):
    # small dict limit + small pages: the writer starts RLE_DICTIONARY,
    # overflows, and finishes the SAME chunk with PLAIN pages
    n = 30_000
    rng = np.random.default_rng(2)
    tbl = pa.table({"x": pa.array(rng.integers(0, 1 << 40, n),
                                  type=pa.int64())})
    path = _write(tmp_path, tbl, dictionary_pagesize_limit=20_000,
                  data_page_size=4096)
    m = _assert_parity(path)
    assert m.get("deviceDecodedValues.PLAIN", 0) > 0, m
    assert m.get("deviceDecodedValues.RLE_DICTIONARY", 0) > 0, m


def test_mixed_types_with_nulls_snappy(tmp_path):
    path = _write(tmp_path, _mixed_table(), compression="snappy",
                  data_page_size=8192)
    _assert_parity(path)


def test_zstd_compression(tmp_path):
    path = _write(tmp_path, _mixed_table(seed=3), compression="zstd")
    _assert_parity(path)


def test_decimal128_flba(tmp_path):
    n = 2000
    rng = np.random.default_rng(4)
    big = [None if i % 11 == 0 else
           int(rng.integers(-10**9, 10**9)) * 10**10 + i
           for i in range(n)]
    tbl = pa.table({"d": pa.array(big, type=pa.decimal128(25, 2))})
    path = _write(tmp_path, tbl)
    _assert_parity(path)


def test_timestamp_micros(tmp_path):
    n = 1500
    rng = np.random.default_rng(5)
    us = rng.integers(0, 2_000_000_000_000_000, n)
    tbl = pa.table({"ts": pa.array(us, type=pa.timestamp("us"))})
    path = _write(tmp_path, tbl, use_dictionary=False)
    _assert_parity(path)


def test_multi_row_group_aggregate(tmp_path):
    n = 20_000
    rng = np.random.default_rng(6)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 9, n), type=pa.int32()),
        "v": pa.array(rng.integers(0, 10**6, n).tolist(),
                      type=pa.decimal128(12, 2)),
    })
    path = _write(tmp_path, tbl, row_group_size=3000)
    _assert_parity(
        path, sql="SELECT k, sum(v) s, count(*) c FROM t "
                  "GROUP BY k ORDER BY k")


# -- fallback behavior -----------------------------------------------------

def test_unsupported_encoding_falls_back_per_column(tmp_path):
    n = 3000
    rng = np.random.default_rng(7)
    tbl = pa.table({
        "delta": pa.array(rng.integers(0, 10**6, n), type=pa.int64()),
        "ok": pa.array(rng.integers(0, 10**6, n), type=pa.int64()),
    })
    path = _write(tmp_path, tbl, use_dictionary=False,
                  column_encoding={"delta": "DELTA_BINARY_PACKED",
                                   "ok": "PLAIN"})
    m = _assert_parity(path, expect_fallback_cols=1)
    # the supported sibling column still decoded on device
    assert m.get("deviceDecodedValues.PLAIN", 0) >= n, m


def test_plain_byte_array_falls_back(tmp_path):
    # PLAIN string pages carry length-prefixed variable bytes — host
    # fallback for that column, device decode for the rest
    n = 2500
    tbl = pa.table({
        "s": pa.array([f"value-{i}" for i in range(n)]),
        "i": pa.array(np.arange(n), type=pa.int64()),
    })
    path = _write(tmp_path, tbl, use_dictionary=False)
    _assert_parity(path, expect_fallback_cols=1)


def test_double_fallback_matches_backend(tmp_path):
    from spark_rapids_tpu.device_caps import f64_bitcast_exact
    n = 2000
    rng = np.random.default_rng(8)
    tbl = pa.table({"f": pa.array(rng.random(n), type=pa.float64())})
    path = _write(tmp_path, tbl, use_dictionary=False)
    expect_fb = 0 if f64_bitcast_exact() else 1
    _assert_parity(path, expect_device=expect_fb == 0,
                   expect_fallback_cols=expect_fb,
                   sql="SELECT f FROM t WHERE f >= 0")


def test_cpu_consumer_never_sees_encoded_batches(tmp_path):
    # rapids disabled: the same conf key must be inert — the scan's
    # emit_encoded gate only opens under a TpuRowToColumnarExec
    path = _write(tmp_path, _mixed_table(n=500, seed=9))
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false",
                             DEV_CONF: "true"})
    try:
        out = spark.read.parquet(path)._execute().to_pydict()
        assert len(out["i64"]) == 500
    finally:
        spark.stop()


def test_partitioned_dataset_device_decode(tmp_path):
    base = str(tmp_path / "part")
    for g in (1, 2):
        os.makedirs(f"{base}/g={g}", exist_ok=True)
        n = 800
        tbl = pa.table({
            "v": pa.array(np.arange(n) * g, type=pa.int64()),
            "s": pa.array([f"p{g}x{i % 3}" for i in range(n)]),
        })
        pq.write_table(tbl, f"{base}/g={g}/part-0.parquet")
    _assert_parity(base,
                   sql="SELECT g, count(*) c, sum(v) s FROM t "
                       "GROUP BY g ORDER BY g")


def test_reader_type_multithreaded_device_decode(tmp_path):
    base = str(tmp_path / "many")
    os.makedirs(base, exist_ok=True)
    rng = np.random.default_rng(10)
    for i in range(6):
        n = 2000
        tbl = pa.table({
            "v": pa.array(rng.integers(0, 10**6, n).tolist(),
                          type=pa.decimal128(10, 2)),
            "k": pa.array(rng.integers(0, 5, n), type=pa.int32()),
        })
        pq.write_table(tbl, f"{base}/f{i}.parquet")
    for rt in ("PERFILE", "MULTITHREADED"):
        host, _ = _collect(
            base, False,
            {"spark.rapids.sql.format.parquet.reader.type": rt},
            sql="SELECT k, sum(v) s FROM t GROUP BY k ORDER BY k")
        dev, m = _collect(
            base, True,
            {"spark.rapids.sql.format.parquet.reader.type": rt},
            sql="SELECT k, sum(v) s FROM t GROUP BY k ORDER BY k")
        assert host == dev
        assert m.get("deviceDecodedBatches", 0) >= 1, (rt, m)


# -- kernel unit tests (ops/rle.py against numpy oracles) ------------------

def _hybrid_stream(values: np.ndarray, width: int):
    """Encode values as one parquet RLE/bit-packed hybrid stream and
    parse it back with the host-side planner, returning the pieces the
    device kernel consumes."""
    from spark_rapids_tpu.io.device_decode import (RunTable,
                                                   _parse_hybrid_runs)
    out = bytearray()
    i, n = 0, len(values)
    while i < n:
        run = 1
        while i + run < n and values[i + run] == values[i]:
            run += 1
        if run >= 8:
            out += _uvarint(run << 1)
            out += int(values[i]).to_bytes((width + 7) // 8, "little")
            i += run
        else:
            j = min(n, i + 8)
            group = list(values[i:j]) + [0] * (8 - (j - i))
            out += _uvarint((1 << 1) | 1)
            bits = 0
            for k, v in enumerate(group):
                bits |= int(v) << (k * width)
            out += bits.to_bytes(width, "little")
            i = j
    runs = RunTable()
    _parse_hybrid_runs(bytes(out), 0, len(out), width, n, 0, 0, runs)
    return np.frombuffer(bytes(out), dtype=np.uint8), runs


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def test_hybrid_lookup_kernel_matches_oracle():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    rng = np.random.default_rng(11)
    for width in (1, 3, 7, 12, 20):
        vals = rng.integers(0, 1 << width, 300)
        vals[40:200] = vals[40]  # force an RLE run
        payload, runs = _hybrid_stream(vals, width)
        words = np.zeros((len(payload) + 3) // 4 * 4, dtype=np.uint8)
        words[:len(payload)] = payload
        bytes_all = R.bytes_of_words(jnp.asarray(words.view(np.int32)))
        arrs = [jnp.asarray(a) for a in runs.arrays(
            max(8, 1 << (len(runs) - 1).bit_length()))]
        pos = jnp.arange(len(vals), dtype=jnp.int64)
        got = np.asarray(R.hybrid_lookup(bytes_all, pos, *arrs))
        assert np.array_equal(got, vals), f"width={width}"


def test_fixed_width_kernels_match_oracle():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    rng = np.random.default_rng(12)
    raw = rng.integers(0, 256, 256).astype(np.uint8)
    words = raw.view(np.int32)
    bytes_all = R.bytes_of_words(jnp.asarray(words))
    # little-endian int64/int32
    offs = np.arange(0, 128, 8, dtype=np.int64)
    got = np.asarray(R.read_le(bytes_all, jnp.asarray(offs), 8))
    assert np.array_equal(got, raw[:128].view(np.int64))
    # big-endian signed (decimal FLBA)
    for w in (3, 7):
        offs = np.arange(0, 10 * w, w, dtype=np.int64)
        got = np.asarray(R.read_be_signed(bytes_all, jnp.asarray(offs), w))
        want = [int.from_bytes(raw[o:o + w].tobytes(), "big", signed=True)
                for o in offs]
        assert got.tolist() == want, f"w={w}"
    # big-endian limbs (decimal128 FLBA)
    w = 13
    offs = np.arange(0, 5 * w, w, dtype=np.int64)
    hi, lo = R.read_be_limbs(bytes_all, jnp.asarray(offs), w)
    for k, o in enumerate(offs):
        full = int.from_bytes(raw[o:o + w].tobytes(), "big", signed=True)
        assert int(hi[k]) == full >> 64
        assert int(lo[k]) & ((1 << 64) - 1) == full & ((1 << 64) - 1)


def test_dense_ranks_kernel():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import rle as R
    v = np.array([True, False, True, True, False, True])
    got = np.asarray(R.dense_ranks(jnp.asarray(v)))
    assert got.tolist() == [0, 0, 1, 2, 2, 3]
