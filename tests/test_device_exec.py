"""Device exec-layer tests through the dual-session harness.

Every case runs the same DataFrame lambda under a CPU session and a TPU
session with ``require_device=True`` so a placement regression (an op
silently falling back to CPU) fails the test — the guard VERDICT round 1
flagged as missing. Mirrors the reference's integration pattern
(integration_tests hash_aggregate_test.py et al. over asserts.py:434).
"""

import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T

from tests.datagen import (BooleanGen, DateGen, DoubleGen, IntegerGen,
                           KeyStringGen, LongGen, SmallIntGen, StringGen,
                           TimestampGen, gen_batch)
from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)

N = 512


def _df(spark, gens, n=N, seed=7, parts=3):
    return spark.createDataFrame(gen_batch(gens, n, seed),
                                 num_partitions=parts)


# ---------------------------------------------------------------------------
# Project / Filter
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("gen", [IntegerGen(), LongGen(), DoubleGen()],
                         ids=["int", "long", "double"])
def test_project_arithmetic(gen):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", gen), ("b", gen)]).select(
            (F.col("a") + F.col("b")).alias("add"),
            (F.col("a") - F.col("b")).alias("sub"),
            (F.col("a") * F.col("b")).alias("mul")),
        expect_execs=["TpuProject"])


def test_project_conditional():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen()), ("b", IntegerGen())]).select(
            F.when(F.col("a") > F.col("b"), F.col("a"))
            .otherwise(F.col("b")).alias("mx"),
            F.coalesce(F.col("a"), F.col("b")).alias("co"),
            F.isnull(F.col("a")).alias("an")),
        expect_execs=["TpuProject"])


def test_filter_predicates():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())])
        .filter((F.col("a") > 3) & F.col("b").isNotNull()),
        expect_execs=["TpuFilter"])


def test_filter_string_predicates():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("s", StringGen())])
        .filter(F.col("s").startswith("a") | (F.length(F.col("s")) > 5)),
        expect_execs=["TpuFilter"])


def test_string_project():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("s", StringGen())]).select(
            F.length(F.col("s")).alias("len"),
            F.concat(F.col("s"), F.lit("_x")).alias("cat")),
        expect_execs=["TpuProject"])


def test_datetime_fields():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DateGen()), ("t", TimestampGen())]).select(
            F.year(F.col("d")).alias("y"),
            F.month(F.col("d")).alias("m"),
            F.dayofmonth(F.col("d")).alias("dm"),
            F.hour(F.col("t")).alias("h")),
        expect_execs=["TpuProject"])


# ---------------------------------------------------------------------------
# Limit / Union / Range
# ---------------------------------------------------------------------------

def test_limit():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen())]).select("a").limit(37)
        .agg(F.count("*").alias("n")),
        expect_execs=["TpuGlobalLimit"])


def test_union():
    def fn(s):
        d1 = _df(s, [("a", IntegerGen())], seed=1)
        d2 = _df(s, [("a", IntegerGen())], seed=2)
        return d1.union(d2)
    assert_tpu_and_cpu_equal_collect(fn, expect_execs=["TpuUnion"])


def test_range():
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.range(1000).select((F.col("id") * 3).alias("x")),
        expect_execs=["TpuRange"])


# ---------------------------------------------------------------------------
# Exchange
# ---------------------------------------------------------------------------

def test_hash_repartition_roundtrip():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", LongGen())])
        .repartition(5, "k").select("k", "v"),
        expect_execs=["TpuExchange"])


def test_exchange_string_keys():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()), ("v", IntegerGen())])
        .repartition(4, "k").select("k", "v"),
        expect_execs=["TpuExchange"])


# ---------------------------------------------------------------------------
# Hash aggregate — the flagship path (VERDICT round 1: must be on device)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("keygen", [SmallIntGen(), KeyStringGen(),
                                    BooleanGen(), DateGen()],
                         ids=["int_keys", "string_keys", "bool_keys",
                              "date_keys"])
def test_grouped_agg_basic(keygen):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", keygen), ("v", IntegerGen())])
        .groupBy("k").agg(
            F.sum("v").alias("s"), F.count("v").alias("c"),
            F.min("v").alias("mn"), F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate mode=partial",
                      "TpuHashAggregate mode=final", "TpuExchange"])


def test_grouped_agg_long_extremes():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", LongGen())])
        .groupBy("k").agg(F.sum("v").alias("s"), F.min("v").alias("mn"),
                          F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate"])


def test_grouped_avg_int():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", IntegerGen())])
        .groupBy("k").agg(F.avg("v").alias("a"), F.count("*").alias("c")),
        expect_execs=["TpuHashAggregate"])


def test_grouped_agg_multi_key():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k1", SmallIntGen()), ("k2", KeyStringGen()),
                          ("v", IntegerGen())])
        .groupBy("k1", "k2").agg(F.sum("v").alias("s"),
                                 F.count("*").alias("c")),
        expect_execs=["TpuHashAggregate"])


def test_grouped_min_max_string():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", StringGen())])
        .groupBy("k").agg(F.min("v").alias("mn"), F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate"])


def test_global_agg():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("v", IntegerGen())]).agg(
            F.sum("v").alias("s"), F.count("v").alias("c"),
            F.min("v").alias("mn"), F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate"])


def test_global_agg_empty_input():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("v", IntegerGen())])
        .filter(F.lit(False)).agg(F.sum("v").alias("s"),
                                  F.count("v").alias("c")),
        require_device=True)


def test_distinct():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen())]).distinct(),
        expect_execs=["TpuHashAggregate"])


def test_agg_with_expr_key():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", IntegerGen()), ("v", LongGen())])
        .groupBy((F.col("k") % 4).alias("km")).agg(F.count("*").alias("c")),
        expect_execs=["TpuHashAggregate"])


def test_float_agg_opt_in():
    # variableFloatAgg default off -> falls back; opt-in runs on device
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", DoubleGen())])
        .groupBy("k").agg(F.sum("v").alias("s")),
        fallback_exec="CpuHashAggregateExec")
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()),
                          ("v", DoubleGen(special=False))])
        .groupBy("k").agg(F.sum("v").alias("s")),
        conf={"spark.rapids.sql.variableFloatAgg.enabled": "true"},
        approx=True,
        expect_execs=["TpuHashAggregate"])


def test_float_min_max_on_device():
    # min/max of floats is ordering-insensitive: stays on device by default
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", DoubleGen())])
        .groupBy("k").agg(F.min("v").alias("mn"), F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate"])


def test_first_last_agg():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", IntegerGen())])
        .groupBy("k").agg(F.first("v", ignorenulls=True).alias("f")),
        expect_execs=["TpuHashAggregate"])


# ---------------------------------------------------------------------------
# Fallback reporting (assert_gpu_fallback_collect pattern)
# ---------------------------------------------------------------------------

def test_fallback_disabled_exec():
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("a", IntegerGen())]).select(
            (F.col("a") + 1).alias("x")),
        fallback_exec="CpuProjectExec",
        conf={"spark.rapids.sql.exec.ProjectExec": "false"})


def test_fallback_disabled_expression():
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("a", IntegerGen())]).select(
            (F.col("a") + 1).alias("x")),
        fallback_exec="CpuProjectExec",
        conf={"spark.rapids.sql.expression.Add": "false"})


def test_decimal_project_on_device():
    """Round 4: decimal arithmetic runs on device (limb kernels); this
    used to assert a CPU fallback."""
    import decimal
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"d": [decimal.Decimal("1.23"), decimal.Decimal("4.56"), None]},
            "d decimal(10,2)").select((0 - F.col("d")).alias("n")),
        expect_execs=["TpuProject"])


def test_incompat_substring_gated():
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("v", StringGen())]).select(
            F.substring(F.col("v"), 1, 3).alias("p")),
        fallback_exec="CpuProjectExec")
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("v", StringGen())]).select(
            F.substring(F.col("v"), 1, 3).alias("p")),
        conf={"spark.rapids.sql.incompatibleOps.enabled": "true"},
        expect_execs=["TpuProject"])


# ---------------------------------------------------------------------------
# Whole-pipeline: scan -> filter -> project -> partial agg -> exchange ->
# final agg, all on device (the reference's TPC-H q1-shaped slice)
# ---------------------------------------------------------------------------

def test_full_pipeline_on_device():
    def fn(s):
        df = _df(s, [("k", SmallIntGen()), ("a", IntegerGen()),
                     ("b", LongGen())], n=2000, parts=4)
        return (df.filter(F.col("a").isNotNull() & (F.col("a") % 3 != 0))
                .select("k", (F.col("a") + F.col("b")).alias("x"))
                .groupBy("k")
                .agg(F.sum("x").alias("s"), F.count("*").alias("c"),
                     F.max("x").alias("mx")))
    assert_tpu_and_cpu_equal_collect(
        fn,
        conf={"spark.rapids.sql.test.forceDevice": "true"},
        expect_execs=["TpuFilter", "TpuProject", "TpuHashAggregate",
                      "TpuExchange"])


# ---------------------------------------------------------------------------
# Rollup / cube (Aggregate over TpuExpand)
# ---------------------------------------------------------------------------

def test_rollup_on_device():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k1", SmallIntGen()), ("k2", BooleanGen()),
                          ("v", LongGen())], n=600)
        .rollup("k1", "k2").agg(F.sum("v").alias("s"),
                                F.count("*").alias("c")),
        expect_execs=["TpuExpand", "TpuHashAggregate"])


def test_cube_on_device():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k1", SmallIntGen()), ("k2", BooleanGen()),
                          ("v", IntegerGen())], n=400)
        .cube("k1", "k2").agg(F.min("v").alias("mn"),
                              F.max("v").alias("mx")),
        expect_execs=["TpuExpand", "TpuHashAggregate"])


def test_rollup_exact_values():
    from spark_rapids_tpu.sql.session import TpuSparkSession
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        df = s.createDataFrame(
            {"k": ["a", "a", "b"], "v": [1, 2, 4]}, "k string, v int")
        rows = {(r.k, r.s) for r in
                df.rollup("k").agg(F.sum("v").alias("s")).collect()}
        assert rows == {("a", 3), ("b", 4), (None, 7)}
    finally:
        s.stop()


def test_coalesce_batches_inserted_after_exchange():
    """Project over a repartition sees TpuCoalesceBatches in the plan."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        df = _df(s, [("k", SmallIntGen()), ("v", IntegerGen())], n=500,
                 parts=4)
        out = df.repartition(4, "k").select(
            (F.col("v") + 1).alias("v1"))
        assert "TpuCoalesceBatches" in s.explain_string(out.plan), \
            s.explain_string(out.plan)
        got = {r.v1 for r in out.collect()}
        want = {r.v1 for r in df.select((F.col("v") + 1).alias("v1"))
                .collect()}
        assert got == want
    finally:
        s.stop()


def test_stddev_variance_device():
    """Round 4: stddev/variance family on device (CentralMomentAgg via
    count/sum/sumsq buffers; n==1 sample -> NaN)."""
    import numpy as np
    rng = np.random.default_rng(8)
    rows = {"k": [f"g{i % 5}" for i in range(300)] + ["solo"],
            "v": rng.uniform(-100, 100, 301).tolist()}
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(rows, "k string, v double")
        .groupBy("k").agg(F.stddev("v").alias("sd"),
                          F.stddev_pop("v").alias("sp"),
                          F.var_samp("v").alias("vs"),
                          F.var_pop("v").alias("vp")).orderBy("k"),
        conf={"spark.rapids.sql.incompatibleOps.enabled": "true",
              "spark.rapids.sql.variableFloatAgg.enabled": "true"},
        approx=True,  # float sum order differs (variableFloatAgg)
        expect_execs=["TpuHashAggregate"])


def test_pivot_device():
    """groupBy().pivot().agg() lowers to conditional aggregates on the
    device path (GpuPivotFirst's CASE WHEN equivalent)."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"k": ["a", "b", "a", "a", "b", None],
             "p": ["x", "x", "y", "y", "x", "y"],
             "v": [1, 2, 3, 4, 5, 6]}, "k string, p string, v int")
        .groupBy("k").pivot("p", ["x", "y", "z"])
        .agg(F.sum("v").alias("s")).orderBy("k"),
        expect_execs=["TpuHashAggregate"])


def test_count_distinct_device():
    """count(DISTINCT x) runs device-placed via the dedup-then-count
    rewrite (RewriteDistinctAggregates single-group shape)."""
    def q(s):
        s.createDataFrame(
            {"k": ["a", "b", "a", "a", "b"], "v": [1, 2, 2, 3, 2]},
            "k string, v int").createOrReplaceTempView("cd")
        return s.sql("SELECT k, count(DISTINCT v) c FROM cd "
                     "GROUP BY k ORDER BY k")
    assert_tpu_and_cpu_equal_collect(
        q, ignore_order=False, expect_execs=["TpuHashAggregate"])


def test_mixed_distinct_and_plain_aggregates_device():
    """count(DISTINCT a), sum(b) in ONE aggregate: the planner splits
    into a distinct-only and a plain aggregate joined on null-safe key
    equality (Spark RewriteDistinctAggregates role, aggregate.scala:1059)
    — round-4 verdict: this shape must not raise. Device-placed
    end-to-end (aggs + null-safe join)."""
    def q(s):
        s.createDataFrame(
            {"k": ["a", "b", None, "a", "b", None],
             "a": [1, 2, 2, None, 2, 1],
             "v": [10, 20, 30, 40, None, 60]},
            "k string, a int, v long").createOrReplaceTempView("md")
        return s.sql(
            "SELECT k, count(DISTINCT a) cd, sum(v) sv, count(v) cv, "
            "avg(v) av FROM md GROUP BY k ORDER BY k")
    assert_tpu_and_cpu_equal_collect(
        q, ignore_order=False,
        expect_execs=["TpuHashAggregate", "TpuShuffledHashJoin"])


def test_mixed_distinct_global():
    def q(s):
        s.createDataFrame({"a": [1, 2, 2, None, 3], "v": [1, 2, 3, 4, 5]},
                          "a int, v int").createOrReplaceTempView("mg")
        return s.sql("SELECT count(DISTINCT a) cd, sum(v) sv FROM mg")
    assert_tpu_and_cpu_equal_collect(q, require_device=False)


def test_null_safe_equality_join_keys():
    """<=> join keys match null to null on BOTH engines (EqualNullSafe
    extracted as equi-keys, not residual)."""
    def fn(s):
        l = s.createDataFrame({"k": [1, None, 2, None], "a": [1, 2, 3, 4]},
                              "k int, a int")
        r = s.createDataFrame({"k2": [None, 1, 3], "b": [10, 20, 30]},
                              "k2 int, b long").repartition(2)
        return l.join(r, F.col("k").eqNullSafe(F.col("k2")), "inner")
    assert_tpu_and_cpu_equal_collect(
        fn, conf={"spark.rapids.sql.autoBroadcastJoinThreshold": "-1"},
        expect_execs=["TpuShuffledHashJoin"])


def test_collect_list_and_set():
    """collect_list/collect_set (AggregateFunctions.scala:953 role):
    CPU-engine aggregation with clean device fallback tagging."""
    def q(s):
        s.createDataFrame(
            {"k": ["a", "b", "a", None, "b", "a"],
             "v": [3, 1, None, 4, 1, 5],
             "d": ["x", "y", "x", None, "y", "z"]},
            "k string, v int, d string").createOrReplaceTempView("cl")
        return s.sql("SELECT k, collect_list(v) lv, collect_set(d) sd, "
                     "sum(v) sv FROM cl GROUP BY k ORDER BY k")
    assert_tpu_fallback_collect(q, fallback_exec="CpuHashAggregateExec")


def test_monotonically_increasing_id_and_partition_id():
    """monotonically_increasing_id / spark_partition_id device-placed
    (GpuMonotonicallyIncreasingID.scala, GpuSparkPartitionID roles):
    pid << 33 | row-position, row positions continuing across batches
    via a device row-start scalar."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"v": list(range(2000))}, "v int", num_partitions=3)
        .select("v", F.monotonically_increasing_id().alias("id"),
                F.spark_partition_id().alias("p")),
        expect_execs=["TpuProject"])


def test_monotonic_id_after_filter():
    def q(s):
        s.createDataFrame({"v": list(range(500))}, "v int",
                          num_partitions=2).createOrReplaceTempView("mi")
        return s.sql("SELECT v, monotonically_increasing_id() i FROM mi "
                     "WHERE v % 3 = 0")
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuProject"])


def test_input_file_name(tmp_path):
    """input_file_name() over a parquet scan (InputFileBlockRule role:
    CPU-confined, scan-adjacent)."""
    import os

    def q(s):
        d = os.path.join(str(tmp_path), "iff")
        if not os.path.exists(d):
            gen = s.createDataFrame({"v": list(range(100))}, "v int",
                                    num_partitions=2)
            gen.write.mode("overwrite").parquet(d)
        return s.read.parquet(d).select(
            F.input_file_name().alias("f"), "v")
    assert_tpu_and_cpu_equal_collect(q, require_device=False)


def _find_exec(plan, name):
    found = []

    def walk(p):
        if p.simple_string().startswith(name):
            found.append(p)
        for c in p.children:
            walk(c)
    walk(plan)
    return found


def test_aqe_runtime_broadcast_flip():
    """AQE v0 (GpuOverrides.scala:3550 role): a shuffled hash join whose
    build side MEASURES under the broadcast threshold at exchange
    materialization flips to a broadcast-style join at runtime — the
    static estimate (pre-filter) kept it shuffled."""
    from spark_rapids_tpu.sql.session import TpuSparkSession

    def build(extra_conf):
        conf = {"spark.rapids.sql.enabled": "true",
                # static estimate of the right side (pre-filter) is far
                # above this, so the PLANNER picks a shuffled join;
                # the filtered runtime bytes land far below it
                "spark.rapids.sql.autoBroadcastJoinThreshold": "4096"}
        conf.update(extra_conf)
        s = TpuSparkSession(conf)
        l = s.createDataFrame(
            {"k": [i % 97 for i in range(5000)],
             "a": list(range(5000))}, "k int, a int", num_partitions=2)
        r = s.createDataFrame(
            {"k2": list(range(2000)), "b": list(range(2000))},
            "k2 int, b long").filter(F.col("k2") < 40)
        q = l.join(r, F.col("k") == F.col("k2"), "inner")
        s.start_capture()
        rows = sorted(map(tuple, q.collect()))
        plan = s.get_captured_plans()[-1]
        joins = _find_exec(plan, "TpuShuffledHashJoin")
        assert joins, plan
        flips = sum(j.metrics.value("aqeBroadcastFlip") for j in joins)
        s.stop()
        return rows, flips

    on_rows, on_flips = build({})
    off_rows, off_flips = build({"spark.sql.adaptive.enabled": "false"})
    assert on_rows == off_rows
    assert on_flips >= 1, "AQE did not flip the small build side"
    assert off_flips == 0


def test_aqe_partition_coalescing():
    """Tiny post-shuffle partitions coalesce toward the advisory size
    before the final aggregate (GpuCustomShuffleReaderExec role)."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    s = TpuSparkSession({
        "spark.rapids.sql.enabled": "true",
        "spark.sql.shuffle.partitions": "8",
        "spark.rapids.sql.shuffle.devicePartitions": "8",
    })
    df = s.createDataFrame(
        {"k": [i % 50 for i in range(1000)], "v": list(range(1000))},
        "k int, v long", num_partitions=4)
    q = df.groupBy("k").agg(F.sum("v").alias("s")).orderBy("k")
    s.start_capture()
    rows = [tuple(r) for r in q.collect()]
    plans = s.get_captured_plans()
    coalesced = 0
    for p in plans:
        for ex in _find_exec(p, "TpuExchange"):
            coalesced += ex.metrics.value("aqeCoalescedPartitions")
    s.stop()
    assert coalesced > 0, "no AQE partition coalescing happened"
    assert rows == sorted(
        [(k, sum(v for v in range(1000) if v % 50 == k))
         for k in range(50)])
