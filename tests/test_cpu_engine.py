"""CPU engine smoke tests: the baseline half of the dual-session harness."""

import math

import pytest

from spark_rapids_tpu.sql.session import TpuSparkSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "false",
                         "spark.sql.shuffle.partitions": "4"})
    yield s
    s.stop()


def test_select_project(spark):
    df = spark.createDataFrame(
        {"a": [1, 2, None, 4], "b": [10.0, 20.0, 30.0, None]},
        "a int, b double")
    out = df.select((F.col("a") + 1).alias("a1"), "b").collect()
    assert [r.a1 for r in out] == [2, 3, None, 5]
    assert [r.b for r in out] == [10.0, 20.0, 30.0, None]


def test_filter(spark):
    df = spark.createDataFrame({"a": [1, 2, None, 4, 5]}, "a int")
    out = df.filter(F.col("a") > 2).collect()
    assert sorted(r.a for r in out) == [4, 5]


def test_three_valued_logic(spark):
    df = spark.createDataFrame(
        {"a": [True, False, None], "b": [None, None, None]},
        "a boolean, b boolean")
    out = df.select(
        (F.col("a") & F.col("b")).alias("and_"),
        (F.col("a") | F.col("b")).alias("or_")).collect()
    assert [r.and_ for r in out] == [None, False, None]
    assert [r.or_ for r in out] == [True, None, None]


def test_groupby_agg(spark):
    df = spark.createDataFrame(
        {"k": ["a", "b", "a", "b", "a", None],
         "v": [1, 2, 3, None, 5, 10]}, "k string, v int")
    out = df.groupBy("k").agg(
        F.sum("v").alias("s"),
        F.count("v").alias("c"),
        F.avg("v").alias("m"),
        F.min("v").alias("lo"),
        F.max("v").alias("hi")).collect()
    by_k = {r.k: r for r in out}
    assert by_k["a"].s == 9 and by_k["a"].c == 3
    assert by_k["b"].s == 2 and by_k["b"].c == 1
    assert by_k[None].s == 10 and by_k[None].c == 1
    assert by_k["a"].m == pytest.approx(3.0)
    assert by_k["a"].lo == 1 and by_k["a"].hi == 5


def test_global_agg_empty_and_nonempty(spark):
    df = spark.createDataFrame({"v": [1, 2, 3]}, "v int")
    out = df.agg(F.sum("v").alias("s"), F.count("*").alias("c")).collect()
    assert out[0].s == 6 and out[0].c == 3
    empty = df.filter(F.col("v") > 100).agg(
        F.sum("v").alias("s"), F.count("*").alias("c")).collect()
    assert empty[0].s is None and empty[0].c == 0


def test_join_inner(spark):
    left = spark.createDataFrame(
        {"k": [1, 2, 3, None], "l": ["a", "b", "c", "d"]},
        "k int, l string")
    right = spark.createDataFrame(
        {"k": [2, 3, 4, None], "r": ["x", "y", "z", "w"]},
        "k int, r string", num_partitions=1)
    out = left.join(right, "k").collect()
    got = sorted((r.k, r.l, r.r) for r in out)
    assert got == [(2, "b", "x"), (3, "c", "y")]


def test_join_left_outer(spark):
    left = spark.createDataFrame({"k": [1, 2], "l": ["a", "b"]},
                                 "k int, l string")
    right = spark.createDataFrame({"k": [2], "r": ["x"]}, "k int, r string")
    out = left.join(right, "k", "left").collect()
    got = {(r.k, r.l, r.r) for r in out}
    assert got == {(1, "a", None), (2, "b", "x")}


def test_sort(spark):
    df = spark.createDataFrame(
        {"a": [3, 1, None, 2], "b": [1.0, float("nan"), 2.0, None]},
        "a int, b double")
    out = df.orderBy(F.col("a")).collect()
    assert [r.a for r in out] == [None, 1, 2, 3]  # nulls first asc
    out2 = df.orderBy(F.col("a").desc()).collect()
    assert [r.a for r in out2] == [3, 2, 1, None]  # nulls last desc
    out3 = df.orderBy(F.col("b")).collect()
    bs = [r.b for r in out3]
    assert bs[0] is None and bs[1] == 1.0 and bs[2] == 2.0 \
        and math.isnan(bs[3])  # NaN sorts greatest


def test_limit_union_distinct(spark):
    df = spark.createDataFrame({"a": [1, 2, 3, 4, 5]}, "a int")
    assert df.limit(3).count() == 3
    assert df.union(df).count() == 10
    assert df.union(df).distinct().count() == 5


def test_case_when_and_cast(spark):
    df = spark.createDataFrame({"a": [1, 2, None]}, "a int")
    out = df.select(
        F.when(F.col("a") > 1, "big").otherwise("small").alias("c"),
        F.col("a").cast("string").alias("s"),
        F.col("a").cast("double").alias("d")).collect()
    assert [r.c for r in out] == ["small", "big", "small"]
    assert [r.s for r in out] == ["1", "2", None]
    assert [r.d for r in out] == [1.0, 2.0, None]


def test_string_functions(spark):
    df = spark.createDataFrame({"s": ["Hello", "WORLD", None, ""]},
                               "s string")
    out = df.select(
        F.upper("s").alias("u"), F.lower("s").alias("l"),
        F.length("s").alias("n"),
        F.substring("s", 2, 3).alias("sub")).collect()
    assert [r.u for r in out] == ["HELLO", "WORLD", None, ""]
    assert [r.n for r in out] == [5, 5, None, 0]
    assert [r.sub for r in out] == ["ell", "ORL", None, ""]


def test_integer_overflow_wraps(spark):
    df = spark.createDataFrame({"a": [2**31 - 1]}, "a int")
    out = df.select((F.col("a") + 1).alias("x")).collect()
    assert out[0].x == -(2**31)


def test_division_semantics(spark):
    df = spark.createDataFrame({"a": [7, -7], "b": [2, 2]}, "a int, b int")
    out = df.select(
        (F.col("a") / F.col("b")).alias("d"),
        (F.col("a") % F.col("b")).alias("m")).collect()
    assert out[0].d == 3.5 and out[1].d == -3.5
    assert out[0].m == 1 and out[1].m == -1  # sign of dividend


def test_hash_partitioning_stability(spark):
    # group results identical regardless of partition count
    data = {"k": [i % 7 for i in range(100)], "v": list(range(100))}
    df1 = TpuSparkSession({"spark.rapids.sql.enabled": "false",
                           "spark.sql.shuffle.partitions": "1"}
                          ).createDataFrame(data, "k int, v long")
    df8 = TpuSparkSession({"spark.rapids.sql.enabled": "false",
                           "spark.sql.shuffle.partitions": "8"}
                          ).createDataFrame(data, "k int, v long")
    r1 = sorted((r.k, r.s) for r in df1.groupBy("k").agg(
        F.sum("v").alias("s")).collect())
    r8 = sorted((r.k, r.s) for r in df8.groupBy("k").agg(
        F.sum("v").alias("s")).collect())
    assert r1 == r8
