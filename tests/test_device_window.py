"""Device window-function tests through the dual-session harness
(GpuWindowExec coverage; reference pattern: window_function_test.py).
"""

import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.functions import Window

from tests.datagen import (DoubleGen, IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, StringGen, gen_batch)
from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)

N = 400


def _df(spark, gens, n=N, seed=13, parts=2):
    return spark.createDataFrame(gen_batch(gens, n, seed),
                                 num_partitions=parts)


def _w(order=True):
    w = Window.partitionBy("k")
    return w.orderBy("o") if order else w


@pytest.mark.parametrize("fn_col", [
    lambda: F.row_number(), lambda: F.rank(), lambda: F.dense_rank(),
    lambda: F.ntile(3)],
    ids=["row_number", "rank", "dense_rank", "ntile"])
def test_ranking_functions(fn_col):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen())])
        .select("k", "o", fn_col().over(_w()).alias("r")),
        expect_execs=["TpuWindow"])


@pytest.mark.parametrize("agg", [
    lambda c: F.sum(c), lambda c: F.count(c), lambda c: F.min(c),
    lambda c: F.max(c)], ids=["sum", "count", "min", "max"])
def test_running_aggregates(agg):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", "v", agg("v").over(_w()).alias("a"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


@pytest.mark.parametrize("agg", [
    lambda c: F.sum(c), lambda c: F.count(c), lambda c: F.min(c),
    lambda c: F.max(c), lambda c: F.avg(c)],
    ids=["sum", "count", "min", "max", "avg"])
def test_whole_partition_aggregates(agg):
    # avg over ints is exact only under the float-agg knob on this backend
    conf = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", IntegerGen())])
        .select("k", "v", agg("v").over(Window.partitionBy("k"))
                .alias("a")),
        conf=conf, approx=True,
        expect_execs=["TpuWindow"])


def test_bounded_rows_frame_sum_count():
    w = _w().rowsBetween(-2, 1)
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", "o", F.sum("v").over(w).alias("s"),
                F.count("v").over(w).alias("c"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


def test_rows_running_frame():
    w = _w().rowsBetween(Window.unboundedPreceding, 0)
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", F.sum("v").over(w).alias("s"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


def test_lag_lead():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", "o", F.lag("v", 1).over(_w()).alias("lg"),
                F.lead("v", 2).over(_w()).alias("ld"),
                F.lag("v", 1, 0).over(_w()).alias("lgd"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


def test_lag_string_values():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", KeyStringGen())])
        .select("k", "o", F.lag("v", 1).over(_w()).alias("lg"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


def test_first_last_over_partition():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", F.first("v").over(_w()).alias("f"),
                F.last("v").over(_w()).alias("l"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


def test_window_no_partition():
    """Empty partitionBy: the whole dataset is one window partition."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("o", IntegerGen()), ("v", LongGen())], n=200)
        .select("o", "v",
                F.row_number().over(Window.orderBy("o", "v")).alias("rn")),
        expect_execs=["TpuWindow"])


def test_window_string_partition_keys():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", F.sum("v").over(_w()).alias("s"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


def test_float_window_sum_falls_back():
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", DoubleGen())])
        .select("k", F.sum("v").over(_w()).alias("s")),
        fallback_exec="CpuWindowExec")


def test_bounded_min_on_device():
    """Round 4: bounded-frame min/max runs on device (sparse-table RMQ);
    this used to assert a CPU fallback."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", LongGen())])
        .select("k", "o", "v",
                F.min("v").over(_w().rowsBetween(-1, 1)).alias("m")),
        expect_execs=["TpuWindow"])


def test_window_then_filter_pipeline():
    def fn(s):
        df = _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                     ("v", LongGen())])
        return (df.withColumn("rn", F.row_number().over(_w()))
                .filter(F.col("rn") <= 3))
    assert_tpu_and_cpu_equal_collect(fn, expect_execs=["TpuWindow",
                                                       "TpuFilter"])


def test_lag_string_with_default():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("o", IntegerGen()),
                          ("v", KeyStringGen())])
        .select("k", "o", F.lag("v", 1, "DFLT").over(_w()).alias("lg"),
                F.row_number().over(_w()).alias("rn")),
        expect_execs=["TpuWindow"])


# -- round 4: bounded min/max, value-bounded RANGE, key batching -----------

def test_bounded_rows_min_max():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()), ("o", IntegerGen()),
                          ("v", IntegerGen())])
        .select("k", "o", "v",
                F.min("v").over(Window.partitionBy("k").orderBy("o", "v")
                                .rowsBetween(-3, 2)).alias("mn"),
                F.max("v").over(Window.partitionBy("k").orderBy("o", "v")
                                .rowsBetween(0, 4)).alias("mx")),
        expect_execs=["TpuWindow"])


def test_value_bounded_range_frames():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()), ("o", IntegerGen()),
                          ("v", IntegerGen())])
        .select("k", "o", "v",
                F.sum("v").over(Window.partitionBy("k").orderBy("o")
                                .rangeBetween(-10, 10)).alias("s"),
                F.count("v").over(Window.partitionBy("k").orderBy("o")
                                  .rangeBetween(0, 25)).alias("c"),
                F.min("v").over(Window.partitionBy("k").orderBy("o")
                                .rangeBetween(-50, 0)).alias("mn")),
        expect_execs=["TpuWindow"])


def test_value_bounded_range_desc_and_nulls():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()),
                          ("o", IntegerGen(null_prob=0.2)),
                          ("v", IntegerGen())])
        .select("k", "o", "v",
                F.max("v").over(Window.partitionBy("k")
                                .orderBy(F.col("o").desc())
                                .rangeBetween(-7, 3)).alias("mx")),
        expect_execs=["TpuWindow"])


def test_window_key_batching_over_budget():
    """Giant partitions stream through the key-batching iterator (chunks
    split only at partition-key boundaries) under a tiny batch goal and
    HBM budget — GpuKeyBatchingIterator + spill-framework contract."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()), ("o", IntegerGen()),
                          ("v", LongGen())], n=2000)
        .select("k", "o", "v",
                F.row_number().over(Window.partitionBy("k").orderBy("o", "v"))
                .alias("rn"),
                F.sum("v").over(Window.partitionBy("k").orderBy("o", "v"))
                .alias("rs")),
        conf={"spark.rapids.sql.batchSizeRows": "256",
              "spark.rapids.memory.tpu.poolSize": str(1 << 16)},
        expect_execs=["TpuWindow"])


def test_value_bounded_range_nan_order_values():
    """NaN order values form their own peer block (Spark total order:
    all NaNs equal, greatest): NaN rows frame the NaN block, finite
    rows' value frames exclude it — on both engines, ASC and DESC."""
    nan = float("nan")
    rows = {"k": ["a"] * 10 + ["b"] * 6,
            "o": [1.0, 2.0, 3.0, nan, nan, None, 4.0, 5.0, nan, None,
                  2.0, nan, 1.0, None, 3.0, nan],
            "v": list(range(16))}
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(rows, "k string, o double, v int")
        .select("k", "o", "v",
                F.sum("v").over(Window.partitionBy("k").orderBy("o")
                                .rangeBetween(-1, 1)).alias("s"),
                F.sum("v").over(Window.partitionBy("k")
                                .orderBy(F.col("o").desc())
                                .rangeBetween(-1, 1)).alias("sd"),
                F.count("v").over(
                    Window.partitionBy("k").orderBy("o")
                    .rangeBetween(Window.unboundedPreceding, 0))
                .alias("cu")),
        expect_execs=["TpuWindow"])


def test_lag_lead_decimal128_on_device():
    """lag/lead over DECIMAL128 columns now runs on device (two-limb
    gather in exec/window.py _offset_fn) — formerly a CPU fallback."""
    from decimal import Decimal


    def q(spark):
        vals = [None if i % 7 == 0 else
                Decimal(10 ** 20 + i * 137) / Decimal(100)
                for i in range(60)]
        df = spark.createDataFrame(
            {"g": [i % 4 for i in range(60)],
             "o": list(range(60)), "d": vals},
            "g int, o int, d decimal(25,2)")
        w = Window.partitionBy("g").orderBy("o")
        return df.select(
            "g", "o",
            F.lag("d", 1).over(w).alias("lg"),
            F.lead("d", 2).over(w).alias("ld"),
            F.lag("d", 1, Decimal("0.55")).over(w).alias("lgd"))
    assert_tpu_and_cpu_equal_collect(q)
