"""Nested arrays + Generate/explode device parity
(GpuGenerateExec.scala:440 / collectionOperations.scala roles)."""

import numpy as np
import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T

from tests.harness import assert_tpu_and_cpu_equal_collect


def _arr_df(s, seed=11, n=400, parts=3, element="bigint"):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        ln = int(rng.integers(0, 5))
        choice = rng.random()
        if choice < 0.1:
            rows.append(None)
        else:
            row = [int(rng.integers(-100, 100)) if rng.random() > 0.15
                   else None for _ in range(ln)]
            rows.append(row)
    data = {"k": list(range(n)), "a": rows}
    return s.createDataFrame(data, f"k int, a array<{element}>",
                             num_partitions=parts)


def test_device_explode():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s).select("k", F.explode("a").alias("x")),
        expect_execs=["TpuGenerate"])


def test_device_explode_outer():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s, seed=12).select(
            "k", F.explode_outer("a").alias("x")),
        expect_execs=["TpuGenerate"])


def test_device_posexplode():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s, seed=13).select("k", F.posexplode("a")),
        expect_execs=["TpuGenerate"])


def test_device_posexplode_outer():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s, seed=14).select(
            "k", F.posexplode_outer("a")),
        expect_execs=["TpuGenerate"])


def test_device_explode_after_filter():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s, seed=15)
        .filter(F.col("k") % 3 != 1)
        .select("k", F.explode("a").alias("x")),
        expect_execs=["TpuGenerate", "TpuFilter"])


def test_device_explode_strings():
    def fn(s):
        rows = [["ab", "c"], [], None, ["xyz", None, "q"], ["zz"]]
        return s.createDataFrame(
            {"k": list(range(5)), "a": rows},
            "k int, a array<string>", num_partitions=2) \
            .select("k", F.explode_outer("a").alias("x"))
    assert_tpu_and_cpu_equal_collect(fn, expect_execs=["TpuGenerate"])


def test_device_size_element_at_contains():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s, seed=16).select(
            "k",
            F.size("a").alias("sz"),
            F.element_at("a", 1).alias("e1"),
            F.element_at("a", -2).alias("em"),
            F.col("a").getItem(0).alias("g0"),
            F.array_contains("a", 42).alias("c42")),
        expect_execs=["TpuProject"])


def test_device_create_array_and_explode():
    def fn(s):
        df = s.createDataFrame(
            {"x": [1, 2, None, 4], "y": [9, None, 7, 6]},
            "x bigint, y bigint", num_partitions=2)
        return df.select(F.explode(F.array("x", "y")).alias("v"))
    # explode over computed arrays falls back to CPU generate; the
    # array construction itself must still be device-placeable
    assert_tpu_and_cpu_equal_collect(fn, require_device=False)


def test_device_generate_after_parquet_roundtrip(tmp_path):
    def fn(s):
        df = _arr_df(s, seed=17, n=100, parts=2)
        path = str(tmp_path / "nested")
        df.write.mode("overwrite").parquet(path)
        return s.read.parquet(path).select(
            "k", F.explode_outer("a").alias("x"))
    assert_tpu_and_cpu_equal_collect(fn, expect_execs=["TpuGenerate"])


def test_heavy_ops_fall_back_on_arrays():
    """Aggregation/sort carrying array columns must fall back cleanly."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _arr_df(s, seed=18, n=60).orderBy("k"),
        ignore_order=False, require_device=False)
