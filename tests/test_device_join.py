"""Device join tests through the dual-session harness (GpuHashJoin
coverage; reference integration pattern: integration_tests join_test.py).
Covers broadcast + shuffled paths, all join types, null keys, duplicate
keys, string/float/multi keys, residual conditions, and self-joins.
The right side is .repartition()-ed to force the shuffled path (the
planner broadcasts small LocalRelations otherwise).
"""

import pytest

from spark_rapids_tpu.sql import functions as F

from tests.datagen import (DoubleGen, IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, StringGen, gen_batch)
from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)

ALL_JOINS = ["inner", "left", "right", "full", "leftsemi", "leftanti"]


def _pair(spark, kgen, n=300, parts=2, seed=3):
    left = spark.createDataFrame(
        gen_batch([("k", kgen), ("a", IntegerGen())], n, seed),
        num_partitions=parts)
    right = spark.createDataFrame(
        gen_batch([("k2", kgen), ("b", LongGen())], n // 2, seed + 1),
        num_partitions=parts)
    return left, right


@pytest.mark.parametrize("jt", ALL_JOINS)
def test_broadcast_join_int_keys(jt):
    # the planner only broadcasts build-right-able join types; right/full
    # plan as shuffled joins (same as Spark's BuildSide constraint)
    expected = ("TpuBroadcastHashJoin"
                if jt in ("inner", "left", "leftsemi", "leftanti")
                else "TpuShuffledHashJoin")

    def fn(s):
        l, r = _pair(s, SmallIntGen())
        return l.join(r, l["k"] == r["k2"], jt)
    assert_tpu_and_cpu_equal_collect(fn, expect_execs=[expected])


@pytest.mark.parametrize("jt", ALL_JOINS)
def test_shuffled_join_int_keys(jt):
    def fn(s):
        l, r = _pair(s, SmallIntGen())
        return l.join(r.repartition(3), l["k"] == r["k2"], jt)
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuShuffledHashJoin"])


@pytest.mark.parametrize("kgen", [KeyStringGen(), DoubleGen(), LongGen()],
                         ids=["string", "double", "long"])
def test_join_key_types(kgen):
    def fn(s):
        l, r = _pair(s, kgen)
        return l.join(r, l["k"] == r["k2"], "inner")
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuBroadcastHashJoin"])


def test_join_multi_key():
    def fn(s):
        l = s.createDataFrame(
            gen_batch([("k1", SmallIntGen()), ("k2", KeyStringGen()),
                       ("a", IntegerGen())], 400, 5), num_partitions=2)
        r = s.createDataFrame(
            gen_batch([("j1", SmallIntGen()), ("j2", KeyStringGen()),
                       ("b", LongGen())], 200, 6), num_partitions=2)
        return l.join(r, (l["k1"] == r["j1"]) & (l["k2"] == r["j2"]),
                      "left")
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuBroadcastHashJoin"])


def test_join_inner_with_condition():
    def fn(s):
        l, r = _pair(s, SmallIntGen())
        return l.join(r, (l["k"] == r["k2"]) & (l["a"] > r["b"]), "inner")
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuBroadcastHashJoin"])


def test_conditional_outer_join_falls_back():
    def fn(s):
        l, r = _pair(s, SmallIntGen())
        return l.join(r, (l["k"] == r["k2"]) & (l["a"] > r["b"]), "left")
    assert_tpu_fallback_collect(fn, fallback_exec="CpuBroadcastHashJoinExec")


def test_self_join():
    def fn(s):
        df = s.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("v", IntegerGen())], 150, 9),
            num_partitions=2)
        other = df.select(F.col("k").alias("k2"),
                          F.col("v").alias("v2"))
        return df.join(other, F.col("k") == F.col("k2"), "inner")
    # threshold -1 pins the shuffled path (the projected LocalRelation
    # would otherwise be size-estimated under the broadcast threshold)
    assert_tpu_and_cpu_equal_collect(
        fn, conf={"spark.rapids.sql.autoBroadcastJoinThreshold": "-1"},
        expect_execs=["TpuShuffledHashJoin"])


def test_join_all_null_keys():
    def fn(s):
        l = s.createDataFrame({"k": [None, None, 1], "a": [1, 2, 3]},
                              "k int, a int")
        r = s.createDataFrame({"k2": [None, 1], "b": [10, 20]},
                              "k2 int, b int")
        return l.join(r, F.col("k") == F.col("k2"), "full")
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuShuffledHashJoin"])


def test_join_empty_sides():
    def fn(s):
        l = s.createDataFrame({"k": [], "a": []}, "k int, a int")
        r = s.createDataFrame({"k2": [1, 2], "b": [10, 20]},
                              "k2 int, b int")
        return l.join(r, F.col("k") == F.col("k2"), "right")
    assert_tpu_and_cpu_equal_collect(fn, require_device=False)


def test_join_duplicate_heavy_keys():
    """Many-to-many expansion: every left row matches many right rows."""
    def fn(s):
        l = s.createDataFrame({"k": [1] * 40 + [2] * 20,
                               "a": list(range(60))}, "k int, a int")
        r = s.createDataFrame({"k2": [1] * 15 + [2] * 25,
                               "b": list(range(40))}, "k2 int, b int")
        return l.join(r, F.col("k") == F.col("k2"), "inner")
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuBroadcastHashJoin"])


def test_join_then_agg_pipeline_on_device():
    def fn(s):
        l, r = _pair(s, SmallIntGen(), n=500)
        return (l.join(r, l["k"] == r["k2"], "inner")
                .groupBy("k").agg(F.count("*").alias("c"),
                                  F.sum("b").alias("sb")))
    assert_tpu_and_cpu_equal_collect(
        fn, expect_execs=["TpuBroadcastHashJoin", "TpuHashAggregate"])


@pytest.mark.parametrize("jt", ["right", "full"])
def test_chunked_outer_join_skewed_partition(jt):
    """Right/full outer over a skewed stream partition with a tiny batch
    budget: the stream side splits into many chunks joined as inner/
    leftouter while the matched-right mask accumulates on device, and
    the unmatched right rows emit once at the end (JoinGatherer.scala:55
    chunked-gather role; fixes the round-4 single-batch limitation)."""
    def fn(s):
        # one fat partition (skew) so the chunker has real work
        l = s.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("a", IntegerGen())],
                      4000, 11),
            num_partitions=1)
        r = s.createDataFrame(
            gen_batch([("k2", SmallIntGen()), ("b", LongGen()),
                       ("sname", StringGen())], 400, 12),
            num_partitions=1).repartition(1)
        return l.join(r, F.col("k") == F.col("k2"), jt)
    assert_tpu_and_cpu_equal_collect(
        fn,
        conf={
            # chunk the 4000-row stream side into ~8 chunks, and keep
            # the spill store small enough that handles demote
            "spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.memory.tpu.poolSize": str(256 << 10),
            "spark.rapids.sql.autoBroadcastJoinThreshold": "-1",
        },
        expect_execs=["TpuShuffledHashJoin"])


def test_broadcast_exchange_reuse_builds_once():
    """One broadcast exchange node feeds two joins and builds ONCE
    (GpuBroadcastExchangeExec.scala:280 + ReuseExchange role)."""
    from spark_rapids_tpu.sql.session import TpuSparkSession

    def run(enabled):
        s = TpuSparkSession({"spark.rapids.sql.enabled": enabled})
        fact = s.createDataFrame(
            {"k": [i % 30 for i in range(2000)],
             "v": list(range(2000))}, "k int, v long", num_partitions=2)
        dim = s.createDataFrame(
            {"k2": list(range(20)),
             "name": [f"d{i}" for i in range(20)]}, "k2 int, name string")
        cond = F.col("k") == F.col("k2")
        q = fact.join(dim, cond, "leftsemi").union(
            fact.join(dim, cond, "leftanti")).orderBy("v")
        s.start_capture()
        rows = [tuple(r) for r in q.collect()]
        plan = s.get_captured_plans()[-1]
        nodes = []

        def walk(p):
            nodes.append(p)
            for c in p.children:
                walk(c)
        walk(plan)
        bx = [n for n in nodes
              if "BroadcastExchange" in n.simple_string()]
        distinct = list({id(n): n for n in bx}.values())
        builds = sum(
            n.metrics.value("broadcastBuilds") if hasattr(n, "metrics")
            else getattr(n, "build_count", 0) for n in distinct)
        s.stop()
        return rows, len(bx), len(distinct), builds

    cpu = run("false")
    tpu = run("true")
    assert cpu[0] == tpu[0]
    for rows, refs, distinct, builds in (cpu, tpu):
        assert refs == 2, "both joins must reference a broadcast exchange"
        assert distinct == 1, "reuse pass must collapse equal broadcasts"
        assert builds == 1, "the shared build side must build once"


def test_broadcast_fk_fast_path_no_sizing_sync():
    """Unique build-side keys certify the whole broadcast for the FK
    fast path: one multiplicity probe replaces the per-chunk sizing
    sync (ops/join.py build_key_max_multiplicity) and the results stay
    identical; duplicate build keys must NOT engage the hint."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.sql import functions as F

    def metric_total(plans, name):
        tot = 0

        def walk(p):
            nonlocal tot
            ms = getattr(p, "metrics", None)
            if ms is not None:
                tot += ms.snapshot().get(name, 0)
            for c in p.children:
                walk(c)
        for p in plans:
            walk(p)
        return tot

    fact = {"k": [1, 2, 3, 4, 2, None], "v": [10, 20, 30, 40, 50, 60]}
    uniq = {"k": [1, 2, 3], "name": ["a", "b", "c"]}
    dup = {"k": [1, 2, 2, 3], "name": ["a", "b", "B", "c"]}

    expected = {}
    for tag, dim in (("uniq", uniq), ("dup", dup)):
        s = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
        try:
            f = s.createDataFrame(fact, "k int, v int")
            d = s.createDataFrame(dim, "k int, name string")
            expected[tag] = sorted(
                map(tuple, f.join(d, "k", "inner").collect()))
        finally:
            s.stop()

    for tag, dim, want_fast in (("uniq", uniq, True), ("dup", dup, False)):
        s = TpuSparkSession({
            "spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.test.forceDevice": "true"})
        try:
            s.start_capture()
            f = s.createDataFrame(fact, "k int, v int")
            d = s.createDataFrame(dim, "k int, name string")
            got = sorted(map(tuple, f.join(d, "k", "inner").collect()))
            plans = s.get_captured_plans()
        finally:
            s.stop()
        assert got == expected[tag], tag
        fast = metric_total(plans, "fkFastPathJoins")
        assert (fast > 0) == want_fast, (tag, fast)
