"""Self-tuning feedback control (docs/tuning.md): TuningController
state units, the closed loop END TO END (a forced retry-storm
signature records a retrySpill action that measurably changes
admission for that signature on the next server run), the site:tuning
injected harmful action auto-reverting within the guard window
(visible in `tools tuning`, the history store and the srt_tuning_*
families), the compile-storm pre-warm ledger replay, the
kernel-fallback conf flip (bit-identical results, accepted at birth),
tuning/revert record EXCLUSION from aggregates / SLO windows / doctor
baselines, tuning-on-vs-off bit identity, the tools tuning/doctor
--all/history --signature CLI contracts, and the `tuning-action` lint
fixtures."""

from __future__ import annotations

import json
import os
import time

import pytest

from spark_rapids_tpu import lifecycle as LC
from spark_rapids_tpu import plan_cache as PC
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.plan_cache import PLAN_CACHE
from spark_rapids_tpu.sql.session import TpuSparkSession
from spark_rapids_tpu.telemetry import history as H
from spark_rapids_tpu.telemetry import triggers as TEL
from spark_rapids_tpu.telemetry import tuning as T

from tests.datagen import (IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, gen_batch)


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()
    H.reset_history()
    TEL.engine().reset()
    PC.set_prewarm_digests(set())
    yield
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()
    H.reset_history()
    TEL.engine().reset()
    PC.set_prewarm_digests(set())
    PLAN_CACHE.clear()


Q1S = """
SELECT flag, status, sum(qty) AS sq, min(price) AS mn,
       max(price) AS mx, count(*) AS c
FROM lineitem WHERE qty % 5 != 0
GROUP BY flag, status ORDER BY flag, status
"""


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tuning_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        li = gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 3000, 31), num_partitions=4)
        li.write.mode("overwrite").parquet(str(d / "lineitem"))
    finally:
        gen.stop()
    return d


@pytest.fixture(scope="module")
def oracle(data_dir):
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                             "spark.rapids.sql.batchSizeRows": "512"})
    try:
        spark.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        return [tuple(r) for r in spark.sql(Q1S)._execute().rows()]
    finally:
        spark.stop()


def _server(data_dir, **conf):
    from spark_rapids_tpu.serve import QueryServer
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.sql.planCache.enabled": "true"}
    base.update({k: str(v) for k, v in conf.items()})
    srv = QueryServer(base)
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    return srv.start()


def _tuning_conf(hdir, **extra):
    base = {"spark.rapids.sql.telemetry.history.dir": str(hdir),
            "spark.rapids.sql.serve.tuning.enabled": "true",
            # the tests drive every tick themselves
            "spark.rapids.sql.serve.tuning.intervalS": "3600",
            "spark.rapids.sql.serve.tuning.guardWindowQueries": "2"}
    base.update({k: str(v) for k, v in extra.items()})
    return base


def _rec(ts, sig="a" * 40, status="finished", wall=0.1, **kw):
    r = {"version": 1, "ts": ts, "signature": sig, "status": status,
         "wallSeconds": wall, "queueWaitSeconds": 0.0,
         "outputRows": 10}
    r.update(kw)
    return r


def _storm_store(hdir, sig, *, baselines=4, **target_kw):
    """A signature baseline plus one regressed newest record carrying
    ``target_kw`` — deterministic doctor-verdict input."""
    store = H.HistoryStore(str(hdir), 1 << 30, 14)
    t0 = time.time()
    for i in range(baselines):
        store.append(_rec(t0 - 60 + i, sig=sig, wall=0.05))
    store.append(_rec(t0, sig=sig, wall=0.5, **target_kw))
    return store


def _admission(**conf):
    from spark_rapids_tpu.serve.scheduler import AdmissionController
    return AdmissionController(TpuConf(dict(conf)))


# ---------------------------------------------------------------------------
# State units
# ---------------------------------------------------------------------------

def test_state_roundtrip_and_torn_file(tmp_path):
    d = str(tmp_path / "hist")
    st = T.load_state(d)  # missing dir -> skeleton, not an error
    assert st["epoch"] == 0 and st["actions"] == []
    st["epoch"] = 3
    st["actions"].append({"epoch": 3, "action": "limitConcurrency",
                          "scope": "a" * 40, "state": "applied"})
    T.save_state(d, st)
    assert T.load_state(d)["epoch"] == 3
    with open(T.state_path(d), "w") as f:
        f.write('{"torn')  # a torn write must not take the server down
    assert T.load_state(d)["actions"] == []


def test_format_tuning_table(tmp_path):
    st = {"version": 1, "epoch": 2, "prewarm": {}, "actions": [
        {"epoch": 1, "action": "limitConcurrency", "scope": "a" * 40,
         "knob": "signatureConcurrency", "oldValue": None,
         "newValue": 2, "state": "applied", "pinned": True},
        {"epoch": 2, "action": "kernelFallback", "scope": "b" * 40,
         "knob": "spark.rapids.sql.kernel.joinProbe.enabled",
         "oldValue": "true", "newValue": "false", "state": "reverted",
         "evidence": {"injected": True}}]}
    out = T.format_tuning(st)
    assert "limitConcurrency" in out and "pinned" in out
    assert "reverted" in out and "injected" in out
    assert "-->2" in out  # old->new column, None rendered as "-"
    assert "true->false" in out
    assert "no tuning actions" in T.format_tuning(
        {"version": 1, "epoch": 0, "actions": [], "prewarm": {}})


def test_action_catalog_declares_bounds_and_docs():
    for name, cat in T.ACTION_CATALOG.items():
        assert cat["verdict"], name
        assert cat["doc"], name
        assert cat["min"] <= cat["max"], name
        for knob in cat.get("knobs", [cat["knob"]]):
            assert knob in T.INTERNAL_KNOBS or \
                knob.startswith("spark.rapids."), (name, knob)


# ---------------------------------------------------------------------------
# Controller units (standalone: explicit collaborators)
# ---------------------------------------------------------------------------

def test_retry_spill_action_bounded_and_audited(tmp_path):
    hdir = tmp_path / "hist"
    sig = "c" * 40
    _storm_store(hdir, sig, retryCount=6)
    conf = TpuConf(_tuning_conf(hdir))
    adm = _admission()
    tun = T.TuningController(conf, admission=adm)
    tun.tick()
    acts = tun.actions()
    limit = [a for a in acts if a["action"] == "limitConcurrency"]
    assert limit and limit[0]["scope"] == sig
    assert limit[0]["newValue"] == 2  # first clamp: None -> 2
    assert adm.signature_limit(sig) == 2
    # bounded: the catalog clamp floor is 1 however hard it's pushed
    act = tun._new_action("limitConcurrency", sig,
                          T.KNOB_SIGNATURE_CONCURRENCY, 2, -5, {})
    assert act["newValue"] == 1
    # audited: a `tuning` history record with the old->new values
    recs = [r for r in H.read_records(str(hdir))
            if r.get("status") == H.STATUS_TUNING]
    assert any(r["action"] == "limitConcurrency"
               and r["signature"] == sig and r["newValue"] == 2
               and r["epoch"] >= 1 for r in recs)
    # convergence: the same evidence on the next tick adds no twin
    tun.tick()
    twins = [a for a in tun.actions()
             if a["action"] == "limitConcurrency"
             and a["scope"] == sig]
    assert len(twins) == 1


def test_seed_out_of_core_rides_retry_spill(tmp_path):
    hdir = tmp_path / "hist"
    sig = "d" * 40
    _storm_store(hdir, sig, retryCount=6)
    writes = {}
    tun = T.TuningController(
        TpuConf(_tuning_conf(hdir)), admission=_admission(),
        set_conf=writes.__setitem__, get_conf=writes.get)
    tun.tick()
    assert writes.get("spark.rapids.sql.outOfCore.enabled") == "true"
    # already-on servers don't get a redundant action
    hdir2 = tmp_path / "hist2"
    _storm_store(hdir2, sig, retryCount=6)
    writes2 = {"spark.rapids.sql.outOfCore.enabled": "true"}
    before = dict(writes2)
    tun2 = T.TuningController(
        TpuConf(_tuning_conf(hdir2)), admission=_admission(),
        set_conf=writes2.__setitem__, get_conf=writes2.get)
    tun2.tick()
    assert not any(a["action"] == "seedOutOfCore"
                   for a in tun2.actions())
    assert writes2 == before


def test_kernel_fallback_flip_accepted_at_birth(tmp_path):
    hdir = tmp_path / "hist"
    sig = "e" * 40
    _storm_store(hdir, sig, kernelFallbacks=6,
                 kernelFallbacksByName={"joinProbe": 6})
    writes = {}
    tun = T.TuningController(
        TpuConf(_tuning_conf(hdir)), admission=_admission(),
        set_conf=writes.__setitem__, get_conf=writes.get)
    tun.tick()
    key = "spark.rapids.sql.kernel.joinProbe.enabled"
    assert writes.get(key) == "false"
    acts = [a for a in tun.actions()
            if a["action"] == "kernelFallback"]
    assert acts and acts[0]["knob"] == key
    assert acts[0]["evidence"]["rebaseline"] is True
    # accepted at birth: the flip re-baselines, so the guardrail never
    # judges it — the next tick graduates it without a window
    tun.tick()
    assert [a for a in tun.actions()
            if a["action"] == "kernelFallback"][0]["state"] \
        == "accepted"
    # a kernel the catalog does not declare is never flipped
    hdir2 = tmp_path / "hist2"
    _storm_store(hdir2, sig, kernelFallbacks=6,
                 kernelFallbacksByName={"rogueKernel": 6})
    writes2 = {}
    tun2 = T.TuningController(
        TpuConf(_tuning_conf(hdir2)), admission=_admission(),
        set_conf=writes2.__setitem__, get_conf=writes2.get)
    tun2.tick()
    assert writes2 == {}


def test_slo_burn_shifts_tenant_weight(tmp_path):
    hdir = tmp_path / "hist"

    class _Slo:
        def evaluate(self):
            return {"acme": {"burnRatio": 0.8, "windowQueries": 5,
                             "objectiveP99Ms": 10,
                             "observedP99Ms": 50.0, "violations": 4}}

    adm = _admission()
    tun = T.TuningController(TpuConf(_tuning_conf(hdir)),
                             admission=adm, slo=_Slo())
    tun.tick()
    acts = [a for a in tun.actions() if a["action"] == "tenantWeight"]
    assert acts and acts[0]["scope"] == "tenant:acme"
    assert adm.tenant_weight("acme") == 1.5
    # clamped to the catalog ceiling however often it compounds
    act = tun._new_action("tenantWeight", "tenant:acme",
                          T.KNOB_TENANT_WEIGHT, 4.0, 6.0, {})
    assert act["newValue"] == 4.0


def test_guardrail_reverts_injected_harmful_action(tmp_path, capsys):
    hdir = tmp_path / "hist"
    os.makedirs(str(hdir))
    conf = TpuConf(_tuning_conf(
        hdir, **{"spark.rapids.sql.test.injectOOM": "site:tuning:2"}))
    adm = _admission()
    tun = T.TuningController(conf, admission=adm)
    sig = "f" * 40
    tun.observe("SELECT 1", sig, "acme")
    tun.tick()  # tick 1: schedule not due
    assert not tun.actions()
    tun.tick()  # tick 2: the harmful clamp lands
    acts = tun.actions()
    assert len(acts) == 1 and acts[0]["evidence"]["injected"]
    assert acts[0]["scope"] == sig and adm.signature_limit(sig) == 1
    # guard window fills with ordinary walls -> epsilon baseline reads
    # as a regression -> auto-revert, old value restored
    store = H.HistoryStore(str(hdir), 1 << 30, 14)
    for _ in range(2):
        store.append(_rec(time.time() + 0.001, sig=sig, wall=0.05))
    tun.tick()  # tick 3: guardrail judges and reverts
    acts = tun.actions()
    assert acts[0]["state"] == "reverted"
    assert adm.signature_limit(sig) is None
    assert tun.stats()["actionsReverted"] == 1
    # visible in the history store ...
    reverts = [r for r in H.read_records(str(hdir))
               if r.get("status") == H.STATUS_REVERT]
    assert reverts and reverts[0]["action"] == "limitConcurrency"
    assert reverts[0]["evidence"]["observed"]["windowQueries"] == 2
    # ... and in the `tools tuning` table
    from spark_rapids_tpu.tools import _main as tools_main
    assert tools_main(["tuning", "--history", str(hdir)]) == 0
    out = capsys.readouterr().out
    assert "reverted" in out and "injected" in out
    assert R.get_fault_injector(conf).stats()[
        "tuningFaultsInjected"] == 1


def test_guardrail_accepts_non_regressed_action(tmp_path):
    hdir = tmp_path / "hist"
    os.makedirs(str(hdir))
    adm = _admission()
    tun = T.TuningController(TpuConf(_tuning_conf(hdir)),
                             admission=adm)
    sig = "1" * 40
    act = tun._new_action(
        "limitConcurrency", sig, T.KNOB_SIGNATURE_CONCURRENCY,
        None, 2, {"baseline": {"p50": 0.05, "p99": 0.05}})
    with tun._lock:
        tun._apply(act)
    store = H.HistoryStore(str(hdir), 1 << 30, 14)
    for _ in range(2):
        store.append(_rec(time.time() + 0.001, sig=sig, wall=0.05))
    tun.tick()
    a = tun.actions()[0]
    assert a["state"] == "accepted"
    assert a["evidence"]["accepted"]["windowQueries"] == 2
    assert adm.signature_limit(sig) == 2  # knob stays


def test_pinned_action_exempt_from_guardrail(tmp_path):
    hdir = tmp_path / "hist"
    os.makedirs(str(hdir))
    adm = _admission()
    tun = T.TuningController(TpuConf(_tuning_conf(hdir)),
                             admission=adm)
    sig = "2" * 40
    act = tun._new_action(
        "limitConcurrency", sig, T.KNOB_SIGNATURE_CONCURRENCY,
        None, 1, {"baseline": {"p50": 1e-9, "p99": 1e-9}})
    act["pinned"] = True
    with tun._lock:
        tun._apply(act)
        T.save_state(str(hdir), tun._state)
    store = H.HistoryStore(str(hdir), 1 << 30, 14)
    for _ in range(3):
        store.append(_rec(time.time() + 0.001, sig=sig, wall=0.05))
    tun.tick()  # would revert (epsilon baseline) were it not pinned
    assert tun.actions()[0]["state"] == "applied"
    assert adm.signature_limit(sig) == 1


def test_cli_revert_request_honored_at_next_tick(tmp_path, capsys):
    hdir = tmp_path / "hist"
    sig = "3" * 40
    _storm_store(hdir, sig, retryCount=6)
    adm = _admission()
    tun = T.TuningController(TpuConf(_tuning_conf(hdir)),
                             admission=adm)
    tun.tick()
    epoch = [a for a in tun.actions()
             if a["action"] == "limitConcurrency"][0]["epoch"]
    assert adm.signature_limit(sig) == 2
    # the operator asks for a rollback THROUGH THE STATE FILE
    from spark_rapids_tpu.tools import _main as tools_main
    assert tools_main(["tuning", "--history", str(hdir),
                       "--revert", str(epoch)]) == 0
    assert "revertRequested = True" in capsys.readouterr().out
    # a healthy newest record so the next scan finds no regression
    # (the rollback must not be immediately re-applied from stale
    # evidence)
    H.HistoryStore(str(hdir), 1 << 30, 14).append(
        _rec(time.time() + 0.002, sig=sig, wall=0.05))
    tun.tick()  # the controller merges the flag and rolls back
    a = [x for x in tun.actions() if x["epoch"] == epoch][0]
    assert a["state"] == "reverted"
    assert adm.signature_limit(sig) is None
    # unknown epoch -> exit 1
    assert tools_main(["tuning", "--history", str(hdir),
                       "--pin", "999"]) == 1


def test_prewarm_ledger_and_replay_on_restart(tmp_path, data_dir,
                                              oracle):
    hdir = tmp_path / "hist"
    sess_conf = {"spark.rapids.sql.enabled": "true",
                 "spark.rapids.sql.batchSizeRows": "512",
                 "spark.rapids.sql.planCache.enabled": "true"}

    def session_for(tenant):
        s = TpuSparkSession(dict(sess_conf))
        s.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        return s

    s0 = session_for("t")
    try:
        assert [tuple(r) for r in s0.sql(Q1S)._execute().rows()] \
            == oracle
        sig = s0.thread_plan_signature()
    finally:
        s0.stop()
    assert sig and len(sig) == 40
    _storm_store(hdir, sig, jitMisses=64)
    tun = T.TuningController(TpuConf(_tuning_conf(hdir)),
                             session_for=session_for)
    tun.observe(Q1S, sig, "t")
    tun.tick()
    state = T.load_state(str(hdir))
    assert sig in state["prewarm"]
    assert state["prewarm"][sig]["sql"] == Q1S
    assert sig in PC.prewarm_digests()
    # "restart": a fresh controller over the same dir replays the
    # ledger BEFORE the first request -> the plan template is already
    # cached when the sql arrives
    PLAN_CACHE.clear()
    PC.set_prewarm_digests(set())
    tun2 = T.TuningController(TpuConf(_tuning_conf(hdir)),
                              session_for=session_for)
    tun2.start()
    try:
        assert tun2.prewarm_replayed == 1
        assert sig in PC.prewarm_digests()
        h0 = PLAN_CACHE.hits
        s1 = session_for("t")
        try:
            assert [tuple(r) for r in s1.sql(Q1S)._execute().rows()] \
                == oracle
        finally:
            s1.stop()
        assert PLAN_CACHE.hits > h0  # served from the pre-warmed plan
        assert tun2.signature_hint(Q1S) == sig  # maps re-seeded
    finally:
        tun2.stop()


# ---------------------------------------------------------------------------
# Exclusion: tuning/revert records never move the observability math
# ---------------------------------------------------------------------------

def _audit_records(sig, tenant=None):
    out = []
    for status in (H.STATUS_TUNING, H.STATUS_REVERT):
        out.append(H.build_tuning_record(
            status=status, action="limitConcurrency", scope=sig,
            knob="signatureConcurrency", old_value=None, new_value=2,
            evidence={"baseline": {"p50": 0.01, "p99": 0.01}},
            epoch=1, tenant=tenant, signature=sig))
    return out


def test_aggregates_and_doctor_ignore_tuning_records(tmp_path):
    sig = "9" * 40
    t0 = time.time()
    plain = [_rec(t0 - 30 + i, sig=sig, wall=0.05 * (1 + i % 3))
             for i in range(6)]
    plain.append(_rec(t0, sig=sig, wall=0.4, retryCount=6))
    noisy = plain[:3] + _audit_records(sig) + plain[3:]
    a = H.signature_aggregates(plain)[sig]
    b = H.signature_aggregates(noisy)[sig]
    # byte-identical aggregates: count, p50/p99, trend slope, retry
    # rate, status histogram — tuning on vs off must not differ
    assert a == b
    assert "tuning" not in b["statuses"]
    assert "revert" not in b["statuses"]
    assert a["count"] == 7 and a["wallP50"] > 0
    # doctor baselines: identical verdict/slowdown/baseline either way
    from spark_rapids_tpu.telemetry.doctor import diagnose_record
    da = diagnose_record(plain, plain[-1])
    db = diagnose_record(noisy, plain[-1])
    assert da["verdict"] == db["verdict"] == "retrySpill"
    assert da["slowdown"] == db["slowdown"]
    assert da["baseline"] == db["baseline"]
    assert da["regressed"] and db["regressed"]


def test_slo_window_ignores_tuning_records(tmp_path):
    d1, d2 = str(tmp_path / "h1"), str(tmp_path / "h2")
    sig = "8" * 40
    t0 = time.time()
    plain = [_rec(t0 - 10 + i, sig=sig, wall=0.2, tenant="acme")
             for i in range(4)]
    for d, recs in ((d1, plain),
                    (d2, plain + _audit_records(sig, tenant="acme"))):
        store = H.HistoryStore(d, 1 << 30, 14)
        for r in recs:
            store.append(r)
    mk = lambda d: H.SloTracker(TpuConf({  # noqa: E731
        "spark.rapids.sql.telemetry.history.dir": d,
        "spark.rapids.sql.serve.slo.p99Ms": "100"}))
    assert mk(d1).evaluate() == mk(d2).evaluate()
    state = mk(d2).evaluate()["acme"]
    assert state["windowQueries"] == 4  # audit records never counted


def test_warm_start_ignores_tuning_records(tmp_path):
    d = str(tmp_path / "hist")
    sig = "7" * 40
    store = H.HistoryStore(d, 1 << 30, 14)
    t0 = time.time()
    for i in range(5):
        store.append(_rec(t0 - 10 + i, sig=sig, wall=0.2))
    for r in _audit_records(sig):
        store.append(r)
    conf = TpuConf({
        "spark.rapids.sql.telemetry.history.dir": d,
        "spark.rapids.sql.telemetry.history.warmStart": "true"})
    summary = H.warm_start(conf)
    assert summary["enabled"]
    assert summary["records"] == 7  # audit rows read ...
    assert summary["walls"] == 5    # ... but never seed the watchdog


# ---------------------------------------------------------------------------
# The closed loop end to end (server embed)
# ---------------------------------------------------------------------------

def test_retry_storm_shapes_admission_on_next_run(tmp_path, data_dir,
                                                  oracle):
    from spark_rapids_tpu.serve import ServeClient
    hdir = tmp_path / "hist"
    conf = _tuning_conf(hdir)
    srv = _server(data_dir, **conf)
    try:
        with ServeClient(srv.port, tenant="acme") as c:
            for _ in range(2):
                b, _hdr = c.sql(Q1S)
                assert [tuple(r) for r in b.rows()] == oracle
        tun = srv._tuning
        assert tun is not None and tun.enabled
        sig = tun.signature_hint(Q1S)
        assert sig and len(sig) == 40
        # the forced retry storm for exactly this signature
        store = H.HistoryStore(str(hdir), 1 << 30, 14)
        store.append(_rec(time.time() + 0.001, sig=sig, wall=1.0,
                          retryCount=6))
        tun.tick()
        assert srv._admission.signature_limit(sig) == 2
        assert srv.stats()["admission"]["signatureLimits"] == {sig: 2}
        # ... and the queries still run, bit-identical, under the clamp
        with ServeClient(srv.port, tenant="acme") as c:
            b, _hdr = c.sql(Q1S)
            assert [tuple(r) for r in b.rows()] == oracle
        text = srv.metrics_text()
        assert "srt_tuning_ticks_total" in text
        assert 'srt_tuning_actions_total{action="limitConcurrency"}' \
            in text
    finally:
        srv.shutdown()
    # THE NEXT RUN: a fresh server over the same history dir re-applies
    # the persisted decision before serving — admission for that
    # signature is measurably different from query one
    srv2 = _server(data_dir, **conf)
    try:
        assert srv2._admission.signature_limit(sig) == 2
        with ServeClient(srv2.port, tenant="acme") as c:
            b, _hdr = c.sql(Q1S)
            assert [tuple(r) for r in b.rows()] == oracle
    finally:
        srv2.shutdown()


def test_injected_harmful_action_reverts_in_server(tmp_path, data_dir,
                                                   oracle):
    from spark_rapids_tpu.serve import ServeClient
    hdir = tmp_path / "hist"
    conf = _tuning_conf(
        hdir, **{"spark.rapids.sql.test.injectOOM": "site:tuning:2"})
    srv = _server(data_dir, **conf)  # tick 1 at start: not due
    try:
        tun = srv._tuning
        with ServeClient(srv.port, tenant="acme") as c:
            b, _hdr = c.sql(Q1S)
            assert [tuple(r) for r in b.rows()] == oracle
        sig = tun.signature_hint(Q1S)
        tun.tick()  # tick 2: harmful clamp on the observed signature
        assert srv._admission.signature_limit(sig) == 1
        # the guard window fills with REAL queries (which still run —
        # the clamp throttles, never breaks)
        with ServeClient(srv.port, tenant="acme") as c:
            for _ in range(2):
                b, _hdr = c.sql(Q1S)
                assert [tuple(r) for r in b.rows()] == oracle
        tun.tick()  # tick 3: auto-revert within the guard window
        assert srv._admission.signature_limit(sig) is None
        st = srv.stats()["tuning"]
        assert st["actionsReverted"] == 1
        assert "srt_tuning_reverts_total 1" in srv.metrics_text()
        assert any(r.get("status") == H.STATUS_REVERT
                   for r in H.read_records(str(hdir)))
        assert "reverted" in T.format_tuning(T.load_state(str(hdir)))
    finally:
        srv.shutdown()


def test_results_bit_identical_tuning_on_vs_off(tmp_path, data_dir,
                                                oracle):
    from spark_rapids_tpu.serve import ServeClient
    rows = {}
    for mode in ("off", "on"):
        hdir = tmp_path / f"hist-{mode}"
        conf = _tuning_conf(hdir) if mode == "on" else {
            "spark.rapids.sql.telemetry.history.dir": str(hdir)}
        srv = _server(data_dir, **conf)
        try:
            assert (srv._tuning is not None) == (mode == "on")
            with ServeClient(srv.port, tenant="acme") as c:
                b, _hdr = c.sql(Q1S)
                first = [tuple(r) for r in b.rows()]
            if mode == "on":
                # force real actions mid-run, then query again
                tun = srv._tuning
                sig = tun.signature_hint(Q1S)
                store = H.HistoryStore(str(hdir), 1 << 30, 14)
                store.append(_rec(time.time() + 0.001, sig=sig,
                                  wall=1.0, retryCount=6,
                                  jitMisses=64))
                tun.tick()
                assert tun.stats()["actionsApplied"] >= 1
            with ServeClient(srv.port, tenant="acme") as c:
                b, _hdr = c.sql(Q1S)
                rows[mode] = (first, [tuple(r) for r in b.rows()])
        finally:
            srv.shutdown()
    assert rows["off"] == rows["on"]
    assert rows["on"][0] == oracle and rows["on"][1] == oracle


# ---------------------------------------------------------------------------
# CLI: tools doctor --all / history --signature
# ---------------------------------------------------------------------------

def test_tools_doctor_all_ranks_regressions(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main as tools_main
    d = tmp_path / "hist"
    _storm_store(d, "a" * 40, retryCount=6)   # regressed
    store = H.HistoryStore(str(d), 1 << 30, 14)
    t0 = time.time()
    for i in range(4):
        store.append(_rec(t0 - 30 + i, sig="b" * 40, wall=0.05))
    assert tools_main(["doctor", "--all", "--history", str(d)]) == 0
    out = capsys.readouterr().out
    assert "2 signature(s) scanned" in out
    assert "<-- regressed" in out
    # the regressed signature ranks first
    lines = [ln for ln in out.splitlines()
             if H.sig_digest("a" * 40) in ln
             or H.sig_digest("b" * 40) in ln]
    assert H.sig_digest("a" * 40) in lines[0]
    assert tools_main(["doctor", "--all", "--history", str(d),
                       "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc[0]["signatureFull"] == "a" * 40
    assert doc[0]["regressed"] and doc[0]["verdict"] == "retrySpill"
    # --all still requires a resolvable directory
    assert tools_main(["doctor", "--all", "--history",
                       str(tmp_path / "nope")]) == 1


def test_tools_history_signature_filter(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main as tools_main
    d = tmp_path / "hist"
    store = H.HistoryStore(str(d), 1 << 30, 14)
    t0 = time.time()
    for i in range(3):
        store.append(_rec(t0 - 30 + i, sig="a" * 40, tenant="acme"))
    store.append(_rec(t0, sig="b" * 40, tenant="zeta"))
    # full digest: exact reader-side filter
    assert tools_main(["history", str(d), "--signature", "a" * 40,
                       "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["records"] == 3 and list(doc["signatures"]) == ["a" * 40]
    # display prefix (12-hex) matches too
    assert tools_main(["history", str(d), "--signature",
                       H.sig_digest("b" * 40)]) == 0
    out = capsys.readouterr().out
    assert "zeta" in out and "acme" not in out
    # and the reader API itself: exact match only for signature=
    assert len(H.read_records(str(d), signature="a" * 40)) == 3
    assert H.read_records(str(d), signature="a" * 12) == []


# ---------------------------------------------------------------------------
# Lint fixtures: tuning-action
# ---------------------------------------------------------------------------

def _lint_tree(tmp_path, files):
    import textwrap
    root = tmp_path / "fixture"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    for d in ("spark_rapids_tpu", "spark_rapids_tpu/telemetry"):
        if (root / d).is_dir():
            init = root / d / "__init__.py"
            if not init.exists():
                init.write_text("")
    return str(root)


def test_lint_tuning_action_bad_and_good(tmp_path):
    from spark_rapids_tpu.lint import LintConfig, run_lint
    root = _lint_tree(tmp_path, {
        "spark_rapids_tpu/conf.py": """
            def conf(key):
                return key

            GOOD = conf("spark.rapids.sql.good.enabled")
        """,
        "spark_rapids_tpu/telemetry/tuning.py": """
            ACTION_CATALOG = {
                "goodAction": {
                    "verdict": "x",
                    "knob": "spark.rapids.sql.good.enabled",
                    "min": 0, "max": 1, "doc": "d"},
                "badKnob": {
                    "verdict": "x",
                    "knob": "spark.rapids.sql.unregistered.enabled",
                    "min": 0, "max": 1, "doc": "d"},
                "listKnobs": {
                    "verdict": "x",
                    "knob": "internalThing",
                    "knobs": ["internalThing",
                              "spark.rapids.sql.good.enabled"],
                    "min": 0, "max": 1, "doc": "d"},
            }

            class C:
                def go(self):
                    self._new_action("goodAction", 1)
                    self._new_action("listKnobs", 2)
                    self._new_action("rogueAction", 3)
                    name = "dynamic"
                    self._new_action(name, 4)
        """})
    r = run_lint(root, LintConfig(check_docs=False))
    msgs = [f.message for f in r.findings
            if f.rule == "tuning-action"]
    assert len(msgs) == 3, r.findings
    assert any("unregistered.enabled" in m for m in msgs)
    assert any("rogueAction" in m for m in msgs)
    assert any("string literal" in m for m in msgs)
    # (the real package's zero-findings gate in test_lint.py covers
    # tuning-action too)
