"""Test config: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile/execute without TPU hardware (SURVEY.md section 4
blueprint: 'jax CPU devices / multiprocess ICI emulation covers what
Mockito does' for the reference's transport suites)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
