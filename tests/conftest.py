"""Test config: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile/execute without TPU hardware (SURVEY.md section 4
blueprint: 'jax CPU devices / multiprocess ICI emulation covers what
Mockito does' for the reference's transport suites).

The hosting environment may pre-register a TPU PJRT plugin via
sitecustomize before this file runs, so os.environ.setdefault is not
enough: set XLA_FLAGS before the backend initializes and override the
platform with jax.config (which works even after jax was imported).
"""

import os
import sys

# No persistent XLA cache under pytest: XLA:CPU AOT entries have
# repeatedly deserialized into SIGSEGV (machine-feature pinning +
# concurrent-writer corruption); CPU compiles are fast enough to redo
os.environ["SPARK_RAPIDS_TPU_XLA_CACHE"] = "off"

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # The suite is compile-bound: hundreds of distinct XLA programs,
    # recompiled per module (see _clear_jax_caches_per_module). Tests
    # assert CORRECTNESS against the CPU oracle, not codegen quality,
    # and O0 halves the wall of the compile-heavy modules while staying
    # bit-identical (XLA optimization passes are semantics-preserving;
    # no fast-math is enabled at any level). bench.py is unaffected.
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Bound in-process XLA:CPU executable accumulation: hundreds of
    tests x fresh program shapes have repeatedly ended in a SIGSEGV
    inside backend_compile late in the run (LLVM JIT state corruption
    after thousands of live executables). Dropping JAX's traces and
    executables between modules keeps the process small; modules
    recompile what they reuse."""
    yield
    import jax
    jax.clear_caches()


def pytest_configure(config):
    # tier-1 selects with `-m 'not slow'`, so `fault` tests (the
    # robustness/fault-injection corpus) run IN tier-1 by default
    config.addinivalue_line(
        "markers", "slow: long-running test excluded from tier-1")
    config.addinivalue_line(
        "markers", "fault: fault-injection robustness test "
        "(docs/robustness.md); included in tier-1")
