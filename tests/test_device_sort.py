"""Device sort / TopN / range-partitioning tests through the dual-session
harness (GpuSortExec + GpuTopN + GpuRangePartitioner coverage; reference
integration pattern: integration_tests sort_test.py over asserts.py:434).
Order-sensitive assertions use ignore_order=False so a wrong permutation
fails, not just wrong membership.
"""

import pytest

from spark_rapids_tpu.sql import functions as F

from tests.datagen import (BooleanGen, DateGen, DoubleGen, FloatGen,
                           IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           StringGen, TimestampGen, gen_batch)
from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)

N = 512


def _df(spark, gens, n=N, seed=11, parts=3):
    return spark.createDataFrame(gen_batch(gens, n, seed),
                                 num_partitions=parts)


@pytest.mark.parametrize("gen", [
    IntegerGen(), LongGen(), DoubleGen(), FloatGen(), BooleanGen(),
    StringGen(), DateGen(), TimestampGen()],
    ids=["int", "long", "double", "float", "bool", "string", "date", "ts"])
def test_orderby_single_key(gen):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", gen), ("b", IntegerGen())]).orderBy("a"),
        ignore_order=False,
        expect_execs=["TpuSort"])


@pytest.mark.parametrize("gen", [IntegerGen(), DoubleGen(), StringGen()],
                         ids=["int", "double", "string"])
def test_orderby_desc(gen):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", gen), ("b", IntegerGen())])
        .orderBy(F.col("a").desc()),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_orderby_nulls_variants():
    for order in (F.col("a").asc_nulls_last(), F.col("a").desc_nulls_first(),
                  F.col("a").asc(), F.col("a").desc()):
        assert_tpu_and_cpu_equal_collect(
            lambda s, o=order: _df(s, [("a", IntegerGen()),
                                       ("b", LongGen())]).orderBy(o),
            ignore_order=False,
            expect_execs=["TpuSort"])


def test_orderby_multi_key():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", DoubleGen()),
                          ("s", KeyStringGen())])
        .orderBy(F.col("k").asc(), F.col("v").desc(), F.col("s").asc()),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_orderby_expression_key():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen()), ("b", IntegerGen())])
        .orderBy((F.col("a") + F.col("b")).asc(), F.col("a").desc()),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_global_sort_fully_on_device():
    """Global sort: range-partitioning exchange AND sort both on device."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", LongGen()), ("b", StringGen())], n=1000,
                      parts=4).orderBy("a", "b"),
        ignore_order=False,
        conf={"spark.rapids.sql.test.forceDevice": "true"},
        expect_execs=["TpuSort", "TpuExchange"])


def test_sort_within_partitions():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())], parts=1)
        .sortWithinPartitions(F.col("b").desc_nulls_first()),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_topn_fusion():
    """orderBy().limit() fuses LocalLimit(Sort) into TpuTopN."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", LongGen()), ("b", StringGen())], n=900,
                      parts=4).orderBy(F.col("a").desc()).limit(17),
        ignore_order=False,
        expect_execs=["TpuTopN"])


def test_sort_after_filter_keeps_masked_rows_out():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen()), ("b", IntegerGen())])
        .filter(F.col("a") > 2).orderBy(F.col("b").asc(), F.col("a").asc()),
        ignore_order=False,
        expect_execs=["TpuFilter", "TpuSort"])


def test_sort_decimal_on_device():
    """Round 4: decimal sort keys run on device (unscaled int64 /
    limb-word radix keys); this used to assert a CPU fallback."""
    import decimal
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"d": [decimal.Decimal("1.23"), None, decimal.Decimal("-4.5")]},
            "d decimal(10,2)").orderBy("d"),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_sort_empty_input():
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame({"a": []}, "a int",
                                    num_partitions=2).orderBy("a"),
        ignore_order=False,
        require_device=False)
