"""Pallas kernel tier corpus (docs/kernels.md): per-kernel property
tests against the XLA-op oracle, query-level bit-identity with kernels
on vs off, the overflow / injected-failure / injected-OOM fallback
protocols, trace/metric attribution, and the `tools hotspots` picker.

Everything here runs the kernels through ``device_caps.pallas_mode()``
— interpreter emulation on the CPU tier-1 backend — so every kernel
path is exercised without hardware. Heavy sweeps are ``slow``."""

import glob
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu import device_caps as DC
from spark_rapids_tpu import kernels as KR
# module-level jnp constants (ops/groupby._SIGN64 et al.) must exist
# BEFORE any jit trace in this module: a first import inside a trace
# would capture them as leaked tracers (production imports these
# eagerly through exec/agg.py)
import spark_rapids_tpu.ops.groupby  # noqa: F401
import spark_rapids_tpu.ops.hashing  # noqa: F401
import spark_rapids_tpu.ops.int128  # noqa: F401
import spark_rapids_tpu.ops.lanes  # noqa: F401
from spark_rapids_tpu.columnar.device import (DeviceColumn,
                                              DeviceDecimal128Column)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.kernels import groupby_hash as KG
from spark_rapids_tpu.kernels import join_probe as KJ
from spark_rapids_tpu.kernels import murmur3 as KM
from spark_rapids_tpu.metrics import describe_metric, registry_snapshot
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.session import TpuSparkSession

TPU = {"spark.rapids.sql.enabled": "true",
       "spark.rapids.sql.test.forceDevice": "true"}
CPU = {"spark.rapids.sql.enabled": "false"}
DEC = T.DecimalType(15, 2)


def _run(conf, views, sql, parts=1):
    """Run one SQL under ``conf`` with {name: HostBatch} views; returns
    (pydict, captured plans)."""
    s = TpuSparkSession(dict(conf))
    try:
        for name, hb in views.items():
            s.createDataFrame(hb, num_partitions=parts) \
                .createOrReplaceTempView(name)
        s.start_capture()
        out = s.sql(sql)._execute().to_pydict()
        return out, s.get_captured_plans()
    finally:
        s.stop()


def _kcounters(plans):
    snap = registry_snapshot(plans)["metrics"]
    return {k: v for k, v in snap.items() if k.startswith("kernel")}


def _groupy_batch(n=6000, ngroups=5, seed=3, null_prob=0.15):
    rng = np.random.default_rng(seed)
    keys = np.array([f"k{i}" for i in range(ngroups)],
                    dtype=object)[rng.integers(0, ngroups, n)]
    vals = rng.integers(-1000, 1000, n)
    dec = rng.integers(100, 100000, n)
    kv = rng.random(n) >= null_prob
    vv = rng.random(n) >= null_prob
    return HostBatch(T.StructType([
        T.StructField("k", T.StringT),
        T.StructField("v", T.LongT),
        T.StructField("d", DEC),
    ]), [HostColumn(T.StringT, keys, kv).normalized(),
         HostColumn(T.LongT, vals, vv).normalized(),
         HostColumn.all_valid(dec, DEC)], n)


Q_AGG = ("SELECT k, sum(v), count(v), min(v), max(v), sum(d), avg(d), "
         "count(*) FROM t GROUP BY k ORDER BY k")


# ---------------------------------------------------------------------------
# environment / registry
# ---------------------------------------------------------------------------

def test_pallas_mode_available():
    # tier-1 runs on CPU -> interpret; real TPU backends probe native.
    # Either way the kernel tier must be exercisable here.
    assert DC.pallas_mode() in ("native", "interpret")


def test_kernel_metric_families_described():
    assert describe_metric("kernelDispatchCount.groupbyHash")
    assert describe_metric("kernelFallbacks.murmur3")


def test_registry_names_have_confs():
    from spark_rapids_tpu.conf import _REGISTRY
    for name in KR.KERNELS:
        key = f"spark.rapids.sql.kernel.{name}.enabled"
        assert key in _REGISTRY, key


# ---------------------------------------------------------------------------
# groupbyHash kernel: direct property tests vs a numpy oracle
# ---------------------------------------------------------------------------

def _gb_direct(cap, keys, kvalid, vals, vvalid, active, slots,
               dec_vals=None):
    """Run hash_groupby inside jit; return numpy views of the result."""
    entries_dt = [(E.PRIM_SUM, T.LongT), (E.PRIM_COUNT, T.LongT),
                  (E.PRIM_MIN, T.LongT), (E.PRIM_MAX, T.LongT)]
    use_dec = dec_vals is not None
    out_dec = T.DecimalType(25, 2)

    @jax.jit
    def run(kd, kv, vd, vv, act, dd):
        kc = DeviceColumn(T.IntegerT, kd, kv)
        vc = DeviceColumn(T.LongT, vd, vv)
        entries = [(vc, p, dt) for p, dt in entries_dt]
        if use_dec:
            entries.append((DeviceColumn(DEC, dd, vv), E.PRIM_SUM,
                            out_dec))
        key_out, bufs, used, cnt, ovf = KG.hash_groupby(
            [kc], entries, act, slots)
        flat = [a for c in key_out for a in c.arrays()]
        flat += [a for c in bufs for a in c.arrays()]
        return flat, used, cnt, ovf

    flat, used, cnt, ovf = run(
        jnp.asarray(keys, jnp.int32), jnp.asarray(kvalid),
        jnp.asarray(vals, jnp.int64), jnp.asarray(vvalid),
        jnp.asarray(active),
        jnp.asarray(dec_vals if use_dec else np.zeros(cap), jnp.int64))
    return ([np.asarray(a) for a in flat], np.asarray(used),
            int(np.asarray(cnt)), bool(np.asarray(ovf)))


def _gb_numpy_oracle(keys, kvalid, vals, vvalid, active, dec_vals=None):
    acc = {}
    for i in range(len(keys)):
        if not active[i]:
            continue
        k = (bool(kvalid[i]), int(keys[i]) if kvalid[i] else 0)
        e = acc.setdefault(k, {"sum": 0, "cnt": 0, "mn": None,
                               "mx": None, "dsum": 0, "dcnt": 0})
        if vvalid[i]:
            v = int(vals[i])
            e["sum"] += v
            e["cnt"] += 1
            e["mn"] = v if e["mn"] is None else min(e["mn"], v)
            e["mx"] = v if e["mx"] is None else max(e["mx"], v)
            if dec_vals is not None:
                e["dsum"] += int(dec_vals[i])
                e["dcnt"] += 1
    return acc


@pytest.mark.parametrize("cap,ngroups,null_prob",
                         [(64, 5, 0.0), (256, 17, 0.3), (96, 9, 0.15)],
                         ids=["tiny", "nulls", "oddcap"])
def test_groupby_kernel_vs_numpy_oracle(cap, ngroups, null_prob):
    rng = np.random.default_rng(cap + ngroups)
    kvalid = rng.random(cap) >= null_prob
    # engine invariant: invalid slots hold zeros (mask_col et al.)
    keys = np.where(kvalid, rng.integers(-3, ngroups, cap), 0)
    vals = rng.integers(-10**6, 10**6, cap)
    vvalid = rng.random(cap) >= null_prob
    active = rng.random(cap) >= 0.1
    dec = rng.integers(-10**9, 10**9, cap)
    flat, used, cnt, ovf = _gb_direct(cap, keys, kvalid, vals, vvalid,
                                      active, 64, dec_vals=dec)
    assert not ovf
    exp = _gb_numpy_oracle(keys, kvalid, vals, vvalid, active,
                           dec_vals=dec)
    assert cnt == len(exp)
    # flat layout: key(data, validity), then per entry (data, validity)
    # x4, then decimal (hi, lo, validity)
    kd, kv = flat[0], flat[1]
    got = {}
    for t in range(len(used)):
        if not used[t]:
            continue
        k = (bool(kv[t]), int(kd[t]) if kv[t] else 0)
        got[k] = {
            "sum": int(flat[2][t]) if flat[3][t] else None,
            "cnt": int(flat[4][t]),
            "mn": int(flat[6][t]) if flat[7][t] else None,
            "mx": int(flat[8][t]) if flat[9][t] else None,
            "dsum": ((int(flat[10][t]) << 64)
                     | (int(flat[11][t]) & ((1 << 64) - 1)))
            if flat[12][t] else None,
        }
    want = {k: {"sum": e["sum"] if e["cnt"] else None, "cnt": e["cnt"],
                "mn": e["mn"], "mx": e["mx"],
                "dsum": e["dsum"] if e["dcnt"] else None}
            for k, e in exp.items()}
    assert got == want


def test_groupby_kernel_empty_and_single_row():
    cap = 64
    zeros = np.zeros(cap, dtype=np.int64)
    none_active = np.zeros(cap, dtype=bool)
    flat, used, cnt, ovf = _gb_direct(cap, zeros, zeros > -1, zeros,
                                      zeros > -1, none_active, 64)
    assert cnt == 0 and not ovf and not used.any()
    one = none_active.copy()
    one[17] = True
    vals = zeros.copy()
    vals[17] = -42
    flat, used, cnt, ovf = _gb_direct(cap, zeros, zeros > -1, vals,
                                      zeros > -1, one, 64)
    assert cnt == 1 and not ovf
    t = int(np.argmax(used))
    assert int(flat[2][t]) == -42 and int(flat[4][t]) == 1


def test_groupby_kernel_overflow_flag():
    cap = 256
    keys = np.arange(cap, dtype=np.int64)  # every row its own group
    valid = np.ones(cap, dtype=bool)
    _flat, _used, _cnt, ovf = _gb_direct(cap, keys, valid, keys, valid,
                                         valid, 64)
    assert ovf  # 256 groups cannot fit a 64-slot table


@pytest.mark.slow
def test_groupby_kernel_property_sweep():
    """Wide interpret-mode sweep: dtype x null pattern x capacity
    bucket x group cardinality, every combination against the numpy
    oracle (slow: dozens of kernel compiles)."""
    for cap in (64, 96, 160, 512):
        for ngroups in (1, 3, 50):
            for null_prob in (0.0, 0.5, 0.95):
                rng = np.random.default_rng(cap * ngroups + 1)
                kvalid = rng.random(cap) >= null_prob
                keys = np.where(kvalid,
                                rng.integers(-2, ngroups, cap), 0)
                vals = rng.integers(-10**9, 10**9, cap)
                vvalid = rng.random(cap) >= null_prob
                active = rng.random(cap) >= 0.2
                flat, used, cnt, ovf = _gb_direct(
                    cap, keys, kvalid, vals, vvalid, active, 128)
                assert not ovf
                exp = _gb_numpy_oracle(keys, kvalid, vals, vvalid,
                                       active)
                assert cnt == len(exp), (cap, ngroups, null_prob)


# ---------------------------------------------------------------------------
# joinProbe kernel: direct property test
# ---------------------------------------------------------------------------

def test_join_probe_kernel_vs_numpy_oracle():
    cap_r, cap_l = 64, 256
    rng = np.random.default_rng(5)
    rk = rng.integers(0, 40, cap_r)
    lk = rng.integers(0, 80, cap_l)
    vr = rng.random(cap_r) > 0.25
    vl = rng.random(cap_l) > 0.25

    @jax.jit
    def run(rk, vr, lk, vl):
        wr = [rk.astype(jnp.int64).view(jnp.uint64)]
        wl = [lk.astype(jnp.int64).view(jnp.uint64)]
        from spark_rapids_tpu.ops.groupby import hash_subkey_words
        return KJ.build_probe(
            KG.pack_words_i64(wr),
            hash_subkey_words(wr).view(jnp.int64), vr,
            KG.pack_words_i64(wl),
            hash_subkey_words(wl).view(jnp.int64), vl)

    m, ri = run(jnp.asarray(rk), jnp.asarray(vr), jnp.asarray(lk),
                jnp.asarray(vl))
    m, ri = np.asarray(m), np.asarray(ri)
    for i in range(cap_l):
        rows = [j for j in range(cap_r) if vr[j] and rk[j] == lk[i]]
        assert m[i] == bool(vl[i] and rows)
        if m[i]:
            # first-occurrence row: the oracle's key-sorted order_r
            # picks the lowest original index too
            assert ri[i] == rows[0]


# ---------------------------------------------------------------------------
# murmur3 kernel: oracle + host-twin drift guard (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def _hash_battery(n=200, seed=9):
    """HostBatch covering every kernel-hashable type, with the edge
    cases the twin-parity guard pins: empty strings, embedded null
    bytes, and high-bit (negative-as-int8) trailing bytes."""
    rng = np.random.default_rng(seed)
    strs = np.empty(n, dtype=object)
    pool = ["", "a", "ab", "abc", "abcd", "abcde", "\x00", "x\x00y",
            "\x7f\x00", "éä", "ÿþ", "0123456789abcdef",
            "tailé"]
    for i in range(n):
        strs[i] = pool[rng.integers(0, len(pool))]
    cols = [
        ("b", T.BooleanT, rng.integers(0, 2, n).astype(bool)),
        ("i", T.IntegerT, rng.integers(-2**31, 2**31, n,
                                       dtype=np.int64).astype(np.int32)),
        ("l", T.LongT, rng.integers(-2**62, 2**62, n)),
        ("f", T.FloatT, np.where(rng.random(n) < 0.1, -0.0,
                                 rng.standard_normal(n)
                                 ).astype(np.float32)),
        ("d", T.DoubleT, np.where(rng.random(n) < 0.1, -0.0,
                                  rng.standard_normal(n))),
        ("dt", T.DateT, rng.integers(-11000, 47000, n
                                     ).astype(np.int32)),
        ("ts", T.TimestampT, rng.integers(-10**15, 10**15, n)),
        ("dec", DEC, rng.integers(-10**10, 10**10, n)),
        ("s", T.StringT, strs),
    ]
    fields, hcols = [], []
    for name, dt, vals in cols:
        valid = rng.random(n) > 0.15
        fields.append(T.StructField(name, dt))
        hcols.append(HostColumn(dt, vals, valid).normalized())
    return HostBatch(T.StructType(fields), hcols, n)


def test_murmur3_host_device_twin_parity():
    """Device murmur3 (ops/hashing.py) vs the host implementation
    (columnar/murmur3.py via expressions._hash_column), swept over all
    hashable column types — the pinned oracle the fused kernel lands
    against."""
    from spark_rapids_tpu.columnar.device import DeviceBatch
    from spark_rapids_tpu.ops import hashing as H
    from spark_rapids_tpu.sql.expressions import _hash_column
    hb = _hash_battery()
    n = hb.num_rows
    host = np.full(n, 42, dtype=np.int32)
    for c in hb.columns:
        host = _hash_column(c, host)
    db = DeviceBatch.from_host(hb)  # capacity-bucketed: compare prefix
    dev = np.asarray(jax.jit(
        lambda: H.murmur3_columns(db.columns, db.capacity, 42))())
    assert np.array_equal(host, dev[:n])


def test_murmur3_kernel_matches_oracle_composition():
    from spark_rapids_tpu.columnar.device import DeviceBatch
    from spark_rapids_tpu.ops import hashing as H
    hb = _hash_battery(seed=10)
    db = DeviceBatch.from_host(hb)
    cap = db.capacity
    assert KM.hash_kernel_eligible([f.data_type
                                    for f in hb.schema.fields])
    want = np.asarray(jax.jit(
        lambda: H.murmur3_columns(db.columns, cap, 42))())
    got = np.asarray(jax.jit(
        lambda: KM.murmur3_columns_kernel(db.columns, cap, 42))())
    assert np.array_equal(want, got)


# ---------------------------------------------------------------------------
# query-level bit-identity: kernels on vs off vs CPU oracle
# ---------------------------------------------------------------------------

def test_q1_shape_bit_identical_kernels_on_off():
    views = {"t": _groupy_batch()}
    cpu, _ = _run(CPU, views, Q_AGG)
    on, plans = _run(TPU, views, Q_AGG)
    off, _ = _run({**TPU, "spark.rapids.sql.kernel.enabled": "false"},
                  views, Q_AGG)
    assert cpu == on == off
    counters = _kcounters(plans)
    assert counters.get("kernelDispatchCount.groupbyHash", 0) > 0
    assert counters.get("kernelFallbacks.groupbyHash", 0) == 0


def test_groupby_overflow_falls_back_bit_identical():
    n = 4000
    rng = np.random.default_rng(8)
    keys = np.array([f"g{i:04d}" for i in rng.integers(0, 1500, n)],
                    dtype=object)
    hb = HostBatch(T.StructType([T.StructField("k", T.StringT),
                                 T.StructField("v", T.LongT)]),
                   [HostColumn.all_valid(keys, T.StringT),
                    HostColumn.all_valid(
                        rng.integers(0, 100, n), T.LongT)], n)
    q = "SELECT k, sum(v), count(*) FROM t GROUP BY k ORDER BY k"
    views = {"t": hb}
    cpu, _ = _run(CPU, views, q)
    small = {**TPU,
             "spark.rapids.sql.kernel.groupbyHash.tableSlots": "64"}
    on, plans = _run(small, views, q)
    assert cpu == on
    counters = _kcounters(plans)
    assert counters.get("kernelFallbacks.groupbyHash", 0) >= 1


@pytest.mark.parametrize("name", ["groupbyHash", "murmur3"])
def test_injected_kernel_failure_falls_back(name):
    views = {"t": _groupy_batch(n=3000)}
    conf = {**TPU, "spark.rapids.sql.shuffle.devicePartitions": "4"}
    cpu, _ = _run(CPU, views, Q_AGG)
    KR.inject_failure(name)
    try:
        on, plans = _run(conf, views, Q_AGG)
    finally:
        KR.inject_failure(name, on=False)
        KR.clear_poison()
    assert cpu == on
    assert _kcounters(plans).get(f"kernelFallbacks.{name}", 0) >= 1


@pytest.mark.fault
def test_groupby_kernel_under_injected_oom():
    """Kernel dispatches ride the PR 4 retry protocol: injected OOM
    spills+retries (and splits) around the kernel program, results
    stay bit-identical, and the kernel path stays on (no fallback —
    OOM is NOT a lowering failure)."""
    views = {"t": _groupy_batch(n=8000)}
    cpu, _ = _run(CPU, views, Q_AGG)
    conf = {**TPU, "spark.rapids.sql.test.injectOOM": "5"}
    on, plans = _run(conf, views, Q_AGG)
    assert cpu == on
    snap = registry_snapshot(plans)["metrics"]
    assert snap.get("retryCount", 0) > 0
    assert snap.get("kernelDispatchCount.groupbyHash", 0) > 0
    assert snap.get("kernelFallbacks.groupbyHash", 0) == 0


def _join_views(m=300, n=3000, dup=False):
    rng = np.random.default_rng(13)
    pk = np.arange(1, m + 1)
    if dup:
        pk = np.concatenate([pk, pk[: m // 4]])
    dim = HostBatch(T.StructType([T.StructField("pk", T.LongT),
                                  T.StructField("nm", T.StringT)]),
                    [HostColumn.all_valid(pk, T.LongT),
                     HostColumn.all_valid(
                         np.array([f"n{i}" for i in range(len(pk))],
                                  dtype=object), T.StringT)], len(pk))
    fkv = rng.integers(1, m + 120, n)
    fvalid = rng.random(n) > 0.1
    fact = HostBatch(T.StructType([T.StructField("fk", T.LongT),
                                   T.StructField("v", T.LongT)]),
                     [HostColumn(T.LongT, fkv, fvalid).normalized(),
                      HostColumn.all_valid(
                          rng.integers(0, 50, n), T.LongT)], n)
    return fact, dim


def _join_rows(conf, jt, dup=False, capture=True):
    fact, dim = _join_views(dup=dup)
    s = TpuSparkSession(dict(conf))
    try:
        f = s.createDataFrame(fact)
        d = s.createDataFrame(dim)
        s.start_capture()
        out = f.join(d, f["fk"] == d["pk"], jt)._execute().to_pydict()
        names = list(out)
        nn = len(out[names[0]]) if names else 0
        rows = sorted((tuple(out[c][i] for c in names)
                       for i in range(nn)),
                      key=lambda r: tuple((v is None, str(v))
                                          for v in r))
        return rows, s.get_captured_plans()
    finally:
        s.stop()


@pytest.mark.parametrize("jt", ["leftsemi", "leftanti", "inner"])
def test_join_kernel_parity(jt):
    cpu, _ = _join_rows(CPU, jt)
    on, plans = _join_rows(TPU, jt)
    off, _ = _join_rows({**TPU,
                         "spark.rapids.sql.kernel.enabled": "false"},
                        jt)
    assert cpu == on == off
    assert _kcounters(plans).get("kernelDispatchCount.joinProbe",
                                 0) > 0


@pytest.mark.parametrize("jt", ["leftsemi", "inner"])
def test_join_kernel_duplicate_build_keys(jt):
    """Duplicate build keys: semi stays on the probe kernel (existence
    only); inner loses its unique-key certificate and must take the
    oracle expansion — both bit-identical."""
    cpu, _ = _join_rows(CPU, jt, dup=True)
    on, _ = _join_rows(TPU, jt, dup=True)
    assert cpu == on


def test_exchange_murmur3_kernel_parity():
    views = {"t": _groupy_batch(n=4000)}
    conf = {**TPU, "spark.rapids.sql.shuffle.devicePartitions": "4"}
    cpu, _ = _run(CPU, views, Q_AGG, parts=3)
    on, plans = _run(conf, views, Q_AGG, parts=3)
    off, _ = _run({**conf, "spark.rapids.sql.kernel.murmur3.enabled":
                   "false"}, views, Q_AGG, parts=3)
    assert cpu == on == off
    counters = _kcounters(plans)
    assert counters.get("kernelDispatchCount.murmur3", 0) > 0


def test_each_kernel_individually_disableable():
    views = {"t": _groupy_batch(n=3000)}
    conf = {**TPU, "spark.rapids.sql.shuffle.devicePartitions": "4"}
    cpu, _ = _run(CPU, views, Q_AGG)
    for name in KR.KERNELS:
        off_one = {**conf,
                   f"spark.rapids.sql.kernel.{name}.enabled": "false"}
        out, plans = _run(off_one, views, Q_AGG)
        assert cpu == out, name
        counters = _kcounters(plans)
        assert counters.get(f"kernelDispatchCount.{name}", 0) == 0, name


# ---------------------------------------------------------------------------
# observability: spans, hotspots CLI
# ---------------------------------------------------------------------------

def test_kernel_dispatch_spans_and_hotspots(tmp_path):
    from spark_rapids_tpu import trace as TR
    from spark_rapids_tpu.tools import hotspots_report
    from spark_rapids_tpu.trace import load_trace
    TR.reset_tracing()
    tdir = str(tmp_path / "traces")
    conf = {**TPU,
            "spark.rapids.sql.shuffle.devicePartitions": "4",
            "spark.rapids.sql.trace.enabled": "true",
            "spark.rapids.sql.trace.dir": tdir}
    try:
        _run(conf, {"t": _groupy_batch(n=3000)}, Q_AGG)
    finally:
        TR.reset_tracing()
    files = sorted(glob.glob(os.path.join(tdir, "trace-*.json")))
    assert files
    spans = [s for fp in files for s in load_trace(fp)["spans"]]
    agg_disp = [s for s in spans
                if s["name"] == "TpuHashAggregateExec.dispatch"
                and s.get("args", {}).get("kernel") == "groupbyHash"]
    assert agg_disp, "agg dispatch spans must carry the kernel attr"
    kd = [s for s in spans if s["name"] == "kernelDispatch"]
    assert any(s.get("args", {}).get("kernel") == "murmur3"
               for s in kd)
    report = hotspots_report(files)
    assert "kernelDispatch[murmur3]" in report
    assert "TpuHashAggregateExec.dispatch" in report


def test_hotspots_cli_exit_contract(tmp_path):
    # PR 12 contract: an EXISTING but empty trace dir is a normal
    # answer ("no spans found", exit 0) — an idle ring recorder must
    # not fail automation tailing it; a missing path stays an error
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "hotspots",
         str(tmp_path)],
        capture_output=True, text=True, cwd=repo)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "no spans found" in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "hotspots",
         str(tmp_path / "does-not-exist")],
        capture_output=True, text=True, cwd=repo)
    assert out.returncode == 1
    assert "no such trace file or directory" in out.stdout


# ---------------------------------------------------------------------------
# conf plumbing
# ---------------------------------------------------------------------------

def test_table_slots_shrinks_to_batch():
    from spark_rapids_tpu.conf import TpuConf
    conf = TpuConf({})
    assert KR.table_slots(conf, 1 << 20) == 1024  # conf bound
    assert KR.table_slots(conf, 64) == 128        # 2x a tiny batch
    conf2 = TpuConf(
        {"spark.rapids.sql.kernel.groupbyHash.tableSlots": "4096"})
    assert KR.table_slots(conf2, 1 << 20) == 4096


def test_kernel_enabled_gates():
    from spark_rapids_tpu.conf import TpuConf
    assert KR.kernel_enabled(TpuConf({}), "groupbyHash") == (
        DC.pallas_mode() is not None)
    assert not KR.kernel_enabled(
        TpuConf({"spark.rapids.sql.kernel.enabled": "false"}),
        "groupbyHash")
    assert not KR.kernel_enabled(
        TpuConf({"spark.rapids.sql.kernel.groupbyHash.enabled":
                 "false"}), "groupbyHash")
    assert KR.kernel_enabled(None, "groupbyHash") is False
