"""Whole-stage fusion parity corpus (exec/fused.py).

Every fusible chain shape is asserted BIT-IDENTICAL between the fused
plan (``spark.rapids.sql.stageFusion.enabled=true``, the default) and
the unfused per-operator plan (``...=false``), plus the dual-session
CPU check through the standard harness. A property test over plans
containing shuffles/transitions asserts the fuser never crosses such a
boundary (a fused stage may only contain filter/project and a partial
hash-aggregate sink).
"""

import random

import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (DoubleGen, IntegerGen, KeyStringGen, LongGen,
                           StringGen, gen_batch)
from tests.harness import assert_tpu_and_cpu_equal_collect
from tests.support import values_equal

N = 512


def _df(spark, gens, n=N, seed=7, parts=3):
    return spark.createDataFrame(gen_batch(gens, n, seed),
                                 num_partitions=parts)


def _collect_fused(plans):
    from spark_rapids_tpu.exec.fused import TpuFusedStageExec
    found = []

    def walk(p):
        if isinstance(p, TpuFusedStageExec):
            found.append(p)
        for c in p.children:
            walk(c)
    for p in plans:
        walk(p)
    return found


def _run_tpu(df_fn, conf):
    spark = TpuSparkSession({**(conf or {}),
                             "spark.rapids.sql.enabled": "true"})
    try:
        spark.start_capture()
        batch = df_fn(spark)._execute()
        return batch.to_pydict(), spark.get_captured_plans()
    finally:
        spark.stop()


def assert_fused_matches_unfused(df_fn, conf=None, expect_fused=True):
    """Core parity assert: same query, fusion on vs off, EXACT equality
    (same partition order either way, so no sorting slack needed)."""
    fused, fplans = _run_tpu(df_fn, {
        **(conf or {}), "spark.rapids.sql.stageFusion.enabled": "true"})
    unfused, uplans = _run_tpu(df_fn, {
        **(conf or {}), "spark.rapids.sql.stageFusion.enabled": "false"})
    fnodes = _collect_fused(fplans)
    if expect_fused:
        assert fnodes, ("expected a TpuFusedStage in:\n"
                        + "\n".join(p.tree_string() for p in fplans))
    assert not _collect_fused(uplans), "fuser must disable cleanly"
    assert set(fused) == set(unfused), (set(fused), set(unfused))
    for col in fused:
        assert len(fused[col]) == len(unfused[col]), col
        for i, (a, b) in enumerate(zip(fused[col], unfused[col])):
            assert values_equal(a, b, approx=False), (
                f"col {col} row {i}: fused={a!r} unfused={b!r}")
    return fnodes


# ---------------------------------------------------------------------------
# Parity corpus: every fusible chain shape
# ---------------------------------------------------------------------------

def test_filter_project_chain():
    fnodes = assert_fused_matches_unfused(
        lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())])
        .filter(F.col("a") > 3)
        .select((F.col("a") * 2).alias("a2"),
                (F.col("b") + 1.5).alias("b1")))
    names = [type(op).__name__ for op in fnodes[0].fused_ops]
    assert names == ["TpuFilterExec", "TpuProjectExec"], names


def test_project_filter_chain():
    assert_fused_matches_unfused(
        lambda s: _df(s, [("a", LongGen()), ("s", StringGen())])
        .select((F.col("a") + 7).alias("a7"), F.col("s"))
        .filter(F.col("a7") % 3 == 0))


def test_long_mixed_chain():
    # filter -> project -> filter -> project: one maximal stage
    fnodes = assert_fused_matches_unfused(
        lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())])
        .filter(F.col("a").isNotNull())
        .select((F.col("a") * F.col("a")).alias("sq"), F.col("b"))
        .filter(F.col("sq") < 400)
        .select((F.col("sq") + F.col("b")).alias("out")))
    assert len(fnodes) == 1, [f.simple_string() for f in fnodes]
    assert len(fnodes[0].fused_ops) == 4


def test_filter_project_partial_agg_chain():
    from spark_rapids_tpu.exec.agg import TpuHashAggregateExec
    fnodes = assert_fused_matches_unfused(
        lambda s: _df(s, [("k", KeyStringGen()), ("v", LongGen()),
                          ("w", DoubleGen())])
        .filter(F.col("v") > 0)
        .select(F.col("k"), (F.col("v") * 3).alias("v3"))
        .groupBy("k").agg(F.sum(F.col("v3")).alias("s"),
                          F.count(F.lit(1)).alias("c")))
    agg_stages = [n for n in fnodes
                  if isinstance(n.fused_ops[-1], TpuHashAggregateExec)]
    assert agg_stages, [f.simple_string() for f in fnodes]
    assert agg_stages[0].fused_ops[-1].mode == "partial"


def test_project_topn_build_chain():
    # chain feeding a TopN (TakeOrderedAndProject) build
    assert_fused_matches_unfused(
        lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())])
        .filter(F.col("b").isNotNull())
        .select(F.col("a"), (F.col("b") * 2.0).alias("b2"))
        .orderBy(F.col("b2")).limit(10))


def test_chain_feeding_join_build_side():
    def q(s):
        left = _df(s, [("k", IntegerGen()), ("v", LongGen())], seed=11)
        right = (_df(s, [("k", IntegerGen()), ("w", LongGen())], seed=13)
                 .filter(F.col("w") > 0)
                 .select(F.col("k"), (F.col("w") + 1).alias("w1")))
        return left.join(right, on="k")
    assert_fused_matches_unfused(q)


def test_global_agg_not_absorbed_but_chain_fuses():
    # complete-mode (no grouping) agg is NOT absorbed; the chain below
    # it still fuses and parity holds
    fnodes = assert_fused_matches_unfused(
        lambda s: _df(s, [("a", LongGen()), ("b", DoubleGen())])
        .filter(F.col("a") > 0)
        .select((F.col("a") * 2).alias("a2"))
        .agg(F.sum(F.col("a2")).alias("s")))
    from spark_rapids_tpu.exec.agg import TpuHashAggregateExec
    for n in fnodes:
        sink = n.fused_ops[-1]
        if isinstance(sink, TpuHashAggregateExec):
            assert sink.mode == "partial"


def test_single_op_not_fused():
    # fusing one operator would just re-wrap its one program
    _, plans = _run_tpu(
        lambda s: _df(s, [("a", IntegerGen())])
        .select((F.col("a") + 1).alias("a1")), {})
    assert not _collect_fused(plans)


def test_fusion_disabled_conf():
    assert_fused_matches_unfused(
        lambda s: _df(s, [("a", IntegerGen())])
        .filter(F.col("a") > 0).select((F.col("a") * 2).alias("x")),
        expect_fused=True)
    _, plans = _run_tpu(
        lambda s: _df(s, [("a", IntegerGen())])
        .filter(F.col("a") > 0).select((F.col("a") * 2).alias("x")),
        {"spark.rapids.sql.stageFusion.enabled": "false"})
    assert not _collect_fused(plans)


def test_cpu_parity_through_harness():
    # the standard dual-session check still holds with fusion on (the
    # default), and the fused stage shows up in the captured plan
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", KeyStringGen()), ("v", LongGen())])
        .filter(F.col("v") > 2)
        .select(F.col("k"), (F.col("v") - 1).alias("vm"))
        .groupBy("k").agg(F.sum(F.col("vm")).alias("s")),
        expect_execs=["TpuFusedStage"])


def test_part_ctx_chain_not_fused():
    # monotonically_increasing_id threads cross-batch device state the
    # fused program does not carry: the chain must stay unfused AND
    # stay correct
    def q(s):
        return (_df(s, [("a", IntegerGen())])
                .filter(F.col("a").isNotNull())
                .select(F.monotonically_increasing_id().alias("i"),
                        F.col("a")))
    _, plans = _run_tpu(q, {})
    for node in _collect_fused(plans):
        for op in node.fused_ops:
            assert "Monotonically" not in repr(
                getattr(op, "project_list", [])), node.tree_string()
    assert_fused_matches_unfused(q, expect_fused=False)


# ---------------------------------------------------------------------------
# Property: fusion never crosses a shuffle / transition boundary
# ---------------------------------------------------------------------------

_BOUNDARY_QUERIES = [
    lambda s: _df(s, [("k", KeyStringGen()), ("v", LongGen())])
    .filter(F.col("v") > 0).select(F.col("k"),
                                   (F.col("v") * 2).alias("v2"))
    .groupBy("k").agg(F.sum(F.col("v2")).alias("s"))
    .filter(F.col("s") > 10).select((F.col("s") + 1).alias("s1")),
    lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())])
    .repartition(4, F.col("a"))
    .filter(F.col("a") > 1).select((F.col("a") + 1).alias("x"),
                                   F.col("b"))
    .orderBy(F.col("x")),
    lambda s: _df(s, [("k", IntegerGen()), ("v", LongGen())], seed=3)
    .join(_df(s, [("k", IntegerGen()), ("w", LongGen())], seed=5)
          .filter(F.col("w") != 0), on="k")
    .select(F.col("k"), (F.col("v") + F.col("w")).alias("vw"))
    .filter(F.col("vw") > 0),
]


@pytest.mark.parametrize("qi", range(len(_BOUNDARY_QUERIES)))
def test_fusion_respects_boundaries(qi):
    from spark_rapids_tpu.exec.agg import TpuHashAggregateExec
    from spark_rapids_tpu.exec.basic import TpuFilterExec, TpuProjectExec
    q = _BOUNDARY_QUERIES[qi]
    assert_fused_matches_unfused(q)
    _, plans = _run_tpu(q, {})
    for node in _collect_fused(plans):
        ops = node.fused_ops
        # every constituent is a per-batch chain op; only the SINK may
        # be a (partial) aggregate — exchanges, transitions, coalesce
        # can never be absorbed
        for op in ops[:-1]:
            assert isinstance(op, (TpuFilterExec, TpuProjectExec)), (
                node.tree_string())
        assert isinstance(ops[-1], (TpuFilterExec, TpuProjectExec,
                                    TpuHashAggregateExec)), (
            node.tree_string())
        if isinstance(ops[-1], TpuHashAggregateExec):
            assert ops[-1].mode == "partial"


def test_random_chain_property():
    """Seeded random filter/project chains: fused == unfused exactly."""
    rng = random.Random(20260803)
    cols = ["a", "b"]
    for case in range(6):
        steps = []
        n_steps = rng.randint(2, 5)
        for _ in range(n_steps):
            if rng.random() < 0.4:
                c = rng.choice(cols)
                thr = rng.randint(-5, 5)
                steps.append(("filter", c, thr))
            else:
                c1, c2 = rng.choice(cols), rng.choice(cols)
                k = rng.randint(1, 4)
                steps.append(("project", c1, c2, k))

        def q(s, _steps=tuple(steps)):
            df = _df(s, [("a", IntegerGen()), ("b", LongGen())],
                     seed=100 + case)
            names = {"a": "a", "b": "b"}
            for st in _steps:
                if st[0] == "filter":
                    df = df.filter(F.col(names[st[1]]) > st[2])
                else:
                    _, c1, c2, k = st
                    df = df.select(
                        (F.col(names[c1]) * k).alias("a"),
                        (F.col(names[c2]) + k).alias("b"))
            return df
        # parity is the property; whether the planner's simplifications
        # leave a >=2-op chain to fuse varies per case
        assert_fused_matches_unfused(q, expect_fused=False)


# ---------------------------------------------------------------------------
# Metrics fan-back + fusion-specific counters
# ---------------------------------------------------------------------------

def test_fused_metrics_fan_back():
    _, plans = _run_tpu(
        lambda s: _df(s, [("a", IntegerGen()), ("b", DoubleGen())])
        .filter(F.col("a") > 0)
        .select((F.col("a") + 1).alias("x"), F.col("b")), {})
    nodes = _collect_fused(plans)
    assert nodes
    node = nodes[0]
    snap = node.metrics.snapshot()
    assert snap.get("fusedOps") == len(node.fused_ops) == 2
    assert snap.get("dispatchCount", 0) >= 1
    # the compile cache is process-global: an identical chain compiled
    # by an earlier test hits; a fresh one misses and books its first
    # call's wall as compile time
    if snap.get("compileCacheMisses", 0):
        assert snap.get("stageCompileTime", 0) > 0
    else:
        assert snap.get("compileCacheHits", 0) >= 1
    # constituent execs keep their stage keys (batch counts fan back)
    for op in node.fused_ops:
        assert op.metrics.value("numOutputBatches") >= 1, (
            type(op).__name__)


def test_agg_prelude_metrics():
    _, plans = _run_tpu(
        lambda s: _df(s, [("k", KeyStringGen()), ("v", LongGen())])
        .filter(F.col("v") > 0).select(F.col("k"),
                                       (F.col("v") * 2).alias("v2"))
        .groupBy("k").agg(F.sum(F.col("v2")).alias("s")), {})
    from spark_rapids_tpu.exec.agg import TpuHashAggregateExec
    nodes = [n for n in _collect_fused(plans)
             if isinstance(n.fused_ops[-1], TpuHashAggregateExec)]
    assert nodes
    agg = nodes[0].fused_ops[-1]
    snap = agg.metrics.snapshot()
    assert snap.get("dispatchCount", 0) >= 1
    for op in nodes[0].fused_ops[:-1]:
        assert op.metrics.value("numOutputBatches") >= 1


def test_dispatch_count_drops_with_fusion():
    """The whole point: fewer device programs per batch."""
    def q(s):
        return (_df(s, [("k", KeyStringGen()), ("v", LongGen())])
                .filter(F.col("v") > 0)
                .select(F.col("k"), (F.col("v") * 2).alias("v2"))
                .groupBy("k").agg(F.sum(F.col("v2")).alias("s")))

    def dispatches(plans):
        total = 0

        def walk(p):
            nonlocal total
            ms = getattr(p, "metrics", None)
            if ms is not None:
                total += ms.snapshot().get("dispatchCount", 0)
            for op in getattr(p, "fused_ops", []):
                total += op.metrics.snapshot().get("dispatchCount", 0)
            for c in p.children:
                walk(c)
        for p in plans:
            walk(p)
        return total

    _, fplans = _run_tpu(q, {})
    _, uplans = _run_tpu(
        q, {"spark.rapids.sql.stageFusion.enabled": "false"})
    assert dispatches(fplans) < dispatches(uplans), (
        dispatches(fplans), dispatches(uplans))


# ---------------------------------------------------------------------------
# Satellites: bounded compile caches + int64 device scalars
# ---------------------------------------------------------------------------

def test_jit_cache_lru_and_stats():
    from spark_rapids_tpu.jit_cache import JitCache, cache_stats
    c = JitCache("test-lru", capacity=2)
    assert c.get("a") is None          # miss
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1             # hit; refreshes LRU order
    c.put("c", 3)                      # evicts b (oldest-used)
    assert c.get("b") is None
    assert c.get("a") == 1 and c.get("c") == 3
    st = c.stats()
    assert st["size"] == 2 and st["evictions"] == 1
    assert st["hits"] == 3 and st["misses"] == 2
    assert "test-lru" in cache_stats()


def test_device_long_is_int64():
    import jax.numpy as jnp

    from spark_rapids_tpu.sql import types as T
    a = T.device_long(1 << 40)  # would wrap as int32
    assert a.dtype == jnp.int64
    assert int(a) == 1 << 40
