"""Seeded random data generators — the DataGen hierarchy twin
(integration_tests data_gen.py:30 in the reference). Deterministic per
seed; every generator mixes nulls and the type's edge values (extremes,
NaN/±Inf/-0.0 for floats, empty/whitespace strings) because those are
where device/CPU semantics diverge first.
"""

from __future__ import annotations

import string
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import types as T

DEFAULT_SEED = 42


class DataGen:
    dtype: T.DataType

    def __init__(self, nullable: bool = True, null_prob: float = 0.1):
        self.nullable = nullable
        self.null_prob = null_prob

    def _values(self, n: int, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    def gen(self, n: int, rng: np.random.Generator) -> HostColumn:
        data = self._values(n, rng)
        if self.nullable:
            validity = rng.random(n) >= self.null_prob
        else:
            validity = np.ones(n, dtype=bool)
        return HostColumn(self.dtype, data, validity).normalized()


class _IntegralGen(DataGen):
    np_dtype: np.dtype
    lo: int
    hi: int

    def _values(self, n, rng):
        vals = rng.integers(self.lo, self.hi, size=n, endpoint=True,
                            dtype=np.int64).astype(self.np_dtype)
        # sprinkle extremes
        for v in (self.lo, self.hi, 0):
            idx = rng.integers(0, n)
            vals[idx] = v
        return vals


class ByteGen(_IntegralGen):
    dtype = T.ByteT
    np_dtype = np.int8
    lo, hi = -128, 127


class ShortGen(_IntegralGen):
    dtype = T.ShortT
    np_dtype = np.int16
    lo, hi = -(1 << 15), (1 << 15) - 1


class IntegerGen(_IntegralGen):
    dtype = T.IntegerT
    np_dtype = np.int32
    lo, hi = -(1 << 31), (1 << 31) - 1


class LongGen(_IntegralGen):
    dtype = T.LongT
    np_dtype = np.int64
    lo, hi = -(1 << 63), (1 << 63) - 1


class SmallIntGen(_IntegralGen):
    """Narrow-range ints: produce key collisions for group/join tests."""
    dtype = T.IntegerT
    np_dtype = np.int32
    lo, hi = -10, 10


class BooleanGen(DataGen):
    dtype = T.BooleanT

    def _values(self, n, rng):
        return rng.integers(0, 2, size=n).astype(bool)


class DoubleGen(DataGen):
    dtype = T.DoubleT

    def __init__(self, nullable=True, null_prob=0.1,
                 special: bool = True, lo=-1e6, hi=1e6):
        super().__init__(nullable, null_prob)
        self.special = special
        self.lo, self.hi = lo, hi

    def _values(self, n, rng):
        vals = rng.uniform(self.lo, self.hi, size=n)
        if self.special and n >= 8:
            specials = [np.nan, np.inf, -np.inf, -0.0, 0.0,
                        np.finfo(np.float64).max, np.finfo(np.float64).min]
            pos = rng.choice(n, size=len(specials), replace=False)
            for p, s in zip(pos, specials):
                vals[p] = s
        return vals


class FloatGen(DoubleGen):
    dtype = T.FloatT

    def _values(self, n, rng):
        return super()._values(n, rng).astype(np.float32)


class StringGen(DataGen):
    dtype = T.StringT

    def __init__(self, nullable=True, null_prob=0.1, max_len: int = 12,
                 charset: str = string.ascii_letters + string.digits + " _",
                 with_empty: bool = True):
        super().__init__(nullable, null_prob)
        self.max_len = max_len
        self.charset = charset
        self.with_empty = with_empty

    def _values(self, n, rng):
        chars = np.array(list(self.charset))
        out = np.empty(n, dtype=object)
        lens = rng.integers(0 if self.with_empty else 1,
                            self.max_len, size=n, endpoint=True)
        for i in range(n):
            out[i] = "".join(rng.choice(chars, size=lens[i]))
        return out


class KeyStringGen(StringGen):
    """Low-cardinality strings for grouping keys."""

    def __init__(self, nullable=True, cardinality: int = 7):
        super().__init__(nullable)
        self.cardinality = cardinality

    def _values(self, n, rng):
        pool = [f"key_{i}" for i in range(self.cardinality)] + ["", " "]
        return np.array([pool[i] for i in
                         rng.integers(0, len(pool), size=n)], dtype=object)


class DateGen(DataGen):
    dtype = T.DateT

    def _values(self, n, rng):
        # 1940..2100 in days-since-epoch
        return rng.integers(-11000, 47000, size=n).astype(np.int32)


class TimestampGen(DataGen):
    dtype = T.TimestampT

    def _values(self, n, rng):
        lo = -1_000_000_000_000_000
        hi = 4_000_000_000_000_000
        return rng.integers(lo, hi, size=n).astype(np.int64)


def gen_batch(named_gens: Sequence[Tuple[str, DataGen]], n: int,
              seed: int = DEFAULT_SEED) -> HostBatch:
    """Deterministic HostBatch from (name, gen) pairs (gen_df twin)."""
    rng = np.random.default_rng(seed)
    cols: List[HostColumn] = []
    fields = []
    for name, g in named_gens:
        cols.append(g.gen(n, rng))
        fields.append(T.StructField(name, g.dtype, g.nullable))
    return HostBatch(T.StructType(fields), cols, n)


def gen_pydict(named_gens, n: int, seed: int = DEFAULT_SEED) -> dict:
    return gen_batch(named_gens, n, seed).to_pydict()
