"""Device columnar layer: host<->device round trips, compaction, murmur3
bit-parity with the host reference, and device-vs-CPU expression equality.

Plays the role of the reference's FuzzerUtils-driven unit suites
(tests/ GpuCoalesceBatchesSuite etc.) at the kernel-library level.
"""

import numpy as np
import pytest

from support import assert_pydicts_equal, lists_equal

from spark_rapids_tpu.columnar import murmur3
from spark_rapids_tpu.columnar.device import (
    DeviceBatch, bucket_capacity, compact, concat_device)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.ops import exprs as X
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import types as T

import jax.numpy as jnp


def _mk_batch(data, schema):
    return HostBatch.from_pydict(data, schema)


MIXED_SCHEMA = T.StructType([
    T.StructField("i", T.IntegerT),
    T.StructField("l", T.LongT),
    T.StructField("d", T.DoubleT),
    T.StructField("s", T.StringT),
    T.StructField("b", T.BooleanT),
])

MIXED_DATA = {
    "i": [1, None, -3, 2147483647, 0, -2147483648],
    "l": [10, 20, None, 9223372036854775807, -1, 0],
    "d": [1.5, float("nan"), -0.0, None, float("inf"), -2.25],
    "s": ["hello", "", None, "a much longer string here", "Ω≈ç√", "x"],
    "b": [True, False, None, True, False, True],
}


def test_round_trip_mixed():
    hb = _mk_batch(MIXED_DATA, MIXED_SCHEMA)
    db = DeviceBatch.from_host(hb)
    assert db.capacity == bucket_capacity(6) == 64
    assert db.row_count() == 6
    back = db.to_host()
    assert_pydicts_equal(back.to_pydict(), hb.to_pydict())


def test_compact_and_concat():
    hb = _mk_batch(MIXED_DATA, MIXED_SCHEMA)
    db = DeviceBatch.from_host(hb)
    # knock out rows 1, 3 via the active mask
    active = np.asarray(db.active).copy()
    active[1] = False
    active[3] = False
    db2 = DeviceBatch(db.schema, db.columns, jnp.asarray(active), None)
    assert db2.row_count() == 4
    c = compact(db2)
    back = c.to_host()
    expect = hb.take(np.array([0, 2, 4, 5]))
    assert_pydicts_equal(back.to_pydict(), expect.to_pydict())

    cc = concat_device([c, c])
    assert cc.row_count() == 8
    expect2 = HostBatch.concat([expect, expect])
    assert_pydicts_equal(cc.to_host().to_pydict(), expect2.to_pydict())


@pytest.mark.parametrize("dtype,name", [
    (T.IntegerT, "i"), (T.LongT, "l"), (T.DoubleT, "d"),
    (T.StringT, "s"), (T.BooleanT, "b")])
def test_murmur3_device_matches_host(dtype, name):
    hb = _mk_batch(MIXED_DATA, MIXED_SCHEMA)
    db = DeviceBatch.from_host(hb)
    ci = hb.schema.field_index(name)
    attr = E.AttributeReference(name, dtype, True)
    expect = E.Murmur3Hash(
        [E.BoundReference(ci, dtype, True)]).eval(hb)
    got = hashing.murmur3_columns([db.columns[ci]], db.capacity)
    np.testing.assert_array_equal(np.asarray(got)[:6], expect.data)


def test_murmur3_multi_column_fold():
    hb = _mk_batch(MIXED_DATA, MIXED_SCHEMA)
    db = DeviceBatch.from_host(hb)
    bound = [E.BoundReference(i, f.data_type, True)
             for i, f in enumerate(MIXED_SCHEMA.fields)]
    expect = E.Murmur3Hash(bound).eval(hb)
    got = hashing.murmur3_columns(db.columns, db.capacity)
    np.testing.assert_array_equal(np.asarray(got)[:6], expect.data)


def test_murmur3_string_edge_lengths():
    # lengths 0..9 cover word + tail code paths
    vals = ["", "a", "ab", "abc", "abcd", "abcde", "abcdef", "abcdefg",
            "abcdefgh", "abcdefghi"]
    schema = T.StructType([T.StructField("s", T.StringT)])
    hb = _mk_batch({"s": vals}, schema)
    db = DeviceBatch.from_host(hb)
    expect = [murmur3.hash_bytes_one(v.encode(), 42) for v in vals]
    got = np.asarray(hashing.murmur3_columns([db.columns[0]], db.capacity))
    np.testing.assert_array_equal(got[:10], np.array(expect, np.int32))


APPROX_EXPRS = (E.Exp, E.Log, E.Log10, E.Sin, E.Cos, E.Tan, E.Asin,
                E.Acos, E.Atan, E.Sinh, E.Cosh, E.Tanh, E.Pow)


def _assert_expr_matches(expr, hb: HostBatch):
    """Evaluate bound expr on CPU and device; compare values + validity."""
    bound = E.bind_references(
        expr, [E.AttributeReference(f.name, f.data_type, True, i + 1000)
               for i, f in enumerate(hb.schema.fields)])
    # rebind: build attrs that map by position
    attrs = [E.AttributeReference(f.name, f.data_type, True)
             for f in hb.schema.fields]
    bound = E.bind_references(_sub_attrs(expr, attrs), attrs)
    cpu = bound.eval(hb)
    db = DeviceBatch.from_host(hb)
    out = X.run_project([bound], db)[0]
    got = DeviceBatch(
        T.StructType([T.StructField("r", bound.data_type)]), [out],
        db.active, None).to_host()
    exp_col = HostColumn(bound.data_type, cpu.data, cpu.validity)
    got_col = got.columns[0]
    approx = isinstance(expr, APPROX_EXPRS)
    assert lists_equal(got_col.to_pylist(), exp_col.to_pylist(), approx), (
        f"{expr!r}: {got_col.to_pylist()} != {exp_col.to_pylist()}")


def _sub_attrs(expr, attrs):
    def rule(e):
        if isinstance(e, E.UnresolvedAttribute):
            for a in attrs:
                if a.name == e.name:
                    return a
        return None
    return expr.transform(rule)


def col(name):
    return E.UnresolvedAttribute(name)


NUM_SCHEMA = T.StructType([
    T.StructField("a", T.IntegerT), T.StructField("b", T.IntegerT),
    T.StructField("x", T.DoubleT), T.StructField("y", T.DoubleT),
    T.StructField("s", T.StringT), T.StructField("t", T.StringT),
])

NUM_DATA = {
    "a": [1, -5, None, 2147483647, 0, 17, -2147483648, 3],
    "b": [3, 0, 7, 1, None, -4, -1, 3],
    "x": [1.5, -0.0, float("nan"), None, float("inf"), 2.5, -3.75, 0.0],
    "y": [2.0, 0.0, 1.0, 4.0, float("nan"), None, -1.0, 0.0],
    "s": ["apple", "Banana split", "", None, "  pad  ", "Zq va", "z", "ab"],
    "t": ["app", "nana", "x", "y", None, "a", "z", "ab"],
}


@pytest.mark.parametrize("expr", [
    E.Add(col("a"), col("b")),
    E.Subtract(col("a"), col("b")),
    E.Multiply(col("a"), col("b")),
    E.Divide(col("x"), col("y")),
    E.IntegralDivide(col("a"), col("b")),
    E.Remainder(col("a"), col("b")),
    E.Pmod(col("a"), col("b")),
    E.UnaryMinus(col("a")),
    E.Abs(col("a")),
    E.EqualTo(col("a"), col("b")),
    E.LessThan(col("x"), col("y")),
    E.GreaterThanOrEqual(col("x"), col("y")),
    E.EqualNullSafe(col("a"), col("b")),
    E.EqualTo(col("s"), col("t")),
    E.LessThan(col("s"), col("t")),
    E.GreaterThan(col("s"), col("t")),
    E.And(E.GreaterThan(col("a"), E.Literal(0)),
          E.LessThan(col("b"), E.Literal(5))),
    E.Or(E.IsNull(col("a")), E.GreaterThan(col("b"), E.Literal(0))),
    E.Not(E.EqualTo(col("a"), col("b"))),
    E.In(col("a"), [E.Literal(1), E.Literal(17), E.Literal(None, T.IntegerT)]),
    E.IsNull(col("x")), E.IsNotNull(col("x")), E.IsNan(col("x")),
    E.Coalesce([col("a"), col("b"), E.Literal(99)]),
    E.If(E.GreaterThan(col("a"), E.Literal(0)), col("a"), col("b")),
    E.CaseWhen([(E.GreaterThan(col("a"), E.Literal(10)), E.Literal(1)),
                (E.GreaterThan(col("b"), E.Literal(0)), E.Literal(2))],
               E.Literal(3)),
    E.Sqrt(col("x")), E.Exp(col("y")), E.Log(col("x")), E.Log10(col("x")),
    E.Sin(col("x")), E.Cos(col("y")), E.Tanh(col("y")),
    E.Floor(col("y")), E.Ceil(col("y")), E.Pow(col("x"), col("y")),
    E.Round(col("x"), E.Literal(1)),
    E.Signum(col("x")),
    E.Length(col("s")),
    E.Upper(col("s")), E.Lower(col("s")),
    E.StringTrim(col("s")),
    E.ConcatStr([col("s"), E.Literal("-"), col("t")]),
    E.Substring(col("s"), E.Literal(2), E.Literal(3)),
    E.Substring(col("s"), E.Literal(-3), E.Literal(2)),
    E.StartsWith(col("s"), col("t")),
    E.EndsWith(col("s"), col("t")),
    E.Contains(col("s"), col("t")),
    E.Murmur3Hash([col("a"), col("s")]),
    E.Cast(col("a"), T.LongT), E.Cast(col("x"), T.IntegerT),
    E.Cast(col("a"), T.DoubleT), E.Cast(col("a"), T.BooleanT),
])
def test_expr_device_matches_cpu(expr):
    hb = _mk_batch(NUM_DATA, NUM_SCHEMA)
    _assert_expr_matches(expr, hb)


def test_datetime_exprs():
    import datetime as dt
    schema = T.StructType([T.StructField("d", T.DateT),
                           T.StructField("ts", T.TimestampT)])
    hb = _mk_batch({
        "d": [dt.date(2020, 2, 29), dt.date(1969, 12, 31), None,
              dt.date(1582, 10, 15), dt.date(2038, 1, 19)],
        "ts": [dt.datetime(2021, 6, 1, 13, 45, 59), dt.datetime(1970, 1, 1),
               None, dt.datetime(1900, 1, 1, 0, 0, 1),
               dt.datetime(2100, 12, 31, 23, 59, 59)],
    }, schema)
    for expr in [E.Year(col("d")), E.Month(col("d")), E.DayOfMonth(col("d")),
                 E.Year(col("ts")), E.Hour(col("ts")), E.Minute(col("ts")),
                 E.Second(col("ts")),
                 E.DateAdd(col("d"), E.Literal(40)),
                 E.DateSub(col("d"), E.Literal(40)),
                 E.DateDiff(col("d"), col("d")),
                 E.Cast(col("d"), T.TimestampT),
                 E.Cast(col("ts"), T.DateT)]:
        _assert_expr_matches(expr, hb)


def test_utf8_exact_string_ops():
    """Non-ASCII strings through the ops that are exact for any UTF-8
    (byte-level semantics match codepoint semantics)."""
    schema = T.StructType([T.StructField("s", T.StringT),
                           T.StructField("t", T.StringT)])
    hb = _mk_batch({
        "s": ["Ωmega", "çava", "日本語テキスト", None, "naïve", "  ü  "],
        "t": ["Ω", "va", "語", "x", None, "ü"],
    }, schema)
    for expr in [E.Length(col("s")), E.EqualTo(col("s"), col("t")),
                 E.LessThan(col("s"), col("t")),
                 E.ConcatStr([col("s"), col("t")]),
                 E.StringTrim(col("s")),
                 E.StartsWith(col("s"), col("t")),
                 E.EndsWith(col("s"), col("t")),
                 E.Contains(col("s"), col("t")),
                 E.Murmur3Hash([col("s")])]:
        _assert_expr_matches(expr, hb)


def test_filter_masks_without_moving_data():
    hb = _mk_batch(NUM_DATA, NUM_SCHEMA)
    db = DeviceBatch.from_host(hb)
    attrs = [E.AttributeReference(f.name, f.data_type, True)
             for f in NUM_SCHEMA.fields]
    cond = E.bind_references(
        E.GreaterThan(col("a"), E.Literal(0)).transform(
            lambda e: next((a for a in attrs if isinstance(
                e, E.UnresolvedAttribute) and a.name == e.name), None)),
        attrs)
    out = X.run_filter(cond, db)
    assert out.capacity == db.capacity  # no reshape
    kept = out.to_host()
    assert kept.to_pydict()["a"] == [1, 2147483647, 17, 3]


def test_varbytes_packed_upload_round_trip():
    """Scan-path string columns carry compact Arrow bytes (varbytes);
    the packed upload must ship those and rebuild the char matrix on
    device bit-identically to the object-array path — including nulls,
    empties, multi-byte UTF-8, and table slices (io/arrow_convert.py
    _string_varbytes + transfer.py 'vstr' decode)."""
    import pyarrow as pa

    from spark_rapids_tpu.columnar.transfer import (PACKED_MIN_ROWS,
                                                    upload_batch)
    from spark_rapids_tpu.io.arrow_convert import (arrow_schema_to_sql,
                                                   arrow_to_host_batch)

    n = PACKED_MIN_ROWS + 257
    vals = []
    for i in range(n):
        r = i % 7
        vals.append(None if r == 0 else "" if r == 1 else
                    f"héllo∆{i % 13}" if r == 2 else "A" if r == 3 else
                    "x" * (i % 17))
    tbl = pa.table({"s": pa.array(vals, type=pa.string()),
                    "v": np.arange(n, dtype=np.int64)})
    for t in (tbl, tbl.slice(1000, PACKED_MIN_ROWS + 5)):
        hb = arrow_to_host_batch(t, arrow_schema_to_sql(t.schema))
        assert hb.columns[0].varbytes is not None
        db = upload_batch(hb, bucket_capacity(t.num_rows))
        got = db.to_host().columns[0].to_pylist()
        exp = hb.columns[0].to_pylist()
        assert got == exp
    # concat keeps varbytes (the R2C goal-coalesce path)
    hb = arrow_to_host_batch(tbl, arrow_schema_to_sql(tbl.schema))
    cc = HostBatch.concat([hb, hb])
    assert cc.columns[0].varbytes is not None
    db = upload_batch(cc, bucket_capacity(2 * n))
    assert db.to_host().columns[0].to_pylist() == 2 * vals
