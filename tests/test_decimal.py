"""Decimal end-to-end: device placement + bit-identical parity for
DECIMAL64 and DECIMAL128 across project/filter/agg/sort/join/exchange
(the decimal rows of the reference's TypeChecks matrix,
TypeChecks.scala:1259 / decimalExpressions.scala, re-based on the
int128 limb kernels)."""

from __future__ import annotations

from decimal import Decimal

import numpy as np
import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T

from tests.harness import assert_tpu_and_cpu_equal_collect


def _dec_rows(n=240, seed=11):
    rng = np.random.default_rng(seed)
    p, d, k, q = [], [], [], []
    for i in range(n):
        if i % 17 == 0:
            p.append(None)
        else:
            p.append(Decimal(int(rng.integers(-(10 ** 13), 10 ** 13)))
                     .scaleb(-2))
        d.append(None if i % 23 == 5 else
                 Decimal(int(rng.integers(0, 11))).scaleb(-2))
        k.append(["A", "B", "C"][i % 3])
        q.append(int(rng.integers(1, 51)))
    return {"p": p, "d": d, "k": k, "q": q}


SCHEMA = "p decimal(15,2), d decimal(15,2), k string, q int"


def _df(s, n=240, seed=11, parts=2):
    return s.createDataFrame(_dec_rows(n, seed), SCHEMA,
                             num_partitions=parts)


def test_decimal_add_sub_mul_project():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).select(
            (F.col("p") + F.col("d")).alias("a"),
            (F.col("p") - F.col("d")).alias("s"),
            (F.col("p") * F.col("d")).alias("m"),
            (F.col("p") * (F.lit(1) - F.col("d"))).alias("disc"),
            (-F.col("p")).alias("n"),
            F.abs(F.col("p")).alias("ab")),
        expect_execs=["TpuProject"])


def test_decimal128_multiply_chain():
    """(15,2)*(16,2) -> (32,4) DECIMAL128; a second multiply lands on
    the adjusted (38,6) with overflow -> NULL semantics."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).select(
            (F.col("p") * (F.lit(1) - F.col("d"))
             * (F.lit(1) + F.col("d"))).alias("charge")),
        expect_execs=["TpuProject"])


def test_decimal_divide():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).select(
            (F.col("p") / F.col("q")).alias("dq"),
            (F.col("p") / F.col("d")).alias("dd")),
        expect_execs=["TpuProject"])


def test_decimal_filter_compare():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).where(
            (F.col("p") > F.lit(0)) & (F.col("d") <= Decimal("0.05"))),
        expect_execs=["TpuFilter"])


def test_decimal_agg_all_functions():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).groupBy("k").agg(
            F.sum("p").alias("sp"),
            F.avg("p").alias("ap"),
            F.min("p").alias("mn"),
            F.max("p").alias("mx"),
            F.count("p").alias("c"),
            F.first("p").alias("f"),
            F.last("p").alias("l")).orderBy("k"),
        expect_execs=["TpuHashAggregate", "TpuExchange"])


def test_decimal128_sum_of_products():
    """q1's shape: sum over a DECIMAL128 product, grouped."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, n=400).groupBy("k").agg(
            F.sum(F.col("p") * (F.lit(1) - F.col("d"))).alias("s1"),
            F.sum(F.col("p") * (F.lit(1) - F.col("d"))
                  * (F.lit(1) + F.col("d"))).alias("s2")).orderBy("k"),
        expect_execs=["TpuHashAggregate"])


def test_decimal_group_by_decimal_key():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).groupBy("d").agg(
            F.count("*").alias("c"), F.sum("q").alias("sq")).orderBy("d"),
        expect_execs=["TpuHashAggregate", "TpuSort"])


def test_decimal_sort_keys():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).orderBy(F.col("p").desc(), F.col("d")),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_decimal128_sort_keys():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).select(
            (F.col("p") * (F.lit(1) - F.col("d"))).alias("m"))
        .orderBy("m"),
        ignore_order=False,
        expect_execs=["TpuSort"])


def test_decimal_join_keys():
    def q(s):
        a = _df(s, n=120, seed=3)
        b = _df(s, n=120, seed=4)
        return a.join(b.select(F.col("d").alias("d2"),
                               F.col("q").alias("q2")),
                      a["d"] == F.col("d2"), "inner")
    # small build side -> the broadcast variant
    assert_tpu_and_cpu_equal_collect(q,
                                     expect_execs=["TpuBroadcastHashJoin"])


def test_decimal_join_keys_no_broadcast():
    def q(s):
        a = _df(s, n=120, seed=3)
        b = _df(s, n=120, seed=4)
        return a.join(b.select(F.col("d").alias("d2"),
                               F.col("q").alias("q2")),
                      a["d"] == F.col("d2"), "inner")
    assert_tpu_and_cpu_equal_collect(
        q, conf={"spark.rapids.sql.autoBroadcastJoinThreshold": "-1"},
        expect_execs=["TpuShuffledHashJoin"])


def test_decimal_cast_legs():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).select(
            F.col("p").cast(T.DecimalType(20, 4)).alias("wide"),
            F.col("p").cast(T.DecimalType(10, 1)).alias("narrow"),
            F.col("p").cast("double").alias("dbl"),
            F.col("p").cast("long").alias("lng"),
            F.col("q").cast(T.DecimalType(12, 3)).alias("fromint")),
        expect_execs=["TpuProject"])


def test_decimal_overflow_nulls():
    """Values that exceed the result precision become NULL (non-ANSI
    CheckOverflow) on both engines."""
    big = Decimal("9" * 8 + "." + "99")  # 99999999.99 at (10,2)
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"a": [big, -big, Decimal("1.00"), None]}, "a decimal(10,2)")
        .select((F.col("a") * F.col("a") * F.col("a")
                 * F.col("a")).alias("m4")),
        expect_execs=["TpuProject"])


def test_decimal_distinct_dedup():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s).select("d").distinct().orderBy("d"),
        expect_execs=["TpuHashAggregate"])


def test_tpcds_q3_shape_force_device():
    """Star join + decimal sum + TopN (TPC-DS q3 shape, BASELINE
    config 2) placed fully on device."""
    def q(s):
        import numpy as np
        rng = np.random.default_rng(3)
        n = 4000
        s.createDataFrame(
            {"ss_sold_date_sk": rng.integers(1, 400, n).tolist(),
             "ss_item_sk": rng.integers(1, 200, n).tolist(),
             "ss_ext_sales_price":
                 [Decimal(int(v)).scaleb(-2)
                  for v in rng.integers(100, 100000, n)]},
            "ss_sold_date_sk long, ss_item_sk long, "
            "ss_ext_sales_price decimal(7,2)",
            num_partitions=2).createOrReplaceTempView("store_sales")
        s.createDataFrame(
            {"d_date_sk": list(range(1, 400)),
             "d_year": [1998 + i % 5 for i in range(399)],
             "d_moy": [1 + i % 12 for i in range(399)]},
            "d_date_sk long, d_year int, d_moy int") \
            .createOrReplaceTempView("date_dim")
        s.createDataFrame(
            {"i_item_sk": list(range(1, 200)),
             "i_brand_id": [i % 37 for i in range(199)],
             "i_brand": [f"b{i % 37}" for i in range(199)],
             "i_manufact_id": [i % 10 for i in range(199)]},
            "i_item_sk long, i_brand_id int, i_brand string, "
            "i_manufact_id int").createOrReplaceTempView("item")
        return s.sql(
            "SELECT d_year, i_brand_id brand_id, i_brand brand, "
            "sum(ss_ext_sales_price) sum_agg "
            "FROM store_sales "
            "JOIN date_dim ON d_date_sk = ss_sold_date_sk "
            "JOIN item ON ss_item_sk = i_item_sk "
            "WHERE i_manufact_id = 3 AND d_moy = 11 "
            "GROUP BY d_year, i_brand_id, i_brand "
            "ORDER BY d_year, sum_agg DESC, brand_id LIMIT 100")
    assert_tpu_and_cpu_equal_collect(
        q, ignore_order=False,
        expect_execs=["TpuHashAggregate", "TpuTopN"])
