"""Dual-session equality harness (spark_session.py:82-88 + asserts.py:434
twins): run the same DataFrame lambda under a CPU session and a TPU
session and assert identical results, plus the fallback-assertion helpers
built on the rewrite report (ExecutionPlanCaptureCallback analogue).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.support import values_equal


def _run(df_fn: Callable, conf: Dict[str, str]):
    spark = TpuSparkSession(conf)
    try:
        df = df_fn(spark)
        batch = df._execute()
        return batch.to_pydict(), spark
    finally:
        spark.stop()


def _sort_key(row):
    out = []
    for v in row:
        if v is None:
            out.append((0, ""))
        elif isinstance(v, float):
            out.append((1, "nan") if math.isnan(v) else (2, v))
        elif isinstance(v, bool):
            out.append((3, v))
        elif isinstance(v, (int,)):
            out.append((2, float(v)) if abs(v) < (1 << 52) else (4, str(v)))
        elif isinstance(v, bytes):
            out.append((5, v.decode("latin1")))
        else:
            out.append((6, str(v)))
    return out


def _rows(pydict: dict):
    names = list(pydict)
    n = len(pydict[names[0]]) if names else 0
    return [tuple(pydict[c][i] for c in names) for i in range(n)]


def assert_tpu_and_cpu_equal_collect(
        df_fn: Callable, conf: Optional[Dict[str, str]] = None,
        ignore_order: bool = True, approx: bool = False,
        require_device: bool = True,
        expect_execs: Optional[list] = None) -> None:
    """assert_gpu_and_cpu_are_equal_collect twin. ``require_device``
    additionally asserts the TPU run actually placed ops on the device
    (so tests can't silently pass on all-CPU fallback); ``expect_execs``
    names Tpu* operators that must appear in the final physical plan
    (the ExecutionPlanCaptureCallback placement assertion)."""
    conf = dict(conf or {})
    cpu_conf = dict(conf)
    cpu_conf["spark.rapids.sql.enabled"] = "false"
    tpu_conf = dict(conf)
    tpu_conf["spark.rapids.sql.enabled"] = "true"

    cpu, _ = _run(df_fn, cpu_conf)

    spark = TpuSparkSession(tpu_conf)
    try:
        spark.start_capture()
        df = df_fn(spark)
        batch = df._execute()
        tpu = batch.to_pydict()
        report = spark.last_rewrite_report
        plans = spark.get_captured_plans()
    finally:
        spark.stop()

    if require_device:
        assert report is not None and report.replaced_any, (
            "no operator was placed on the device; fallbacks:\n"
            + (report.format() if report else "<no report>"))
    if expect_execs:
        plan_str = "\n".join(p.tree_string() for p in plans)
        for name in expect_execs:
            assert name in plan_str, (
                f"expected {name} in the physical plan:\n{plan_str}")

    assert set(cpu) == set(tpu), (set(cpu), set(tpu))
    crows, trows = _rows(cpu), _rows(tpu)
    assert len(crows) == len(trows), (len(crows), len(trows))
    if ignore_order:
        crows = sorted(crows, key=_sort_key)
        trows = sorted(trows, key=_sort_key)
    for i, (cr, tr) in enumerate(zip(crows, trows)):
        for j, (a, b) in enumerate(zip(cr, tr)):
            assert values_equal(a, b, approx), (
                f"row {i} col {list(cpu)[j]}: CPU={a!r} TPU={b!r}\n"
                f"CPU row: {cr}\nTPU row: {tr}")


def assert_tpu_fallback_collect(df_fn: Callable, fallback_exec: str,
                                conf: Optional[Dict[str, str]] = None
                                ) -> None:
    """assert_gpu_fallback_collect twin: results must match AND the named
    exec class must have stayed on CPU with a recorded reason."""
    conf = dict(conf or {})
    tpu_conf = dict(conf)
    tpu_conf["spark.rapids.sql.enabled"] = "true"
    spark = TpuSparkSession(tpu_conf)
    try:
        df = df_fn(spark)
        df._execute()
        report = spark.last_rewrite_report
    finally:
        spark.stop()
    assert report is not None
    names = [n for n, _ in report.fallbacks]
    assert fallback_exec in names, (
        f"expected fallback of {fallback_exec}, got {report.fallbacks}")
    # and the two engines still agree
    assert_tpu_and_cpu_equal_collect(df_fn, conf, require_device=False)
