"""Vectorized pandas UDF path: ArrowEvalPython extraction + the python
worker pool (python/worker.py, python/pool.py, exec/python_exec.py —
GpuArrowEvalPythonExec.scala:487 / GpuMapInPandasExec roles)."""

import pytest

from harness import assert_tpu_and_cpu_equal_collect

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSparkSession


def test_scalar_pandas_udf_dual_session():
    @F.pandas_udf("long")
    def plus_one(s):
        return s + 1

    @F.pandas_udf("string")
    def shout(s):
        return s.str.upper() + "!"

    def q(spark):
        df = spark.createDataFrame(
            {"a": [1, 2, None, 4, 5] * 20,
             "s": ["x", None, "zz", "w", "héllo"] * 20},
            "a long, s string")
        return df.select(F.col("a"), plus_one("a").alias("a1"),
                         shout("s").alias("u"),
                         plus_one(F.col("a") * 2).alias("a2"))
    assert_tpu_and_cpu_equal_collect(q)


def test_pandas_udf_two_args_and_dedup():
    @F.pandas_udf("double")
    def ratio(a, b):
        return a / b

    def q(spark):
        df = spark.createDataFrame(
            {"a": [1.0, 2.0, None, 4.0], "b": [2.0, 0.5, 1.0, None]},
            "a double, b double")
        # the same UDF call twice must evaluate once (extractor dedup)
        return df.select(ratio("a", "b").alias("r1"),
                         (ratio("a", "b") * 2).alias("r2"))
    assert_tpu_and_cpu_equal_collect(q, approx=True)


def test_pandas_udf_placement_device():
    """The surrounding plan stays ON DEVICE around the python exchange
    (the whole point of GpuArrowEvalPythonExec)."""
    @F.pandas_udf("long")
    def twice(s):
        return s * 2

    sp = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                          "spark.rapids.sql.test.forceDevice": "true"})
    try:
        sp.start_capture()
        df = sp.createDataFrame({"a": list(range(100))}, "a long")
        out = df.select(twice("a").alias("t")) \
            .filter(F.col("t") > 100).collect()
        plans = sp.get_captured_plans()
    finally:
        sp.stop()
    assert sorted(r[0] for r in out) == list(range(102, 200, 2))
    s = "\n".join(p.tree_string() for p in plans)
    assert "TpuArrowEvalPython" in s, s
    assert "TpuFilter" in s, s


def test_pandas_udf_error_propagates():
    @F.pandas_udf("long")
    def boom(s):
        raise ValueError("intentional udf failure")

    from spark_rapids_tpu.python.pool import PythonWorkerError
    sp = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        df = sp.createDataFrame({"a": [1, 2]}, "a long")
        with pytest.raises(PythonWorkerError,
                           match="intentional udf failure"):
            df.select(boom("a").alias("b")).collect()
        # the worker survives a UDF error and serves the next call
        ok = df.select(F.col("a")).collect()
        assert [r[0] for r in ok] == [1, 2]
    finally:
        sp.stop()


def test_map_in_pandas_dual_session():
    def add_cols(it):
        for pdf in it:
            pdf = pdf.copy()
            pdf["b"] = pdf["a"] * 3
            yield pdf[["a", "b"]]

    def q(spark):
        df = spark.createDataFrame(
            {"a": list(range(50)), "junk": ["x"] * 50},
            "a long, junk string")
        return df.mapInPandas(add_cols, "a long, b long")
    assert_tpu_and_cpu_equal_collect(q)


def test_map_in_pandas_changes_row_count():
    def explode_evens(it):
        for pdf in it:
            keep = pdf[pdf["a"] % 2 == 0]
            import pandas as pd
            yield pd.concat([keep, keep])

    def q(spark):
        df = spark.createDataFrame({"a": list(range(20))}, "a long")
        return df.mapInPandas(explode_evens, "a long")
    assert_tpu_and_cpu_equal_collect(q, ignore_order=True)


def test_worker_pool_reuse():
    """One worker serves many batches (no per-batch process spawn)."""
    from spark_rapids_tpu.python import pool as pool_mod
    from spark_rapids_tpu.conf import TpuConf
    p = pool_mod.get_worker_pool(TpuConf({}))
    import cloudpickle
    import pyarrow as pa
    from spark_rapids_tpu.exec.python_exec import _ipc_bytes, _ipc_read

    schema_ipc = _ipc_bytes(pa.schema([("x", pa.int64())]).empty_table())
    payload = ([cloudpickle.dumps(lambda s: s + 1)], [[0]], schema_ipc)
    for i in range(4):
        tbl = pa.table({"v": pa.array([i, i + 1], pa.int64())})
        out = _ipc_read(p.run("scalar", payload, _ipc_bytes(tbl)))
        assert out.column(0).to_pylist() == [i + 1, i + 2]
    assert p._created <= p.size
