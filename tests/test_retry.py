"""Task-level OOM retry / split-and-retry parity corpus
(RmmRapidsRetryIterator + DeviceMemoryEventHandler coverage, driven by
the deterministic FaultInjector — SURVEY.md:377-385 names the missing
fault-injection framework this closes).

q1/q3-shaped pipelines run under swept injected-OOM schedules and must
be bit-identical to the clean run with ``retryCount``/``splitRetryCount``
metrics > 0; persistent chip-failure injection must degrade the mesh
(identical results, ``degradedChips`` > 0) instead of failing the query;
reader IO injection must retry with bounded backoff and re-raise the
original error on exhaustion.
"""

import glob
import os

import numpy as np
import pytest

from spark_rapids_tpu import memory as MEM
from spark_rapids_tpu import metrics as M
from spark_rapids_tpu import resource
from spark_rapids_tpu import retry as R
from spark_rapids_tpu.columnar.device import DeviceBatch
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.metrics import MetricRegistry, sum_plan_metrics
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           StringGen, gen_batch)
from tests.harness import _rows, _sort_key, values_equal

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _fresh_injection():
    """Deterministic schedules: every test starts a fresh injector."""
    R.reset_fault_injection()
    yield
    R.reset_fault_injection()


def _conf(injection=None, **extra):
    conf = {
        "spark.rapids.sql.enabled": "true",
        # small batches -> many wrapped allocation points per query
        "spark.rapids.sql.batchSizeRows": "256",
        # fast, bounded backoff so injected sweeps stay quick
        "spark.rapids.sql.retry.backoffMs": "1",
        "spark.rapids.sql.retry.maxBackoffMs": "4",
    }
    if injection:
        conf["spark.rapids.sql.test.injectOOM"] = injection
    conf.update(extra)
    return conf


def _run_clean_vs_injected(df_fn, conf, ignore_order=True):
    """CPU clean run vs TPU injected run: assert bit-identical rows;
    return the captured TPU plans (for metric assertions)."""
    cpu_conf = dict(conf)
    cpu_conf["spark.rapids.sql.enabled"] = "false"
    # the clean oracle must not see injection (deterministic schedules
    # are a property of the process-wide injector)
    for k in list(cpu_conf):
        if k.startswith("spark.rapids.sql.test.inject"):
            del cpu_conf[k]
    spark = TpuSparkSession(cpu_conf)
    try:
        cpu = df_fn(spark)._execute().to_pydict()
    finally:
        spark.stop()

    R.reset_fault_injection()
    spark = TpuSparkSession(conf)
    try:
        spark.start_capture()
        tpu = df_fn(spark)._execute().to_pydict()
        report = spark.last_rewrite_report
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    assert report is not None and report.replaced_any, (
        "nothing placed on device:\n" + (report.format() if report else ""))

    assert set(cpu) == set(tpu)
    crows, trows = _rows(cpu), _rows(tpu)
    assert len(crows) == len(trows), (len(crows), len(trows))
    if ignore_order:
        crows = sorted(crows, key=_sort_key)
        trows = sorted(trows, key=_sort_key)
    for cr, tr in zip(crows, trows):
        for a, b in zip(cr, tr):
            assert values_equal(a, b, False), (cr, tr)
    return plans


def _metric(plans, name) -> int:
    return sum(sum_plan_metrics(plans, name).values())


# ---------------------------------------------------------------------------
# Combinator units
# ---------------------------------------------------------------------------

def _device_batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    schema = T.StructType([T.StructField("v", T.LongT),
                           T.StructField("s", T.StringT)])
    return DeviceBatch.from_host(HostBatch(schema, [
        HostColumn(T.LongT, rng.integers(0, 1 << 40, n),
                   np.ones(n, dtype=bool)),
        HostColumn(T.StringT,
                   np.array([f"s{i % 7}" for i in range(n)], dtype=object),
                   np.ones(n, dtype=bool)),
    ], n))


def test_with_retry_recovers_from_injected_oom():
    from spark_rapids_tpu.conf import TpuConf
    conf = TpuConf({"spark.rapids.sql.test.injectOOM": "2:2",
                    "spark.rapids.sql.retry.backoffMs": "1",
                    "spark.rapids.sql.retry.maxBackoffMs": "2"})
    metrics = MetricRegistry()
    # allocation 1 passes; allocation 2 starts a 2-failure streak
    assert R.with_retry(lambda: "a", conf, metrics) == "a"
    assert metrics.value(M.RETRY_COUNT) == 0
    calls = []
    out = R.with_retry(lambda: calls.append(1) or 42, conf, metrics)
    assert out == 42
    # the streak failed two attempts pre-dispatch, the third succeeded
    assert metrics.value(M.RETRY_COUNT) == 2
    assert len(calls) == 1  # fn itself only ran once (faults pre-empt it)
    inj = R.get_fault_injector(conf)
    assert inj is not None and inj.oom_injected == 2


def test_with_retry_exhausts_and_reraises():
    from spark_rapids_tpu.conf import TpuConf
    conf = TpuConf({"spark.rapids.sql.test.injectOOM": "1:100",
                    "spark.rapids.sql.retry.maxRetries": "2",
                    "spark.rapids.sql.retry.backoffMs": "1",
                    "spark.rapids.sql.retry.maxBackoffMs": "1"})
    metrics = MetricRegistry()
    with pytest.raises(R.TpuRetryOOM):
        R.with_retry(lambda: 1, conf, metrics)
    assert metrics.value(M.RETRY_COUNT) == 2


def test_with_split_retry_splits_and_preserves_order():
    """A fn that refuses pieces above 16 rows forces recursive halving;
    the concatenated results must be the original rows in order."""
    b = _device_batch(64, seed=3)
    metrics = MetricRegistry()

    def fn(piece):
        if piece.row_count() > 16:
            raise R.TpuSplitAndRetryOOM("too big")
        return piece

    outs = R.with_split_retry(b, fn, None, metrics)
    assert len(outs) == 4
    assert metrics.value(M.SPLIT_RETRY_COUNT) == 3  # 64 -> 2x32 -> 4x16
    from spark_rapids_tpu.columnar.device import concat_device
    got = concat_device(outs).to_host().to_pydict()
    want = b.to_host().to_pydict()
    assert got == want


def test_split_device_batch_respects_active_mask():
    """Split balances ACTIVE rows and keeps their original order even
    when the active mask is scattered."""
    import jax.numpy as jnp
    b = _device_batch(32, seed=4)
    scatter = jnp.asarray(np.arange(b.capacity) % 3 == 0)
    b = DeviceBatch(b.schema, b.columns, b.active & scatter, None)
    halves = R.split_device_batch(b)
    assert halves is not None and len(halves) == 2
    want = b.to_host().to_pydict()
    from spark_rapids_tpu.columnar.device import concat_device
    got = concat_device(halves).to_host().to_pydict()
    assert got == want


def test_split_single_row_reports_unsplittable():
    b = _device_batch(1, seed=5)
    assert R.split_device_batch(b) is None
    hb = HostBatch.from_pydict({"v": [1]}, T.StructType(
        [T.StructField("v", T.LongT)]))
    assert R.split_host_batch(hb) is None


def test_injector_determinism():
    """Two injectors with the same spec fire at exactly the same
    events — for the counter grammar and the seeded-random one."""
    for spec in ("5:2", "seed:42:0.3"):
        patterns = []
        for _ in range(2):
            inj = R.FaultInjector(oom_spec=spec)
            fired = []
            for _i in range(100):
                try:
                    inj.on_alloc()
                    fired.append(False)
                except R.TpuRetryOOM:
                    fired.append(True)
            patterns.append(fired)
        assert patterns[0] == patterns[1], spec
        assert any(patterns[0]), spec


def test_seeded_io_schedule_independent_of_oom():
    """A seeded IO schedule must work with injectOOM unset, and when
    both are set each schedule follows its OWN deterministic stream
    (regression: the RNG was built from the OOM schedule only)."""
    inj = R.FaultInjector(io_spec="seed:7:0.4")
    fired = []
    for _ in range(50):
        try:
            inj.on_io("p")
            fired.append(False)
        except IOError:
            fired.append(True)
    assert any(fired)
    # same IO pattern when an OOM schedule (different seed) is present
    both = R.FaultInjector(oom_spec="seed:99:0.4", io_spec="seed:7:0.4")
    fired2 = []
    for _ in range(50):
        try:
            both.on_io("p")
            fired2.append(False)
        except IOError:
            fired2.append(True)
    assert fired2 == fired


def test_injection_suppressed_in_recovery():
    inj = R.FaultInjector(oom_spec="1")
    with R.suppress_injection():
        inj.on_alloc()  # no raise
    with pytest.raises(R.TpuRetryOOM):
        inj.on_alloc()


# ---------------------------------------------------------------------------
# Store hooks (spill-on-retry + disk-tier hygiene satellites)
# ---------------------------------------------------------------------------

def test_store_spill_device_down_frees_hbm():
    store = MEM.DeviceStore(1 << 30, 1 << 30, "/tmp/srt_spill_t")
    b1, b2 = _device_batch(128, 6), _device_batch(128, 7)
    h1, h2 = store.register(b1), store.register(b2)
    assert store.device_bytes > 0
    freed = store.spill_device_down()
    assert freed > 0 and store.device_bytes == 0
    got = np.asarray(h1.get().columns[0].data)[:128]
    assert (got == np.asarray(b1.columns[0].data)[:128]).all()
    h1.close()
    h2.close()


def test_disk_files_tracked_and_swept_on_close(tmp_path):
    store = MEM.DeviceStore(device_budget=1, host_budget=1,
                            spill_dir=str(tmp_path))
    handles = [store.register(_device_batch(64, s)) for s in range(3)]
    assert store.stats()["diskFilesLive"] >= 1
    assert glob.glob(str(tmp_path / "spill-*.bin"))
    # promote one: its file must be removed and the counter decremented
    live_before = store.disk_files_live
    handles[0].get()
    assert store.disk_files_live < live_before + 1  # no double count
    store.close()
    assert store.stats()["diskFilesLive"] == 0
    assert not glob.glob(str(tmp_path / "spill-*.bin"))
    # a closed store's handles are released too
    assert store.device_bytes == 0 and store.host_bytes == 0


# ---------------------------------------------------------------------------
# q1/q3-shaped parity sweeps under injected OOM
# ---------------------------------------------------------------------------

def _q1_shape(s):
    """filter -> 2-key groupBy with sum/min/max/count over decimal-free
    columns (the q1 silhouette at test scale)."""
    df = s.createDataFrame(
        gen_batch([("flag", KeyStringGen(cardinality=3)),
                   ("status", SmallIntGen()),
                   ("qty", LongGen()), ("price", IntegerGen())],
                  3000, 11),
        num_partitions=4)
    return (df.filter(F.col("qty") % 5 != 0)
            .groupBy("flag", "status")
            .agg(F.sum("qty").alias("sq"), F.min("price").alias("mn"),
                 F.max("price").alias("mx"), F.count("*").alias("c")))


def _q3_shape(s):
    """fact-dim join -> groupBy -> orderBy/limit (the q3 silhouette)."""
    fact = s.createDataFrame(
        gen_batch([("k", SmallIntGen()), ("item", IntegerGen()),
                   ("amt", LongGen())], 2500, 12),
        num_partitions=3)
    dim = s.createDataFrame(
        gen_batch([("item2", IntegerGen()),
                   ("brand", KeyStringGen(cardinality=5))], 400, 13),
        num_partitions=2)
    return (fact.join(dim, fact["item"] == dim["item2"], "inner")
            .groupBy("brand").agg(F.sum("amt").alias("sa"),
                                  F.count("*").alias("c"))
            .orderBy("brand").limit(50))


OOM_SCHEDULES = ["3", "4:2", "seed:42:0.2"]


@pytest.mark.parametrize("sched", OOM_SCHEDULES)
def test_q1_shape_bit_identical_under_oom_sweep(sched):
    plans = _run_clean_vs_injected(_q1_shape, _conf(sched))
    assert _metric(plans, M.RETRY_COUNT) > 0, sched


def test_q1_shape_split_and_retry():
    """The split:N schedule forces TpuSplitAndRetryOOM: split-capable
    sites (upload, fused stage, partial agg) must split — and both
    counters must show activity."""
    plans = _run_clean_vs_injected(_q1_shape, _conf("split:3"))
    assert _metric(plans, M.SPLIT_RETRY_COUNT) > 0
    assert _metric(plans, M.RETRY_COUNT) > 0  # retry-only sites degrade


def test_exhaustion_escalates_into_split():
    """Consecutive failures beyond maxRetries: with_retry exhausts and
    with_split_retry escalates into halving instead of failing — the
    halves then succeed once the failure streak is consumed."""
    from spark_rapids_tpu.conf import TpuConf
    conf = TpuConf({"spark.rapids.sql.retry.maxRetries": "2",
                    "spark.rapids.sql.retry.backoffMs": "1",
                    "spark.rapids.sql.retry.maxBackoffMs": "1"})
    metrics = MetricRegistry()
    b = _device_batch(32, seed=8)
    state = {"fails": 4}

    def fn(piece):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise R.TpuRetryOOM("synthetic alloc failure")
        return piece

    outs = R.with_split_retry(b, fn, conf, metrics)
    # 4 failures vs 3 attempts (1 + maxRetries=2): the whole batch
    # exhausted and split once; the last failure lands on the first
    # half, whose retry then succeeds
    assert metrics.value(M.SPLIT_RETRY_COUNT) == 1
    assert metrics.value(M.RETRY_COUNT) == 3
    assert len(outs) == 2
    from spark_rapids_tpu.columnar.device import concat_device
    got = concat_device(outs).to_host().to_pydict()
    assert got == b.to_host().to_pydict()


def test_split_oom_on_unsplittable_piece_degrades_to_retry():
    """A split-demand on a piece that cannot shrink (single row) must
    fall back to the plain spill+retry protocol instead of failing the
    task outright (regression: an aggressive split:2 sweep used to
    escape through the 1-row floor and kill the query)."""
    from spark_rapids_tpu.conf import TpuConf
    conf = TpuConf({"spark.rapids.sql.retry.backoffMs": "1",
                    "spark.rapids.sql.retry.maxBackoffMs": "1"})
    metrics = MetricRegistry()
    b = _device_batch(1, seed=9)
    state = {"fails": 2}

    def fn(piece):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise R.TpuSplitAndRetryOOM("split demanded on 1-row piece")
        return piece

    outs = R.with_split_retry(b, fn, conf, metrics)
    assert len(outs) == 1
    assert metrics.value(M.SPLIT_RETRY_COUNT) == 0  # nothing could split
    assert metrics.value(M.RETRY_COUNT) == 1  # degraded retry recovered
    assert outs[0].to_host().to_pydict() == b.to_host().to_pydict()
    # and when even the retry budget exhausts, the OOM still re-raises
    state["fails"] = 10**6
    with pytest.raises(R.TpuRetryOOM):
        R.with_split_retry(b, fn, conf, metrics)


@pytest.mark.parametrize("sched", ["3", "split:4"])
def test_q3_shape_bit_identical_under_oom_sweep(sched):
    plans = _run_clean_vs_injected(
        _q3_shape, _conf(sched), ignore_order=False)
    assert _metric(plans, M.RETRY_COUNT) > 0, sched


def test_oom_sweep_with_tiny_budget_spills_on_retry():
    """Injected OOM + a tiny device budget: retries must actually spill
    the store down (spillBytesOnRetry > 0) and stay correct."""
    conf = _conf("3", **{
        "spark.rapids.memory.tpu.poolSize": str(256 << 10)})
    plans = _run_clean_vs_injected(_q1_shape, conf)
    assert _metric(plans, M.RETRY_COUNT) > 0
    assert _metric(plans, M.SPILL_BYTES_ON_RETRY) > 0


def test_oom_sweep_under_task_parallelism():
    """Concurrent task threads share the injector and the store; the
    sweep must stay bit-identical with permits correctly returned."""
    conf = _conf("4", **{"spark.rapids.sql.taskParallelism": "3"})
    plans = _run_clean_vs_injected(_q1_shape, conf)
    assert _metric(plans, M.RETRY_COUNT) > 0
    sem = resource._SEMAPHORE
    if sem is not None:
        assert sem.in_use == 0


# ---------------------------------------------------------------------------
# Semaphore-leak regression (satellite)
# ---------------------------------------------------------------------------

def test_semaphore_permits_restored_after_failed_query():
    """A query that dies mid-drain (every allocation fails, beyond any
    retry/split budget) must return every device permit: pool task
    threads are discarded, so a leaked permit would shrink the
    semaphore for the process lifetime."""
    conf = _conf("1:1000000", **{
        "spark.rapids.sql.retry.maxRetries": "1",
        "spark.rapids.sql.taskParallelism": "2",
    })
    spark = TpuSparkSession(conf)
    try:
        with pytest.raises(Exception):
            _q1_shape(spark)._execute()
    finally:
        spark.stop()
    sem = resource._SEMAPHORE
    assert sem is not None
    assert sem.in_use == 0, (
        f"leaked {sem.in_use} device permit(s)")


# ---------------------------------------------------------------------------
# Reader IO retry (satellite)
# ---------------------------------------------------------------------------

def _write_parquet(tmp_path, spark):
    path = str(tmp_path / "t")
    df = spark.createDataFrame(
        gen_batch([("k", SmallIntGen()), ("v", LongGen())], 1200, 14),
        num_partitions=3)
    df.write.mode("overwrite").parquet(path)
    return path


@pytest.mark.parametrize("reader_type", ["PERFILE", "MULTITHREADED"])
def test_reader_retries_transient_io_errors(tmp_path, reader_type):
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        path = _write_parquet(tmp_path, gen)
    finally:
        gen.stop()
    R.reset_fault_injection()
    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.test.injectIOError": "2",
        "spark.rapids.sql.reader.retryBackoffMs": "1",
        "spark.rapids.sql.format.parquet.reader.type": reader_type,
    }
    spark = TpuSparkSession(conf)
    try:
        spark.start_capture()
        got = spark.read.parquet(path).groupBy("k").agg(
            F.sum("v").alias("s"))._execute().to_pydict()
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    assert _metric(plans, M.IO_RETRY_COUNT) > 0
    # oracle: clean CPU read of the same files
    R.reset_fault_injection()
    cpu = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        want = cpu.read.parquet(path).groupBy("k").agg(
            F.sum("v").alias("s"))._execute().to_pydict()
    finally:
        cpu.stop()
    assert sorted(_rows(got), key=_sort_key) == \
        sorted(_rows(want), key=_sort_key)


def test_reader_reraises_original_after_exhaustion(tmp_path):
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        path = _write_parquet(tmp_path, gen)
    finally:
        gen.stop()
    R.reset_fault_injection()
    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.test.injectIOError": "1:1000000",
        "spark.rapids.sql.reader.maxRetries": "2",
        "spark.rapids.sql.reader.retryBackoffMs": "1",
    }
    spark = TpuSparkSession(conf)
    try:
        with pytest.raises(IOError, match="injected IO error"):
            spark.read.parquet(path)._execute()
    finally:
        spark.stop()


def test_mesh_sharded_streams_retry_io(tmp_path):
    """The per-chip reader streams of the mesh scan go through the same
    retry-wrapped decode."""
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        path = _write_parquet(tmp_path, gen)
    finally:
        gen.stop()
    R.reset_fault_injection()
    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.shuffle.mode": "ici",
        "spark.rapids.sql.test.injectIOError": "2",
        "spark.rapids.sql.reader.retryBackoffMs": "1",
    }
    spark = TpuSparkSession(conf)
    try:
        spark.start_capture()
        got = spark.read.parquet(path).repartition(4, "k").groupBy("k") \
            .agg(F.sum("v").alias("s"))._execute().to_pydict()
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    assert _metric(plans, M.IO_RETRY_COUNT) > 0
    R.reset_fault_injection()
    cpu = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        want = cpu.read.parquet(path).groupBy("k").agg(
            F.sum("v").alias("s"))._execute().to_pydict()
    finally:
        cpu.stop()
    assert sorted(_rows(got), key=_sort_key) == \
        sorted(_rows(want), key=_sort_key)


# ---------------------------------------------------------------------------
# Chip-failure injection -> graceful mesh degradation
# ---------------------------------------------------------------------------

def _ici_conf(chips: str, **extra):
    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.shuffle.mode": "ici",
        "spark.rapids.sql.test.injectChipFailure": chips,
        "spark.rapids.sql.batchSizeRows": "256",
    }
    conf.update(extra)
    return conf


def _shuffle_query(s):
    df = s.createDataFrame(
        gen_batch([("k", SmallIntGen()), ("v", LongGen()),
                   ("w", IntegerGen())], 3000, 15),
        num_partitions=4)
    return df.repartition(8, "k").groupBy("k").agg(
        F.sum("v").alias("s"), F.count("w").alias("c"))


def test_chip_failure_degrades_mesh_identical_results():
    """One persistently failing chip: the exchange demotes it and the
    query completes on the survivors, bit-identical, with
    degradedChips > 0."""
    import jax
    assert len(jax.devices()) >= 2
    chip = str(jax.devices()[1].id)
    plans = _run_clean_vs_injected(_shuffle_query, _ici_conf(chip))
    assert _metric(plans, M.DEGRADED_CHIPS) > 0
    from spark_rapids_tpu.parallel import mesh as PM
    assert PM.get_active_mesh() is None  # session cleaned up


def test_chip_failures_degrade_to_single_chip():
    """All but one chip failing persistently walks the whole ladder
    down to single-chip in-process execution — never a failed query."""
    import jax
    devs = jax.devices()
    assert len(devs) >= 2
    chips = ",".join(str(d.id) for d in devs[:-1])
    plans = _run_clean_vs_injected(_shuffle_query, _ici_conf(chips))
    assert _metric(plans, M.DEGRADED_CHIPS) == len(devs) - 1


def test_chip_failure_with_mesh_scan(tmp_path):
    """Mesh-sharded scan + failing chip: the degraded re-plan re-shards
    the reader streams over the survivors (scan + exchange demote
    together)."""
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        path = _write_parquet(tmp_path, gen)
    finally:
        gen.stop()
    import jax
    chip = str(jax.devices()[0].id)

    def q(s):
        return s.read.parquet(path).repartition(4, "k").groupBy("k") \
            .agg(F.sum("v").alias("s"))

    plans = _run_clean_vs_injected(q, _ici_conf(chip))
    assert _metric(plans, M.DEGRADED_CHIPS) > 0


def test_chip_failure_race_retries_not_reraises():
    """execute_collect decides retry-vs-reraise against a pre-attempt
    snapshot: a chip another thread demoted MID-attempt still retries
    (regression: mark_chip_failed()==False used to re-raise and fail
    the query on concurrent failures of the same chip); only a failure
    on a chip demoted BEFORE the attempt began re-raises."""
    from spark_rapids_tpu.parallel import mesh as PM
    from spark_rapids_tpu.sql import physical as P
    from spark_rapids_tpu.sql import types as T

    class _StubPlan(P.PhysicalPlan):
        def __init__(self, script):
            self.children = []
            self._script = list(script)

        @property
        def output(self):
            return []

        @property
        def schema(self):
            return T.StructType([])

        def partitions(self):
            step = self._script.pop(0)
            if step == "ok":
                return []
            if step == "race":
                # another thread demotes the chip before our raise lands
                PM.mark_chip_failed(step_chip)
            raise R.TpuChipFailure(step_chip)

    step_chip = 3
    with PM.active_mesh(PM.build_mesh()):
        # plain failure -> demote -> retry -> ok
        out = _StubPlan(["fail", "ok"]).execute_collect()
        assert out.num_rows == 0
        assert step_chip in PM.failed_chips()
    with PM.active_mesh(PM.build_mesh()):
        # demotion race mid-attempt -> still retries
        out = _StubPlan(["race", "ok"]).execute_collect()
        assert out.num_rows == 0
    with PM.active_mesh(PM.build_mesh()):
        # chip already demoted before the attempt -> failure is
        # elsewhere: re-raise, bounded loop
        PM.mark_chip_failed(step_chip)
        with pytest.raises(R.TpuChipFailure):
            _StubPlan(["fail"]).execute_collect()


def test_degraded_mesh_state_resets_per_activation():
    from spark_rapids_tpu.parallel import mesh as PM
    with PM.active_mesh(PM.build_mesh()):
        assert PM.mark_chip_failed(0)
        assert not PM.mark_chip_failed(0)  # already demoted: no recount
        assert PM.degraded_chip_count() == 1
        hm = PM.healthy_mesh()
        assert hm is not None
        assert 0 not in [d.id for d in hm.devices.flat]
    with PM.active_mesh(PM.build_mesh()):
        assert PM.degraded_chip_count() == 0  # fresh activation
        assert PM.healthy_mesh() is PM.get_active_mesh()
