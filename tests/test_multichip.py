"""Multi-chip shuffle + distributed aggregation over the virtual 8-device
CPU mesh (the RapidsShuffleClientSuite/ServerSuite role, SURVEY.md §4.3 —
real collectives over emulated devices instead of Mockito mocks)."""

import numpy as np
import pytest

import spark_rapids_tpu.sql.functions as F
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.columnar.device import DeviceBatch
from spark_rapids_tpu.parallel import build_mesh, active_mesh
from spark_rapids_tpu.parallel import ici
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import types as T

from tests.harness import assert_tpu_and_cpu_equal_collect


@pytest.fixture(scope="module")
def mesh8():
    return build_mesh(8)


def _slots(rng, schema, n_dev, gen_row):
    slots, all_rows = [], []
    for _ in range(n_dev):
        n = int(rng.integers(1, 60))
        rows = [gen_row(rng) for _ in range(n)]
        all_rows.extend(rows)
        cols = {k: [r[i] for r in rows]
                for i, k in enumerate(f.name for f in schema.fields)}
        slots.append(DeviceBatch.from_host(
            HostBatch.from_pydict(cols, schema)))
    return slots, all_rows


def test_mesh_exchange_matches_cpu_partitioning(mesh8):
    """Every row lands in exactly the partition CPU Spark's
    pmod(murmur3(key, 42), n) puts it in, and partition p is owned by
    chip p % n_dev."""
    schema = T.StructType([T.StructField("k", T.LongT),
                           T.StructField("s", T.StringT)])
    rng = np.random.default_rng(3)
    slots, all_rows = _slots(
        rng, schema, 8,
        lambda r: (int(r.integers(-1000, 1000)),
                   "v%d" % r.integers(0, 99)))
    bound = [E.BoundReference(0, T.LongT, True)]
    n_parts = 16
    out = ici.mesh_exchange(slots, bound, n_parts, mesh8)

    hb = HostBatch.from_pydict(
        {"k": [r[0] for r in all_rows], "s": [r[1] for r in all_rows]},
        schema)
    hv = E.Murmur3Hash([E.BoundReference(0, T.LongT, True)]).eval(hb) \
        .data.astype(np.int64)
    pids = np.mod(hv, n_parts)
    expect = {p: sorted((all_rows[i] for i in np.nonzero(pids == p)[0]))
              for p in range(n_parts)}
    for p in range(n_parts):
        got = []
        for b in out[p]:
            h = b.to_host()
            got.extend((h.columns[0].data[i], h.columns[1].data[i])
                       for i in range(h.num_rows))
        assert sorted(got) == expect[p], f"partition {p}"


def test_mesh_exchange_null_keys(mesh8):
    schema = T.StructType([T.StructField("k", T.LongT, True)])
    rng = np.random.default_rng(11)
    slots = []
    total = 0
    for _ in range(8):
        vals = [None if rng.random() < 0.3 else int(rng.integers(0, 10))
                for _ in range(int(rng.integers(1, 40)))]
        total += len(vals)
        slots.append(DeviceBatch.from_host(
            HostBatch.from_pydict({"k": vals}, schema)))
    out = ici.mesh_exchange(slots, [E.BoundReference(0, T.LongT, True)],
                            8, mesh8)
    got = sum(b.row_count() for bs in out for b in bs)
    assert got == total  # null-keyed rows are routed, not dropped


def test_sum_count_step(mesh8):
    """The fused partial->exchange->final program gives the exact global
    answer with each key on exactly one chip (__graft_entry__ dryrun)."""
    import __graft_entry__ as g
    g.dryrun_multichip(8)
    g.dryrun_multichip(2)


def test_engine_aggregate_over_mesh(mesh8):
    """End-to-end dual-session: groupBy aggregate with the ICI exchange
    active matches CPU bit-exactly."""
    data = {"k": [int(x) for x in
                  np.random.default_rng(5).integers(0, 25, 500)],
            "v": [int(x) for x in
                  np.random.default_rng(6).integers(-100, 100, 500)]}

    def q(spark):
        df = spark.createDataFrame(data, num_partitions=6)
        return df.groupBy("k").agg(
            F.sum("v").alias("s"), F.count("v").alias("c"),
            F.min("v").alias("mn"), F.max("v").alias("mx"))

    with active_mesh(mesh8):
        assert_tpu_and_cpu_equal_collect(
            q, expect_execs=["TpuExchange", "TpuHashAggregate"])


def test_engine_strings_over_mesh(mesh8):
    rng = np.random.default_rng(9)
    data = {"name": ["u%02d" % x for x in rng.integers(0, 30, 400)],
            "v": [int(x) for x in rng.integers(0, 1000, 400)]}

    def q(spark):
        df = spark.createDataFrame(data, num_partitions=5)
        return df.groupBy("name").agg(F.sum("v").alias("s"))

    with active_mesh(mesh8):
        assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuExchange"])


def test_mesh_matches_inprocess_path(mesh8):
    """The ICI exchange and the in-process exchange produce identical
    partition contents (transport equivalence, RapidsShuffleTestHelper
    role)."""
    data = {"k": [int(x) for x in
                  np.random.default_rng(2).integers(0, 50, 300)],
            "v": list(range(300))}

    def q(spark):
        df = spark.createDataFrame(data, num_partitions=4)
        return df.groupBy("k").agg(F.sum("v").alias("s"))

    with active_mesh(mesh8):
        assert_tpu_and_cpu_equal_collect(q)
    # no mesh: in-process path
    assert_tpu_and_cpu_equal_collect(q)


def test_shuffle_mode_ici_conf_activates_mesh():
    """spark.rapids.shuffle.mode=ici wires the mesh at SESSION start —
    no test-side active_mesh — and the exchange takes the ICI path
    (RapidsShuffleManager configuration wiring, GpuShuffleEnv.scala:26
    role)."""
    from spark_rapids_tpu.parallel.mesh import get_active_mesh
    from tests.harness import assert_tpu_and_cpu_equal_collect
    from tests.datagen import LongGen, SmallIntGen, gen_batch
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSparkSession

    assert get_active_mesh() is None
    conf = {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.shuffle.mode": "ici",
    }
    spark = TpuSparkSession(conf)
    try:
        assert get_active_mesh() is not None
        spark.start_capture()
        df = spark.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("v", LongGen())], 3000, 77),
            num_partitions=4)
        got = (df.groupBy("k")
               .agg(F.sum("v").alias("s"), F.count("*").alias("c"))
               .collect())
        plans = spark.get_captured_plans()
        ici = 0
        def walk(p):
            nonlocal ici
            m = getattr(p, "metrics", None)
            if m is not None:
                ici += m.metrics["numIciExchanges"].value \
                    if "numIciExchanges" in m.metrics else 0
            for c in getattr(p, "children", []):
                walk(c)
        for p in plans:
            walk(p)
        assert ici > 0, "exchange did not take the ICI path"
    finally:
        spark.stop()
    assert get_active_mesh() is None  # stop() tears the mesh down

    cpu = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = cpu.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("v", LongGen())], 3000, 77),
            num_partitions=4)
        want = (df.groupBy("k")
                .agg(F.sum("v").alias("s"), F.count("*").alias("c"))
                .collect())
    finally:
        cpu.stop()
    def canon(rows):
        return sorted((tuple(r) for r in rows),
                      key=lambda t: tuple((v is None,
                                           0 if v is None else v)
                                          for v in t))
    assert canon(got) == canon(want)


def test_join_over_mesh(mesh8):
    """Shuffled hash join with BOTH sides' exchanges riding the ICI
    all-to-all (shuffle.mode=ici) matches the CPU engine."""
    from tests.harness import assert_tpu_and_cpu_equal_collect
    from spark_rapids_tpu.sql import functions as F
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"k": [i % 13 for i in range(400)],
             "v": list(range(400))}, "k long, v long", num_partitions=4)
        .join(s.createDataFrame(
            {"k2": [i % 13 for i in range(60)],
             "w": list(range(60))}, "k2 long, w long", num_partitions=2),
            F.col("k") == F.col("k2"), "inner")
        .groupBy("k").agg(F.count("*").alias("c"),
                          F.sum("w").alias("sw")).orderBy("k"),
        conf={"spark.rapids.shuffle.mode": "ici",
              "spark.rapids.sql.autoBroadcastJoinThreshold": "-1"},
        expect_execs=["TpuShuffledHashJoin"])


def test_sort_over_mesh(mesh8):
    """Global orderBy with ici mode active: hash exchanges ride the
    mesh, the range exchange stays in-process; results match."""
    from tests.harness import assert_tpu_and_cpu_equal_collect
    from spark_rapids_tpu.sql import functions as F
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"k": [i % 7 for i in range(500)],
             "v": [(i * 37) % 211 for i in range(500)]},
            "k long, v long", num_partitions=4)
        .groupBy("k").agg(F.sum("v").alias("s"))
        .orderBy(F.col("s").desc(), "k"),
        conf={"spark.rapids.shuffle.mode": "ici"},
        ignore_order=False,
        expect_execs=["TpuSort", "TpuHashAggregate"])


def test_q1_shape_over_mesh(mesh8):
    """The full q1 shape (filter -> decimal aggregate -> orderBy) with
    shuffle.mode=ici on the 8-device mesh, bit-identical to CPU."""
    from decimal import Decimal
    from tests.harness import assert_tpu_and_cpu_equal_collect

    def q(s):
        import numpy as np
        rng = np.random.default_rng(12)
        n = 1200
        s.createDataFrame(
            {"l_returnflag": [["A", "N", "R"][i % 3] for i in range(n)],
             "l_linestatus": [["O", "F"][i % 2] for i in range(n)],
             "l_quantity": [Decimal(int(v)) for v in
                            rng.integers(1, 51, n)],
             "l_extendedprice": [Decimal(int(v)).scaleb(-2) for v in
                                 rng.integers(90100, 10494951, n)],
             "l_discount": [Decimal(int(v)).scaleb(-2) for v in
                            rng.integers(0, 11, n)],
             "l_shipdate": rng.integers(8000, 10500, n).tolist()},
            "l_returnflag string, l_linestatus string, "
            "l_quantity decimal(15,2), l_extendedprice decimal(15,2), "
            "l_discount decimal(15,2), l_shipdate int",
            num_partitions=4).createOrReplaceTempView("lineitem")
        return s.sql(
            "SELECT l_returnflag, l_linestatus, sum(l_quantity) sq, "
            "sum(l_extendedprice * (1 - l_discount)) sd, "
            "avg(l_discount) ad, count(*) c FROM lineitem "
            "WHERE l_shipdate <= 10000 "
            "GROUP BY l_returnflag, l_linestatus "
            "ORDER BY l_returnflag, l_linestatus")
    assert_tpu_and_cpu_equal_collect(
        q, conf={"spark.rapids.shuffle.mode": "ici"},
        ignore_order=False,
        expect_execs=["TpuHashAggregate", "TpuSort"])


# -- mesh-sharded scan (PR 3) ----------------------------------------------
#
# Skew/degenerate sharding coverage: the unit scheduler, and end-to-end
# parquet scans over the 8-device mesh that must stay bit-identical to
# BOTH the in-process (single-chip) TPU path and the CPU engine —
# including unit counts not divisible by the mesh size, chips that
# receive zero scan units, and an empty (fully pruned) relation.

class _Unit:
    def __init__(self, size_bytes):
        self.size_bytes = size_bytes


def test_shard_units_by_bytes_balances_skew():
    from spark_rapids_tpu.io.readers import shard_units_by_bytes
    rng = np.random.default_rng(4)
    sizes = [int(s) for s in rng.integers(1, 1_000_000, 37)]
    streams = shard_units_by_bytes([_Unit(s) for s in sizes], 8)
    assert sum(len(st) for st in streams) == 37
    loads = [sum(u.size_bytes for u in st) for st in streams]
    # least-loaded-first: no stream exceeds the ideal share by more
    # than one max-sized unit
    assert max(loads) - min(loads) <= max(sizes)


def test_shard_units_by_bytes_fewer_units_than_streams():
    from spark_rapids_tpu.io.readers import shard_units_by_bytes
    streams = shard_units_by_bytes([_Unit(10), _Unit(20)], 8)
    assert sum(len(st) for st in streams) == 2
    # empty streams are KEPT (stable per-chip structure)
    assert len(streams) == 8
    assert sum(1 for st in streams if not st) == 6


def test_shard_units_by_bytes_zero_byte_units_spread():
    from spark_rapids_tpu.io.readers import shard_units_by_bytes
    streams = shard_units_by_bytes([_Unit(0) for _ in range(8)], 4)
    assert [len(st) for st in streams] == [2, 2, 2, 2]


def _write_scan_table(spark, path, n_files, rows_per_file=80):
    n = n_files * rows_per_file
    rng = np.random.default_rng(n_files)
    df = spark.createDataFrame(
        {"k": [int(x) for x in rng.integers(0, 23, n)],
         "v": [int(x) for x in rng.integers(-500, 500, n)],
         "s": ["t%03d" % x for x in rng.integers(0, 50, n)]},
        "k long, v long, s string", num_partitions=n_files)
    df.write.mode("overwrite").parquet(path)


def _scan_agg(path):
    def q(spark):
        df = spark.read.parquet(path)
        return (df.where(F.col("v") > -400).groupBy("k")
                .agg(F.sum("v").alias("sv"), F.count("*").alias("c"),
                     F.max("s").alias("mx"))
                .orderBy("k"))
    return q


def _collect_rows(q, conf):
    from spark_rapids_tpu.sql.session import TpuSparkSession
    spark = TpuSparkSession(conf)
    try:
        spark.start_capture()
        rows = [tuple(r) for r in q(spark).collect()]
        return rows, spark.get_captured_plans()
    finally:
        spark.stop()


def _sum_metric(plans, prefix):
    from spark_rapids_tpu.metrics import sum_plan_metrics
    return sum_plan_metrics(plans, prefix)


def _assert_mesh_matches_all_paths(q, tmp_path_unused=None):
    """ici-mesh run == in-process single-chip TPU run == CPU engine,
    bit-identical (ORDER BY makes row order deterministic)."""
    ici, ici_plans = _collect_rows(q, {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.shuffle.mode": "ici"})
    inproc, _ = _collect_rows(q, {"spark.rapids.sql.enabled": "true"})
    cpu, _ = _collect_rows(q, {"spark.rapids.sql.enabled": "false"})
    assert ici == inproc, "mesh path diverged from in-process TPU path"
    assert ici == cpu, "mesh path diverged from CPU engine"
    return ici_plans


def test_mesh_scan_units_not_divisible_by_mesh(tmp_path):
    """11 scan units over 8 chips: uneven streams, same answer."""
    import os
    path = os.path.join(str(tmp_path), "t11")
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _write_scan_table(gen, path, n_files=11)
    finally:
        gen.stop()
    plans = _assert_mesh_matches_all_paths(_scan_agg(path))
    units = _sum_metric(plans, "meshScanUnits.chip")
    assert len(units) == 8 and sum(units.values()) == 11
    assert all(v >= 1 for v in units.values())  # every chip scans


def test_mesh_scan_chip_with_zero_units(tmp_path):
    """2 scan units over 8 chips: six chips get no units, the empty
    streams still yield stable (empty) partitions."""
    import os
    path = os.path.join(str(tmp_path), "t2")
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _write_scan_table(gen, path, n_files=2)
    finally:
        gen.stop()
    plans = _assert_mesh_matches_all_paths(_scan_agg(path))
    units = _sum_metric(plans, "meshScanUnits.chip")
    assert sum(units.values()) == 2
    assert sum(1 for v in units.values() if v == 0) == 6


def test_mesh_scan_empty_relation(tmp_path):
    """Fully-pruned scan (pushdown removes every row group): the mesh
    path sees zero units on every chip and still agrees everywhere."""
    import os
    path = os.path.join(str(tmp_path), "tempty")
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _write_scan_table(gen, path, n_files=3)
    finally:
        gen.stop()

    def q(spark):
        df = spark.read.parquet(path)
        return (df.where(F.col("v") > 10_000)  # prunes every row group
                .groupBy("k").agg(F.sum("v").alias("sv"))
                .orderBy("k"))
    _assert_mesh_matches_all_paths(q)


def test_mesh_scan_batches_resident_per_chip(tmp_path):
    """The q1 shape over the mesh scan: every chip runs scan units AND
    dispatches device programs on ITS resident batches (per-chip
    dispatch counters all nonzero), and the exchange reports the
    cross-chip padding overhead (meshPadWaste)."""
    import os
    path = os.path.join(str(tmp_path), "t16")
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _write_scan_table(gen, path, n_files=16, rows_per_file=200)
    finally:
        gen.stop()
    rows, plans = _collect_rows(_scan_agg(path), {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.shuffle.mode": "ici"})
    units = _sum_metric(plans, "meshScanUnits.chip")
    assert len(units) == 8 and all(v >= 1 for v in units.values())
    dispatch = _sum_metric(plans, "dispatchCount.chip")
    assert len(dispatch) >= 8 and all(v >= 1 for v in dispatch.values()), \
        f"expected device programs on every chip, got {dispatch}"
    pad = _sum_metric(plans, "meshPadWaste")
    assert "meshPadWaste" in pad  # emitted (value may be 0 if aligned)


def test_multichip_scan_disabled_falls_back(tmp_path):
    """multichip.scan.enabled=false: ici exchange still works but the
    scan stays a single stream (no per-chip scan-unit counters)."""
    import os
    path = os.path.join(str(tmp_path), "tdis")
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _write_scan_table(gen, path, n_files=8)
    finally:
        gen.stop()
    rows, plans = _collect_rows(_scan_agg(path), {
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.shuffle.mode": "ici",
        "spark.rapids.sql.multichip.scan.enabled": "false"})
    assert not _sum_metric(plans, "meshScanUnits.chip")
    cpu, _ = _collect_rows(_scan_agg(path),
                           {"spark.rapids.sql.enabled": "false"})
    assert rows == cpu


def test_collective_section_serializes_served_queries():
    """Served sessions' mesh collective sections are mutually
    exclusive (the XLA CPU rendezvous-deadlock guard,
    spark.rapids.sql.multichip.serializeServedQueries); non-served
    sessions and the conf-off case skip the mutex; the section is
    reentrant on one thread."""
    import threading
    import time as _t

    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.parallel.mesh import collective_section

    def max_overlap(conf, workers=4):
        state = {"inside": 0, "peak": 0}
        lock = threading.Lock()
        start = threading.Barrier(workers)

        def worker():
            start.wait()
            with collective_section(conf):
                with lock:
                    state["inside"] += 1
                    state["peak"] = max(state["peak"], state["inside"])
                _t.sleep(0.03)
                with lock:
                    state["inside"] -= 1

        ts = [threading.Thread(target=worker) for _ in range(workers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in ts)
        return state["peak"]

    served = TpuConf({"spark.rapids.sql.serve.tenantId": "t1"})
    assert max_overlap(served) == 1
    # conf off / non-served: no exclusion (sections overlap freely)
    off = TpuConf({
        "spark.rapids.sql.serve.tenantId": "t1",
        "spark.rapids.sql.multichip.serializeServedQueries": "false"})
    assert max_overlap(off) > 1
    assert max_overlap(TpuConf({})) > 1
    # reentrancy: a nested section on the same thread must not deadlock
    with collective_section(served):
        with collective_section(served):
            pass
