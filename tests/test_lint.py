"""tpu-lint corpus (docs/linting.md): fixture-driven good/bad pairs
for every rule family, suppression + baseline semantics, JSON output
schema, the CLI exit-code contract, and the zero-findings gate over
the real package (which makes tier-1 the lint CI gate)."""

import json
import os
import subprocess
import sys
import textwrap

from spark_rapids_tpu.lint import (LintConfig, load_config, render_json,
                                   run_lint)
from spark_rapids_tpu.lint.engine import default_root, write_baseline


def _tree(tmp_path, files):
    root = tmp_path / "fixture"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    for d in ("spark_rapids_tpu", "spark_rapids_tpu/exec",
              "spark_rapids_tpu/serve"):
        if (root / d).is_dir():
            init = root / d / "__init__.py"
            if not init.exists():
                init.write_text("")
    return str(root)


def _lint(root, **over):
    cfg = LintConfig(check_docs=False, **over)
    return run_lint(root, cfg)


def _rules(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# family 1: retry coverage
# ---------------------------------------------------------------------------

def test_retry_coverage_bad_and_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from spark_rapids_tpu import retry as R

        def bad(staged, device):
            return finish_upload(staged, device)

        def good(staged, device, conf):
            return R.with_retry(lambda: finish_upload(staged, device),
                                conf)
    """})
    r = _lint(root)
    assert _rules(r) == ["retry-coverage"]
    assert len(r.findings) == 1
    assert r.findings[0].line == 4  # only the unwrapped site


def test_retry_coverage_transitive_local_closure(tmp_path):
    # with_retry re-runs the whole closure: a local def passed BY NAME
    # to the combinator covers everything it calls in-module
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from spark_rapids_tpu import retry as R

        def outer(src, conf):
            def upload_host(hb):
                return inner(hb)
            return R.with_split_retry(src, upload_host, conf)

        def inner(hb):
            return upload_batch(hb, 8)
    """})
    assert _lint(root).clean


def test_retry_coverage_allowlist_and_scope(tmp_path):
    files = {"spark_rapids_tpu/exec/x.py": """
        def proto(staged, device):
            return finish_upload(staged, device)
    """,
             # out of retry scope: same code, no finding
             "spark_rapids_tpu/sql/y.py": """
        def elsewhere(staged, device):
            return finish_upload(staged, device)
    """}
    root = _tree(tmp_path, files)
    assert _rules(_lint(root)) == ["retry-coverage"]
    allow = {"spark_rapids_tpu/exec/x.py::proto":
             "fixture protocol layer"}
    assert _lint(root, retry_allowlist=allow).clean


# ---------------------------------------------------------------------------
# family 2: compile discipline
# ---------------------------------------------------------------------------

def test_jit_direct_bad_and_routed_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax
        from spark_rapids_tpu.jit_cache import JitCache

        _C = JitCache("fixture")

        def bad(fn):
            return jax.jit(fn)

        def good(key, fn):
            got = _C.get(key)
            if got is None:
                got = _C.put(key, jax.jit(fn))
            return got

        def also_good(key):
            fn, _ = _C.get_or_build(key, lambda: _builder())
            return fn

        def _builder():
            return jax.jit(lambda x: x)
    """})
    r = _lint(root)
    assert _rules(r) == ["jit-direct"]
    assert [f.line for f in r.findings] == [7]


def test_jit_builder_resolves_across_modules(tmp_path):
    # _STAGE_CACHE.put(key, X.build_fn(...)) in one module makes the
    # jax.jit inside other_module.build_fn compliant
    root = _tree(tmp_path, {
        "spark_rapids_tpu/exec/a.py": """
            from spark_rapids_tpu.jit_cache import JitCache
            from spark_rapids_tpu.exec import b as B

            _C = JitCache("x")

            def use(key, steps):
                return _C.put(key, B.build_fn(steps))
        """,
        "spark_rapids_tpu/exec/b.py": """
            import jax

            def build_fn(steps):
                return jax.jit(lambda c: c)
        """})
    assert _lint(root).clean


def test_pallas_call_treated_like_jit(tmp_path):
    # pl.pallas_call is compile-discipline traffic exactly like
    # jax.jit: sanctioned inside the kernels/ registry package or a
    # JitCache builder closure, a finding anywhere else
    root = _tree(tmp_path, {
        "spark_rapids_tpu/exec/x.py": """
            import jax
            from jax.experimental import pallas as pl
            from spark_rapids_tpu.jit_cache import JitCache

            _C = JitCache("fixture")

            def bad(x):
                return pl.pallas_call(_k, out_shape=x)(x)

            def good(key):
                fn, _ = _C.get_or_build(key, lambda: _builder())
                return fn

            def _builder():
                return jax.jit(lambda x: pl.pallas_call(
                    _k, out_shape=None)(x))
        """,
        "spark_rapids_tpu/kernels/__init__.py": "",
        "spark_rapids_tpu/kernels/k.py": """
            from jax.experimental import pallas as pl

            def build_kernel(shape):
                # registry home: pallas_call sanctioned here
                return pl.pallas_call(_kern, out_shape=shape)
        """})
    r = _lint(root)
    assert _rules(r) == ["jit-direct"]
    assert [f.line for f in r.findings] == [8]
    assert "pl.pallas_call" in r.findings[0].message


def test_pallas_call_suppressible_with_reason(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from jax.experimental import pallas as pl

        def probe(shape):
            return pl.pallas_call(_k, out_shape=shape)  # tpu-lint: disable=jit-direct(one-shot capability probe)
    """})
    assert _lint(root).clean


def test_jit_module_cache_flags_raw_dicts(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from collections import OrderedDict
        from spark_rapids_tpu.jit_cache import JitCache

        _BAD_CACHE = {}
        _ALSO_BAD_CACHE = OrderedDict()
        _GOOD_CACHE = JitCache("good")
        _PLAIN_TABLE = {}
    """})
    r = _lint(root)
    assert _rules(r) == ["jit-module-cache"]
    assert [f.line for f in r.findings] == [4, 5]


# ---------------------------------------------------------------------------
# family 3: concurrency
# ---------------------------------------------------------------------------

_LOCKY = """
    import threading
    import time

    class DeviceStore:
        def __init__(self):
            self._lock = threading.Lock()
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._d = {}
"""


def test_lock_order_cycle_flagged(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
    """})
    r = _lint(root)
    assert _rules(r) == ["lock-order"]
    assert "DeviceStore._a" in r.findings[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                with self._b:
                    pass
    """})
    assert _lint(root).clean


def test_lock_order_interprocedural_edge(tmp_path):
    # with A held, calling a method that takes B adds the A->B edge
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def one(self):
            with self._a:
                self.takes_b()

        def takes_b(self):
            with self._b:
                pass

        def two(self):
            with self._b:
                with self._a:
                    pass
    """})
    assert _rules(_lint(root)) == ["lock-order"]


def test_blocking_call_under_critical_lock(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def bad_sleep(self):
            with self._lock:
                time.sleep(0.1)

        def bad_dispatch(self, staged):
            with self._lock:
                return finish_upload(staged)

        def good(self):
            with self._lock:
                n = 1
            time.sleep(0.1)
            return n
    """})
    r = _lint(root)
    assert _rules(r) == ["lock-blocking-call"]
    assert len(r.findings) == 2


def test_wait_on_different_lock_flagged(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": """
        import threading

        class DeviceStore:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def bad(self):
                with self._lock:
                    self._cv.wait()

            def fine(self):
                with self._cv:
                    self._cv.wait()
    """})
    r = _lint(root)
    assert _rules(r) == ["lock-blocking-call"]
    assert len(r.findings) == 1
    assert "different lock" in r.findings[0].message


def test_check_then_act_bad_and_guarded(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/serve/s.py": """
        import threading

        class Sessions:
            def __init__(self):
                self._lock = threading.Lock()
                self._by_tenant = {}

            def racy(self, k):
                if k not in self._by_tenant:
                    self._by_tenant[k] = object()
                return self._by_tenant[k]

            def guarded(self, k):
                with self._lock:
                    if k not in self._by_tenant:
                        self._by_tenant[k] = object()
                    return self._by_tenant[k]
    """})
    r = _lint(root)
    assert _rules(r) == ["check-then-act"]
    assert len(r.findings) == 1
    assert "_by_tenant" in r.findings[0].message


# ---------------------------------------------------------------------------
# family 4: drift
# ---------------------------------------------------------------------------

def test_metric_key_rule(tmp_path):
    root = _tree(tmp_path, {
        "spark_rapids_tpu/metrics.py": """
            OP_TIME = "opTime"
            ROGUE = "notDescribedConstant"
            METRIC_DESCRIPTIONS = {
                OP_TIME: "operator wall",
                "goodKey": "described",
            }
            METRIC_PREFIX_DESCRIPTIONS = {"perChip.": "per chip <N>"}
        """,
        "spark_rapids_tpu/exec/x.py": """
            from spark_rapids_tpu import metrics as M

            def use(metrics):
                metrics.create("goodKey").add(1)
                metrics.create(M.OP_TIME).add(1)
                metrics.create("perChip.3").add(1)
                metrics.create("rogueLiteral").add(1)
                metrics.create(dynamic_key()).add(1)  # invisible: ok
        """})
    r = _lint(root)
    assert _rules(r) == ["metric-key"]
    msgs = " ".join(f.message for f in r.findings)
    assert "notDescribedConstant" in msgs  # constant direction
    assert "rogueLiteral" in msgs          # call-site direction
    assert len(r.findings) == 2


def test_conf_key_rule(tmp_path):
    root = _tree(tmp_path, {
        "spark_rapids_tpu/conf.py": """
            def conf(key):
                return key

            conf("spark.rapids.sql.fixture.enabled")
        """,
        "spark_rapids_tpu/exec/x.py": """
            GOOD = "spark.rapids.sql.fixture.enabled"
            BAD = "spark.rapids.sql.fixture.typo"
            PREFIX = "spark.rapids.sql.fixture."  # namespace match: ok
        """})
    r = _lint(root)
    assert _rules(r) == ["conf-key"]
    assert len(r.findings) == 1
    assert "typo" in r.findings[0].message


def test_span_scope_rule(tmp_path):
    root = _tree(tmp_path, {
        "spark_rapids_tpu/trace.py": "def span(*a, **k): pass\n",
        "spark_rapids_tpu/exec/x.py": """
            from spark_rapids_tpu import trace as _trace

            def use():
                _trace.span("leaky")
                with _trace.span("fine"):
                    pass
        """})
    r = _lint(root)
    assert _rules(r) == ["span-scope"]
    assert [f.line for f in r.findings] == [4]


def test_generated_doc_content_carries_drift_tables():
    """The content direction of the retired runtime drift tests:
    docs-drift proves docs == generator output byte-for-byte; this
    proves the GENERATOR still emits the metric description table and
    the conf/profile sections (otherwise regenerating stale docs could
    silently drop them both)."""
    import spark_rapids_tpu.profile  # noqa: F401 — registers confs
    import spark_rapids_tpu.trace  # noqa: F401 — registers confs
    from spark_rapids_tpu import metrics as M
    from spark_rapids_tpu.tools import generate_observability_docs
    doc = generate_observability_docs()
    for name in M.METRIC_DESCRIPTIONS:
        assert name in doc, name
    for key in ("spark.rapids.sql.profile.enabled",
                "spark.rapids.sql.profile.dir",
                "spark.rapids.sql.explain",
                "spark.rapids.sql.trace.enabled"):
        assert key in doc, key
    assert "Reading a query profile" in doc
    assert "Explain / fallback reasons" in doc


# ---------------------------------------------------------------------------
# family 5: cancellation discipline
# ---------------------------------------------------------------------------

def test_cancel_checkpoint_bad_and_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/serve/w.py": """
        import threading
        import time

        _CV = threading.Condition()

        def bad_wait():
            with _CV:
                _CV.wait()

        def bad_sleep():
            time.sleep(0.5)

        def bad_queue_get(q):
            return q.get()

        def bad_explicit_blocking_get(q):
            return q.get(block=True)

        def good_bounded_wait():
            with _CV:
                _CV.wait(timeout=0.05)

        def good_positional_wait(ev):
            ev.wait(0.05)

        def good_queue_get(q):
            return q.get(timeout=0.1)

        def good_nonblocking_get(q):
            return q.get(block=False)

        def fine_dict_get(d, k):
            return d.get(k)
    """})
    r = _lint(root)
    assert _rules(r) == ["cancel-checkpoint"]
    assert len(r.findings) == 4
    msgs = " | ".join(f.message for f in r.findings)
    assert "time.sleep" in msgs
    assert "unbounded .wait()" in msgs
    assert "blocking queue .get()" in msgs


def test_cancel_checkpoint_none_timeout_and_scope(tmp_path):
    files = {
        # timeout=None is NOT bounded
        "spark_rapids_tpu/jit_cache.py": """
        def bad(ev):
            ev.wait(timeout=None)
    """,
        # same primitives OUTSIDE the lifecycle-critical scope: clean
        "spark_rapids_tpu/exec/y.py": """
        import time

        def elsewhere(ev, q):
            time.sleep(0.5)
            ev.wait()
            return q.get()
    """}
    root = _tree(tmp_path, files)
    r = _lint(root)
    assert _rules(r) == ["cancel-checkpoint"]
    assert len(r.findings) == 1
    assert r.findings[0].path == "spark_rapids_tpu/jit_cache.py"


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, JSON schema
# ---------------------------------------------------------------------------

def test_suppression_requires_reason(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(fixture program, bounded)

        def b(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct
    """})
    r = _lint(root)
    # the reasoned suppression holds; the reasonless one does NOT
    # suppress and is itself a finding
    assert r.suppressed == 1
    assert _rules(r) == ["bad-suppression", "jit-direct"]
    bad = [f for f in r.findings if f.rule == "jit-direct"]
    assert [f.line for f in bad] == [7]


def test_malformed_suppression_lists_fail_closed(tmp_path):
    # parens inside a reason / prose after the list must fail the
    # WHOLE comment (nothing suppressed, one bad-suppression), never
    # register fragments of free text as rules
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(probe (one-shot) cap)

        def b(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(why) see docs/linting.md

        def c(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(ok reason), span-scope(also fine)
    """})
    r = _lint(root)
    assert r.suppressed == 1  # only c's well-formed multi-item list
    rules = sorted(f.rule for f in r.findings)
    assert rules.count("jit-direct") == 2  # a and b stay findings
    assert rules.count("bad-suppression") == 2


def test_standalone_suppression_covers_next_line(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        # tpu-lint: disable=jit-direct(fixture program, bounded)
        _FN = jax.jit(lambda x: x)
    """})
    r = _lint(root)
    assert r.clean and r.suppressed == 1


def test_baseline_semantics_and_fix_baseline(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)
    """})
    cfg = LintConfig(check_docs=False)
    r = run_lint(root, cfg)
    assert len(r.findings) == 1 and r.baselined == 0
    # --fix-baseline captures current findings as accepted debt
    path = write_baseline(root, cfg, r.findings, r.pctx)
    data = json.load(open(path))
    assert data["version"] == 1 and len(data["findings"]) == 1
    assert data["findings"][0]["rule"] == "jit-direct"
    r2 = run_lint(root, cfg)
    assert r2.clean and r2.baselined == 1
    # baseline is line-TEXT keyed: edits above the site don't churn it
    p = os.path.join(root, "spark_rapids_tpu/exec/x.py")
    src = open(p).read()
    open(p, "w").write("import os  # shift lines\n" + src)
    r3 = run_lint(root, cfg)
    assert r3.clean and r3.baselined == 1
    # re-capturing with a NEW finding present must keep the still-live
    # old debt (what run_cli --fix-baseline writes), not drop it
    # (distinct line text: identical lines share a fingerprint by
    # design, like any text-keyed baseline)
    open(p, "a").write(
        "\n\ndef c(fn):\n    return jax.jit(fn, static_argnums=0)\n")
    r4 = run_lint(root, cfg)
    assert len(r4.findings) == 1 and r4.baselined == 1
    write_baseline(root, cfg, r4.findings + r4.baselined_findings,
                   r4.pctx)
    data = json.load(open(path))
    assert len(data["findings"]) == 2
    r5 = run_lint(root, cfg)
    assert r5.clean and r5.baselined == 2


def test_json_output_schema(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)
    """})
    r = run_lint(root, LintConfig(check_docs=False))
    out = json.loads(render_json(r, r.pctx))
    assert out["version"] == 1
    assert out["clean"] is False
    assert set(out["counts"]) == {"findings", "suppressed", "baselined",
                                  "files"}
    f = out["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message",
                      "fingerprint"}
    assert f["rule"] == "jit-direct"
    assert "jit-direct" in out["rules"]
    assert out["internalErrors"] == []


def test_config_file_overrides(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        def proto(staged, device):
            return finish_upload(staged, device)
    """})
    (tmp_path / "fixture" / "tpu-lint.json").write_text(json.dumps({
        "check_docs": False,
        "retry_allowlist": {
            "spark_rapids_tpu/exec/x.py::proto": "fixture exemption"},
    }))
    cfg = load_config(root)
    assert cfg.check_docs is False
    assert run_lint(root, cfg).clean


# ---------------------------------------------------------------------------
# the real package is the ultimate fixture: zero findings, every
# suppression reasoned — this test IS the tier-1 lint gate
# ---------------------------------------------------------------------------

def test_real_package_is_lint_clean():
    root = default_root()
    cfg = load_config(root)
    assert cfg.check_docs  # docs-drift runs against the real docs/
    r = run_lint(root, cfg)
    assert r.internal_errors == []
    assert r.findings == [], "\n".join(
        f"{f.path}:{f.line} [{f.rule}] {f.message}" for f in r.findings)
    # the hand-audited invariants are live: suppressions exist and each
    # carried a reason (reasonless ones would be findings above)
    assert r.suppressed > 0
    assert r.files > 50


def test_cli_exit_contract(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # 0: clean repo (shells the real CLI — the CI gate invocation)
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--json"],
        capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True

    # 1: findings
    bad = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)
    """})
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", bad], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "jit-direct" in out.stdout

    # --fix-baseline flips it back to 0
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", bad, "--fix-baseline"],
        capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", bad], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr

    # 2: internal error (unparseable source)
    broken = _tree(tmp_path / "b",
                   {"spark_rapids_tpu/x.py": "def broken(:\n"})
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", broken], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 2, out.stdout + out.stderr

    # 2: zero files collected (a wrong --root must not pass the gate)
    empty = str(tmp_path / "empty")
    os.makedirs(empty, exist_ok=True)
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", empty], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "no files found" in out.stdout
