"""tpu-lint corpus (docs/linting.md): fixture-driven good/bad pairs
for every rule family, suppression + baseline semantics, JSON output
schema, the CLI exit-code contract, and the zero-findings gate over
the real package (which makes tier-1 the lint CI gate)."""

import json
import os
import subprocess
import sys
import textwrap

from spark_rapids_tpu.lint import (LintConfig, load_config, render_json,
                                   run_lint)
from spark_rapids_tpu.lint.engine import default_root, write_baseline


def _tree(tmp_path, files):
    root = tmp_path / "fixture"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    for d in ("spark_rapids_tpu", "spark_rapids_tpu/exec",
              "spark_rapids_tpu/serve"):
        if (root / d).is_dir():
            init = root / d / "__init__.py"
            if not init.exists():
                init.write_text("")
    return str(root)


def _lint(root, **over):
    cfg = LintConfig(check_docs=False, **over)
    return run_lint(root, cfg)


def _rules(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# family 1: retry coverage
# ---------------------------------------------------------------------------

def test_retry_coverage_bad_and_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from spark_rapids_tpu import retry as R

        def bad(staged, device):
            return finish_upload(staged, device)

        def good(staged, device, conf):
            return R.with_retry(lambda: finish_upload(staged, device),
                                conf)
    """})
    r = _lint(root)
    assert _rules(r) == ["retry-coverage"]
    assert len(r.findings) == 1
    assert r.findings[0].line == 4  # only the unwrapped site


def test_retry_coverage_transitive_local_closure(tmp_path):
    # with_retry re-runs the whole closure: a local def passed BY NAME
    # to the combinator covers everything it calls in-module
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from spark_rapids_tpu import retry as R

        def outer(src, conf):
            def upload_host(hb):
                return inner(hb)
            return R.with_split_retry(src, upload_host, conf)

        def inner(hb):
            return upload_batch(hb, 8)
    """})
    assert _lint(root).clean


def test_retry_coverage_allowlist_and_scope(tmp_path):
    files = {"spark_rapids_tpu/exec/x.py": """
        def proto(staged, device):
            return finish_upload(staged, device)
    """,
             # out of retry scope: same code, no finding
             "spark_rapids_tpu/sql/y.py": """
        def elsewhere(staged, device):
            return finish_upload(staged, device)
    """}
    root = _tree(tmp_path, files)
    assert _rules(_lint(root)) == ["retry-coverage"]
    allow = {"spark_rapids_tpu/exec/x.py::proto":
             "fixture protocol layer"}
    assert _lint(root, retry_allowlist=allow).clean


# ---------------------------------------------------------------------------
# family 2: compile discipline
# ---------------------------------------------------------------------------

def test_jit_direct_bad_and_routed_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax
        from spark_rapids_tpu.jit_cache import JitCache

        _C = JitCache("fixture")

        def bad(fn):
            return jax.jit(fn)

        def good(key, fn):
            got = _C.get(key)
            if got is None:
                got = _C.put(key, jax.jit(fn))
            return got

        def also_good(key):
            fn, _ = _C.get_or_build(key, lambda: _builder())
            return fn

        def _builder():
            return jax.jit(lambda x: x)
    """})
    r = _lint(root)
    assert _rules(r) == ["jit-direct"]
    assert [f.line for f in r.findings] == [7]


def test_jit_builder_resolves_across_modules(tmp_path):
    # _STAGE_CACHE.put(key, X.build_fn(...)) in one module makes the
    # jax.jit inside other_module.build_fn compliant
    root = _tree(tmp_path, {
        "spark_rapids_tpu/exec/a.py": """
            from spark_rapids_tpu.jit_cache import JitCache
            from spark_rapids_tpu.exec import b as B

            _C = JitCache("x")

            def use(key, steps):
                return _C.put(key, B.build_fn(steps))
        """,
        "spark_rapids_tpu/exec/b.py": """
            import jax

            def build_fn(steps):
                return jax.jit(lambda c: c)
        """})
    assert _lint(root).clean


def test_pallas_call_treated_like_jit(tmp_path):
    # pl.pallas_call is compile-discipline traffic exactly like
    # jax.jit: sanctioned inside the kernels/ registry package or a
    # JitCache builder closure, a finding anywhere else
    root = _tree(tmp_path, {
        "spark_rapids_tpu/exec/x.py": """
            import jax
            from jax.experimental import pallas as pl
            from spark_rapids_tpu.jit_cache import JitCache

            _C = JitCache("fixture")

            def bad(x):
                return pl.pallas_call(_k, out_shape=x)(x)

            def good(key):
                fn, _ = _C.get_or_build(key, lambda: _builder())
                return fn

            def _builder():
                return jax.jit(lambda x: pl.pallas_call(
                    _k, out_shape=None)(x))
        """,
        "spark_rapids_tpu/kernels/__init__.py": "",
        "spark_rapids_tpu/kernels/k.py": """
            from jax.experimental import pallas as pl

            def build_kernel(shape):
                # registry home: pallas_call sanctioned here
                return pl.pallas_call(_kern, out_shape=shape)
        """})
    r = _lint(root)
    assert _rules(r) == ["jit-direct"]
    assert [f.line for f in r.findings] == [8]
    assert "pl.pallas_call" in r.findings[0].message


def test_pallas_call_suppressible_with_reason(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from jax.experimental import pallas as pl

        def probe(shape):
            return pl.pallas_call(_k, out_shape=shape)  # tpu-lint: disable=jit-direct(one-shot capability probe)
    """})
    assert _lint(root).clean


def test_jit_module_cache_flags_raw_dicts(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        from collections import OrderedDict
        from spark_rapids_tpu.jit_cache import JitCache

        _BAD_CACHE = {}
        _ALSO_BAD_CACHE = OrderedDict()
        _GOOD_CACHE = JitCache("good")
        _PLAIN_TABLE = {}
    """})
    r = _lint(root)
    assert _rules(r) == ["jit-module-cache"]
    assert [f.line for f in r.findings] == [4, 5]


# ---------------------------------------------------------------------------
# family 3: concurrency
# ---------------------------------------------------------------------------

_LOCKY = """
    import threading
    import time

    class DeviceStore:
        def __init__(self):
            self._lock = threading.Lock()
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._d = {}
"""


def test_lock_order_cycle_flagged(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
    """})
    r = _lint(root)
    assert _rules(r) == ["lock-order"]
    assert "DeviceStore._a" in r.findings[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                with self._b:
                    pass
    """})
    assert _lint(root).clean


def test_lock_order_interprocedural_edge(tmp_path):
    # with A held, calling a method that takes B adds the A->B edge
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def one(self):
            with self._a:
                self.takes_b()

        def takes_b(self):
            with self._b:
                pass

        def two(self):
            with self._b:
                with self._a:
                    pass
    """})
    assert _rules(_lint(root)) == ["lock-order"]


def test_blocking_call_under_critical_lock(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": _LOCKY + """
        def bad_sleep(self):
            with self._lock:
                time.sleep(0.1)

        def bad_dispatch(self, staged):
            with self._lock:
                return finish_upload(staged)

        def good(self):
            with self._lock:
                n = 1
            time.sleep(0.1)
            return n
    """})
    r = _lint(root)
    assert _rules(r) == ["lock-blocking-call"]
    assert len(r.findings) == 2


def test_wait_on_different_lock_flagged(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/memory.py": """
        import threading

        class DeviceStore:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def bad(self):
                with self._lock:
                    self._cv.wait()

            def fine(self):
                with self._cv:
                    self._cv.wait()
    """})
    r = _lint(root)
    assert _rules(r) == ["lock-blocking-call"]
    assert len(r.findings) == 1
    assert "different lock" in r.findings[0].message


def test_check_then_act_bad_and_guarded(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/serve/s.py": """
        import threading

        class Sessions:
            def __init__(self):
                self._lock = threading.Lock()
                self._by_tenant = {}

            def racy(self, k):
                if k not in self._by_tenant:
                    self._by_tenant[k] = object()
                return self._by_tenant[k]

            def guarded(self, k):
                with self._lock:
                    if k not in self._by_tenant:
                        self._by_tenant[k] = object()
                    return self._by_tenant[k]
    """})
    r = _lint(root)
    assert _rules(r) == ["check-then-act"]
    assert len(r.findings) == 1
    assert "_by_tenant" in r.findings[0].message


# ---------------------------------------------------------------------------
# family 4: drift
# ---------------------------------------------------------------------------

def test_metric_key_rule(tmp_path):
    root = _tree(tmp_path, {
        "spark_rapids_tpu/metrics.py": """
            OP_TIME = "opTime"
            ROGUE = "notDescribedConstant"
            METRIC_DESCRIPTIONS = {
                OP_TIME: "operator wall",
                "goodKey": "described",
            }
            METRIC_PREFIX_DESCRIPTIONS = {"perChip.": "per chip <N>"}
        """,
        "spark_rapids_tpu/exec/x.py": """
            from spark_rapids_tpu import metrics as M

            def use(metrics):
                metrics.create("goodKey").add(1)
                metrics.create(M.OP_TIME).add(1)
                metrics.create("perChip.3").add(1)
                metrics.create("rogueLiteral").add(1)
                metrics.create(dynamic_key()).add(1)  # invisible: ok
        """})
    r = _lint(root)
    assert _rules(r) == ["metric-key"]
    msgs = " ".join(f.message for f in r.findings)
    assert "notDescribedConstant" in msgs  # constant direction
    assert "rogueLiteral" in msgs          # call-site direction
    assert len(r.findings) == 2


def test_conf_key_rule(tmp_path):
    root = _tree(tmp_path, {
        "spark_rapids_tpu/conf.py": """
            def conf(key):
                return key

            conf("spark.rapids.sql.fixture.enabled")
        """,
        "spark_rapids_tpu/exec/x.py": """
            GOOD = "spark.rapids.sql.fixture.enabled"
            BAD = "spark.rapids.sql.fixture.typo"
            PREFIX = "spark.rapids.sql.fixture."  # namespace match: ok
        """})
    r = _lint(root)
    assert _rules(r) == ["conf-key"]
    assert len(r.findings) == 1
    assert "typo" in r.findings[0].message


def test_span_scope_rule(tmp_path):
    root = _tree(tmp_path, {
        "spark_rapids_tpu/trace.py": "def span(*a, **k): pass\n",
        "spark_rapids_tpu/exec/x.py": """
            from spark_rapids_tpu import trace as _trace

            def use():
                _trace.span("leaky")
                with _trace.span("fine"):
                    pass
        """})
    r = _lint(root)
    assert _rules(r) == ["span-scope"]
    assert [f.line for f in r.findings] == [4]


def test_generated_doc_content_carries_drift_tables():
    """The content direction of the retired runtime drift tests:
    docs-drift proves docs == generator output byte-for-byte; this
    proves the GENERATOR still emits the metric description table and
    the conf/profile sections (otherwise regenerating stale docs could
    silently drop them both)."""
    import spark_rapids_tpu.profile  # noqa: F401 — registers confs
    import spark_rapids_tpu.trace  # noqa: F401 — registers confs
    from spark_rapids_tpu import metrics as M
    from spark_rapids_tpu.tools import generate_observability_docs
    doc = generate_observability_docs()
    for name in M.METRIC_DESCRIPTIONS:
        assert name in doc, name
    for key in ("spark.rapids.sql.profile.enabled",
                "spark.rapids.sql.profile.dir",
                "spark.rapids.sql.explain",
                "spark.rapids.sql.trace.enabled"):
        assert key in doc, key
    assert "Reading a query profile" in doc
    assert "Explain / fallback reasons" in doc


# ---------------------------------------------------------------------------
# family 5: cancellation discipline
# ---------------------------------------------------------------------------

def test_cancel_checkpoint_bad_and_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/serve/w.py": """
        import threading
        import time

        _CV = threading.Condition()

        def bad_wait():
            with _CV:
                _CV.wait()

        def bad_sleep():
            time.sleep(0.5)

        def bad_queue_get(q):
            return q.get()

        def bad_explicit_blocking_get(q):
            return q.get(block=True)

        def good_bounded_wait():
            with _CV:
                _CV.wait(timeout=0.05)

        def good_positional_wait(ev):
            ev.wait(0.05)

        def good_queue_get(q):
            return q.get(timeout=0.1)

        def good_nonblocking_get(q):
            return q.get(block=False)

        def fine_dict_get(d, k):
            return d.get(k)
    """})
    r = _lint(root)
    assert _rules(r) == ["cancel-checkpoint"]
    assert len(r.findings) == 4
    msgs = " | ".join(f.message for f in r.findings)
    assert "time.sleep" in msgs
    assert "unbounded .wait()" in msgs
    assert "blocking queue .get()" in msgs


def test_cancel_checkpoint_none_timeout_and_scope(tmp_path):
    files = {
        # timeout=None is NOT bounded
        "spark_rapids_tpu/jit_cache.py": """
        def bad(ev):
            ev.wait(timeout=None)
    """,
        # same primitives OUTSIDE the lifecycle-critical scope: clean
        "spark_rapids_tpu/exec/y.py": """
        import time

        def elsewhere(ev, q):
            time.sleep(0.5)
            ev.wait()
            return q.get()
    """}
    root = _tree(tmp_path, files)
    r = _lint(root)
    assert _rules(r) == ["cancel-checkpoint"]
    assert len(r.findings) == 1
    assert r.findings[0].path == "spark_rapids_tpu/jit_cache.py"


# ---------------------------------------------------------------------------
# family 6: interprocedural data-flow (tpu-lint v2)
# ---------------------------------------------------------------------------

def _of(result, rule):
    return [f for f in result.findings if f.rule == rule]


def test_donation_safety_direct_bad_and_good(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        _F = jax.jit(lambda a: a, donate_argnums=(0,))

        def bad(x):
            y = _F(x)
            return x.shape  # read after donate

        def good(x):
            n = x.shape  # staged BEFORE the donating dispatch
            y = _F(x)
            return y, n

        def rebound(x):
            y = _F(x)
            x = y
            return x.shape  # rebinding kills the flag

        def canonical(x):
            x = _F(x)  # rebound IN the donating statement
            return x.shape  # reads the program's output: clean

        def canonical_loop(batches, acc):
            for b in batches:
                use(acc)
                acc = _F(acc)  # same-statement rebind: clean
    """})
    r = _lint(root)
    bad = _of(r, "donation-safety")
    assert [f.line for f in bad] == [7]
    assert "`x` is read after being donated" in bad[0].message


def test_donation_safety_through_helper_one_level(tmp_path):
    # the helper donates ITS positional parameter; the caller's read
    # after the helper call is the finding (one call level deep)
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        _F = jax.jit(lambda a: a, donate_argnums=(0,))

        def helper(buf):
            return _F(buf)

        def caller(x):
            out = helper(x)
            return x.shape  # flagged: x was donated one call down
    """})
    r = _lint(root)
    bad = _of(r, "donation-safety")
    assert [f.line for f in bad] == [10]
    assert "helper" in bad[0].message


def test_donation_safety_resolves_jitcache_builder(tmp_path):
    # the real package's shape: fn, miss = CACHE.get_or_build(key,
    # lambda: build(...)) where build returns a MAY-donating jit
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax
        from spark_rapids_tpu.jit_cache import JitCache

        _C = JitCache("fixture")

        def build(donate):
            def fn(a, b):
                return a
            return jax.jit(fn, donate_argnums=(0, 1) if donate else ())

        def run(b, lits):
            fn, miss = _C.get_or_build("k", lambda: build(True))
            cols, act = fn(b.columns, b.active)
            return b.rows  # read after the donating dispatch

        def run_ok(b, lits):
            fn, miss = _C.get_or_build("k", lambda: build(True))
            rows = b.rows  # staged before
            cols, act = fn(b.columns, b.active)
            return rows
    """})
    r = _lint(root)
    bad = _of(r, "donation-safety")
    assert [f.line for f in bad] == [14]


def test_donation_safety_loop_back_edge(tmp_path):
    # the read PRECEDES the call in source but follows it on the loop's
    # back edge; the for target rebinds, so only the un-rebound name
    # (the accumulator) is flagged
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        _F = jax.jit(lambda a: a, donate_argnums=(0,))

        def bad(batches, acc):
            for b in batches:
                use(acc)  # next iteration reads the donated acc
                _F(acc)

        def good(batches):
            for b in batches:
                use(b)
                _F(b)  # b rebinds at the loop head: clean
    """})
    r = _lint(root)
    bad = _of(r, "donation-safety")
    assert [f.line for f in bad] == [7]
    assert "`acc`" in bad[0].message


def test_hidden_sync_tainted_flagged_host_value_not(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import numpy as np
        import jax.numpy as jnp

        def bad(col):
            s = jnp.sum(col)
            return s.item()  # device scalar forced on the hot path

        def bad2(col):
            s = jnp.sum(col)
            return float(np.asarray(s))  # one finding: the asarray

        def fine(host_list):
            a = np.asarray(host_list)  # NOT a device value
            return int(a[0])

        def kwargs_only(rows):
            return np.array(object=rows)  # no positional arg: no crash

        def outer(col):
            s = jnp.sum(col)

            def cb(s):
                return float(s)  # SHADOWED host param: not the device s
            return cb
    """})
    r = _lint(root)
    bad = _of(r, "hidden-sync")
    assert [f.line for f in bad] == [6, 10]
    assert ".item()" in bad[0].message


def test_hidden_sync_scope_and_allowlist(tmp_path):
    files = {"spark_rapids_tpu/exec/x.py": """
        import jax.numpy as jnp

        def drain(col):
            s = jnp.sum(col)
            return int(s)
    """,
             # identical code OUTSIDE the hot-path scopes: clean
             "spark_rapids_tpu/sql/y.py": """
        import jax.numpy as jnp

        def elsewhere(col):
            s = jnp.sum(col)
            return int(s)
    """}
    root = _tree(tmp_path, files)
    r = _lint(root)
    assert [(f.path, f.line) for f in _of(r, "hidden-sync")] == \
        [("spark_rapids_tpu/exec/x.py", 5)]
    allow = {"spark_rapids_tpu/exec/x.py::drain":
             "fixture sanctioned drain point"}
    assert not _of(_lint(root, sync_allowlist=allow), "hidden-sync")


def test_handle_leak_bad_and_escapes(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        def leak(staged, device):
            tok = start_upload(staged, device)  # never finished
            return None

        def dropped(staged, device):
            start_upload(staged, device)  # result dropped

        def tracked(store, b, out):
            h = store.register(b)
            out.append(h)  # escapes to the tracked container: fine

        def closed(store, b):
            h = store.register(b)
            try:
                return h.get()
            finally:
                h.close()

        def returned(store, b):
            return store.register(b)

        def except_only(store, b):
            h = store.register(b)
            try:
                return compute(h.get())
            except Exception:
                h.close()  # success path still leaks
                raise
    """})
    r = _lint(root)
    bad = _of(r, "handle-leak")
    assert [f.line for f in bad] == [2, 6, 23]
    assert "never closed" in bad[0].message
    assert "result dropped" in bad[1].message
    assert "exception path" in bad[2].message


def test_trace_purity_two_calls_deep(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import time

        import jax

        _REG = {}

        def build():
            return jax.jit(_traced)

        def _traced(x):
            return _helper(x)

        def _helper(x):
            t = time.time()  # host clock two calls below the builder
            _REG["k"] = t    # module-state mutation
            return x
    """})
    r = _lint(root)
    bad = _of(r, "trace-purity")
    assert [f.line for f in bad] == [14, 15]
    assert "host clock" in bad[0].message
    assert "mutates free state" in bad[1].message


def test_trace_purity_conf_read_and_pure_twin(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def build(conf):
            limit = conf.get("k")  # snapshotted OUTSIDE the trace: ok
            return jax.jit(lambda x: _traced(x, limit))

        def _traced(x, limit):
            return x + limit

        def build_bad(conf):
            def fn(x):
                return x + conf.get("k")  # read AT TRACE TIME
            return jax.jit(fn)
    """})
    r = _lint(root)
    bad = _of(r, "trace-purity")
    assert [f.line for f in bad] == [12]
    assert "dynamic conf read" in bad[0].message


def test_trace_purity_cross_module_from_import(tmp_path):
    # `from mod import helper` flows must resolve across files: the
    # impurity sits one from-imported call below the traced root
    root = _tree(tmp_path, {
        "spark_rapids_tpu/exec/a.py": """
            import jax
            from spark_rapids_tpu.exec.b import helper

            def build():
                return jax.jit(_traced)

            def _traced(x):
                return helper(x)
        """,
        "spark_rapids_tpu/exec/b.py": """
            import time

            def helper(x):
                return x + time.time()
        """})
    bad = _of(_lint(root), "trace-purity")
    assert [(f.path, f.line) for f in bad] == \
        [("spark_rapids_tpu/exec/b.py", 4)]


def test_donation_attribute_receiver_no_name_collision(tmp_path):
    # `obj.dispatch(...)` must NOT resolve to an unrelated same-file
    # donating `def dispatch` — only self/cls receivers match in-file
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        _F = jax.jit(lambda a: a, donate_argnums=(0,))

        def dispatch(buf):
            return _F(buf)

        def unrelated(obj, y):
            obj.dispatch(y)
            return y.shape  # obj.dispatch is NOT the donating helper

        class C:
            def dispatch(self, buf):
                return _F(buf)

            def caller(self, z):
                self.dispatch(z)
                return z.shape  # self.dispatch IS: flagged
    """})
    bad = _of(_lint(root), "donation-safety")
    assert [f.line for f in bad] == [18]


def test_trace_purity_closure_accumulator_is_pure(tmp_path):
    # per-trace bookkeeping (the decode programs' lazy byte memo, the
    # lane planners' append) binds in an ENCLOSING function — that is
    # deterministic trace-local state, not cross-trace impurity
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def build():
            def fn(x):
                lanes = []
                memo = None

                def add(v):
                    nonlocal memo
                    lanes.append(v)
                    memo = v
                    return memo
                return add(x)
            return jax.jit(fn)
    """})
    assert not _of(_lint(root), "trace-purity")


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, JSON schema
# ---------------------------------------------------------------------------

def test_suppression_requires_reason(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(fixture program, bounded)

        def b(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct
    """})
    r = _lint(root)
    # the reasoned suppression holds; the reasonless one does NOT
    # suppress and is itself a finding
    assert r.suppressed == 1
    assert _rules(r) == ["bad-suppression", "jit-direct"]
    bad = [f for f in r.findings if f.rule == "jit-direct"]
    assert [f.line for f in bad] == [7]


def test_malformed_suppression_lists_fail_closed(tmp_path):
    # parens inside a reason / prose after the list must fail the
    # WHOLE comment (nothing suppressed, one bad-suppression), never
    # register fragments of free text as rules
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(probe (one-shot) cap)

        def b(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(why) see docs/linting.md

        def c(fn):
            return jax.jit(fn)  # tpu-lint: disable=jit-direct(ok reason), span-scope(also fine)
    """})
    r = _lint(root)
    assert r.suppressed == 1  # only c's well-formed multi-item list
    rules = sorted(f.rule for f in r.findings)
    assert rules.count("jit-direct") == 2  # a and b stay findings
    assert rules.count("bad-suppression") == 2


def test_standalone_suppression_covers_next_line(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        # tpu-lint: disable=jit-direct(fixture program, bounded)
        _FN = jax.jit(lambda x: x)
    """})
    r = _lint(root)
    assert r.clean and r.suppressed == 1


def test_baseline_semantics_and_fix_baseline(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)
    """})
    cfg = LintConfig(check_docs=False)
    r = run_lint(root, cfg)
    assert len(r.findings) == 1 and r.baselined == 0
    # --fix-baseline captures current findings as accepted debt
    path = write_baseline(root, cfg, r.findings, r.pctx)
    data = json.load(open(path))
    assert data["version"] == 1 and len(data["findings"]) == 1
    assert data["findings"][0]["rule"] == "jit-direct"
    r2 = run_lint(root, cfg)
    assert r2.clean and r2.baselined == 1
    # baseline is line-TEXT keyed: edits above the site don't churn it
    p = os.path.join(root, "spark_rapids_tpu/exec/x.py")
    src = open(p).read()
    open(p, "w").write("import os  # shift lines\n" + src)
    r3 = run_lint(root, cfg)
    assert r3.clean and r3.baselined == 1
    # re-capturing with a NEW finding present must keep the still-live
    # old debt (what run_cli --fix-baseline writes), not drop it
    # (distinct line text: identical lines share a fingerprint by
    # design, like any text-keyed baseline)
    open(p, "a").write(
        "\n\ndef c(fn):\n    return jax.jit(fn, static_argnums=0)\n")
    r4 = run_lint(root, cfg)
    assert len(r4.findings) == 1 and r4.baselined == 1
    write_baseline(root, cfg, r4.findings + r4.baselined_findings,
                   r4.pctx)
    data = json.load(open(path))
    assert len(data["findings"]) == 2
    r5 = run_lint(root, cfg)
    assert r5.clean and r5.baselined == 2


def test_json_output_schema(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)
    """})
    r = run_lint(root, LintConfig(check_docs=False))
    out = json.loads(render_json(r, r.pctx))
    assert out["version"] == 1
    assert out["clean"] is False
    assert set(out["counts"]) == {"findings", "suppressed", "baselined",
                                  "files"}
    f = out["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message",
                      "fingerprint"}
    assert f["rule"] == "jit-direct"
    assert "jit-direct" in out["rules"]
    assert out["internalErrors"] == []


def test_config_file_overrides(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        def proto(staged, device):
            return finish_upload(staged, device)
    """})
    (tmp_path / "fixture" / "tpu-lint.json").write_text(json.dumps({
        "check_docs": False,
        "retry_allowlist": {
            "spark_rapids_tpu/exec/x.py::proto": "fixture exemption"},
    }))
    cfg = load_config(root)
    assert cfg.check_docs is False
    assert run_lint(root, cfg).clean


# ---------------------------------------------------------------------------
# engine v2: timings + budget, github format, changed-only, stale
# baseline pruning
# ---------------------------------------------------------------------------

_BAD_JIT = """
    import jax

    def a(fn):
        return jax.jit(fn)
"""


def test_json_timings_and_budget_exit(tmp_path, capsys):
    from spark_rapids_tpu.lint import run_cli
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": _BAD_JIT})
    (tmp_path / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False}))
    assert run_cli(root=root, as_json=True) == 1
    out = json.loads(capsys.readouterr().out)
    t = out["timings"]
    assert t["totalSeconds"] >= 0 and t["budgetSeconds"] == 60.0
    assert set(t["perRule"]) == set(out["rules"])
    assert all(v >= 0 for v in t["perRule"].values())
    # a --time-budget override must show up in the JSON it judges by
    assert run_cli(root=root, as_json=True, time_budget=45.0) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["timings"]["budgetSeconds"] == 45.0
    # an unaffordable run fails the gate even when findings-free:
    # exit 2, not a quietly slower tier-1
    clean = _tree(tmp_path / "c",
                  {"spark_rapids_tpu/exec/x.py": "X = 1\n"})
    ((tmp_path / "c") / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False}))
    assert run_cli(root=clean) == 0
    capsys.readouterr()
    assert run_cli(root=clean, time_budget=1e-9) == 2
    # the breach goes to STDERR so --json stdout stays parseable
    captured = capsys.readouterr()
    assert "exceeded" in captured.err and "exceeded" not in captured.out


def test_time_budget_config_override(tmp_path, capsys):
    from spark_rapids_tpu.lint import run_cli
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": "X = 1\n"})
    (tmp_path / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False, "time_budget_s": 1e-9}))
    assert run_cli(root=root) == 2
    assert "exceeded" in capsys.readouterr().err


def test_github_format_annotations(tmp_path, capsys):
    from spark_rapids_tpu.lint import run_cli
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": _BAD_JIT})
    (tmp_path / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False}))
    assert run_cli(root=root, fmt="github") == 1
    out = capsys.readouterr().out
    assert ("::error file=spark_rapids_tpu/exec/x.py,line=4,col=12,"
            "title=tpu-lint jit-direct::") in out
    # the whole annotation (message included) stays on ONE line — a
    # raw newline would truncate the workflow command
    err_lines = [ln for ln in out.splitlines()
                 if ln.startswith("::error")]
    assert len(err_lines) == 1 and "jit-direct" in err_lines[0]


def test_changed_only_filters_to_git_diff(tmp_path, capsys):
    from spark_rapids_tpu.lint import run_cli
    root = _tree(tmp_path, {
        "spark_rapids_tpu/exec/old.py": _BAD_JIT,
        "spark_rapids_tpu/exec/new.py": _BAD_JIT,
    })
    (tmp_path / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False}))
    git = ["git", "-C", root, "-c", "user.email=t@t",
           "-c", "user.name=t"]
    subprocess.run(git + ["init", "-q"], check=True)
    subprocess.run(git + ["add", "spark_rapids_tpu/exec/old.py"],
                   check=True)
    subprocess.run(git + ["commit", "-qm", "seed"], check=True)
    # full run sees both files' findings; --changed-only only the
    # untracked one (old.py is committed and unchanged vs HEAD)
    assert run_cli(root=root) == 1
    full = capsys.readouterr().out
    assert "old.py" in full and "new.py" in full
    assert run_cli(root=root, changed_only="HEAD") == 1
    changed = capsys.readouterr().out
    assert "new.py" in changed and "old.py:" not in changed
    # a bad base ref must not silently lint nothing
    assert run_cli(root=root, changed_only="no-such-ref") == 2


def test_changed_only_nested_root(tmp_path, capsys):
    # git toplevel ABOVE the lint root: `git diff` emits toplevel-
    # relative paths ("fixture/...") that must re-base onto the root,
    # or the incremental mode silently passes bad code
    from spark_rapids_tpu.lint import run_cli
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/old.py": _BAD_JIT})
    (tmp_path / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False}))
    git = ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
           "-c", "user.name=t"]
    subprocess.run(git + ["init", "-q"], check=True)
    subprocess.run(git + ["add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "seed"], check=True)
    p = os.path.join(root, "spark_rapids_tpu/exec/old.py")
    open(p, "a").write(
        "\n\ndef b(fn):\n    return jax.jit(fn, static_argnums=0)\n")
    assert run_cli(root=root, changed_only="HEAD") == 1
    assert "old.py" in capsys.readouterr().out


def test_stale_baseline_reported_and_pruned(tmp_path, capsys):
    from spark_rapids_tpu.lint import run_cli
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": _BAD_JIT})
    cfg = LintConfig(check_docs=False)
    r = run_lint(root, cfg)
    write_baseline(root, cfg, r.findings, r.pctx)
    # fix the violation: the baseline entry goes stale but the run
    # stays CLEAN (informational note, exit 0)
    p = os.path.join(root, "spark_rapids_tpu/exec/x.py")
    open(p, "w").write("def a(fn):\n    return fn\n")
    r2 = run_lint(root, cfg)
    assert r2.clean and r2.baselined == 0
    assert [e["rule"] for e in r2.stale_baseline] == ["jit-direct"]
    out = json.loads(render_json(r2, r2.pctx))
    assert out["clean"] is True
    assert out["staleBaseline"][0]["rule"] == "jit-direct"
    # --fix-baseline prunes the dead entry and says so
    (tmp_path / "fixture" / "tpu-lint.json").write_text(
        json.dumps({"check_docs": False}))
    assert run_cli(root=root, fix_baseline=True) == 0
    assert "1 stale entry pruned" in capsys.readouterr().out
    path = os.path.join(root, cfg.baseline)
    assert json.load(open(path))["findings"] == []
    assert run_lint(root, cfg).clean


def test_fix_baseline_no_churn_when_unchanged(tmp_path):
    # same accepted-debt SET (text-keyed fingerprints) -> the file is
    # left byte-identical even though line numbers shifted
    root = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": _BAD_JIT})
    cfg = LintConfig(check_docs=False)
    r = run_lint(root, cfg)
    path = write_baseline(root, cfg, r.findings, r.pctx)
    before = open(path).read()
    p = os.path.join(root, "spark_rapids_tpu/exec/x.py")
    src = open(p).read()
    open(p, "w").write("import os  # shift\n" + src)
    r2 = run_lint(root, cfg)
    assert r2.clean and r2.baselined == 1 and not r2.stale_baseline
    write_baseline(root, cfg,
                   r2.findings + r2.baselined_findings, r2.pctx)
    assert open(path).read() == before


# ---------------------------------------------------------------------------
# the real package is the ultimate fixture: zero findings, every
# suppression reasoned — this test IS the tier-1 lint gate
# ---------------------------------------------------------------------------

def test_real_package_is_lint_clean():
    root = default_root()
    cfg = load_config(root)
    assert cfg.check_docs  # docs-drift runs against the real docs/
    r = run_lint(root, cfg)
    assert r.internal_errors == []
    assert r.findings == [], "\n".join(
        f"{f.path}:{f.line} [{f.rule}] {f.message}" for f in r.findings)
    # the hand-audited invariants are live: suppressions exist and each
    # carried a reason (reasonless ones would be findings above)
    assert r.suppressed > 0
    assert r.files > 50


def test_cli_exit_contract(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # 0: clean repo (shells the real CLI — the CI gate invocation)
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--json"],
        capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True

    # 1: findings
    bad = _tree(tmp_path, {"spark_rapids_tpu/exec/x.py": """
        import jax

        def a(fn):
            return jax.jit(fn)
    """})
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", bad], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "jit-direct" in out.stdout

    # --fix-baseline flips it back to 0
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", bad, "--fix-baseline"],
        capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", bad], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr

    # 2: internal error (unparseable source)
    broken = _tree(tmp_path / "b",
                   {"spark_rapids_tpu/x.py": "def broken(:\n"})
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", broken], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 2, out.stdout + out.stderr

    # 2: zero files collected (a wrong --root must not pass the gate)
    empty = str(tmp_path / "empty")
    os.makedirs(empty, exist_ok=True)
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "lint",
         "--root", empty], capture_output=True, text=True, env=env,
        cwd=default_root(), timeout=300)
    assert out.returncode == 2, out.stdout + out.stderr
    assert "no files found" in out.stdout
