"""Inexact-backend gating tests.

CI runs on a CPU mesh where every device_caps probe returns True, so the
fallback branches added for TPU f64 emulation would otherwise be dead in
the suite (code-review round 2 finding). These tests monkeypatch the
probes to False to exercise the exact behavior measured on TPU v5
hardware: f64 arithmetic and float division/transcendentals diverge,
int64 stays exact.
"""

import pytest

from spark_rapids_tpu import device_caps
from spark_rapids_tpu.sql import functions as F

from tests.datagen import DoubleGen, IntegerGen, SmallIntGen, gen_batch
from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)


@pytest.fixture
def inexact_backend(monkeypatch):
    monkeypatch.setattr(device_caps, "f64_arith_exact", lambda: False)
    monkeypatch.setattr(device_caps, "float_div_exact", lambda: False)


def _df(spark, gens, n=256):
    return spark.createDataFrame(gen_batch(gens, n), num_partitions=2)


def test_double_arith_falls_back(inexact_backend):
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("a", DoubleGen()), ("b", DoubleGen())])
        .select((F.col("a") + F.col("b")).alias("x")),
        fallback_exec="CpuProjectExec")


def test_double_arith_incompat_opt_in(inexact_backend):
    # incompatibleOps un-gates float arithmetic; results still match here
    # because the *test* backend is the exact CPU mesh — we assert
    # placement, which is what the knob controls
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", DoubleGen()), ("b", DoubleGen())])
        .select((F.col("a") + F.col("b")).alias("x")),
        conf={"spark.rapids.sql.incompatibleOps.enabled": "true"},
        expect_execs=["TpuProject"])


def test_int_arith_unaffected(inexact_backend):
    # int64 is exact on TPU: no gate
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", IntegerGen()), ("b", IntegerGen())])
        .select((F.col("a") * F.col("b")).alias("x")),
        expect_execs=["TpuProject"])


def test_f32_add_unaffected_f64_gated(inexact_backend, monkeypatch):
    # f32 add/mul are native on TPU — only the f64 probe failing must not
    # gate them (FloatGen arithmetic promotes per Spark rules to float)
    from tests.datagen import FloatGen
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", FloatGen(special=False))])
        .select((F.col("a") + F.col("a")).alias("x")),
        expect_execs=["TpuProject"])


def test_avg_int_falls_back(inexact_backend):
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", IntegerGen())])
        .groupBy("k").agg(F.avg("v").alias("a")),
        fallback_exec="CpuHashAggregateExec")


def test_avg_variable_float_agg_opt_in(inexact_backend):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", IntegerGen())])
        .groupBy("k").agg(F.avg("v").alias("a")),
        conf={"spark.rapids.sql.variableFloatAgg.enabled": "true"},
        expect_execs=["TpuHashAggregate"])


def test_int_agg_unaffected(inexact_backend):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", IntegerGen())])
        .groupBy("k").agg(F.sum("v").alias("s"), F.count("v").alias("c"),
                          F.min("v").alias("mn"), F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate"])


def test_float_min_max_unaffected(inexact_backend):
    # min/max pick winning rows by total-order bits: exact on any backend
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("k", SmallIntGen()), ("v", DoubleGen())])
        .groupBy("k").agg(F.min("v").alias("mn"), F.max("v").alias("mx")),
        expect_execs=["TpuHashAggregate"])


def test_float_compare_filter_unaffected(inexact_backend):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", DoubleGen())]).filter(F.col("a") > 0.5),
        expect_execs=["TpuFilter"])
