"""Round-3 expression breadth: bitwise, extra math, extra strings, extra
datetime, xxhash64 — device parity vs the CPU engine through the dual
harness (cast_test.py / string_test.py / date_time_test.py roles in the
reference's integration suite)."""

import numpy as np
import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T

from tests.datagen import (DateGen, DoubleGen, IntegerGen, LongGen,
                           SmallIntGen, StringGen, TimestampGen, gen_batch)
from tests.harness import assert_tpu_and_cpu_equal_collect

INCOMPAT = {"spark.rapids.sql.incompatibleOps.enabled": "true"}


def _df(s, cols, n=200, seed=7, parts=2):
    return s.createDataFrame(gen_batch(cols, n, seed), num_partitions=parts)


# -- bitwise ---------------------------------------------------------------

def test_bitwise_and_or_xor_not():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", LongGen()), ("b", LongGen()),
                          ("i", IntegerGen())])
        .select((F.col("a").bitwiseAND(F.col("b"))).alias("x"),
                (F.col("a").bitwiseOR(F.col("b"))).alias("y"),
                (F.col("a").bitwiseXOR(F.col("b"))).alias("z"),
                F.bitwise_not(F.col("i")).alias("n")),
        expect_execs=["TpuProject"])


@pytest.mark.parametrize("fn", [F.shiftleft, F.shiftright,
                                F.shiftrightunsigned])
def test_shifts(fn):
    def q(s):
        df = _df(s, [("a", LongGen()), ("i", IntegerGen()),
                     ("n", SmallIntGen())])
        return df.select(fn(F.col("a"), F.col("n")).alias("l"),
                         fn(F.col("i"), F.col("n")).alias("j"),
                         fn(F.col("a"), 65).alias("big"),
                         fn(F.col("a"), -1).alias("neg"))
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuProject"])


def test_greatest_least():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", LongGen(nullable=True)),
                          ("b", LongGen(nullable=True)),
                          ("c", LongGen(nullable=True))])
        .select(F.greatest("a", "b", "c").alias("g"),
                F.least("a", "b", "c").alias("l")),
        expect_execs=["TpuProject"])


def test_greatest_least_float_nan():
    def q(s):
        from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
        vals_a = [1.0, np.nan, None, -0.0, np.inf]
        vals_b = [2.0, 5.0, None, 0.0, np.nan]
        batch = HostBatch(
            T.StructType([T.StructField("a", T.DoubleT),
                          T.StructField("b", T.DoubleT)]),
            [HostColumn.from_pylist(vals_a, T.DoubleT),
             HostColumn.from_pylist(vals_b, T.DoubleT)], 5)
        return s.createDataFrame(batch).select(
            F.greatest("a", "b").alias("g"), F.least("a", "b").alias("l"))
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuProject"])


# -- math ------------------------------------------------------------------

def test_extra_math_unary():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DoubleGen())])
        .select(F.log2(F.abs(F.col("d")) + 1).alias("l2"),
                F.log1p(F.abs(F.col("d"))).alias("l1p"),
                F.expm1(F.col("d") / 1e300).alias("em1"),
                F.cbrt(F.col("d")).alias("cb"),
                F.rint(F.col("d")).alias("ri"),
                F.degrees(F.col("d")).alias("dg"),
                F.radians(F.col("d")).alias("rd")),
        approx=True, expect_execs=["TpuProject"])


def test_atan2_hypot():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", DoubleGen()), ("b", DoubleGen())])
        .select(F.atan2("a", "b").alias("at"),
                F.hypot("a", "b").alias("hy")),
        approx=True, expect_execs=["TpuProject"])


# -- strings ---------------------------------------------------------------

ASCII_GEN = StringGen(nullable=True)


def test_concat_ws():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", ASCII_GEN), ("b", ASCII_GEN),
                          ("c", ASCII_GEN)])
        .select(F.concat_ws("-", "a", "b", "c").alias("x"),
                F.concat_ws("", "a", "b").alias("y")),
        expect_execs=["TpuProject"])


def test_repeat_lpad_rpad():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", ASCII_GEN)])
        .select(F.repeat(F.col("a"), 3).alias("r3"),
                F.repeat(F.col("a"), 0).alias("r0"),
                F.lpad(F.col("a"), 8, "xy").alias("lp"),
                F.rpad(F.col("a"), 8, "xy").alias("rp"),
                F.lpad(F.col("a"), 2, "").alias("lpe"),
                F.rpad(F.col("a"), 0, "z").alias("rp0")),
        conf=INCOMPAT, expect_execs=["TpuProject"])


def test_translate_replace():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", ASCII_GEN)])
        .select(F.translate(F.col("a"), "abc", "XY").alias("tr"),
                F.replace(F.col("a"), "a", "zz").alias("rp"),
                F.replace(F.col("a"), "ab", "").alias("del"),
                F.replace(F.col("a"), "", "q").alias("noop")),
        expect_execs=["TpuProject"])


def test_translate_duplicate_matching_chars():
    """First occurrence of a duplicated char in `matching` wins
    (Spark/Hive semantics) on both engines: translate('aab','aba','xyz')
    must be 'xxy' everywhere."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", ASCII_GEN)])
        .select(F.translate(F.col("a"), "aba", "xyz").alias("tr"),
                F.translate(F.col("a"), "aa", "xy").alias("tr2")),
        expect_execs=["TpuProject"])


def test_instr_locate():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", ASCII_GEN)])
        .select(F.instr(F.col("a"), "a").alias("i1"),
                F.instr(F.col("a"), "").alias("ie"),
                F.locate("b", F.col("a")).alias("l1"),
                F.locate("b", F.col("a"), 2).alias("l2"),
                F.locate("b", F.col("a"), 0).alias("l0")),
        conf=INCOMPAT, expect_execs=["TpuProject"])


def test_initcap_reverse_trims_ascii_chr():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", StringGen(nullable=True)),
                          ("n", IntegerGen())])
        .select(F.initcap(F.col("a")).alias("ic"),
                F.reverse(F.col("a")).alias("rv"),
                F.ltrim(F.col("a")).alias("lt"),
                F.rtrim(F.col("a")).alias("rt"),
                F.ascii(F.col("a")).alias("as_"),
                F.chr(F.col("n")).alias("ch")),
        conf=INCOMPAT, expect_execs=["TpuProject"])


def test_string_funcs_via_sql():
    def q(s):
        _df(s, [("a", ASCII_GEN), ("n", SmallIntGen())]) \
            .createOrReplaceTempView("t")
        return s.sql(
            "SELECT concat_ws(':', a, a) AS cw, repeat(a, 2) AS rp, "
            "lpad(a, 6, '.') AS lp, translate(a, 'xyz', 'XY') AS tr, "
            "instr(a, 'e') AS i, initcap(a) AS ic, reverse(a) AS rv, "
            "ascii(a) AS asc, chr(n) AS ch, ltrim(a) AS lt FROM t")
    assert_tpu_and_cpu_equal_collect(q, conf=INCOMPAT,
                                     expect_execs=["TpuProject"])


# -- datetime --------------------------------------------------------------

def test_extra_date_fields():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DateGen(nullable=True))])
        .select(F.quarter("d").alias("q"),
                F.dayofweek("d").alias("dw"),
                F.weekday("d").alias("wd"),
                F.dayofyear("d").alias("dy"),
                F.weekofyear("d").alias("wy"),
                F.last_day("d").alias("ld")),
        expect_execs=["TpuProject"])


def test_add_months_trunc():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DateGen(nullable=True)),
                          ("n", SmallIntGen())])
        .select(F.add_months("d", F.col("n")).alias("am"),
                F.add_months("d", 1).alias("am1"),
                F.trunc("d", "year").alias("ty"),
                F.trunc("d", "month").alias("tm"),
                F.trunc("d", "quarter").alias("tq"),
                F.trunc("d", "week").alias("tw"),
                F.trunc("d", "bogus").alias("tb")),
        expect_execs=["TpuProject"])


def test_months_between():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d1", DateGen(nullable=True)),
                          ("d2", DateGen())])
        .select(F.months_between("d1", "d2").alias("mb")),
        conf=INCOMPAT, approx=True, expect_execs=["TpuProject"])


def test_date_format_roundtrip():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DateGen(nullable=True)),
                          ("ts", TimestampGen(nullable=True))])
        .select(F.date_format("d", "yyyy-MM-dd").alias("fd"),
                F.date_format("ts", "yyyy-MM-dd HH:mm:ss").alias("ft"),
                F.date_format("ts", "dd/MM/yyyy").alias("fr")),
        expect_execs=["TpuProject"])


def test_unix_timestamp_family():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DateGen(nullable=True)),
                          ("ts", TimestampGen(nullable=True)),
                          ("n", IntegerGen())])
        .select(F.unix_timestamp(F.col("ts")).alias("ut"),
                F.unix_timestamp(F.col("d")).alias("ud"),
                F.from_unixtime(F.col("n")).alias("fu")),
        expect_execs=["TpuProject"])


def test_to_date_to_timestamp_parse():
    def q(s):
        df = _df(s, [("d", DateGen(nullable=True))])
        str_df = df.select(
            F.date_format("d", "yyyy-MM-dd").alias("sd"))
        return str_df.select(
            F.to_date(F.col("sd"), "yyyy-MM-dd").alias("pd"),
            F.to_timestamp(F.col("sd"), "yyyy-MM-dd").alias("pt"),
            F.to_date(F.concat(F.col("sd"), F.lit("x")),
                      "yyyy-MM-dd").alias("bad"))
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuProject"])


# -- hash ------------------------------------------------------------------

def test_xxhash64_fixed_width():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", LongGen(nullable=True)),
                          ("b", IntegerGen(nullable=True)),
                          ("d", DoubleGen()), ("dt", DateGen())])
        .select(F.xxhash64("a", "b", "d", "dt").alias("h")),
        expect_execs=["TpuProject"])


def test_xxhash64_strings():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", StringGen(nullable=True)),
                          ("b", LongGen())], n=300)
        .select(F.xxhash64("a", "b").alias("h"),
                F.xxhash64("a").alias("hs")),
        expect_execs=["TpuProject"])


# -- LIKE / regexp / split (round 5) ---------------------------------------

@pytest.mark.parametrize("pat", [
    "app%", "%ple", "%ppl%", "a%e", "%", "a%p%e", "ap\\%%", "%apple%", ""])
def test_like_literal_patterns_device(pat):
    """LITERAL %-patterns compile to a device sliding-compare program
    (GpuLike, stringFunctions.scala:670)."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("a", StringGen(nullable=True))], n=300)
        .select(F.col("a"), F.col("a").like(pat).alias("m")),
        expect_execs=["TpuProject"])


def test_like_underscore_falls_back():
    """_ patterns run on CPU (byte vs character semantics)."""
    from tests.harness import assert_tpu_fallback_collect
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("a", StringGen(nullable=True))], n=100)
        .select(F.col("a").like("a_b").alias("m")),
        fallback_exec="CpuProjectExec")


def test_rlike_regexp_split_cpu_parity():
    """RLIKE / regexp_extract / regexp_replace / split: CPU
    implementations with device fallback tagging (stringFunctions.scala
    :670,1014 roles)."""
    def q(s):
        _df(s, [("a", StringGen(nullable=True))],
            n=200).createOrReplaceTempView("rx")
        return s.sql(
            "SELECT a, a RLIKE 'a.b' r, regexp_extract(a, '(\\\\w)(\\\\w+)', 2) g, "
            "regexp_replace(a, '[aeiou]+', '_') rr, split(a, 'a') sp "
            "FROM rx")
    assert_tpu_and_cpu_equal_collect(q, require_device=False)
