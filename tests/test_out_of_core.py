"""Planned out-of-core execution (docs/out_of_core.md): budget-oracle
partition planning, spill-backed partitioned joins/aggs, recursive
re-partitioning, and the degradation ladder.

The acceptance contract: a working set far over the device budget
streams through partitioned buckets BIT-IDENTICAL to the in-memory
path with retryCount == 0 — the retry protocol stays a backstop, never
the steady state — and ``tools doctor`` classifies a correctly-planned
big-input run as ``biggerInput``, not ``retrySpill``.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from spark_rapids_tpu import retry as R
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.memory import get_budget_oracle
from spark_rapids_tpu.metrics import registry_snapshot
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.harness import assert_tpu_and_cpu_equal_collect

NO_BCAST = {"spark.rapids.sql.autoBroadcastJoinThreshold": "-1"}
TINY_BUDGET = {"spark.rapids.sql.memory.deviceBudgetBytes": "8192"}

_OOC_KEYS = ("plannedPartitions", "plannedOutOfCoreEscalations",
             "budgetPressurePeak", "retryCount", "splitRetryCount")


@pytest.fixture(autouse=True)
def _fresh_injection():
    R.reset_fault_injection()
    yield
    R.reset_fault_injection()


def _run_counters(df_fn, conf):
    """Run once on the TPU engine and return the plan counter deltas
    the out-of-core acceptance asserts over."""
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true", **conf})
    try:
        spark.start_capture()
        df_fn(spark)._execute()
        vals = registry_snapshot(
            plans=spark.get_captured_plans())["metrics"]
    finally:
        spark.stop()
    return {k: int(vals.get(k, 0)) for k in _OOC_KEYS}


def _join_data(spark, n=1000, seed=5, nulls=False, strings=False,
               skew=False, parts=3):
    rng = np.random.RandomState(seed)
    lk = rng.randint(0, 300, n)
    rk = rng.randint(0, 300, n)
    if skew:  # one hot key owns most rows: rehashing cannot split it
        lk[: n * 9 // 10] = 7
        rk[: n // 2] = 7
    def col(keys):
        out = []
        for i, v in enumerate(keys):
            if nulls and i % 11 == 0:
                out.append(None)
            elif strings:
                out.append(f"k{int(v):03d}")
            else:
                out.append(int(v))
        return out
    l = spark.createDataFrame(
        {"k": col(lk), "v": [int(i) for i in range(n)]},
        num_partitions=parts)
    r = spark.createDataFrame(
        {"k2": col(rk), "w": [int(i * 3) for i in range(n)]},
        num_partitions=parts)
    return l, r


# ---------------------------------------------------------------------------
# Partitioned join: bit-identical to the in-memory oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("jt", ["inner", "left", "leftsemi", "full"])
def test_ooc_join_parity(jt):
    def fn(s):
        l, r = _join_data(s, nulls=True)
        return l.join(r, l.k == r.k2, jt)
    assert_tpu_and_cpu_equal_collect(
        fn, conf={**NO_BCAST, **TINY_BUDGET},
        expect_execs=["TpuShuffledHashJoin"])
    c = _run_counters(fn, {**NO_BCAST, **TINY_BUDGET})
    assert c["plannedPartitions"] > 0, c
    assert c["retryCount"] == 0 and c["splitRetryCount"] == 0, c


def test_ooc_join_parity_string_keys():
    def fn(s):
        l, r = _join_data(s, strings=True, nulls=True)
        return l.join(r, l.k == r.k2, "inner")
    assert_tpu_and_cpu_equal_collect(
        fn, conf={**NO_BCAST, **TINY_BUDGET},
        expect_execs=["TpuShuffledHashJoin"])


def test_ooc_join_skewed_keys_recursion_backstop():
    """One hot key owns 90% of the build rows: doubling the modulus
    can never split it, so the plan recurses to maxRecursion and the
    backstop tier takes the bucket — results still bit-identical."""
    def fn(s):
        l, r = _join_data(s, skew=True)
        return l.join(r, l.k == r.k2, "inner")
    conf = {**NO_BCAST, **TINY_BUDGET,
            "spark.rapids.sql.outOfCore.maxRecursion": "1"}
    assert_tpu_and_cpu_equal_collect(
        fn, conf=conf, expect_execs=["TpuShuffledHashJoin"])
    c = _run_counters(fn, conf)
    assert c["plannedPartitions"] > 0, c
    assert c["plannedOutOfCoreEscalations"] > 0, c


def test_ooc_join_recursive_repartition():
    """maxPartitions=2 makes the first plan far too coarse: buckets
    must recursively re-partition (doubled modulus) until they fit,
    with the escalation counter recording every re-plan."""
    def fn(s):
        l, r = _join_data(s)
        return l.join(r, l.k == r.k2, "inner")
    conf = {**NO_BCAST, **TINY_BUDGET,
            "spark.rapids.sql.outOfCore.maxPartitions": "2"}
    assert_tpu_and_cpu_equal_collect(
        fn, conf=conf, expect_execs=["TpuShuffledHashJoin"])
    c = _run_counters(fn, conf)
    assert c["plannedOutOfCoreEscalations"] > 0, c
    assert c["retryCount"] == 0, c


def test_ooc_disabled_stays_in_memory():
    def fn(s):
        l, r = _join_data(s)
        return l.join(r, l.k == r.k2, "inner")
    conf = {**NO_BCAST, **TINY_BUDGET,
            "spark.rapids.sql.outOfCore.enabled": "false"}
    assert_tpu_and_cpu_equal_collect(
        fn, conf=conf, expect_execs=["TpuShuffledHashJoin"])
    c = _run_counters(fn, conf)
    assert c["plannedPartitions"] == 0, c


# ---------------------------------------------------------------------------
# Aggregation: hash-bucketed sort fallback
# ---------------------------------------------------------------------------

def test_ooc_agg_parity():
    def fn(s):
        rng = np.random.RandomState(9)
        t = s.createDataFrame(
            {"g": [int(v) for v in rng.randint(0, 200, 1600)],
             "x": [int(v) for v in range(1600)]},
            num_partitions=3)
        return t.groupBy("g").agg(F.sum("x").alias("s"),
                                  F.count("*").alias("c"),
                                  F.min("x").alias("mn"),
                                  F.max("x").alias("mx"))
    assert_tpu_and_cpu_equal_collect(
        fn, conf=TINY_BUDGET, expect_execs=["TpuHashAggregate"])
    c = _run_counters(fn, TINY_BUDGET)
    assert c["plannedPartitions"] > 0, c
    assert c["retryCount"] == 0 and c["splitRetryCount"] == 0, c


def test_ooc_agg_parity_string_keys_with_nulls():
    def fn(s):
        rng = np.random.RandomState(2)
        g = [None if i % 13 == 0 else f"g{int(v):03d}"
             for i, v in enumerate(rng.randint(0, 150, 1200))]
        t = s.createDataFrame(
            {"g": g, "x": [int(v) for v in range(1200)]},
            num_partitions=3)
        return t.groupBy("g").agg(F.sum("x").alias("s"),
                                  F.count("*").alias("c"))
    assert_tpu_and_cpu_equal_collect(
        fn, conf=TINY_BUDGET, expect_execs=["TpuHashAggregate"])


# ---------------------------------------------------------------------------
# 8x-over-budget end-to-end: steady occupancy, zero retries
# ---------------------------------------------------------------------------

def test_ooc_e2e_8x_over_budget_q1_shape():
    """q1-shaped (filter + grouped agg + sort) over a working set >8x
    the device budget: bit-identical to CPU and retryCount == 0 — the
    planned path, not the retry ladder, absorbs the pressure."""
    n = 4000  # ~96KB of key+value columns vs an 8KB budget
    def fn(s):
        rng = np.random.RandomState(4)
        t = s.createDataFrame(
            {"flag": [int(v) for v in rng.randint(0, 3, n)],
             "status": [int(v) for v in rng.randint(0, 5, n)],
             "qty": [int(v) for v in rng.randint(0, 50, n)]},
            num_partitions=4)
        return (t.filter(F.col("qty") % 5 != 0)
                .groupBy("flag", "status")
                .agg(F.sum("qty").alias("sq"), F.count("*").alias("c"))
                .orderBy("flag", "status"))
    assert_tpu_and_cpu_equal_collect(fn, conf=TINY_BUDGET)
    c = _run_counters(fn, TINY_BUDGET)
    assert c["plannedPartitions"] > 0, c
    assert c["retryCount"] == 0 and c["splitRetryCount"] == 0, c


def test_ooc_e2e_8x_over_budget_q3_shape():
    """q3-shaped (join + grouped agg + limit) over-budget run: the
    join AND the downstream agg both ride the planned tier with zero
    retries."""
    def fn(s):
        l, r = _join_data(s, n=1600, parts=4)
        return (l.join(r, l.k == r.k2, "inner")
                .groupBy("k").agg(F.sum("w").alias("sw"),
                                  F.count("*").alias("c"))
                .orderBy("k").limit(50))
    conf = {**NO_BCAST, **TINY_BUDGET}
    assert_tpu_and_cpu_equal_collect(fn, conf=conf)
    c = _run_counters(fn, conf)
    assert c["plannedPartitions"] > 0, c
    assert c["retryCount"] == 0 and c["splitRetryCount"] == 0, c


# ---------------------------------------------------------------------------
# Budget oracle + site:budget fault grammar
# ---------------------------------------------------------------------------

def test_budget_oracle_pow2_plan():
    conf = TpuConf({"spark.rapids.sql.memory.deviceBudgetBytes": "1024"})
    o = get_budget_oracle(conf)
    share = o.operator_share()
    assert share == 512
    assert o.plan_partitions(100) == 1  # fits: no partitioning
    n = o.plan_partitions(10 * share)
    assert n == 16 and (n & (n - 1)) == 0  # pow2-rounded up
    assert o.plan_partitions(10 ** 9) == o.max_partitions


def test_budget_oracle_disabled_never_partitions():
    conf = TpuConf({"spark.rapids.sql.memory.deviceBudgetBytes": "1024",
                    "spark.rapids.sql.outOfCore.enabled": "false"})
    o = get_budget_oracle(conf)
    assert o.plan_partitions(10 ** 9) == 1


@pytest.mark.fault
def test_site_budget_fault_halves_headroom():
    conf = TpuConf({"spark.rapids.sql.memory.deviceBudgetBytes": "4096",
                    "spark.rapids.sql.test.injectOOM": "site:budget:2"})
    o = get_budget_oracle(conf)
    rooms = [o.headroom() for _ in range(4)]
    # every 2nd oracle query reports HALF the real headroom
    assert rooms[0] == 4096 and rooms[1] == 2048, rooms
    assert rooms[2] == 4096 and rooms[3] == 2048, rooms
    inj = R.get_fault_injector(conf)
    assert inj is not None and inj.stats()["budgetFaultsInjected"] == 2


@pytest.mark.fault
def test_site_budget_fault_escalates_without_retries():
    """Injected budget lies (half headroom on every oracle query) make
    the plan MORE conservative — more partitions — but never push the
    run onto the retry ladder, and results stay bit-identical."""
    def fn(s):
        l, r = _join_data(s)
        return l.join(r, l.k == r.k2, "inner")
    clean_conf = {**NO_BCAST, **TINY_BUDGET}
    fault_conf = {**clean_conf,
                  "spark.rapids.sql.test.injectOOM": "site:budget:1"}
    clean = _run_counters(fn, clean_conf)
    R.reset_fault_injection()
    assert_tpu_and_cpu_equal_collect(fn, conf=fault_conf)
    faulted = _run_counters(fn, fault_conf)
    assert faulted["plannedPartitions"] >= clean["plannedPartitions"], \
        (clean, faulted)
    assert faulted["retryCount"] == 0 and \
        faulted["splitRetryCount"] == 0, faulted
    inj = R.get_fault_injector(TpuConf(fault_conf))
    assert inj is not None and inj.stats()["budgetFaultsInjected"] > 0


# ---------------------------------------------------------------------------
# Doctor: planned big-input is biggerInput, never retrySpill
# ---------------------------------------------------------------------------

def _hist_record(qid, *, wall, rows, retries=0, spill=0, poc=None):
    rec = {"queryId": qid, "signature": "sig-ooc",
           "status": "finished", "tenant": "t", "wallSeconds": wall,
           "queueWaitSeconds": 0.0, "outputRows": rows,
           "retryCount": retries, "splitRetryCount": 0,
           "spillBytes": spill, "kernelFallbacks": 0, "jitMisses": 0}
    if poc:
        rec["plannedOutOfCore"] = poc
    return rec


def _write_history(tmp_path, recs):
    hdir = tmp_path / "hist"
    hdir.mkdir(exist_ok=True)
    with open(hdir / "history-0-0-0000.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(hdir)


def test_doctor_planned_big_input_is_bigger_input(tmp_path):
    """A correctly-planned 10x-over-budget run spills by DESIGN with
    zero retries: the doctor must rank biggerInput over retrySpill
    (the planned-out-of-core record field is the tiebreaker)."""
    from spark_rapids_tpu.telemetry.doctor import diagnose
    recs = [_hist_record(f"b{i}", wall=1.0, rows=1000)
            for i in range(3)]
    recs.append(_hist_record(
        "target", wall=3.0, rows=10000, retries=0,
        spill=50_000_000,
        poc={"plannedPartitions": 16, "budgetPressurePeak": 1000}))
    hdir = _write_history(tmp_path, recs)
    d = diagnose(hdir, "target")
    assert d.get("error") is None
    assert d["verdict"] == "biggerInput", d["verdicts"]
    by_class = {v["class"]: v for v in d["verdicts"]}
    assert by_class["biggerInput"]["score"] > \
        by_class.get("retrySpill", {"score": 0.0})["score"]
    assert any("planned out-of-core" in e
               for e in by_class["biggerInput"]["evidence"])


def test_doctor_retry_storm_recommends_planned_out_of_core(tmp_path):
    """An UNplanned retry storm (high retries, no plannedOutOfCore on
    record) keeps its retrySpill verdict and the evidence now names
    the confs that move the workload onto the planned tier."""
    from spark_rapids_tpu.telemetry.doctor import diagnose
    recs = [_hist_record(f"b{i}", wall=1.0, rows=1000)
            for i in range(3)]
    recs.append(_hist_record(
        "storm", wall=4.0, rows=1000, retries=9,
        spill=50_000_000))
    hdir = _write_history(tmp_path, recs)
    d = diagnose(hdir, "storm")
    assert d.get("error") is None
    by_class = {v["class"]: v for v in d["verdicts"]}
    assert "retrySpill" in by_class, d["verdicts"]
    assert any("deviceBudgetBytes" in e
               for e in by_class["retrySpill"]["evidence"])
