"""Exactness of the shared 128-bit limb kernels against Python ints,
under BOTH numpy (CPU engine) and jax.numpy (device programs)."""

from __future__ import annotations

import numpy as np
import pytest

from spark_rapids_tpu.ops import int128 as I


def _rand_vals(rng, n, bits):
    out = []
    for _ in range(n):
        b = int(rng.integers(1, bits + 1))
        v = int(rng.integers(0, 1 << min(b, 62)))
        for _ in range(b // 62):
            v = (v << 62) | int(rng.integers(0, 1 << 62))
        v &= (1 << b) - 1
        if rng.random() < 0.5:
            v = -v
        lim = (1 << 127) - 1
        out.append(max(-lim, min(lim, v)))
    out.extend([0, 1, -1, (1 << 126), -(1 << 126), 10 ** 38 - 1,
                -(10 ** 38 - 1)])
    return out


def _xps():
    import jax.numpy as jnp
    return [np, jnp]


def _half_up_div(v: int, d: int) -> int:
    """Exact integer HALF_UP (round half away from zero) reference."""
    q, r = divmod(abs(v), abs(d))
    if 2 * r >= abs(d):
        q += 1
    return q if (v < 0) == (d < 0) else -q


@pytest.mark.parametrize("xp_i", [0, 1])
def test_roundtrip_add_sub_neg_cmp(xp_i):
    xp = _xps()[xp_i]
    rng = np.random.default_rng(42)
    vals = _rand_vals(rng, 200, 126)
    hi, lo = I.from_pyints(vals)
    hi, lo = xp.asarray(hi), xp.asarray(lo)
    assert I.to_pyints(np.asarray(hi), np.asarray(lo)).tolist() == vals
    v2 = list(reversed(vals))
    h2, l2 = I.from_pyints(v2)
    h2, l2 = xp.asarray(h2), xp.asarray(l2)
    sh, sl = I.add(xp, hi, lo, h2, l2)
    expect = [(a + b) for a, b in zip(vals, v2)]
    # wrap to signed 128 like the kernel does
    expect = [((e + (1 << 127)) % (1 << 128)) - (1 << 127) for e in expect]
    assert I.to_pyints(np.asarray(sh), np.asarray(sl)).tolist() == expect
    dh, dl = I.sub(xp, hi, lo, h2, l2)
    exp2 = [((a - b + (1 << 127)) % (1 << 128)) - (1 << 127)
            for a, b in zip(vals, v2)]
    assert I.to_pyints(np.asarray(dh), np.asarray(dl)).tolist() == exp2
    lt = I.cmp_lt(xp, hi, lo, h2, l2)
    assert np.asarray(lt).tolist() == [a < b for a, b in zip(vals, v2)]


@pytest.mark.parametrize("xp_i", [0, 1])
def test_mul_i64_exact(xp_i):
    xp = _xps()[xp_i]
    rng = np.random.default_rng(7)
    a = rng.integers(-(1 << 62), 1 << 62, 300)
    b = rng.integers(-(1 << 62), 1 << 62, 300)
    a[:4] = [0, -1, (1 << 62), -(1 << 62)]
    b[:4] = [(1 << 62), -(1 << 62), -1, 0]
    hi, lo = I.mul_i64(xp, xp.asarray(a), xp.asarray(b))
    got = I.to_pyints(np.asarray(hi), np.asarray(lo)).tolist()
    assert got == [int(x) * int(y) for x, y in zip(a, b)]


@pytest.mark.parametrize("xp_i", [0, 1])
def test_mul_by_i64_and_overflow(xp_i):
    xp = _xps()[xp_i]
    rng = np.random.default_rng(9)
    vals = _rand_vals(rng, 200, 120)
    mult = [int(rng.integers(-(10 ** 15), 10 ** 15)) or 3
            for _ in vals]
    hi, lo = I.from_pyints(vals)
    rh, rl, over = I.mul_by_i64(xp, xp.asarray(hi), xp.asarray(lo),
                                xp.asarray(np.array(mult, np.int64)))
    got = I.to_pyints(np.asarray(rh), np.asarray(rl)).tolist()
    ov = np.asarray(over).tolist()
    for g, o, v, m in zip(got, ov, vals, mult):
        exact = v * m
        if -(1 << 127) <= exact < (1 << 127):
            assert not o and g == exact, (v, m, g, exact)
        else:
            assert o, (v, m)


@pytest.mark.parametrize("xp_i", [0, 1])
@pytest.mark.parametrize("dbits", [5, 31, 40, 63])
def test_div_halfup_exact(xp_i, dbits):
    xp = _xps()[xp_i]
    rng = np.random.default_rng(13 + dbits)
    vals = _rand_vals(rng, 200, 120)
    ds = [int(rng.integers(1, 1 << dbits)) for _ in vals]
    ds = [d if rng2 % 2 else -d for d, rng2 in zip(ds, range(len(ds)))]
    hi, lo = I.from_pyints(vals)
    qh, ql = I.div_halfup(xp, xp.asarray(hi), xp.asarray(lo),
                          xp.asarray(np.array(ds, np.int64)))
    got = I.to_pyints(np.asarray(qh), np.asarray(ql)).tolist()
    for g, v, d in zip(got, vals, ds):
        exact = _half_up_div(v, d)
        assert g == exact, (v, d, g, exact)


@pytest.mark.parametrize("xp_i", [0, 1])
def test_rescale_and_bounds(xp_i):
    xp = _xps()[xp_i]
    rng = np.random.default_rng(21)
    vals = _rand_vals(rng, 100, 90)
    hi, lo = I.from_pyints(vals)
    hi, lo = xp.asarray(hi), xp.asarray(lo)
    for delta in (0, 3, 18, -1, -6, -18):
        from spark_rapids_tpu.ops import decimal_ops as D
        rh, rl, over = D.rescale_to(xp, hi, lo, delta)
        got = I.to_pyints(np.asarray(rh), np.asarray(rl)).tolist()
        for g, o, v in zip(got, np.asarray(over).tolist(), vals):
            if delta >= 0:
                exact = v * 10 ** delta
                if -(1 << 127) <= exact < (1 << 127):
                    assert not o and g == exact
                else:
                    assert o
            else:
                exact = _half_up_div(v, 10 ** -delta)
                assert g == exact, (v, delta, g, exact)
    fits = I.fits_precision(xp, hi, lo, 20)
    for f, v in zip(np.asarray(fits).tolist(), vals):
        assert f == (abs(v) < 10 ** 20)
