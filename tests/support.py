"""Shared test helpers: NaN-aware recursive equality (asserts.py _assert_equal
in the reference's integration harness) used by the kernel and dual-session
suites."""

import math


def values_equal(a, b, approx: bool = False) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) or math.isnan(b):
            return math.isnan(a) and math.isnan(b)
        if a == 0.0 and b == 0.0:
            # distinguish -0.0 from 0.0: bit-identity matters
            return math.copysign(1.0, a) == math.copysign(1.0, b)
        if approx:
            # approximate_float marker analogue: libm implementations
            # (XLA vs numpy) differ in the last ULPs for transcendentals
            return math.isclose(a, b, rel_tol=1e-12, abs_tol=1e-300)
        return a == b
    return a == b


def lists_equal(xs, ys, approx: bool = False) -> bool:
    return len(xs) == len(ys) and all(
        values_equal(a, b, approx) for a, b in zip(xs, ys))


def assert_pydicts_equal(got: dict, expect: dict, context: str = ""):
    assert set(got) == set(expect), (set(got), set(expect))
    for k in expect:
        assert lists_equal(got[k], expect[k]), (
            f"{context} column {k}: {got[k]} != {expect[k]}")
