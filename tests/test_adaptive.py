"""Adaptive-query-execution corpus (docs/adaptive.md): unit coverage of
the replan calculus (exchange stats, coalesce grouping, skew plans, the
batch-fusion key), the skewed-join property sweep (hot key at 10x/100x
the median, nulls in join keys, empty partitions after coalesce)
asserting bit-identity adaptive-on vs adaptive-off vs the CPU oracle,
broadcast demotion and partition coalescing end-to-end, plan-signature
invariance under adaptive/batchFusion confs, the doctor's
``skewedShuffle`` verdict, and same-signature batch fusion under the
server (one admission slot, per-member billing, member-only eviction on
cancel)."""

from __future__ import annotations

import threading
import time

import pytest

from spark_rapids_tpu import adaptive as A
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.metrics import registry_snapshot
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           gen_batch)
from tests.harness import _rows, _sort_key


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()


# ---------------------------------------------------------------------------
# Unit: the replan calculus
# ---------------------------------------------------------------------------

def test_exchange_stats_median_ignores_empty_partitions():
    st = A.ExchangeStats((0, 100, 0, 300, 100), (0, 10, 0, 30, 10))
    assert st.num_partitions == 5
    assert st.total_bytes == 500
    assert st.max_bytes == 300
    # median over NON-EMPTY partitions {100, 100, 300} = 100, not the
    # zero-dragged median over all five
    assert st.median_bytes == 100
    assert st.skew_ratio == 3.0


def test_exchange_stats_all_empty():
    st = A.ExchangeStats((0, 0), (0, 0))
    assert st.median_bytes == 0
    assert st.skew_ratio == 0.0
    assert A.skew_splits(st, 4.0) == {}


def test_skew_splits_thresholds_and_cap():
    st = A.ExchangeStats((10, 10, 10, 200), (1, 1, 1, 20))
    assert st.median_bytes == 10
    # 200/10 = 20x the median -> capped at MAX_SKEW_SPLITS
    assert A.skew_splits(st, 4.0) == {3: A.MAX_SKEW_SPLITS}
    # a 5x partition aims back at the median: ceil(50/10) = 5 slices
    st2 = A.ExchangeStats((10, 10, 10, 50), (1, 1, 1, 5))
    assert A.skew_splits(st2, 4.0) == {3: 5}
    # at or under the factor: no replan
    assert A.skew_splits(st2, 5.0) == {}
    # factor <= 0 disables the pass entirely
    assert A.skew_splits(st, 0.0) == {}
    assert A.skew_splits(st, -1.0) == {}


def test_coalesce_groups_adjacent_up_to_target():
    assert A.coalesce_groups((10, 10, 10, 100), 40) == [[0, 1, 2], [3]]
    # an oversize partition still gets its own group (never dropped)
    assert A.coalesce_groups((100, 5, 5), 40) == [[0], [1, 2]]
    # already-fat partitions pass through unmerged
    assert A.coalesce_groups((50, 50), 40) == [[0], [1]]
    assert A.coalesce_groups((), 40) == []


def test_slice_groups_contiguous_and_bounded():
    assert A.slice_groups([5] * 6, 3) == [[0, 1], [2, 3], [4, 5]]
    # never more than k groups even with pathological weights
    for k in (1, 2, 3, 7):
        gs = A.slice_groups([1, 1, 1, 100, 1], k)
        assert len(gs) <= k
        assert [i for g in gs for i in g] == list(range(5))
    # k > n clamps to one item per group
    assert A.slice_groups([3, 3], 16) == [[0], [1]]
    assert A.slice_groups([], 4) == [[]]


def test_fusion_key_normalizes_literals():
    a = A.fusion_key("SELECT a FROM t WHERE b = 5 AND c = 'x'")
    b = A.fusion_key("SELECT a FROM t  WHERE b = 17 AND c = 'yy'")
    assert a[0] == b[0] == "SELECT a FROM t WHERE b = ? AND c = ?"
    assert a[1] == ("'x'", "5")
    assert b[1] == ("'yy'", "17")
    # identical text => identical binding vector (one execution)
    assert A.fusion_key("SELECT 1") == A.fusion_key("SELECT  1")
    # embedded '' quote stays inside ONE string literal
    t, lits = A.fusion_key("SELECT * FROM t WHERE s = 'it''s' AND x = 2")
    assert lits == ("'it''s'", "2")
    # numbers inside identifiers/qualified names are NOT literals
    t2, lits2 = A.fusion_key("SELECT col2 FROM t2 WHERE col2 > 9")
    assert "col2" in t2 and lits2 == ("9",)


# ---------------------------------------------------------------------------
# Engine: skewed-join sweep, broadcast demotion, coalesce
# ---------------------------------------------------------------------------

def _collect(df_fn, conf):
    """Run one DataFrame lambda in its own session; returns
    (sorted rows, summed plan metrics)."""
    spark = TpuSparkSession({k: str(v) for k, v in conf.items()})
    try:
        spark.start_capture()
        batch = df_fn(spark)._execute()
        rows = sorted(_rows(batch.to_pydict()), key=_sort_key)
        mets = registry_snapshot(spark.get_captured_plans())["metrics"]
    finally:
        spark.stop()
    return rows, mets


_SKEW_BASE = {
    "spark.rapids.sql.batchSizeRows": "256",
    # -1 disables BOTH broadcast paths (adaptive.autoBroadcastBytes
    # inherits it), so the skew-split replan is the one that can fire
    "spark.rapids.sql.autoBroadcastJoinThreshold": "-1",
    "spark.rapids.sql.shuffle.devicePartitions": "4",
}


def _skew_frames(spark, hot_mult, with_nulls):
    """A shuffled-join pair whose left side carries ONE hot key at
    ``hot_mult`` x the median partition size (48 base keys spread the
    other partitions evenly); optional None join keys on both sides."""
    rep = 12
    lk = [100 + (i % 48) for i in range(48 * rep)]
    hot_n = hot_mult * rep * 12  # ~hot_mult x the per-partition base
    lk += [7] * hot_n
    lv = list(range(len(lk)))
    rk = list(range(100, 148)) * 2 + [7, 7]
    rw = [i * 10 for i in range(len(rk))]
    if with_nulls:
        lk += [None] * 25
        lv += list(range(25))
        rk += [None] * 5
        rw += list(range(5))
    left = spark.createDataFrame({"k": lk, "v": lv}, "k int, v long",
                                 num_partitions=3)
    right = spark.createDataFrame({"k2": rk, "w": rw}, "k2 int, w long",
                                  num_partitions=2)
    return left, right


@pytest.mark.parametrize("hot_mult", [10, 100], ids=["10x", "100x"])
@pytest.mark.parametrize("jt", ["inner", "left"])
@pytest.mark.parametrize("with_nulls", [False, True],
                         ids=["dense", "nullkeys"])
def test_skewed_join_sweep_bit_identical(hot_mult, jt, with_nulls):
    """The satellite property sweep: adaptive-on, adaptive-off and the
    CPU oracle must agree bit-for-bit on skewed shapes, the adaptive
    run must actually have split (aqeSkewSplits > 0), and the clean
    adaptive run takes zero retries."""
    if hot_mult == 100 and with_nulls:
        pytest.skip("covered by the 10x null sweep; 100x adds rows, "
                    "not a new null path")

    def fn(s):
        l, r = _skew_frames(s, hot_mult, with_nulls)
        return l.join(r, l["k"] == r["k2"], jt)

    cpu, _ = _collect(fn, {**_SKEW_BASE,
                           "spark.rapids.sql.enabled": "false"})
    off, m_off = _collect(fn, {**_SKEW_BASE,
                               "spark.rapids.sql.enabled": "true",
                               "spark.rapids.sql.adaptive.enabled":
                               "false"})
    on, m_on = _collect(fn, {**_SKEW_BASE,
                             "spark.rapids.sql.enabled": "true"})

    assert on == off == cpu, (
        f"adaptive replan changed results ({jt}, {hot_mult}x, "
        f"nulls={with_nulls})")
    assert m_on.get("aqeSkewSplits", 0) > 0, m_on
    assert m_on.get("aqeReplans", 0) > 0
    assert m_off.get("aqeSkewSplits", 0) == 0
    assert m_on.get("retryCount", 0) == 0
    assert m_on.get("splitRetryCount", 0) == 0


def test_skewed_join_injected_oom_contrast():
    """The retry contrast from the acceptance bar, with injection
    standing in for a real HBM OOM storm (the CPU backend spills
    instead of raising, so an organic monolithic-partition OOM is not
    reproducible here): the UNADAPTIVE run retries under an injected
    OOM schedule and stays correct; the adaptive run of the same shape
    with no injection completes with retryCount == 0."""
    def fn(s):
        l, r = _skew_frames(s, 10, False)
        return l.join(r, l["k"] == r["k2"], "inner")

    cpu, _ = _collect(fn, {**_SKEW_BASE,
                           "spark.rapids.sql.enabled": "false"})
    off, m_off = _collect(fn, {
        **_SKEW_BASE,
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.adaptive.enabled": "false",
        "spark.rapids.sql.test.injectOOM": "2:2",
        "spark.rapids.sql.retry.backoffMs": "5",
        "spark.rapids.sql.retry.maxBackoffMs": "20"})
    R.reset_fault_injection()
    on, m_on = _collect(fn, {**_SKEW_BASE,
                             "spark.rapids.sql.enabled": "true"})

    assert m_off.get("retryCount", 0) > 0, m_off
    assert off == cpu, "retried unadaptive run diverged"
    assert on == cpu, "adaptive run diverged"
    assert m_on.get("retryCount", 0) == 0
    assert m_on.get("aqeSkewSplits", 0) > 0


def test_broadcast_demotion_fires_and_matches():
    """A shuffled join whose realized build side is tiny demotes to
    broadcast at runtime (aqeBroadcastFlip) and stays bit-identical to
    both the unadaptive plan and the CPU oracle."""
    def fn(s):
        l = s.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("a", IntegerGen())],
                      400, 11), num_partitions=2)
        r = s.createDataFrame(
            gen_batch([("k2", SmallIntGen()), ("b", LongGen())],
                      60, 12), num_partitions=2)
        return l.join(r.repartition(3), l["k"] == r["k2"], "inner")

    cpu, _ = _collect(fn, {"spark.rapids.sql.enabled": "false"})
    off, m_off = _collect(fn, {"spark.rapids.sql.enabled": "true",
                               "spark.rapids.sql.adaptive.enabled":
                               "false"})
    on, m_on = _collect(fn, {"spark.rapids.sql.enabled": "true"})

    assert on == off == cpu
    assert m_on.get("aqeBroadcastFlip", 0) >= 1, m_on
    assert m_on.get("aqeReplans", 0) >= 1
    assert m_off.get("aqeBroadcastFlip", 0) == 0


def test_coalesce_merges_undersized_partitions():
    """An aggregation over a many-partition exchange with mostly-empty
    partitions coalesces toward targetPartitionBytes (empty partitions
    disappear into their neighbours) without changing results."""
    conf = {"spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.sql.shuffle.devicePartitions": "8"}

    def fn(s):
        # 3 distinct groups hashed over 8 partitions: most are EMPTY
        df = s.createDataFrame(
            {"g": [i % 3 for i in range(600)],
             "v": list(range(600))}, "g int, v long",
            num_partitions=4)
        from spark_rapids_tpu.sql import functions as F
        return df.groupBy("g").agg(F.sum("v").alias("sv"))

    cpu, _ = _collect(fn, {**conf, "spark.rapids.sql.enabled": "false"})
    off, m_off = _collect(fn, {**conf,
                               "spark.rapids.sql.enabled": "true",
                               "spark.rapids.sql.adaptive.enabled":
                               "false"})
    on, m_on = _collect(fn, {**conf, "spark.rapids.sql.enabled": "true"})

    assert on == off == cpu
    assert m_on.get("aqeCoalescedPartitions", 0) > 0, m_on
    assert m_off.get("aqeCoalescedPartitions", 0) == 0


# ---------------------------------------------------------------------------
# Shared parquet data (signature / doctor / serving tests)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("adaptive_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        li = gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 2000, 71), num_partitions=4)
        li.write.mode("overwrite").parquet(str(d / "lineitem"))
    finally:
        gen.stop()
    return d


QA = """
SELECT status, sum(qty) AS sq, count(*) AS c
FROM lineitem WHERE qty % 7 != 0
GROUP BY status ORDER BY status
"""


def _run_sql(data_dir, sql, **conf):
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                             **{k: str(v) for k, v in conf.items()}})
    try:
        spark.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        return [tuple(r) for r in spark.sql(sql)._execute().rows()]
    finally:
        spark.stop()


def test_plan_signature_excludes_adaptive_and_fusion_confs(
        data_dir, tmp_path):
    """Satellite: adaptive.* and serve.batchFusion.* confs gate RUNTIME
    behaviour, not plan shape — runs differing only in them must land
    on ONE history signature (shared baselines/quarantine/doctor
    attribution), while a real planning conf still splits it."""
    from spark_rapids_tpu.telemetry import history as H
    hdir = str(tmp_path / "hist")
    base = {"spark.rapids.sql.telemetry.history.dir": hdir,
            "spark.rapids.sql.planCache.enabled": "true"}

    _run_sql(data_dir, QA, **base)
    _run_sql(data_dir, QA, **base,
             **{"spark.rapids.sql.adaptive.enabled": "false",
                "spark.rapids.sql.adaptive.skewFactor": "9.5",
                "spark.rapids.sql.adaptive.autoBroadcastBytes": "123",
                "spark.rapids.sql.adaptive.targetPartitionBytes": "1m",
                "spark.rapids.sql.serve.batchFusion.enabled": "false",
                "spark.rapids.sql.serve.batchFusion.windowMs": "99",
                "spark.rapids.sql.serve.batchFusion.maxBatch": "4"})
    _run_sql(data_dir, QA, **base,
             **{"spark.rapids.sql.batchSizeRows": "333"})

    recs = H.read_records(hdir)
    assert len(recs) == 3
    sigs = [r["signature"] for r in recs]
    assert sigs[0] == sigs[1], (
        "adaptive/batchFusion confs must not change the signature")
    assert sigs[0] != sigs[2], (
        "a planning conf (batchSizeRows) must change the signature")


def test_doctor_skewed_shuffle_verdict(tmp_path):
    """The doctor reads the exchange-stat metrics out of the profile
    artifact and raises a ``skewedShuffle`` verdict when one partition
    dwarfs the median; the adaptive-off run leaves aqeActions empty so
    the evidence points at the adaptive confs."""
    from spark_rapids_tpu.telemetry import history as H
    from spark_rapids_tpu.telemetry.doctor import (diagnose,
                                                   format_diagnosis)
    hdir = str(tmp_path / "hist")

    def fn(s):
        l, r = _skew_frames(s, 10, False)
        return l.join(r, l["k"] == r["k2"], "inner")

    spark = TpuSparkSession({k: str(v) for k, v in {
        **_SKEW_BASE,
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.adaptive.enabled": "false",
        "spark.rapids.sql.telemetry.history.dir": hdir,
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(tmp_path / "prof"),
    }.items()})
    try:
        fn(spark)._execute()
    finally:
        spark.stop()

    recs = H.read_records(hdir)
    assert len(recs) == 1
    rec = recs[0]
    assert "aqeActions" not in rec, (
        "adaptive-off run must not record aqeActions")

    d = diagnose(hdir, str(rec["queryId"]))
    assert d.get("error") is None
    assert d["exchangeSkew"].get("ratio", 0) >= 4.0, d["exchangeSkew"]
    classes = [v["class"] for v in d["verdicts"]]
    assert "skewedShuffle" in classes, d["verdicts"]
    sv = next(v for v in d["verdicts"] if v["class"] == "skewedShuffle")
    assert any("adaptive" in e for e in sv["evidence"]), sv
    assert "skewedShuffle" in format_diagnosis(d)


def test_history_records_aqe_actions(tmp_path):
    """The adaptive-on run of the same skewed shape lands its replan
    counters in the history record's aqeActions field, and the doctor
    evidence flips to 'pre-split'."""
    from spark_rapids_tpu.telemetry import history as H
    from spark_rapids_tpu.telemetry.doctor import diagnose
    hdir = str(tmp_path / "hist")

    def fn(s):
        l, r = _skew_frames(s, 10, False)
        return l.join(r, l["k"] == r["k2"], "inner")

    spark = TpuSparkSession({k: str(v) for k, v in {
        **_SKEW_BASE,
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.telemetry.history.dir": hdir,
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(tmp_path / "prof"),
    }.items()})
    try:
        fn(spark)._execute()
    finally:
        spark.stop()

    rec = H.read_records(hdir)[0]
    acts = rec.get("aqeActions")
    assert acts and acts.get("aqeSkewSplits", 0) > 0, rec
    assert acts.get("aqeReplans", 0) > 0

    d = diagnose(hdir, str(rec["queryId"]))
    assert d["aqeActions"] == acts
    if any(v["class"] == "skewedShuffle" for v in d["verdicts"]):
        sv = next(v for v in d["verdicts"]
                  if v["class"] == "skewedShuffle")
        assert any("pre-split" in e for e in sv["evidence"]), sv


# ---------------------------------------------------------------------------
# Serving: same-signature batch fusion
# ---------------------------------------------------------------------------

def _server(data_dir, **conf):
    from spark_rapids_tpu.serve import QueryServer
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    base.update({k: str(v) for k, v in conf.items()})
    srv = QueryServer(base).start()
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    return srv


def _park(srv, slow_tenant, started, release):
    """Park ``slow_tenant`` queries at a lifecycle checkpoint between
    admission and planning (the test_lifecycle hook)."""
    from spark_rapids_tpu import lifecycle as LC
    orig_session = srv._session

    def hook(tenant):
        s = orig_session(tenant)
        if tenant == slow_tenant and not getattr(s, "_park_hook", None):
            orig_sql = s.sql

            def parked_sql(text):
                started.set()
                end = time.monotonic() + 60
                while not release.is_set() and time.monotonic() < end:
                    LC.checkpoint("batch")
                    time.sleep(0.01)
                return orig_sql(text)

            s._park_hook = True
            s.sql = parked_sql
        return s

    srv._session = hook


def _variant(i):
    return ("SELECT status, sum(qty) AS sq, count(*) AS c "
            f"FROM lineitem WHERE qty % 7 != {i} "
            "GROUP BY status ORDER BY status")


def test_batch_fusion_same_signature_burst(data_dir):
    """16 same-template queries (distinct literal bindings) blocked
    behind one busy slot fuse into ONE admission slot and split results
    per requester, bit-identical to serial execution; every member is
    billed on its own tenant ledger."""
    from spark_rapids_tpu.serve import ServeClient
    oracles = {i: _run_sql(data_dir, _variant(i)) for i in range(3)}

    srv = _server(
        data_dir,
        **{"spark.rapids.sql.serve.maxConcurrentQueries": 1,
           "spark.rapids.sql.serve.maxQueued": 64,
           "spark.rapids.sql.serve.maxConcurrentPerTenant": 32,
           "spark.rapids.sql.serve.batchFusion.windowMs": "2000",
           "spark.rapids.sql.serve.batchFusion.maxBatch": "16"})
    started, release = threading.Event(), threading.Event()
    _park(srv, "slow", started, release)
    errors: list = []
    results: dict = {}

    def blocker():
        try:
            with ServeClient(srv.port, tenant="slow") as c:
                c.collect(_variant(0))
        except Exception as e:  # noqa: BLE001
            errors.append(("blocker", repr(e)))

    def worker(i):
        try:
            with ServeClient(srv.port, tenant=f"t{i % 4}") as c:
                results[i] = c.collect(_variant(i % 3))
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))

    try:
        bt = threading.Thread(target=blocker)
        bt.start()
        assert started.wait(timeout=60)
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        # the window closes early at maxBatch=16; free the slot once
        # everyone has had time to join the batch
        time.sleep(0.5)
        release.set()
        bt.join(timeout=120)
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 16
        for i, rows in results.items():
            assert rows == oracles[i % 3], (
                f"member {i} diverged from serial execution")
        st = srv.stats()
        bf = st.get("batchFusion")
        assert bf is not None
        assert bf["fusedQueries"] >= 16, bf
        assert bf["fusedBatches"] >= 1
        # blocker + every fused member is billed admitted exactly once
        assert st["admission"]["admitted"] == 17, st["admission"]
        assert st["admission"]["rejected"] == 0
    finally:
        release.set()
        srv.shutdown()


def test_batch_fusion_cancel_evicts_only_member(data_dir):
    """Satellite: cancelling ONE fused member while the batch is queued
    evicts that member alone — survivors still execute, bit-identical,
    and the evicted member is neither billed admitted nor counted in
    the fused totals."""
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    oracle = _run_sql(data_dir, _variant(1))

    srv = _server(
        data_dir,
        **{"spark.rapids.sql.serve.maxConcurrentQueries": 1,
           "spark.rapids.sql.serve.maxQueued": 64,
           "spark.rapids.sql.serve.maxConcurrentPerTenant": 32,
           "spark.rapids.sql.serve.batchFusion.windowMs": "800",
           "spark.rapids.sql.serve.batchFusion.maxBatch": "16"})
    started, release = threading.Event(), threading.Event()
    _park(srv, "slow", started, release)
    errors: list = []
    out: dict = {}

    def blocker():
        try:
            with ServeClient(srv.port, tenant="slow") as c:
                c.collect(_variant(0))
        except Exception as e:  # noqa: BLE001
            errors.append(("blocker", repr(e)))

    def member(name):
        try:
            with ServeClient(srv.port, tenant=name) as c:
                batch, _hdr = c.sql(_variant(1),
                                    query_id=f"m-{name}")
                out[name] = [tuple(r) for r in batch.rows()]
        except ServeCancelled as e:
            out[name] = ("cancelled", e.reason)
        except Exception as e:  # noqa: BLE001
            errors.append((name, repr(e)))

    try:
        bt = threading.Thread(target=blocker)
        bt.start()
        assert started.wait(timeout=60)
        threads = [threading.Thread(target=member, args=(n,))
                   for n in ("ta", "tb", "tc")]
        for t in threads:
            t.start()
        time.sleep(0.25)  # inside the 800ms fusion window
        with ServeClient(srv.port) as cc:
            assert cc.cancel(query_id="m-tb", tenant="tb") == 1
        release.set()
        bt.join(timeout=120)
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert out.get("tb") == ("cancelled", "cancel"), out
        assert out.get("ta") == oracle
        assert out.get("tc") == oracle
        st = srv.stats()
        assert st["batchFusion"]["fusedQueries"] == 2, st["batchFusion"]
        # blocker + ta + tc admitted; the evicted tb never billed
        assert st["admission"]["admitted"] == 3, st["admission"]
        assert st["queriesCancelled"] == 1
    finally:
        release.set()
        srv.shutdown()


def test_batch_fusion_disabled_conf(data_dir):
    """batchFusion.enabled=false removes the coordinator: stats carry
    no batchFusion block and queries run the unfused path."""
    from spark_rapids_tpu.serve import ServeClient
    oracle = _run_sql(data_dir, _variant(1))
    srv = _server(
        data_dir,
        **{"spark.rapids.sql.serve.batchFusion.enabled": "false"})
    try:
        with ServeClient(srv.port, tenant="a") as c:
            assert c.collect(_variant(1)) == oracle
        st = srv.stats()
        assert "batchFusion" not in st
    finally:
        srv.shutdown()
