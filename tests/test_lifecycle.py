"""Query-lifecycle corpus (docs/serving.md "Query lifecycle"):
CancelToken semantics, cancellation reaching every wait site
(semaphore, jit single-flight, admission queue, backoff sleeps),
deadlines enforced from admission, the `cancel` protocol verb,
cancel-on-client-disconnect freeing the admission slot / semaphore
permit / tenant HBM ledger (the leak-class regression), the
stuck-query watchdog riding the trigger engine, the poison-query
quarantine, graceful drain cancelling stragglers, `site:cancel`
injection, ServeClient.reconnect, and `tools top --once` / clean exit
when the server goes away."""

from __future__ import annotations

import gc
import glob
import json
import os
import socket
import threading
import time

import pytest

from spark_rapids_tpu import lifecycle as LC
from spark_rapids_tpu import memory as MEM
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, gen_batch)


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()


Q1S = """
SELECT flag, status, sum(qty) AS sq, min(price) AS mn,
       max(price) AS mx, count(*) AS c
FROM lineitem WHERE qty % 5 != 0
GROUP BY flag, status ORDER BY flag, status
"""


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("lifecycle_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        li = gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 3000, 31), num_partitions=4)
        li.write.mode("overwrite").parquet(str(d / "lineitem"))
    finally:
        gen.stop()
    return d


@pytest.fixture(scope="module")
def oracle(data_dir):
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                             "spark.rapids.sql.batchSizeRows": "512"})
    try:
        spark.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        return [tuple(r) for r in spark.sql(Q1S)._execute().rows()]
    finally:
        spark.stop()


def _server(data_dir, **conf):
    from spark_rapids_tpu.serve import QueryServer
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    base.update({k: str(v) for k, v in conf.items()})
    srv = QueryServer(base).start()
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    return srv


def _hook_parked_query(srv, slow_tenant, started, release):
    """Queries from ``slow_tenant`` park at a LIFECYCLE CHECKPOINT
    between admission and planning, so cancellation (verb, deadline,
    disconnect, watchdog, drain) can interrupt them deterministically
    — unlike a plain Event.wait, which no cancel could reach."""
    orig_session = srv._session

    def hook(tenant):
        s = orig_session(tenant)
        if tenant == slow_tenant and not getattr(s, "_park_hook",
                                                 None):
            orig_sql = s.sql

            def parked_sql(text):
                started.set()
                end = time.monotonic() + 60
                while not release.is_set() and time.monotonic() < end:
                    LC.checkpoint("batch")
                    time.sleep(0.01)
                return orig_sql(text)

            s._park_hook = True
            s.sql = parked_sql
        return s

    srv._session = hook


# ---------------------------------------------------------------------------
# Token + checkpoint units
# ---------------------------------------------------------------------------

def test_cancel_token_semantics():
    tok = LC.CancelToken(tenant="t", query_id="q")
    assert not tok.cancelled()
    assert tok.cancel("cancel") is True
    assert tok.cancel("deadline") is False  # first cancel wins
    assert tok.reason == "cancel"
    with pytest.raises(LC.TpuQueryCancelled) as ei:
        tok.check()
    assert ei.value.reason == "cancel"
    # deadline converts into a cancellation on observation
    tok2 = LC.CancelToken()
    tok2.set_deadline(0.0)
    time.sleep(0.01)
    assert tok2.cancelled()
    assert tok2.reason == "deadline"
    # checkpoints are free outside a scope, cooperative inside
    LC.checkpoint("batch")
    with LC.token_scope(tok2):
        with pytest.raises(LC.TpuQueryCancelled):
            LC.checkpoint("batch")


def test_cancellable_sleep_interrupts():
    tok = LC.CancelToken()
    t = threading.Timer(0.1, tok.cancel, args=("cancel",))
    t.start()
    t0 = time.perf_counter()
    with LC.token_scope(tok):
        with pytest.raises(LC.TpuQueryCancelled):
            LC.cancellable_sleep(30.0)
    assert time.perf_counter() - t0 < 5.0, \
        "cancel must interrupt the sleep, not wait it out"
    t.join()


def test_cancel_interrupts_semaphore_wait():
    import spark_rapids_tpu.resource as RES
    sem = RES.TpuSemaphore(1)
    sem.acquire_if_necessary()  # this thread holds the only permit
    tok = LC.CancelToken()
    out = {}

    def blocked():
        with LC.token_scope(tok):
            try:
                sem.acquire_if_necessary()
                out["got"] = True
            except LC.TpuQueryCancelled as e:
                out["cancelled"] = e.reason

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.1)
    tok.cancel("cancel")
    t.join(timeout=10)
    assert out.get("cancelled") == "cancel"
    assert sem.in_use == 1  # the cancelled waiter took no permit
    sem.release_if_necessary()


def test_cancel_interrupts_jit_single_flight_wait():
    from spark_rapids_tpu.jit_cache import JitCache
    cache = JitCache("testCancelWait", capacity=4)
    in_build = threading.Event()
    release = threading.Event()

    def build():
        in_build.set()
        release.wait(timeout=30)
        return "compiled"

    tok = LC.CancelToken()
    out = {}

    def builder():
        out["built"] = cache.get_or_build("k", build)

    def waiter():
        in_build.wait(timeout=30)
        with LC.token_scope(tok):
            try:
                cache.get_or_build("k", build)
            except LC.TpuQueryCancelled:
                out["cancelled"] = True

    t1 = threading.Thread(target=builder)
    t2 = threading.Thread(target=waiter)
    t1.start()
    t2.start()
    time.sleep(0.2)
    tok.cancel("cancel")
    t2.join(timeout=10)
    assert out.get("cancelled") is True
    release.set()  # the BUILDER is unaffected by the waiter's cancel
    t1.join(timeout=30)
    assert out["built"] == ("compiled", True)


def test_deadline_in_admission_queue():
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.serve.scheduler import AdmissionController
    ac = AdmissionController(TpuConf({
        "spark.rapids.sql.serve.maxConcurrentQueries": "1",
        "spark.rapids.sql.serve.maxQueued": "8"}))
    ac.acquire("A")
    tok = LC.CancelToken(tenant="B")
    tok.set_deadline(0.1)
    t0 = time.perf_counter()
    with pytest.raises(LC.TpuQueryCancelled) as ei:
        ac.acquire("B", token=tok)
    assert ei.value.reason == "deadline"
    assert time.perf_counter() - t0 < 5.0
    st = ac.stats()
    assert st["queued"] == 0, "the expired ticket must leave the queue"
    ac.release("A")


def test_fault_injector_site_cancel_unit():
    from spark_rapids_tpu.conf import TpuConf
    inj = R.get_fault_injector(TpuConf(
        {"spark.rapids.sql.test.injectOOM": "site:cancel:3"}))
    tok = LC.CancelToken()
    with LC.token_scope(tok):
        LC.checkpoint("batch")
        LC.checkpoint("batch")
        with pytest.raises(LC.TpuQueryCancelled) as ei:
            LC.checkpoint("batch")  # the 3rd checkpoint cancels
    assert ei.value.reason == "injected"
    assert inj.stats()["cancelsInjected"] == 1
    # the schedule never fires the ALLOC path
    assert inj.stats()["oomInjected"] == 0


# ---------------------------------------------------------------------------
# Wire-level lifecycle: deadline, cancel verb, disconnect, drain
# ---------------------------------------------------------------------------

def test_deadline_returns_cancelled_and_client_survives(data_dir,
                                                        oracle):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    srv = _server(data_dir)
    started = threading.Event()
    release = threading.Event()
    _hook_parked_query(srv, "slow", started, release)
    try:
        with ServeClient(srv.port, tenant="slow") as c:
            t0 = time.perf_counter()
            with pytest.raises(ServeCancelled) as ei:
                c.sql(Q1S, timeout_ms=200)
            assert ei.value.reason == "deadline"
            # acceptance bound: deadline + one batch interval (the
            # checkpoint slice is 50ms; generous CI slack)
            assert time.perf_counter() - t0 < 5.0
            # cancelled queries must NOT mark the client broken
            assert not c.broken
            release.set()
            rows = c.collect(Q1S, tenant="fast")
            assert rows == oracle
        st = srv.stats()
        assert st["queriesCancelled"] == 1
        assert st["lifecycle"]["cancelledByReason"] == {"deadline": 1}
    finally:
        release.set()
        srv.shutdown()


def test_per_tenant_timeout_override(data_dir):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    srv = _server(
        data_dir,
        **{"spark.rapids.sql.serve.queryTimeoutMs.impatient": "150"})
    started = threading.Event()
    release = threading.Event()
    _hook_parked_query(srv, "impatient", started, release)
    try:
        with ServeClient(srv.port, tenant="impatient") as c:
            with pytest.raises(ServeCancelled) as ei:
                c.sql(Q1S)  # no per-request timeout: tenant conf rules
            assert ei.value.reason == "deadline"
    finally:
        release.set()
        srv.shutdown()


def test_cancel_verb_mid_flight(data_dir):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    srv = _server(data_dir)
    started = threading.Event()
    release = threading.Event()
    _hook_parked_query(srv, "slow", started, release)
    out = {}
    try:
        def submit():
            try:
                with ServeClient(srv.port, tenant="slow") as c:
                    c.sql(Q1S, query_id="job-1")
                    out["status"] = "ok"
            except ServeCancelled as e:
                out["status"] = "cancelled"
                out["reason"] = e.reason
                out["t_resp"] = time.perf_counter()

        t = threading.Thread(target=submit)
        t.start()
        assert started.wait(timeout=60)
        t_cancel = time.perf_counter()
        with ServeClient(srv.port) as cc:
            assert cc.cancel(query_id="job-1", tenant="slow") == 1
        t.join(timeout=60)
        assert out.get("status") == "cancelled"
        assert out.get("reason") == "cancel"
        # the status:cancelled response lands promptly (the bench
        # measures this as cancel latency)
        assert out["t_resp"] - t_cancel < 5.0
        st = srv.stats()
        assert st["lifecycle"]["cancelledByReason"] == {"cancel": 1}
        assert st["admission"]["inFlight"] == 0
    finally:
        release.set()
        srv.shutdown()


def test_disconnect_mid_query_frees_slot_permit_and_ledger(data_dir):
    """THE leak-class regression (satellite): a client that vanishes
    mid-query must free the admission slot, the semaphore permit, and
    the tenant HBM ledger — asserted via server stats + store stats."""
    import spark_rapids_tpu.resource as RES
    from spark_rapids_tpu.serve import ServeClient, protocol
    srv = _server(data_dir)
    started = threading.Event()
    release = threading.Event()  # never released: only cancel ends it
    _hook_parked_query(srv, "ghost", started, release)
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=30)
        protocol.send_msg(sock, {"op": "sql", "sql": Q1S,
                                 "tenant": "ghost"})
        assert started.wait(timeout=60)
        st = srv.stats()
        assert st["admission"]["inFlight"] == 1
        sock.close()  # the client vanishes mid-flight
        deadline = time.time() + 30
        while time.time() < deadline:
            st = srv.stats()
            if st["admission"]["inFlight"] == 0:
                break
            time.sleep(0.05)
        assert st["admission"]["inFlight"] == 0, \
            "disconnect must free the admission slot"
        assert st["lifecycle"]["cancelledByReason"] \
            .get("disconnect") == 1
        # semaphore permits restored
        sem = RES._SEMAPHORE
        assert sem is None or sem.in_use == 0
        # tenant HBM ledger freed (handles closed deterministically on
        # the cancel path; GC is only the backstop)
        gc.collect()
        ledger = MEM.store_tenant_stats().get("ghost", {})
        assert ledger.get("liveBytes", 0) == 0
        # a live client still gets service afterwards
        with ServeClient(srv.port, tenant="fast") as c:
            assert len(c.collect(Q1S)) > 0
    finally:
        release.set()
        srv.shutdown()


def test_graceful_drain_cancels_stragglers(data_dir):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    import spark_rapids_tpu.resource as RES
    srv = _server(data_dir)
    started = threading.Event()
    release = threading.Event()  # never set: the query would park 60s
    _hook_parked_query(srv, "straggler", started, release)
    out = {}
    try:
        def submit():
            try:
                with ServeClient(srv.port, tenant="straggler") as c:
                    c.sql(Q1S)
                    out["status"] = "ok"
            except ServeCancelled as e:
                out["status"] = "cancelled"
                out["reason"] = e.reason

        t = threading.Thread(target=submit)
        t.start()
        assert started.wait(timeout=60)
        drained = srv.shutdown(timeout=1.0)  # tiny drain deadline
        t.join(timeout=60)
        assert drained is True, \
            "straggler cancellation must complete the drain"
        assert out.get("status") == "cancelled"
        assert out.get("reason") == "shutdown"
        with srv._sessions_lock:
            assert not srv._sessions
        sem = RES._SEMAPHORE
        assert sem is None or sem.in_use == 0
        assert LC.live_queries() == []
    finally:
        release.set()


def test_site_cancel_injection_through_server(data_dir):
    """site:cancel:N end-to-end: the schedule cancels the query at a
    real engine checkpoint; the wire reports reason=injected."""
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    srv = _server(data_dir,
                  **{"spark.rapids.sql.test.injectOOM":
                     "site:cancel:3"})
    try:
        with ServeClient(srv.port, tenant="a") as c:
            with pytest.raises(ServeCancelled) as ei:
                c.sql(Q1S)
            assert ei.value.reason == "injected"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Watchdog + quarantine
# ---------------------------------------------------------------------------

def _hook_parked_after_planning(srv, slow_tenant, started, release):
    """Park AFTER plan_physical returns — the token's plan-cache
    signature is resolved by then, which is what the watchdog keys
    its p99 comparison on."""
    orig_session = srv._session

    def hook(tenant):
        s = orig_session(tenant)
        if tenant == slow_tenant and not getattr(s, "_pp_hook", None):
            orig_pp = s.plan_physical

            def parked_pp(plan, execute_subqueries=True):
                out = orig_pp(plan,
                              execute_subqueries=execute_subqueries)
                started.set()
                end = time.monotonic() + 60
                while not release.is_set() and time.monotonic() < end:
                    LC.checkpoint("batch")
                    time.sleep(0.01)
                return out

            s._pp_hook = True
            s.plan_physical = parked_pp
        return s

    srv._session = hook


def test_watchdog_fires_bundle_and_cancels(data_dir, oracle, tmp_path):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    from spark_rapids_tpu.telemetry import triggers as TEL
    tel_dir = str(tmp_path / "tel")
    srv = _server(
        data_dir,
        **{"spark.rapids.sql.serve.watchdogFactor": "3",
           "spark.rapids.sql.serve.watchdogCancel": "true",
           "spark.rapids.sql.telemetry.dir": tel_dir,
           "spark.rapids.sql.telemetry.triggerMinIntervalS": "0"})
    started = threading.Event()
    release = threading.Event()
    _hook_parked_after_planning(srv, "stuck", started, release)
    try:
        # build the signature's p99 history (>= 5 samples)
        with ServeClient(srv.port, tenant="warm") as c:
            for _ in range(6):
                assert c.collect(Q1S) == oracle
        # now park one: elapsed quickly exceeds factor x p99
        with ServeClient(srv.port, tenant="stuck") as c:
            with pytest.raises(ServeCancelled) as ei:
                c.sql(Q1S)
            assert ei.value.reason == "watchdog"
        st = srv.stats()
        assert st["lifecycle"]["watchdogFlagged"] >= 1
        assert st["lifecycle"]["watchdogCancelled"] >= 1
        # the stuckQuery bundle landed (rides the trigger engine)
        assert TEL.engine().drain(timeout=15)
        bundles = glob.glob(os.path.join(tel_dir,
                                         "bundle-*-stuckQuery.json"))
        assert bundles, "stuckQuery must emit a slow-query bundle"
        with open(bundles[0]) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "stuckQuery"
        assert bundle["condition"]["willCancel"] is True
    finally:
        release.set()
        srv.shutdown()
        TEL.engine().reset()


def test_quarantine_after_consecutive_fatal_failures(data_dir):
    """K consecutive runtime-fatal failures blacklist the signature;
    the next submission fails FAST (no device work) and a success
    after reset clears the streak."""
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.sql.planCache.enabled": "true",
            "spark.rapids.sql.serve.quarantineThreshold": "2",
            "spark.rapids.sql.test.injectIOError": "1:99",
            "spark.rapids.sql.reader.maxRetries": "1"}
    spark = TpuSparkSession(conf)
    try:
        spark.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        q = spark.sql(Q1S)
        for _ in range(2):
            with pytest.raises(OSError):
                q._execute()
        inj = R.get_fault_injector(spark.conf_obj)
        io_before = inj.stats()["ioInjected"]
        t0 = time.perf_counter()
        with pytest.raises(LC.TpuQueryQuarantined):
            q._execute()
        assert time.perf_counter() - t0 < 2.0
        # fail-fast: the quarantined run never reached the reader
        assert inj.stats()["ioInjected"] == io_before
    finally:
        spark.stop()


def test_quarantine_streak_clears_on_success_unit():
    """CONSECUTIVE is load-bearing: one success resets the streak, so
    an occasionally-failing signature is never blacklisted."""
    assert not LC.record_runtime_failure("sigX", 3)
    assert not LC.record_runtime_failure("sigX", 3)
    LC.record_success("sigX")
    assert not LC.record_runtime_failure("sigX", 3)
    assert not LC.record_runtime_failure("sigX", 3)
    assert LC.record_runtime_failure("sigX", 3) is True
    assert LC.is_quarantined("sigX")
    assert not LC.is_quarantined("sigOther")


def test_release_plan_handles_closes_registered_batches():
    """The cancellation path's deterministic HBM release: handles
    registered under a plan's metric registries close with the plan,
    without waiting for GC."""
    import numpy as np
    from spark_rapids_tpu.columnar.device import DeviceBatch
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.metrics import MetricRegistry
    from spark_rapids_tpu.sql import types as T

    store = MEM.DeviceStore(device_budget=1 << 30,
                            host_budget=1 << 30,
                            spill_dir="/tmp/srt_spill_lc_test")
    try:
        reg = MetricRegistry("MODERATE", owner="FakeExec")
        data = np.arange(64, dtype=np.int64)
        hb = HostBatch(
            T.StructType([T.StructField("x", T.LongT)]),
            [HostColumn(T.LongT, data,
                        np.ones(64, dtype=bool))], 64)
        h = store.register(DeviceBatch.from_host(hb), owner="FakeExec",
                           metrics=reg)
        assert store.device_bytes > 0

        released = store.release_for_registries({id(reg)})
        assert released == 1
        assert store.device_bytes == 0
        assert h.closed
        # foreign registries' handles are untouched
        assert store.release_for_registries({id(object())}) == 0
    finally:
        store.close()


def test_quarantined_status_on_the_wire(data_dir):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeQuarantined
    srv = _server(
        data_dir,
        **{"spark.rapids.sql.serve.quarantineThreshold": "2",
           "spark.rapids.sql.test.injectIOError": "1:99",
           "spark.rapids.sql.reader.maxRetries": "1"})
    try:
        with ServeClient(srv.port, tenant="poison") as c:
            from spark_rapids_tpu.serve.client import ServeError
            for _ in range(2):
                with pytest.raises(ServeError):
                    c.sql(Q1S)
            with pytest.raises(ServeQuarantined):
                c.sql(Q1S)
        assert srv.stats()["lifecycle"]["queriesQuarantined"] == 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Client satellites: reconnect, top
# ---------------------------------------------------------------------------

def test_reconnect_after_transport_error(data_dir, oracle):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeError
    srv = _server(data_dir)
    try:
        c = ServeClient(srv.port, tenant="alice")
        assert c.collect(Q1S) == oracle
        # a real transport error marks the client broken...
        c._sock.close()
        with pytest.raises(ServeError):
            c.collect(Q1S)
        assert c.broken
        with pytest.raises(ServeError):
            c.ping()  # refuses while broken
        # ...reconnect resumes WITHOUT rebuilding tenant state
        c.reconnect()
        assert not c.broken
        assert c.collect(Q1S) == oracle
        c.close()
        # tenant session survived the connection churn (one session)
        with srv._sessions_lock:
            assert list(srv._sessions) == ["alice"]
    finally:
        srv.shutdown()


def test_top_once_and_clean_exit_when_server_goes_away(data_dir,
                                                       capsys):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.telemetry.top import run_top
    srv = _server(data_dir)
    port = srv.port
    try:
        with ServeClient(port, tenant="a") as c:
            c.collect(Q1S)
        # --once: exactly one frame, exit 0
        assert run_top(port, once=True) == 0
        out = capsys.readouterr().out
        assert "spark-rapids-tpu serve" in out
        # mid-poll disappearance: clean message + exit 0
        results = {}

        def poll():
            results["rc"] = run_top(port, interval=0.1, iterations=50)

        t = threading.Thread(target=poll)
        t.start()
        time.sleep(0.3)
        srv.shutdown()
        t.join(timeout=30)
        assert results["rc"] == 0
        out = capsys.readouterr().out
        assert "went away" in out
    finally:
        srv.shutdown()
    # initial connect failure stays an ERROR (exit 1)
    assert run_top(port) == 1