"""Persistent query-history corpus (docs/observability.md "Query
history" / "SLO tracking" / "tools doctor"): store units (rotation,
compaction, crash-safe reads), per-signature aggregates + trends, the
session/server write paths, event-log status/reason agreement, the
RESTART ROUND TRIP acceptance (warm watchdog p99 + warm quarantine on
a fresh server over the same history dir), the retry-storm doctor
acceptance (retryBlock named as the divergent stage), SLO families +
the sloBurn trigger, telemetry-artifact retention, a Prometheus scrape
racing graceful drain, the tools history/doctor CLI contracts, and the
`history-field` lint fixtures."""

from __future__ import annotations

import glob
import json
import os
import threading
import time

import pytest

from spark_rapids_tpu import lifecycle as LC
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.sql.session import TpuSparkSession
from spark_rapids_tpu.telemetry import history as H
from spark_rapids_tpu.telemetry import triggers as TEL

from tests.datagen import (IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, gen_batch)


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()
    H.reset_history()
    TEL.engine().reset()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()
    H.reset_history()
    TEL.engine().reset()


Q1S = """
SELECT flag, status, sum(qty) AS sq, min(price) AS mn,
       max(price) AS mx, count(*) AS c
FROM lineitem WHERE qty % 5 != 0
GROUP BY flag, status ORDER BY flag, status
"""


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("history_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        li = gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 3000, 31), num_partitions=4)
        li.write.mode("overwrite").parquet(str(d / "lineitem"))
    finally:
        gen.stop()
    return d


@pytest.fixture(scope="module")
def oracle(data_dir):
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                             "spark.rapids.sql.batchSizeRows": "512"})
    try:
        spark.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        return [tuple(r) for r in spark.sql(Q1S)._execute().rows()]
    finally:
        spark.stop()


def _session(data_dir, **conf):
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.sql.planCache.enabled": "true"}
    base.update({k: str(v) for k, v in conf.items()})
    s = TpuSparkSession(base)
    s.read.parquet(str(data_dir / "lineitem")) \
        .createOrReplaceTempView("lineitem")
    return s


def _server(data_dir, **conf):
    from spark_rapids_tpu.serve import QueryServer
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    base.update({k: str(v) for k, v in conf.items()})
    srv = QueryServer(base).start()
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    return srv


# ---------------------------------------------------------------------------
# Store units
# ---------------------------------------------------------------------------

def _rec(ts, sig="a" * 40, status="finished", wall=0.1, **kw):
    r = {"version": 1, "ts": ts, "signature": sig, "status": status,
         "wallSeconds": wall, "queueWaitSeconds": 0.0,
         "outputRows": 10}
    r.update(kw)
    return r


def test_store_roundtrip_and_crash_safety(tmp_path):
    d = str(tmp_path / "hist")
    store = H.HistoryStore(d, max_bytes=1 << 20, max_age_days=14)
    # ts in the PAST (like real append-time records): the since-filter
    # skips whole segments by mtime, which tracks the last append
    t0 = time.time() - 10
    for i in range(10):
        store.append(_rec(t0 + i, wall=0.1 * (i + 1),
                          tenant=("a" if i % 2 else "b")))
    # a torn tail line (crash mid-append) must be skipped, not fatal
    seg = sorted(glob.glob(os.path.join(d, "history-*.jsonl")))[-1]
    with open(seg, "a") as f:
        f.write('{"version": 1, "ts": 99, "trunc')
    recs = H.read_records(d)
    assert len(recs) == 10
    assert [r["wallSeconds"] for r in recs] == \
        pytest.approx([0.1 * (i + 1) for i in range(10)])
    # filters
    assert len(H.read_records(d, tenant="a")) == 5
    assert len(H.read_records(d, since=t0 + 7.5)) == 2
    st = store.stats()
    assert st["appended"] == 10 and st["segments"] >= 1


def test_store_rotation_and_size_compaction(tmp_path):
    d = str(tmp_path / "hist")
    store = H.HistoryStore(d, max_bytes=2048, max_age_days=0)
    assert store.segment_target == 64 << 10  # floor respected
    store.SEGMENT_FLOOR = 512  # tiny segments for the unit
    t0 = time.time()
    for i in range(200):
        store.append(_rec(t0 + i, extra_pad="x" * 64))
    store.compact()
    segs = glob.glob(os.path.join(d, "history-*.jsonl"))
    total = sum(os.path.getsize(p) for p in segs)
    assert len(segs) > 1, "rotation must produce segments"
    # total bounded at maxBytes + one active segment's slack
    assert total <= store.max_bytes + store.segment_target
    assert store.pruned_segments > 0
    # the NEWEST records survive compaction
    recs = H.read_records(d)
    assert recs and recs[-1]["ts"] == pytest.approx(t0 + 199)


def test_store_age_compaction(tmp_path):
    d = str(tmp_path / "hist")
    store = H.HistoryStore(d, max_bytes=1 << 30, max_age_days=1)
    store.append(_rec(time.time() - 90000))
    # rotate so the old segment is not the active one
    with store._lock:
        store._open_segment_locked()
    store.append(_rec(time.time()))
    old_seg = sorted(glob.glob(os.path.join(d, "history-*.jsonl")))[0]
    past = time.time() - 2 * 86400
    os.utime(old_seg, (past, past))
    assert store.compact() == 1
    assert not os.path.exists(old_seg)
    assert len(H.read_records(d)) == 1


def test_signature_aggregates_and_trend():
    t0 = time.time()
    recs = [_rec(t0 + i * 3600, wall=0.1 + 0.05 * i, tenant="t",
                 retryCount=(1 if i == 3 else 0))
            for i in range(4)]
    recs.append(_rec(t0 + 5 * 3600, status="failed", wall=0.0))
    recs.append(_rec(t0, sig="b" * 40, kernelFallbacks=2))
    aggs = H.signature_aggregates(recs)
    a = aggs["a" * 40]
    assert a["count"] == 5 and a["finished"] == 4
    assert a["statuses"] == {"finished": 4, "failed": 1}
    # wall grows 0.05 s per hour of history
    assert a["trendSlopePerHour"] == pytest.approx(0.05, rel=1e-3)
    assert a["retryRate"] == pytest.approx(0.25)
    assert a["tenants"] == ["t"]
    b = aggs["b" * 40]
    assert b["fallbackRate"] == 1.0
    # display digest: 40-hex signatures show their own prefix
    assert H.sig_digest("a" * 40) == "a" * 12


# ---------------------------------------------------------------------------
# Write paths: session terminal statuses + event-log agreement
# ---------------------------------------------------------------------------

def test_session_appends_finished_and_failed_records(
        tmp_path, data_dir, oracle):
    hdir = str(tmp_path / "hist")
    # reader.maxRetries rides in BOTH confs: it is a planning-visible
    # key (in the signature), unlike the test.inject* schedule
    spark = _session(
        data_dir,
        **{"spark.rapids.sql.telemetry.history.dir": hdir,
           "spark.rapids.sql.profile.enabled": "true",
           "spark.rapids.sql.profile.dir": str(tmp_path / "prof"),
           "spark.rapids.sql.reader.maxRetries": "1"})
    try:
        assert [tuple(r) for r in
                spark.sql(Q1S)._execute().rows()] == oracle
    finally:
        spark.stop()
    recs = H.read_records(hdir)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["status"] == "finished"
    assert rec["outputRows"] == len(oracle)
    assert rec["wallSeconds"] > 0
    assert len(rec["signature"]) == 40  # the digest, not the plan
    assert rec["retryCount"] == 0 and rec["jitMisses"] >= 0
    assert os.path.exists(rec["profilePath"])
    # a runtime-fatal failure appends status=failed with the SAME
    # signature (test.inject* confs are excluded from the signature)
    fail = _session(
        data_dir,
        **{"spark.rapids.sql.telemetry.history.dir": hdir,
           "spark.rapids.sql.profile.enabled": "true",
           "spark.rapids.sql.profile.dir": str(tmp_path / "prof"),
           "spark.rapids.sql.test.injectIOError": "1:99",
           "spark.rapids.sql.reader.maxRetries": "1"})
    try:
        with pytest.raises(OSError):
            fail.sql(Q1S)._execute()
    finally:
        fail.stop()
    recs = H.read_records(hdir)
    assert [r["status"] for r in recs] == ["finished", "failed"]
    assert recs[1]["signature"] == rec["signature"]


def test_event_log_and_history_agree_on_cancelled_outcome(
        tmp_path, data_dir):
    from spark_rapids_tpu.event_log import read_events
    hdir = str(tmp_path / "hist")
    log_dir = str(tmp_path / "events")
    spark = _session(
        data_dir,
        **{"spark.rapids.sql.telemetry.history.dir": hdir,
           "spark.rapids.sql.eventLog.dir": log_dir})
    try:
        tok = LC.CancelToken(tenant="t", query_id="q-7")
        tok.set_deadline(0.0)
        time.sleep(0.01)
        with LC.token_scope(tok):
            with pytest.raises(LC.TpuQueryCancelled):
                spark.sql(Q1S)._execute()
    finally:
        spark.stop()
    recs = H.read_records(hdir)
    assert [r["status"] for r in recs] == ["timed-out"]
    assert recs[0]["reason"] == "deadline"
    assert recs[0]["queryId"] == "q-7"
    evs = [e for e in read_events(log_dir)
           if e.get("event") == "queryCompleted"]
    assert [e["status"] for e in evs] == ["timed-out"]
    assert evs[0]["reason"] == "deadline"
    # a pre-status line (older writer) normalizes to finished
    with open(os.path.join(log_dir, "events-1-1.jsonl"), "w") as f:
        f.write(json.dumps({"event": "queryCompleted", "version": 2,
                            "ts": 1.0, "queryId": 1,
                            "wallSeconds": 0.1, "outputRows": 5,
                            "plan": "p", "ops": []}) + "\n")
    old = [e for e in read_events(log_dir)
           if e.get("queryId") == 1]
    assert old[0]["status"] == "finished"


# ---------------------------------------------------------------------------
# THE acceptance: restart round trip (warm watchdog + warm quarantine)
# ---------------------------------------------------------------------------

def _hook_parked_after_planning(srv, slow_tenant, started, release):
    orig_session = srv._session

    def hook(tenant):
        s = orig_session(tenant)
        if tenant == slow_tenant and not getattr(s, "_pp_hook", None):
            orig_pp = s.plan_physical

            def parked_pp(plan, execute_subqueries=True):
                out = orig_pp(plan,
                              execute_subqueries=execute_subqueries)
                started.set()
                end = time.monotonic() + 60
                while not release.is_set() and time.monotonic() < end:
                    LC.checkpoint("batch")
                    time.sleep(0.01)
                return out

            s._pp_hook = True
            s.plan_physical = parked_pp
        return s

    srv._session = hook


def test_restart_round_trip_warm_watchdog(data_dir, oracle, tmp_path,
                                          capsys):
    """Run N served queries, stop the server, start a FRESH one on the
    same telemetry.history.dir: the watchdog p99 is warm (a parked
    query fires stuckQuery with ZERO post-restart samples) and `tools
    history` shows the pre-restart signatures."""
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    from spark_rapids_tpu.tools import _main as tools_main
    hdir = str(tmp_path / "hist")
    tel_dir = str(tmp_path / "tel")
    # non-serve confs must MATCH across both servers (they enter the
    # plan signature); the watchdog knobs are serve.* (excluded)
    shared = {"spark.rapids.sql.telemetry.history.dir": hdir,
              "spark.rapids.sql.telemetry.dir": tel_dir,
              "spark.rapids.sql.telemetry.triggerMinIntervalS": "0"}
    srv = _server(data_dir, **shared)
    try:
        with ServeClient(srv.port, tenant="warm") as c:
            for _ in range(6):
                assert c.collect(Q1S) == oracle
    finally:
        srv.shutdown()
    recs = H.read_records(hdir)
    assert len(recs) == 6
    sig = recs[0]["signature"]
    assert all(r["signature"] == sig for r in recs)
    assert all(r["tenant"] == "warm" for r in recs)
    assert recs[0]["queueWaitSeconds"] >= 0

    # --- "restart": lifecycle state dies with the process ---
    LC.reset_lifecycle()
    assert LC.signature_p99(sig) is None

    srv2 = _server(
        data_dir,
        **{**shared,
           "spark.rapids.sql.serve.watchdogFactor": "3",
           "spark.rapids.sql.serve.watchdogCancel": "true"})
    started = threading.Event()
    release = threading.Event()
    _hook_parked_after_planning(srv2, "stuck", started, release)
    try:
        assert srv2.warm_start_summary["enabled"] is True
        assert srv2.warm_start_summary["walls"] == 6
        # warm: the p99 exists with ZERO post-restart samples
        assert LC.signature_p99(sig) is not None
        with ServeClient(srv2.port, tenant="stuck") as c:
            with pytest.raises(ServeCancelled) as ei:
                c.sql(Q1S)
            assert ei.value.reason == "watchdog"
        st = srv2.stats()
        assert st["lifecycle"]["watchdogFlagged"] >= 1
        assert st["history"]["appended"] >= 6
        assert st["history"]["warmStart"]["walls"] == 6
        assert TEL.engine().drain(timeout=15)
        assert glob.glob(os.path.join(tel_dir,
                                      "bundle-*-stuckQuery.json"))
    finally:
        release.set()
        srv2.shutdown()

    # `tools history` renders the pre-restart signatures
    assert tools_main(["history", hdir]) == 0
    out = capsys.readouterr().out
    assert H.sig_digest(sig) in out
    assert "warm" in out


def test_quarantine_survives_restart_via_warm_start(data_dir,
                                                    tmp_path):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import (ServeError,
                                               ServeQuarantined)
    hdir = str(tmp_path / "hist")
    # reader.maxRetries is planning-visible (in the signature) so it
    # rides in BOTH servers' confs; the test.inject* schedule is not
    shared = {"spark.rapids.sql.telemetry.history.dir": hdir,
              "spark.rapids.sql.serve.quarantineThreshold": "2",
              "spark.rapids.sql.reader.maxRetries": "1"}
    srv = _server(data_dir, **shared,
                  **{"spark.rapids.sql.test.injectIOError": "1:99"})
    try:
        with ServeClient(srv.port, tenant="poison") as c:
            for _ in range(2):
                with pytest.raises(ServeError):
                    c.sql(Q1S)
    finally:
        srv.shutdown()
    recs = H.read_records(hdir)
    assert [r["status"] for r in recs] == ["failed", "failed"]
    sig = recs[0]["signature"]

    # --- "restart" ---
    LC.reset_lifecycle()
    R.reset_fault_injection()
    assert not LC.is_quarantined(sig)

    # the fresh server has NO injection conf — test.inject* keys are
    # excluded from the signature, so the shape still matches
    srv2 = _server(data_dir, **shared)
    try:
        assert srv2.warm_start_summary["quarantined"] == 1
        assert LC.is_quarantined(sig)
        t0 = time.perf_counter()
        with ServeClient(srv2.port, tenant="poison") as c:
            with pytest.raises(ServeQuarantined):
                c.sql(Q1S)
        assert time.perf_counter() - t0 < 2.0, "must fail FAST"
        recs = H.read_records(hdir)
        assert recs[-1]["status"] == "quarantined"
    finally:
        srv2.shutdown()


def test_server_records_queued_cancellation(data_dir, tmp_path):
    """A query cancelled while still QUEUED never reaches the session:
    the SERVER path appends its terminal record."""
    from spark_rapids_tpu.serve import ServeClient, protocol
    from spark_rapids_tpu.serve.client import ServeCancelled
    import socket
    hdir = str(tmp_path / "hist")
    srv = _server(
        data_dir,
        **{"spark.rapids.sql.telemetry.history.dir": hdir,
           "spark.rapids.sql.serve.maxConcurrentQueries": "1",
           "spark.rapids.sql.serve.maxQueued": "8"})
    started = threading.Event()
    release = threading.Event()
    orig_session = srv._session

    def hook(tenant):
        s = orig_session(tenant)
        if tenant == "slow" and not getattr(s, "_park", None):
            orig_sql = s.sql

            def parked_sql(text):
                started.set()
                end = time.monotonic() + 60
                while not release.is_set() and time.monotonic() < end:
                    LC.checkpoint("batch")
                    time.sleep(0.01)
                return orig_sql(text)

            s._park = True
            s.sql = parked_sql
        return s

    srv._session = hook
    try:
        slow_sock = socket.create_connection(("127.0.0.1", srv.port),
                                             timeout=30)
        protocol.send_msg(slow_sock, {"op": "sql", "sql": Q1S,
                                      "tenant": "slow"})
        assert started.wait(timeout=60)
        # the second query queues behind the parked one and times out
        # IN THE QUEUE
        with ServeClient(srv.port, tenant="queued") as c:
            with pytest.raises(ServeCancelled) as ei:
                c.sql(Q1S, timeout_ms=150, query_id="q-queued")
            assert ei.value.where == "queued"
        recs = [r for r in H.read_records(hdir)
                if r.get("tenant") == "queued"]
        assert len(recs) == 1
        assert recs[0]["status"] == "timed-out"
        assert recs[0]["queryId"] == "q-queued"
        assert recs[0]["queueWaitSeconds"] > 0
        slow_sock.close()
    finally:
        release.set()
        srv.shutdown()


# ---------------------------------------------------------------------------
# THE acceptance: doctor on an injected retry storm
# ---------------------------------------------------------------------------

def test_doctor_retry_storm_names_retry_block(data_dir, oracle,
                                              tmp_path, capsys):
    from spark_rapids_tpu.telemetry.doctor import (diagnose,
                                                   format_diagnosis)
    from spark_rapids_tpu.tools import _main as tools_main
    hdir = str(tmp_path / "hist")
    base_conf = {
        "spark.rapids.sql.telemetry.history.dir": hdir,
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(tmp_path / "prof"),
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.dir": str(tmp_path / "traces"),
        # consulted only when retries happen: harmless on the clean
        # baseline runs, and keeping it in BOTH confs keeps the plan
        # signature identical across baseline and storm sessions
        "spark.rapids.sql.retry.backoffMs": "30",
        "spark.rapids.sql.retry.maxBackoffMs": "200",
    }
    spark = _session(data_dir, **base_conf)
    try:
        for _ in range(3):
            assert [tuple(r) for r in
                    spark.sql(Q1S)._execute().rows()] == oracle
    finally:
        spark.stop()
    TR.reset_tracing()

    storm = _session(
        data_dir, **base_conf,
        **{"spark.rapids.sql.test.injectOOM": "2:2"})
    try:
        assert [tuple(r) for r in
                storm.sql(Q1S)._execute().rows()] == oracle
    finally:
        storm.stop()
        R.reset_fault_injection()

    recs = H.read_records(hdir)
    assert len(recs) == 4
    sig = recs[0]["signature"]
    assert all(r["signature"] == sig for r in recs), \
        "injection confs must not change the plan signature"
    storm_rec = recs[-1]
    assert storm_rec["retryCount"] > 0
    assert os.path.exists(storm_rec["tracePath"])

    d = diagnose(hdir, str(storm_rec["queryId"]))
    assert d.get("error") is None
    assert d["baseline"]["count"] == 3
    assert d["verdict"] == "retrySpill", d["verdicts"]
    assert d["divergentStage"] == "retryBlock", d["stageDiff"][:4]
    text = format_diagnosis(d)
    assert "retrySpill" in text and "retryBlock" in text

    # CLI contract: selector resolves -> exit 0; bogus -> exit 1
    assert tools_main(["doctor", str(storm_rec["queryId"]),
                       "--history", hdir]) == 0
    out = capsys.readouterr().out
    assert "retrySpill" in out
    assert tools_main(["doctor", "no-such-query",
                       "--history", hdir]) == 1
    # the signature digest is a selector too
    assert tools_main(["doctor", H.sig_digest(sig),
                       "--history", hdir, "--json"]) == 0


# ---------------------------------------------------------------------------
# SLO burn tracking
# ---------------------------------------------------------------------------

def test_slo_tracking_families_and_burn_trigger(data_dir, oracle,
                                                tmp_path):
    from spark_rapids_tpu.serve import ServeClient
    hdir = str(tmp_path / "hist")
    tel_dir = str(tmp_path / "tel")
    srv = _server(
        data_dir,
        **{"spark.rapids.sql.telemetry.history.dir": hdir,
           "spark.rapids.sql.telemetry.dir": tel_dir,
           "spark.rapids.sql.telemetry.triggerMinIntervalS": "0",
           # 1 ms objective: every real query violates -> burn
           "spark.rapids.sql.serve.slo.p99Ms.gold": "1",
           # generous objective: no violation for this tenant
           "spark.rapids.sql.serve.slo.p99Ms.lead": "3600000"})
    try:
        with ServeClient(srv.port, tenant="gold") as c:
            assert c.collect(Q1S) == oracle
            assert c.collect(Q1S) == oracle
        with ServeClient(srv.port, tenant="lead") as c:
            assert c.collect(Q1S) == oracle
        time.sleep(1.1)  # step past the tracker's 1 s result cache
        st = srv.stats()
        slo = st["slo"]
        assert slo["gold"]["objectiveP99Ms"] == 1
        assert slo["gold"]["windowQueries"] == 2
        assert slo["gold"]["violations"] == 2
        assert slo["gold"]["burnRatio"] == 1.0
        assert slo["gold"]["observedP99Ms"] > 1
        assert slo["lead"]["violations"] == 0
        # Prometheus families (scrape parses; family names are
        # SERVER_FAMILY_HELP entries by the prom-family lint)
        text = srv.metrics_text()
        assert 'srt_slo_objective_p99_ms{tenant="gold"} 1' in text
        assert 'srt_slo_burn_ratio{tenant="gold"} 1.0' in text
        assert 'srt_slo_window_violations{tenant="lead"} 0' in text
        # the sloBurn bundle fired (rate limit 0)
        assert TEL.engine().drain(timeout=15)
        bundles = glob.glob(os.path.join(tel_dir,
                                         "bundle-*-sloBurn.json"))
        assert bundles
        with open(bundles[0]) as f:
            b = json.load(f)
        assert b["condition"]["tenant"] == "gold"
        assert b["condition"]["observedP99Ms"] > 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Telemetry artifact retention (satellite)
# ---------------------------------------------------------------------------

def test_bundle_retention_prunes_oldest_first(tmp_path):
    from spark_rapids_tpu.conf import TpuConf
    tel_dir = str(tmp_path / "tel")
    os.makedirs(tel_dir)
    # pre-existing ring dumps count toward retention and are OLDER
    # than every bundle -> pruned first
    for i in range(2):
        p = os.path.join(tel_dir, f"trace-ring-1-{i:05d}.json")
        with open(p, "w") as f:
            f.write("{}")
        past = time.time() - 1000 + i
        os.utime(p, (past, past))
    eng = TEL.engine()
    eng.configure(TpuConf({
        "spark.rapids.sql.telemetry.dir": tel_dir,
        "spark.rapids.sql.telemetry.maxBundles": "3",
        "spark.rapids.sql.telemetry.triggerMinIntervalS": "0"}))
    for i in range(5):
        assert eng._maybe_fire("slowQuery", {"i": i},
                               out_dir=tel_dir, min_interval=0.0)
        assert eng.drain(timeout=15)  # prune runs per write
    files = sorted(os.listdir(tel_dir))
    assert len(files) == 3, files
    # oldest-first: the ring dumps and the earliest bundles are gone,
    # the NEWEST bundles survive
    assert all(f.startswith("bundle-") for f in files)
    assert eng.stats()["pruned"] == 4
    # server stats surface the pruned count
    assert eng.stats()["fired"]["slowQuery"] == 5


def test_bundle_retention_byte_bound(tmp_path):
    from spark_rapids_tpu.conf import TpuConf
    tel_dir = str(tmp_path / "tel")
    eng = TEL.engine()
    eng.configure(TpuConf({
        "spark.rapids.sql.telemetry.dir": tel_dir,
        "spark.rapids.sql.telemetry.maxBundles": "0",
        "spark.rapids.sql.telemetry.maxBundleBytes": "1",
        "spark.rapids.sql.telemetry.triggerMinIntervalS": "0"}))
    for i in range(6):
        assert eng._maybe_fire("retryStorm", {"i": i},
                               out_dir=tel_dir, min_interval=0.0)
    assert eng.drain(timeout=15)
    # a 1-byte bound prunes everything but (at most) the bundle whose
    # write raced the sweep — the point is the BYTE bound engages
    assert len(os.listdir(tel_dir)) <= 1
    assert eng.stats()["pruned"] >= 5


# ---------------------------------------------------------------------------
# Prometheus scrape racing graceful drain (satellite)
# ---------------------------------------------------------------------------

def _parse_exposition(text):
    """Minimal Prometheus text parser: {family: {sample_key: value}};
    asserts completeness (every sample's family declared with HELP +
    TYPE before its samples, no partial tail line)."""
    assert text.endswith("\n"), "truncated exposition"
    declared = {}
    samples = {}
    for ln in text.splitlines():
        if ln.startswith("# HELP "):
            declared.setdefault(ln.split()[2], set()).add("help")
        elif ln.startswith("# TYPE "):
            parts = ln.split()
            declared.setdefault(parts[2], set()).add("type")
            samples.setdefault(parts[2], {})[
                "__type__"] = parts[3]
        elif ln and not ln.startswith("#"):
            name_lab, _, val = ln.rpartition(" ")
            fam = name_lab.split("{", 1)[0]
            assert fam in declared and declared[fam] == \
                {"help", "type"}, f"undeclared family in {ln!r}"
            float(val)  # parseable
            samples.setdefault(fam, {})[name_lab] = float(val)
    return samples


def test_prometheus_scrape_racing_graceful_drain(data_dir, oracle):
    """A scrape racing shutdown() must return a complete, parseable
    exposition with MONOTONE counters — never an error or a partial
    family."""
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeError
    srv = _server(data_dir)
    started = threading.Event()
    release = threading.Event()
    _hook_parked_after_planning(srv, "slow", started, release)
    scrapes = []
    errors = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                scrapes.append(srv.metrics_text())
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(repr(e))
            time.sleep(0.01)

    def submit():
        try:
            with ServeClient(srv.port, tenant="slow") as c:
                c.sql(Q1S)
        except ServeError:
            pass  # drain cancels the straggler

    try:
        with ServeClient(srv.port, tenant="warm") as c:
            assert c.collect(Q1S) == oracle
        t = threading.Thread(target=submit)
        t.start()
        assert started.wait(timeout=60)
        sc = threading.Thread(target=scraper)
        sc.start()
        time.sleep(0.05)
        assert srv.shutdown(timeout=0.5) is True
        time.sleep(0.05)
        stop.set()
        sc.join(timeout=30)
        t.join(timeout=30)
    finally:
        release.set()
        stop.set()
        srv.shutdown(timeout=5)
    assert not errors, errors
    assert len(scrapes) >= 2, "scrapes must keep succeeding mid-drain"
    prev = None
    for text in scrapes:
        fams = _parse_exposition(text)
        if prev is not None:
            for fam, entries in prev.items():
                if entries.get("__type__") != "counter":
                    continue
                for key, v in entries.items():
                    if key == "__type__" or fam not in fams:
                        continue
                    cur = fams[fam].get(key)
                    if cur is not None:
                        assert cur >= v, \
                            f"counter {key} went backwards mid-drain"
        prev = fams


# ---------------------------------------------------------------------------
# tools history CLI contract
# ---------------------------------------------------------------------------

def test_tools_history_cli_contract(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main as tools_main
    # missing path -> error, exit 1
    assert tools_main(["history", str(tmp_path / "nope")]) == 1
    assert "no such history" in capsys.readouterr().out
    # empty store -> a normal answer, exit 0
    d = tmp_path / "hist"
    d.mkdir()
    assert tools_main(["history", str(d)]) == 0
    assert "no history records" in capsys.readouterr().out
    # populated: table + filters + json
    store = H.HistoryStore(str(d), 1 << 20, 14)
    t0 = time.time()
    for i in range(4):
        store.append(_rec(t0 - 7200 + i * 3600, tenant="acme",
                          wall=0.2))
    assert tools_main(["history", str(d)]) == 0
    out = capsys.readouterr().out
    assert "acme" in out and H.sig_digest("a" * 40) in out
    assert tools_main(["history", str(d), "--since", "5400",
                       "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["records"] == 3  # ts -7200 filtered, -3600/0/+3600 kept
    assert tools_main(["history", str(d), "--tenant", "nobody"]) == 0
    assert "no history records" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Lint fixtures: history-field
# ---------------------------------------------------------------------------

def _lint_tree(tmp_path, files):
    import textwrap
    root = tmp_path / "fixture"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    for d in ("spark_rapids_tpu", "spark_rapids_tpu/telemetry"):
        if (root / d).is_dir():
            init = root / d / "__init__.py"
            if not init.exists():
                init.write_text("")
    return str(root)


def test_lint_history_field_bad_and_good(tmp_path):
    from spark_rapids_tpu.lint import LintConfig, run_lint
    root = _lint_tree(tmp_path, {
        "spark_rapids_tpu/telemetry/history.py": """
            HISTORY_FIELD_CATALOG = {
                "goodField": "a documented field",
                "ts": "timestamp",
                "bad_snake_case": "violates naming",
            }

            def build(x):
                rec = {"goodField": 1, "rogueField": 2}
                rec["ts"] = 3
                rec["rogueStore"] = 4
                other = {"notRec": 5}  # unchecked: not the rec dict
                return rec, other
        """})
    r = run_lint(root, LintConfig(check_docs=False))
    msgs = [f.message for f in r.findings if f.rule == "history-field"]
    assert len(msgs) == 3, r.findings
    assert any("rogueField" in m for m in msgs)
    assert any("rogueStore" in m for m in msgs)
    assert any("bad_snake_case" in m for m in msgs)
    # (the real package's zero-findings gate in test_lint.py now
    # covers history-field too)
