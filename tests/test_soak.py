"""Chaos soak (docs/serving.md "Query lifecycle"): the lifecycle
acceptance leg — mixed q1/q3 tenants under rotating FaultInjector
schedules WHILE deadlines, explicit cancels, and client disconnects
are injected. Asserts no hangs (global watchdog), bit-identical
survivors vs the CPU oracle, and zero leaked HBM/permits/sessions
after every round's graceful drain.

The quick leg runs in tier-1; the full sweep (every schedule,
including the ICI chip-failure round) is marked ``slow``."""

from __future__ import annotations

import pytest

from spark_rapids_tpu import lifecycle as LC
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.soak import run_soak


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()
    LC.reset_lifecycle()


@pytest.mark.fault
def test_quick_soak(tmp_path):
    """c=8 mixed tenants, two rounds (clean + memory pressure: a tiny
    device budget plus injected budget faults forcing the planned
    out-of-core tier), lifecycle injections on: the acceptance
    criteria in miniature."""
    report = run_soak(rounds=2, concurrency=8, queries_per_tenant=2,
                      seed=11, data_dir=str(tmp_path),
                      log=lambda m: None)
    assert report["ok"], report["errors"]
    totals = report["totals"]
    # the action mix must actually have exercised the lifecycle legs
    assert totals["ok"] > 0, "no survivors at all"
    assert totals["cancelled"] + totals["disconnected"] > 0, \
        "no lifecycle injection landed"
    for rep in report["roundReports"]:
        inv = rep["invariants"]
        assert inv["drained"] is True
        assert inv.get("semaphoreInUse", 0) == 0
        assert inv.get("liveSessions") == 0
        assert inv.get("liveQueryTokens") == 0


@pytest.mark.fault
@pytest.mark.slow
def test_full_soak(tmp_path):
    """The full schedule sweep: every FaultInjector schedule (memory
    pressure, OOM, IO, split+IO, site:cancel, chip failure when
    multi-device) x lifecycle injections, more rounds and queries."""
    report = run_soak(rounds=7, concurrency=8, queries_per_tenant=4,
                      seed=7, data_dir=str(tmp_path),
                      log=lambda m: None)
    assert report["ok"], report["errors"]
    assert report["totals"]["ok"] > 0