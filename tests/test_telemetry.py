"""Live-telemetry corpus (docs/observability.md "Live telemetry"):
flight recorder (ring mode bit-identity, bounded memory, Chrome-schema
dumps loading in `tools trace`), the trigger engine (forced slow-query
bundle round trip under the server, per-trigger rate limiting, HBM /
queue / retry-storm units), the Prometheus endpoint (exposition
parseability, describe_metric coverage, monotone counters across
registry GC, the protocol verb + HTTP twin), `tools top`,
`tools bench-diff` (injected regression flags + exit contract), the
empty-trace-dir CLI contract, the profile kernel summary satellite,
the stats-under-concurrent-mutation satellite, and lint fixtures for
the span-kind / prom-family rules."""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time

import pytest

from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSparkSession
from spark_rapids_tpu.telemetry import triggers as TEL

from tests.datagen import (IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           gen_batch)
from tests.test_trace import _check_wellformed


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    TEL.engine().reset()
    yield
    TR.reset_tracing()
    TEL.engine().reset()


def _base_conf(**extra):
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    conf.update(extra)
    return conf


def _agg_df(s):
    df = s.createDataFrame(
        gen_batch([("flag", KeyStringGen(cardinality=3)),
                   ("status", SmallIntGen()),
                   ("qty", LongGen()), ("price", IntegerGen())],
                  3000, 41),
        num_partitions=4)
    return (df.filter(F.col("qty") % 5 != 0)
            .groupBy("flag", "status")
            .agg(F.sum("qty").alias("sq"), F.count("*").alias("c"))
            .orderBy("flag", "status"))


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_ring_mode_bit_identical_and_writes_no_files(tmp_path):
    clean = None
    s = TpuSparkSession(_base_conf())
    try:
        clean = _agg_df(s)._execute().to_pydict()
    finally:
        s.stop()
    TR.reset_tracing()
    tdir = tmp_path / "should-stay-empty"
    s = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.mode": "ring",
        "spark.rapids.sql.trace.dir": str(tdir)}))
    try:
        ringed = _agg_df(s)._execute().to_pydict()
    finally:
        s.stop()
    assert ringed == clean
    # ring mode never writes per-query files; the recorder holds spans
    assert not glob.glob(str(tdir / "*.json"))
    ring = TR.ring_active()
    assert ring is not None
    counts = ring.record_counts()
    assert counts["spans"] > 0 and counts["queriesBegun"] >= 1


def test_ring_dump_schema_and_tools_trace(tmp_path, capsys):
    from spark_rapids_tpu.telemetry import dump_ring
    from spark_rapids_tpu.tools import _main, analyze_trace
    s = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.mode": "ring"}))
    try:
        _agg_df(s)._execute()
        _agg_df(s)._execute()
    finally:
        s.stop()
    path = dump_ring(str(tmp_path / "dumps"))
    assert path is not None and os.path.basename(path).startswith(
        "trace-ring-")
    with open(path) as f:
        names = _check_wellformed(json.load(f))
    # dispatch + compile + queryEnd survive in the window
    assert any(n.endswith(".dispatch") or n == "compile"
               for n in names), names
    tr = TR.load_trace(path)
    assert {i["name"] for i in tr["instants"]} >= {"queryEnd"}
    # the offline analyzers work unchanged on dumps
    assert analyze_trace(path)["spanCount"] == len(tr["spans"])
    assert _main(["trace", path]) == 0
    assert "critical path" in capsys.readouterr().out
    assert _main(["hotspots", str(tmp_path / "dumps")]) == 0


def test_ring_memory_is_bounded():
    s = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.mode": "ring",
        "spark.rapids.sql.trace.ringSpans": "64"}))
    try:
        for _ in range(3):
            _agg_df(s)._execute()
    finally:
        s.stop()
    ring = TR.ring_active()
    assert ring is not None and ring.capacity == 64
    for rings in (ring._span_rings, ring._instant_rings):
        for dq in rings.values():
            assert len(dq) <= 64
    assert len(ring._counter_ring) <= 64


def test_file_mode_query_parks_and_restores_the_ring(tmp_path):
    """A file-mode traced query must not destroy the process-lifetime
    flight recorder: the ring is parked for the file trace's duration
    and reinstalled when it closes (review fix)."""
    s_ring = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.mode": "ring"}))
    try:
        _agg_df(s_ring)._execute()
    finally:
        s_ring.stop()
    ring = TR.ring_active()
    assert ring is not None
    begun = ring.record_counts()["queriesBegun"]
    tdir = tmp_path / "file-traces"
    s_file = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.dir": str(tdir)}))
    try:
        _agg_df(s_file)._execute()
    finally:
        s_file.stop()
    # the file trace was written AND the same recorder is back
    assert glob.glob(str(tdir / "trace-*.json"))
    assert TR.ring_active() is ring
    assert ring.record_counts()["queriesBegun"] == begun


def test_server_respects_explicit_file_trace_choice(tmp_path):
    """An operator who sets ONLY trace.enabled=true gets the
    documented default (per-query files), not a silent ring flip
    (review fix)."""
    from spark_rapids_tpu.serve import QueryServer
    srv = QueryServer({"spark.rapids.sql.enabled": "true",
                       "spark.rapids.sql.trace.enabled": "true"})
    assert "spark.rapids.sql.trace.mode" not in srv._base_conf
    srv2 = QueryServer({"spark.rapids.sql.enabled": "true"})
    assert srv2._base_conf["spark.rapids.sql.trace.mode"] == "ring"


# ---------------------------------------------------------------------------
# Trigger engine
# ---------------------------------------------------------------------------

def test_trigger_rate_limit_unit():
    eng = TEL.TriggerEngine()
    assert eng._maybe_fire("slowQuery", {"x": 1}, out_dir="/tmp",
                           min_interval=3600.0) is True
    assert eng._maybe_fire("slowQuery", {"x": 2}, out_dir="/tmp",
                           min_interval=3600.0) is False
    # a DIFFERENT trigger is not limited by slowQuery's window
    assert eng._maybe_fire("hbmWatermark", {"x": 3}, out_dir="/tmp",
                           min_interval=3600.0) is True
    assert eng.drain(10.0)
    st = eng.stats()
    assert st["fired"] == {"slowQuery": 1, "hbmWatermark": 1}
    assert st["rateLimited"] == {"slowQuery": 1}


def test_watermark_triggers_unit(tmp_path):
    from spark_rapids_tpu.conf import TpuConf
    eng = TEL.TriggerEngine()
    eng.configure(TpuConf({
        "spark.rapids.sql.telemetry.dir": str(tmp_path),
        "spark.rapids.sql.telemetry.hbmWatermark": "0.8",
        "spark.rapids.sql.telemetry.queueWatermark": "0.5",
        "spark.rapids.sql.telemetry.retryStormThreshold": "3",
        "spark.rapids.sql.telemetry.triggerMinIntervalS": "3600"}))
    assert eng.armed
    eng.on_store_sample(70, 100)    # under: no fire
    eng.on_store_sample(90, 100)    # over the 0.8 watermark
    eng.on_admission(1, 10)         # under
    eng.on_admission(8, 10)         # over the 0.5 watermark
    for _ in range(5):
        eng.on_retry()              # 5 > 3 in the window
    assert eng.drain(10.0)
    fired = eng.stats()["fired"]
    assert fired.get("hbmWatermark") == 1
    assert fired.get("queueSaturation") == 1
    assert fired.get("retryStorm") == 1
    bundles = sorted(os.listdir(tmp_path))
    assert [b.split("-")[-1] for b in bundles
            if b.startswith("bundle-")] == \
        ["hbmWatermark.json", "queueSaturation.json",
         "retryStorm.json"]
    with open(tmp_path / [b for b in bundles
                          if "hbmWatermark" in b][0]) as f:
        b = json.load(f)
    assert b["condition"]["occupancy"] == 0.9
    assert b["trigger"] == "hbmWatermark"


def test_default_sessions_never_disarm_a_configured_engine(tmp_path):
    from spark_rapids_tpu.conf import TpuConf
    eng = TEL.TriggerEngine()
    eng.configure(TpuConf({
        "spark.rapids.sql.telemetry.hbmWatermark": "0.5",
        "spark.rapids.sql.telemetry.dir": str(tmp_path)}))
    assert eng.armed and eng._hbm_watermark == 0.5
    eng.configure(TpuConf({"spark.rapids.sql.enabled": "true"}))
    assert eng.armed and eng._hbm_watermark == 0.5


# ---------------------------------------------------------------------------
# Shared serving fixtures (slow-query bundle + endpoint + S4)
# ---------------------------------------------------------------------------

Q1S = """
SELECT flag, status, sum(qty) AS sq, min(price) AS mn,
       max(price) AS mx, count(*) AS c
FROM lineitem WHERE qty % 5 != 0
GROUP BY flag, status ORDER BY flag, status
"""

Q3S = """
SELECT brand, sum(amt) AS sa, count(*) AS c
FROM fact JOIN dim ON item = item2
GROUP BY brand ORDER BY brand LIMIT 50
"""


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("telemetry_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 3000, 42),
            num_partitions=4).write.mode("overwrite") \
            .parquet(str(d / "lineitem"))
        gen.createDataFrame(gen_batch(
            [("k", SmallIntGen()), ("item", IntegerGen()),
             ("amt", LongGen())], 2500, 43),
            num_partitions=3).write.mode("overwrite") \
            .parquet(str(d / "fact"))
        gen.createDataFrame(gen_batch(
            [("item2", IntegerGen()),
             ("brand", KeyStringGen(cardinality=5))], 400, 44),
            num_partitions=2).write.mode("overwrite") \
            .parquet(str(d / "dim"))
    finally:
        gen.stop()
    return d


def _serial_rows(data_dir, sql):
    spark = TpuSparkSession(_base_conf())
    try:
        spark.read.parquet(str(data_dir / "lineitem")) \
            .createOrReplaceTempView("lineitem")
        spark.read.parquet(str(data_dir / "fact")) \
            .createOrReplaceTempView("fact")
        spark.read.parquet(str(data_dir / "dim")) \
            .createOrReplaceTempView("dim")
        return [tuple(r) for r in spark.sql(sql)._execute().rows()]
    finally:
        spark.stop()


@pytest.fixture(scope="module")
def oracle(data_dir):
    return {"q1": _serial_rows(data_dir, Q1S),
            "q3": _serial_rows(data_dir, Q3S)}


def _server(data_dir, **extra):
    from spark_rapids_tpu.serve import QueryServer
    conf = _base_conf(**extra)
    srv = QueryServer(conf).start()
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    srv.register_view("fact", str(data_dir / "fact"))
    srv.register_view("dim", str(data_dir / "dim"))
    return srv


def test_forced_slow_query_bundle_roundtrip_under_server(
        data_dir, oracle, tmp_path):
    """ISSUE 12 acceptance: a forced slow-query trigger under the
    server produces a bundle whose ring dump passes the Chrome-trace
    schema check and loads in `tools trace`."""
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.tools import _main
    tdir = tmp_path / "telemetry"
    pdir = tmp_path / "profiles"
    srv = _server(data_dir, **{
        "spark.rapids.sql.telemetry.dir": str(tdir),
        "spark.rapids.sql.telemetry.slowQueryMs": "1",
        "spark.rapids.sql.telemetry.triggerMinIntervalS": "3600",
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(pdir)})
    try:
        with ServeClient(srv.port, tenant="probe") as c:
            batch, header = c.sql(Q1S)
            assert [tuple(r) for r in batch.rows()] == oracle["q1"]
        assert TEL.engine().drain(30.0)
        bundles = sorted(glob.glob(str(tdir / "bundle-*.json")))
        assert len(bundles) == 1, bundles
        with open(bundles[0]) as f:
            b = json.load(f)
        assert b["trigger"] == "slowQuery"
        assert b["condition"]["tenant"] == "probe"
        assert b["condition"]["wallMs"] > 1
        # the bundle ties all three surfaces together
        assert b["profile"] and os.path.exists(b["profile"])
        assert b["serverStats"]["admission"]["admitted"] >= 1
        assert b["storeStats"] is not None
        ring_dump = b["ringDump"]
        assert ring_dump and os.path.exists(ring_dump)
        with open(ring_dump) as f:
            _check_wellformed(json.load(f))
        assert _main(["trace", ring_dump]) == 0
    finally:
        srv.shutdown()


def test_server_metrics_verb_and_http_twin(data_dir, oracle):
    import urllib.request
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir)
    try:
        http_port = srv.start_metrics_http(0)
        with ServeClient(srv.port, tenant="alpha") as c:
            batch, _ = c.sql(Q1S)
            assert [tuple(r) for r in batch.rows()] == oracle["q1"]
            text = c.metrics()
        _assert_prometheus_wellformed(text)
        assert "srt_queries_ok_total 1" in text
        assert 'srt_tenant_admitted_total{tenant="alpha"} 1' in text
        assert re.search(r"^srt_undescribed_metric_keys 0$", text,
                         re.M), "endpoint exported an undescribed key"
        # the HTTP twin serves the same exposition
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics",
                timeout=10) as resp:
            assert resp.status == 200
            http_text = resp.read().decode("utf-8")
        _assert_prometheus_wellformed(http_text)
        assert "srt_queries_ok_total" in http_text
    finally:
        srv.shutdown()


_SAMPLE_RE = re.compile(
    r"^[a-z_][a-z0-9_]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? -?[0-9][0-9.e+-]*$")


def _assert_prometheus_wellformed(text: str) -> None:
    seen_type = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert len(parts) >= 4 and parts[2], line
            if parts[1] == "TYPE":
                seen_type[parts[2]] = parts[3]
            continue
        assert _SAMPLE_RE.match(line), f"unparseable sample: {line!r}"
        fam = line.split("{", 1)[0].split(" ", 1)[0]
        assert fam in seen_type, f"sample before TYPE: {line!r}"


def test_prometheus_engine_families_from_described_keys():
    s = TpuSparkSession(_base_conf())
    try:
        _agg_df(s)._execute()
    finally:
        s.stop()
    from spark_rapids_tpu.telemetry.prometheus import render_prometheus
    text = render_prometheus()
    _assert_prometheus_wellformed(text)
    assert re.search(r"^srt_num_output_rows_total \d+$", text, re.M)
    assert re.search(r"^srt_op_time_seconds_total \d", text, re.M)
    assert re.search(r"^srt_undescribed_metric_keys 0$", text, re.M)
    # prefix families carry their member as a label
    assert re.search(
        r'^srt_kernel_dispatch_count_total\{key="groupbyHash"\} \d+$',
        text, re.M)


def test_prometheus_counters_monotone_across_registry_gc():
    import gc
    from spark_rapids_tpu.metrics import MetricRegistry
    from spark_rapids_tpu.telemetry.prometheus import aggregator
    reg = MetricRegistry(owner="GcProbe")
    reg.create("numOutputRows").add(7)
    before = aggregator().scrape()[0].get("numOutputRows", 0)
    assert before >= 7
    del reg
    gc.collect()
    after = aggregator().scrape()[0].get("numOutputRows", 0)
    # the retired base keeps the dead registry's contribution
    assert after >= before


def test_prometheus_delta_aggregator_reuses_unchanged_snapshots():
    from spark_rapids_tpu.metrics import MetricRegistry
    from spark_rapids_tpu.telemetry.prometheus import RegistryAggregator
    agg = RegistryAggregator()
    reg = MetricRegistry(owner="DeltaProbe")
    m = reg.create("numOutputRows")
    m.add(1)
    totals, _ = agg.scrape()
    assert totals.get("numOutputRows", 0) >= 1
    # nothing changed in THIS registry: its cached snapshot is reused
    _, changed_idle = agg.scrape()
    m.add(1)
    _, changed_after = agg.scrape()
    assert changed_after >= 1
    assert reg is not None  # keep it alive through the scrapes


# ---------------------------------------------------------------------------
# S4: stats/metrics under concurrent mutation
# ---------------------------------------------------------------------------

def test_server_stats_consistent_under_concurrent_mutation(
        data_dir, oracle):
    """Hammer stats+metrics from the main thread while c=8 mixed
    queries run: snapshots are internally consistent (complete
    per-tenant rows, counters monotone) and every query result stays
    bit-identical to serial."""
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir, **{
        "spark.rapids.sql.serve.maxConcurrentQueries": "8",
        "spark.rapids.sql.serve.maxConcurrentPerTenant": "8",
        "spark.rapids.sql.serve.maxQueued": "64"})
    mismatches: list = []
    errors: list = []

    def worker(i):
        try:
            with ServeClient(srv.port, tenant=f"t{i % 3}") as c:
                kind = "q1" if i % 2 == 0 else "q3"
                batch, _ = c.sql(Q1S if kind == "q1" else Q3S)
                rows = [tuple(r) for r in batch.rows()]
                if rows != oracle[kind]:
                    mismatches.append((i, kind))
        except Exception as e:  # noqa: BLE001 - asserted below
            errors.append(repr(e))

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        snapshots = []
        ok_series = []
        with ServeClient(srv.port, tenant="observer") as sc:
            while True:
                st = sc.stats()
                snapshots.append(st)
                m = re.search(r"^srt_queries_ok_total (\d+)$",
                              sc.metrics(), re.M)
                ok_series.append(int(m.group(1)))
                if not any(t.is_alive() for t in threads):
                    break
                time.sleep(0.01)
        for t in threads:
            t.join()
    finally:
        srv.shutdown()
    assert not errors, errors[:3]
    assert not mismatches, mismatches
    assert snapshots
    prev = None
    for st in snapshots:
        adm = st["admission"]
        # bounds hold in every snapshot (no torn counter pairs)
        assert 0 <= adm["inFlight"] <= adm["maxConcurrentQueries"]
        assert adm["queued"] >= 0
        for tenant, row in adm["tenants"].items():
            # no torn per-tenant rows: every field present and sane
            assert set(row) >= {"admitted", "rejected", "inFlight",
                                "queueWaitMs"}, (tenant, row)
            assert row["admitted"] >= 0 and row["inFlight"] >= 0
        if prev is not None:
            padm = prev["admission"]
            assert adm["admitted"] >= padm["admitted"]
            assert adm["rejected"] >= padm["rejected"]
            assert st["queriesOk"] >= prev["queriesOk"]
            for tenant, row in padm["tenants"].items():
                cur = adm["tenants"].get(tenant)
                assert cur is not None, f"tenant {tenant} vanished"
                assert cur["admitted"] >= row["admitted"]
        prev = st
    assert ok_series == sorted(ok_series), "endpoint counter not " \
        "monotone under load"


# ---------------------------------------------------------------------------
# tools top
# ---------------------------------------------------------------------------

def test_tools_top_format_and_live_poll(data_dir, oracle):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.telemetry.top import format_top, run_top
    srv = _server(data_dir)
    try:
        with ServeClient(srv.port, tenant="topten") as c:
            batch, _ = c.sql(Q1S)
            assert [tuple(r) for r in batch.rows()] == oracle["q1"]
        frame = format_top(srv.stats())
        assert "topten" in frame and "qps" in frame and "p99ms" in frame
        # per-tenant QPS from an admitted-count delta between frames
        prev = srv.stats()
        cur = json.loads(json.dumps(prev))
        cur["admission"]["tenants"]["topten"]["admitted"] += 5
        delta_frame = format_top(cur, prev=prev, interval=1.0)
        assert re.search(r"topten\s+5\.00", delta_frame), delta_frame
        assert run_top(srv.port, interval=0.1, iterations=1) == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# bench-diff
# ---------------------------------------------------------------------------

def _bench_doc(value=1.0e6, wall=5.0, qps=3.0):
    return {"metric": "tpch_q1_sf1_parquet", "value": value,
            "detail": {"device_wall_s": wall,
                       "tpcds_q3": {"device_wall_s": 2.0},
                       "serving": {"concurrency": {"c4": {"qps": qps}}},
                       "telemetry": {"ringOverhead": 1.01}}}


def test_bench_diff_flags_injected_regression(tmp_path):
    from spark_rapids_tpu.telemetry.bench_diff import (bench_diff,
                                                       format_diff)
    # >= 10% wall regression on the candidate side
    report = bench_diff(_bench_doc(), _bench_doc(value=0.88e6,
                                                 wall=5.8))
    assert report["verdict"] == "regression"
    assert "value" in report["regressed"]
    assert "detail.device_wall_s" in report["regressed"]
    assert "REGRESSED" in format_diff(report)
    # identical runs: ok, and an IMPROVEMENT is not a regression
    assert bench_diff(_bench_doc(), _bench_doc())["verdict"] == "ok"
    assert bench_diff(_bench_doc(),
                      _bench_doc(value=2e6))["verdict"] == "ok"
    # informational checks never gate: worse CPU wall alone stays ok
    a = _bench_doc()
    a["detail"]["cpu_engine_wall_s"] = 10.0
    b = _bench_doc()
    b["detail"]["cpu_engine_wall_s"] = 20.0
    assert bench_diff(a, b)["verdict"] == "ok"


def test_bench_diff_cli_exit_contract(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main
    a, b = tmp_path / "a.json", tmp_path / "BENCH_r07.json"
    with open(a, "w") as f:
        json.dump(_bench_doc(), f)
    with open(b, "w") as f:
        json.dump(_bench_doc(value=0.8e6, wall=6.5), f)
    assert _main(["bench-diff", str(a), str(b)]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert _main(["bench-diff", str(a), str(a)]) == 0
    capsys.readouterr()  # drop the ok-run table
    # --json is machine-readable
    assert _main(["bench-diff", "--json", str(a), str(b)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"] == "regression"
    # directory candidate: the newest BENCH_r*.json in it
    assert _main(["bench-diff", str(a), str(tmp_path)]) == 1
    # missing files: exit 2, clean message
    assert _main(["bench-diff", str(a),
                  str(tmp_path / "nope.json")]) == 2
    # harness-wrapper shape (BENCH_r0*.json): parsed field unwraps
    wrapped = tmp_path / "BENCH_r08.json"
    with open(wrapped, "w") as f:
        json.dump({"n": 8, "rc": 0, "parsed": _bench_doc()}, f)
    assert _main(["bench-diff", str(a), str(wrapped)]) == 0


# ---------------------------------------------------------------------------
# S1: trace/hotspots CLI on empty or span-free inputs
# ---------------------------------------------------------------------------

def test_trace_cli_empty_dir_and_missing_path(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main
    empty = tmp_path / "empty"
    empty.mkdir()
    for cmd in ("trace", "hotspots"):
        assert _main([cmd, str(empty)]) == 0
        assert "no spans found" in capsys.readouterr().out
        assert _main([cmd, str(tmp_path / "missing")]) == 1
        assert "no such trace file" in capsys.readouterr().out
    # a span-free trace FILE is also a clean answer
    from spark_rapids_tpu.trace import QueryTrace, write_chrome_trace
    qt = QueryTrace(1)
    spanfree = empty / "trace-1-q00001.json"
    write_chrome_trace(str(spanfree), qt)
    assert _main(["trace", str(empty)]) == 0
    assert "no spans recorded" in capsys.readouterr().out
    assert _main(["hotspots", str(empty)]) == 0
    assert "no spans recorded" in capsys.readouterr().out
    # garbage input: clean error, not a stack trace
    bad = empty / "trace-2-q00002.json"
    bad.write_text("{not json")
    assert _main(["trace", str(bad)]) == 1
    assert "not a readable Chrome-trace file" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# S2: kernel summary in the profile artifact + rendered tree
# ---------------------------------------------------------------------------

def test_profile_kernel_summary_and_rendering(tmp_path):
    from spark_rapids_tpu.profile import format_profile, read_profiles
    pdir = tmp_path / "profiles"
    s = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(pdir)}))
    try:
        _agg_df(s)._execute()
        path = s.last_profile_path
    finally:
        s.stop()
    assert path
    prof = next(read_profiles(path))
    kern = prof["kernels"]
    # the partial-agg update rides the groupbyHash kernel by default
    assert kern["dispatches"].get("groupbyHash", 0) > 0, kern
    text = format_profile(prof)
    assert "kernel tier" in text
    assert "groupbyHash=" in text
    # per-node attribution is in the headline metric list too
    assert "kernelDispatchCount.groupbyHash=" in text


def test_profile_kernel_summary_shows_oracle_ride(tmp_path):
    """A query forced onto the oracle path reports ZERO dispatches in
    the summary — visible without grepping raw metrics."""
    from spark_rapids_tpu.profile import read_profiles
    pdir = tmp_path / "profiles"
    s = TpuSparkSession(_base_conf(**{
        "spark.rapids.sql.kernel.enabled": "false",
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(pdir)}))
    try:
        _agg_df(s)._execute()
        path = s.last_profile_path
    finally:
        s.stop()
    prof = next(read_profiles(path))
    assert prof["kernels"] == {"dispatches": {}, "fallbacks": {}}


# ---------------------------------------------------------------------------
# Lint fixtures: span-kind + prom-family
# ---------------------------------------------------------------------------

def _lint_tree(tmp_path, files):
    import textwrap
    root = tmp_path / "fixture"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"))
    for d in ("spark_rapids_tpu", "spark_rapids_tpu/telemetry"):
        if (root / d).is_dir():
            init = root / d / "__init__.py"
            if not init.exists():
                init.write_text("")
    return str(root)


def _lint(root):
    from spark_rapids_tpu.lint import LintConfig, run_lint
    return run_lint(root, LintConfig(check_docs=False))


def test_lint_span_kind_bad_and_good(tmp_path):
    root = _lint_tree(tmp_path, {
        "spark_rapids_tpu/trace.py": """
            SPAN_CATALOG = {"goodSpan": "a documented span"}
            INSTANT_CATALOG = {"goodMark": "a documented instant"}
        """,
        "spark_rapids_tpu/x.py": """
            from spark_rapids_tpu import trace as TR

            def f(qt):
                with TR.span("goodSpan"):
                    pass
                with TR.span("rogueSpan"):
                    pass
                TR.instant("goodMark")
                TR.instant("rogueMark")
                qt.add("goodSpan", 0, 1)
                qt.add("rogueQt", 0, 1)
                qt.mark("goodMark")
        """})
    r = _lint(root)
    kinds = sorted(f.message.split("'")[1] for f in r.findings
                   if f.rule == "span-kind")
    assert kinds == ["rogueMark", "rogueQt", "rogueSpan"], r.findings


def test_lint_prom_family_bad_and_good(tmp_path):
    root = _lint_tree(tmp_path, {
        "spark_rapids_tpu/trace.py": """
            SPAN_CATALOG = {}
            INSTANT_CATALOG = {}
        """,
        "spark_rapids_tpu/telemetry/prometheus.py": """
            SERVER_FAMILY_HELP = {
                "srt_good_total": ("counter", "fine"),
                "srt-BAD-name": ("counter", "violates naming"),
            }

            def _emit_server(out, name, value, labels=None):
                pass

            def render(out):
                _emit_server(out, "srt_good_total", 1)
                _emit_server(out, "srt_unlisted_total", 1)
        """})
    r = _lint(root)
    msgs = [f.message for f in r.findings if f.rule == "prom-family"]
    assert len(msgs) == 2, r.findings
    assert any("srt-BAD-name" in m for m in msgs)
    assert any("srt_unlisted_total" in m for m in msgs)
