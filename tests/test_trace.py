"""Span tracing corpus (docs/observability.md): Chrome-trace schema
well-formedness (matched B/E pairs, monotone per-tid timestamps),
bit-identical results with tracing on vs off (including under injected
OOM so retry markers appear), deterministic sampling at a fixed seed,
the tracing-overhead bound, the `tools trace` CLI, and the
metric-name-in-docs drift guard plus the event-log v2 /
registry_snapshot satellites."""

from __future__ import annotations

import glob
import json
import os

import pytest

from spark_rapids_tpu import metrics as M
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           gen_batch)

# "C" = counter samples (device/host pool occupancy, PR 6 profile work)
VALID_PH = {"M", "B", "E", "i", "I", "X", "C"}


@pytest.fixture(autouse=True)
def _fresh_tracing():
    """Deterministic sampling streams + no cross-test trace bleed."""
    TR.reset_tracing()
    R.reset_fault_injection()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()


def _conf(trace_dir=None, **extra):
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    if trace_dir is not None:
        conf["spark.rapids.sql.trace.enabled"] = "true"
        conf["spark.rapids.sql.trace.dir"] = str(trace_dir)
    conf.update(extra)
    return conf


def _q1_silhouette(s):
    """scan-shaped filter -> 2-key groupBy -> orderBy (q1 at test
    scale)."""
    df = s.createDataFrame(
        gen_batch([("flag", KeyStringGen(cardinality=3)),
                   ("status", SmallIntGen()),
                   ("qty", LongGen()), ("price", IntegerGen())],
                  3000, 21),
        num_partitions=4)
    return (df.filter(F.col("qty") % 5 != 0)
            .groupBy("flag", "status")
            .agg(F.sum("qty").alias("sq"), F.min("price").alias("mn"),
                 F.max("price").alias("mx"), F.count("*").alias("c"))
            .orderBy("flag", "status"))


def _q3_silhouette(s):
    fact = s.createDataFrame(
        gen_batch([("k", SmallIntGen()), ("item", IntegerGen()),
                   ("amt", LongGen())], 2500, 22),
        num_partitions=3)
    dim = s.createDataFrame(
        gen_batch([("item2", IntegerGen()),
                   ("brand", KeyStringGen(cardinality=5))], 400, 23),
        num_partitions=2)
    return (fact.join(dim, fact["item"] == dim["item2"], "inner")
            .groupBy("brand").agg(F.sum("amt").alias("sa"),
                                  F.count("*").alias("c"))
            .orderBy("brand").limit(50))


def _run(df_fn, conf):
    spark = TpuSparkSession(conf)
    try:
        return df_fn(spark)._execute().to_pydict()
    finally:
        spark.stop()


def _trace_files(trace_dir) -> list:
    return sorted(glob.glob(os.path.join(str(trace_dir),
                                         "trace-*.json")))


def _write_parquet(tmp_path):
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        path = str(tmp_path / "t")
        gen.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("v", LongGen())], 1500, 24),
            num_partitions=3).write.mode("overwrite").parquet(path)
        return path
    finally:
        gen.stop()


# ---------------------------------------------------------------------------
# Schema well-formedness
# ---------------------------------------------------------------------------

def _check_wellformed(doc) -> set:
    """Valid Chrome trace: known phases, monotone per-tid timestamps,
    matched B/E pairs (names agree, stacks empty at EOF). Returns the
    set of span names."""
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    stacks, last_ts, names = {}, {}, set()
    for ev in events:
        assert ev.get("ph") in VALID_PH, ev
        if ev["ph"] == "M":
            continue
        tid = ev["tid"]
        ts = float(ev["ts"])
        assert ts >= last_ts.get(tid, -1e18) - 1e-6, (
            f"non-monotone ts on tid {tid}: {ts} after {last_ts[tid]}")
        last_ts[tid] = ts
        if ev["ph"] == "B":
            stacks.setdefault(tid, []).append(ev)
            names.add(ev["name"])
        elif ev["ph"] == "E":
            st = stacks.get(tid)
            assert st, f"E without B on tid {tid}: {ev}"
            b = st.pop()
            assert b["name"] == ev["name"], (b, ev)
    leftover = {t: st for t, st in stacks.items() if st}
    assert not leftover, f"unmatched B events: {leftover}"
    return names


def test_trace_file_wellformed_with_expected_kinds(tmp_path):
    data = _write_parquet(tmp_path)
    tdir = tmp_path / "traces"
    spark = TpuSparkSession(_conf(tdir))
    try:
        df = (spark.read.parquet(data).filter(F.col("v") % 3 != 0)
              .groupBy("k").agg(F.sum("v").alias("sv"),
                                F.count("*").alias("c"))
              .orderBy("k"))
        df._execute()
    finally:
        spark.stop()
    files = _trace_files(tdir)
    assert len(files) == 1, files
    with open(files[0]) as f:
        doc = json.load(f)
    names = _check_wellformed(doc)
    meta = doc["otherData"]
    assert meta["queryId"] == 1 and meta["outputRows"] > 0
    # every stage of a batch's life is represented: reader decode plan
    # (device decode is the default scan path), producer-thread
    # prefetch, host pack, upload-ahead + decode-program completion
    # (chip-attributed), device dispatch, exchange, JIT compile,
    # semaphore wait
    for expected in ("FileScan.deviceDecodeTime",
                     "scanPrefetch",
                     "uploadAhead",
                     "TpuRowToColumnarExec.packBatchTime",
                     "TpuRowToColumnarExec.copyToDeviceTime",
                     "TpuHashAggregateExec.dispatch",
                     "exchangeMaterialize",
                     "compile",
                     "semaphoreWait"):
        assert expected in names, (expected, sorted(names))
    # the loader round-trips the same stream
    tr = TR.load_trace(files[0])
    assert len(tr["spans"]) == meta["spanCount"]


def test_scan_pipeline_trace_and_critical_path(tmp_path):
    """The ISSUE 9 acceptance probe at test scale: a traced parquet
    aggregation's Chrome stream stays well-formed with the pipeline
    spans present, and the critical path contains no host
    FileScan.decodeTime (the scan is off the critical path — decode
    rides the device program / prefetch threads)."""
    from spark_rapids_tpu.tools import analyze_trace
    data = _write_parquet(tmp_path)
    tdir = tmp_path / "traces"
    spark = TpuSparkSession(_conf(tdir))
    try:
        df = (spark.read.parquet(data).filter(F.col("v") % 3 != 0)
              .groupBy("k").agg(F.sum("v").alias("sv"))
              .orderBy("k"))
        df._execute()
    finally:
        spark.stop()
    files = _trace_files(tdir)
    assert files
    with open(files[-1]) as f:
        _check_wellformed(json.load(f))
    analysis = analyze_trace(files[-1])
    cp = analysis.get("criticalPath_s", {})
    assert cp, analysis
    assert "FileScan.decodeTime" not in cp, cp


# ---------------------------------------------------------------------------
# Bit-identical results, tracing on vs off (incl. under injected OOM)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("df_fn", [_q1_silhouette, _q3_silhouette],
                         ids=["q1", "q3"])
def test_traced_results_bit_identical(df_fn, tmp_path):
    clean = _run(df_fn, _conf())
    traced = _run(df_fn, _conf(tmp_path / "tr"))
    assert traced == clean
    assert _trace_files(tmp_path / "tr")


@pytest.mark.fault
def test_traced_results_bit_identical_under_injected_oom(tmp_path):
    clean = _run(_q1_silhouette, _conf())
    R.reset_fault_injection()
    tdir = tmp_path / "tr"
    traced = _run(_q1_silhouette, _conf(
        tdir,
        **{"spark.rapids.sql.test.injectOOM": "3",
           "spark.rapids.sql.retry.backoffMs": "1",
           "spark.rapids.sql.retry.maxBackoffMs": "4"}))
    assert traced == clean
    tr = TR.load_trace(_trace_files(tdir)[-1])
    marks = {i["name"] for i in tr["instants"]}
    assert "retryOOM" in marks, marks
    # the recovery block is a nested span (the exclusive-time fix)
    assert any(s["name"] == "retryBlock" for s in tr["spans"])


# ---------------------------------------------------------------------------
# Sampling determinism
# ---------------------------------------------------------------------------

def _run_sampled_queries(trace_dir, n=8):
    TR.reset_tracing()
    spark = TpuSparkSession(_conf(
        trace_dir,
        **{"spark.rapids.sql.trace.sampleRate": "0.5",
           "spark.rapids.sql.trace.sampleSeed": "7"}))
    try:
        for _ in range(n):
            spark.range(0, 64).selectExpr("id + 1 as x")._execute()
    finally:
        spark.stop()
    return [os.path.basename(f) for f in _trace_files(trace_dir)]


def test_sampling_deterministic_at_fixed_seed(tmp_path):
    first = _run_sampled_queries(tmp_path / "a")
    second = _run_sampled_queries(tmp_path / "b")
    assert first == second
    assert 0 < len(first) < 8  # the rate actually samples


# ---------------------------------------------------------------------------
# Overhead bound (acceptance: traced q1 wall <= 1.15x untraced)
# ---------------------------------------------------------------------------

def test_tracing_overhead_bound(tmp_path):
    import time

    def wall(df):
        t0 = time.perf_counter()
        df._execute()
        return time.perf_counter() - t0

    # INTERLEAVED best-of-5: measuring all untraced walls then all
    # traced walls lets a load shift between the phases (GC, another
    # suite's leftovers) masquerade as tracing overhead on these
    # millisecond-scale smoke walls; alternating exposes both modes to
    # the same machine state
    off = TpuSparkSession(_conf())
    on = TpuSparkSession(_conf(tmp_path / "tr"))
    try:
        q_off, q_on = _q1_silhouette(off), _q1_silhouette(on)
        q_off._execute()  # compile warm-up (caches are process-wide)
        q_on._execute()
        offs, ons = [], []
        for _ in range(5):
            offs.append(wall(q_off))
            ons.append(wall(q_on))
        t_off, t_on = min(offs), min(ons)
    finally:
        on.stop()
        off.stop()
    # 1.15x per the acceptance bound, plus a tiny absolute allowance so
    # millisecond-scale smoke walls don't flake on scheduler noise
    assert t_on <= t_off * 1.15 + 0.05, (t_on, t_off)


# ---------------------------------------------------------------------------
# tools: trace CLI + analyzer + docs drift guard
# ---------------------------------------------------------------------------

def test_tools_trace_cli_smoke(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main, analyze_trace
    tdir = tmp_path / "tr"
    _run(_q1_silhouette, _conf(tdir))
    path = _trace_files(tdir)[0]
    assert _main(["trace", path]) == 0
    out = capsys.readouterr().out
    assert "critical path" in out
    assert "per-chip occupancy" in out
    assert "exclusive self-time" in out
    # directory mode reports every trace in it
    assert _main(["trace", str(tdir)]) == 0
    # machine-readable form (bench detail.trace)
    a = analyze_trace(path)
    assert a["spanCount"] > 0
    assert a["criticalPath_s"]
    assert abs(sum(a["criticalPath_s"].values())
               + a["criticalPathIdle_s"] - a["criticalPathSpan_s"]) \
        <= 0.01 * max(1.0, a["criticalPathSpan_s"])


# The metric-constant-in-generated-docs drift guard that lived here is
# now STATIC: tpu-lint's `metric-key` rule checks every metrics.py
# constant against METRIC_DESCRIPTIONS and `docs-drift` diffs
# docs/observability.md against the generator (tests/test_lint.py runs
# both over the real package every tier-1).


# ---------------------------------------------------------------------------
# Satellites: registry_snapshot, event-log v2, semaphore-wait coverage
# ---------------------------------------------------------------------------

def test_registry_snapshot_merges_plan_registries():
    spark = TpuSparkSession(_conf())
    try:
        spark.start_capture()
        _q1_silhouette(spark)._execute()
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    snap = M.registry_snapshot(plans)
    assert snap["metrics"].get(M.NUM_OUTPUT_ROWS, 0) > 0
    assert snap["metrics"].get(M.DISPATCH_COUNT, 0) > 0
    assert "jitCaches" in snap and snap["jitCaches"]
    # process-wide form includes at least the same names
    whole = M.registry_snapshot()
    assert whole["metrics"].get(M.NUM_OUTPUT_ROWS, 0) \
        >= snap["metrics"][M.NUM_OUTPUT_ROWS]


def test_event_log_v2_zero_metrics_conf_and_injector(tmp_path):
    from spark_rapids_tpu.event_log import read_events
    log_dir = str(tmp_path / "events")
    conf = _conf(**{"spark.rapids.sql.eventLog.dir": log_dir,
                    "spark.rapids.sql.test.injectOOM": "4",
                    "spark.rapids.sql.retry.backoffMs": "1",
                    "spark.rapids.sql.retry.maxBackoffMs": "4"})
    _run(_q1_silhouette, conf)
    events = list(read_events(log_dir))
    assert len(events) == 1
    ev = events[0]
    assert ev["version"] == 2
    # conf snapshot: the session's explicit settings ride along
    assert ev["conf"]["spark.rapids.sql.enabled"] == "true"
    assert ev["conf"]["spark.rapids.sql.test.injectOOM"] == "4"
    # fault-injector summary
    assert ev["faultInjector"]["oomInjected"] > 0
    # zero-valued metrics are now present (distinguishable from absent)
    all_metrics = [m for o in ev["ops"]
                   for m in o.get("metrics", {}).items()]
    assert any(v == 0 for _k, v in all_metrics), (
        "expected at least one zero-valued metric in the v2 event")
    # old lines (no version field) normalize to 1
    legacy = tmp_path / "events" / "events-0-legacy.jsonl"
    with open(legacy, "w") as f:
        f.write(json.dumps({"event": "queryCompleted", "ts": 0.0,
                            "queryId": 99, "wallSeconds": 0.1,
                            "outputRows": 1, "plan": "", "ops": []})
                + "\n")
    versions = {e["queryId"]: e["version"] for e in read_events(log_dir)}
    assert versions[99] == 1


def test_semaphore_wait_timed_on_exchange_and_broadcast_paths():
    """Satellite: semaphoreWaitTime must be recorded on the exchange
    drain and the broadcast build too, not only the per-task collect
    path."""
    from spark_rapids_tpu.exec.exchange import (TpuBroadcastExchangeExec,
                                                TpuShuffleExchangeExec)
    conf = _conf(**{"spark.rapids.sql.taskParallelism": "2",
                    "spark.rapids.sql.autoBroadcastJoinThreshold":
                        str(10 << 20)})
    spark = TpuSparkSession(conf)
    try:
        spark.start_capture()
        _q3_silhouette(spark)._execute()
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    found = {"exchange": False, "broadcast": False}

    def walk(p):
        if isinstance(p, TpuShuffleExchangeExec):
            if M.SEMAPHORE_WAIT_TIME in p.metrics.metrics:
                found["exchange"] = True
        if isinstance(p, TpuBroadcastExchangeExec):
            if M.SEMAPHORE_WAIT_TIME in p.metrics.metrics:
                found["broadcast"] = True
        for c in p.children:
            walk(c)

    for p in plans:
        walk(p)
    assert found["exchange"] or found["broadcast"], (
        "semaphoreWaitTime recorded on neither the exchange drain nor "
        "the broadcast build")
