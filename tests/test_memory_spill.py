"""HBM accounting + spill tests (RapidsBufferCatalog /
SpillableColumnarBatch coverage): exchanges and final aggregation over a
deliberately tiny device budget must complete correctly WITH spills.
"""

import numpy as np
import pytest

from spark_rapids_tpu import memory as MEM
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.columnar.device import DeviceBatch
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import IntegerGen, LongGen, SmallIntGen, gen_batch
from tests.harness import assert_tpu_and_cpu_equal_collect


def _store_for(budget, host_budget=1 << 30, spill_dir="/tmp/srt_spill_t"):
    return MEM.DeviceStore(budget, host_budget, spill_dir)


def _batch(n=256, seed=0):
    rng = np.random.default_rng(seed)
    col = HostColumn(T.LongT, rng.integers(0, 1 << 40, n),
                     np.ones(n, dtype=bool))
    return DeviceBatch.from_host(
        HostBatch(T.StructType([T.StructField("v", T.LongT)]), [col], n))


def test_store_spills_lru_and_repromotes():
    b1, b2, b3 = _batch(256, 1), _batch(256, 2), _batch(256, 3)
    budget = b1.sizeof() * 2 + 10
    store = _store_for(budget)
    h1, h2, h3 = (store.register(b) for b in (b1, b2, b3))
    assert store.spill_count >= 1            # h1 went to host (LRU)
    assert store.device_bytes <= budget
    out1 = h1.get()                          # re-promotes, evicts another
    assert out1.row_count() == 256
    got = np.asarray(out1.columns[0].data)[:256]
    want = np.asarray(b1.columns[0].data)[:256]
    assert (got == want).all()
    for h in (h1, h2, h3):
        h.close()
    assert store.device_bytes == 0 and store.host_bytes == 0


def test_store_disk_tier(tmp_path):
    b1, b2 = _batch(512, 4), _batch(512, 5)
    store = MEM.DeviceStore(device_budget=b1.sizeof() + 10,
                            host_budget=100,  # force host -> disk
                            spill_dir=str(tmp_path))
    h1 = store.register(b1)
    h2 = store.register(b2)
    assert store.disk_spill_count >= 1
    got = np.asarray(h1.get().columns[0].data)[:512]
    want = np.asarray(b1.columns[0].data)[:512]
    assert (got == want).all()
    h1.close()
    h2.close()


def test_exchange_completes_under_tiny_budget_with_spill():
    """An exchange whose materialized output exceeds the HBM budget by far
    must still produce exact results, with spill metrics > 0."""
    conf = {
        "spark.rapids.memory.tpu.poolSize": str(64 << 10),  # 64 KiB
    }
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("v", LongGen())], 4000, 21),
            num_partitions=4)
        .repartition(8, "k").groupBy("k").agg(F.sum("v").alias("s"),
                                              F.count("*").alias("c")),
        conf=conf,
        expect_execs=["TpuExchange", "TpuHashAggregate"])
    store = MEM.get_device_store.__globals__["_STORE"]
    assert store is not None and store.spill_count > 0
    assert store.peak_device_bytes > 0


def test_global_sort_under_tiny_budget():
    conf = {"spark.rapids.memory.tpu.poolSize": str(64 << 10)}
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            gen_batch([("a", LongGen()), ("b", IntegerGen())], 3000, 22),
            num_partitions=4).orderBy("a", "b"),
        conf=conf, ignore_order=False,
        expect_execs=["TpuSort", "TpuExchange"])


def test_final_agg_bounded_merge():
    """Many partial batches with a small batchSizeRows force multi-round
    bounded merging; results must stay exact."""
    conf = {
        "spark.rapids.sql.batchSizeRows": "256",
        "spark.rapids.memory.tpu.poolSize": str(64 << 10),
    }
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            gen_batch([("k", IntegerGen()), ("v", LongGen())], 5000, 23),
            num_partitions=6)
        .groupBy("k").agg(F.sum("v").alias("s"), F.min("v").alias("mn"),
                          F.max("v").alias("mx"), F.count("v").alias("c")),
        conf=conf,
        expect_execs=["TpuHashAggregate"])


def test_out_of_core_sort_emits_bounded_sorted_batches():
    """A sort partition far beyond batchSizeRows takes the rank-split
    out-of-core path (multiple bounded output batches, spills under the
    tiny budget) and stays bit-identical, including key ties."""
    conf = {
        "spark.rapids.sql.batchSizeRows": "512",
        "spark.rapids.memory.tpu.poolSize": str(64 << 10),
    }
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            gen_batch([("a", SmallIntGen()), ("b", LongGen()),
                       ("c", IntegerGen())], 6000, 41),
            num_partitions=2).sortWithinPartitions("a", "b"),
        conf=conf, ignore_order=False,
        expect_execs=["TpuSort"])
    store = MEM.get_device_store.__globals__["_STORE"]
    assert store is not None and store.spill_count > 0


def test_chunked_join_under_tiny_budget():
    """A join whose stream side exceeds batchSizeRows joins in chunks
    against the resident build side; spills happen and results match."""
    conf = {
        "spark.rapids.sql.batchSizeRows": "512",
        "spark.rapids.memory.tpu.poolSize": str(64 << 10),
        # force the shuffled (chunked-stream) path
        "spark.rapids.sql.autoBroadcastJoinThreshold": "-1",
    }

    def fn(s):
        left = s.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("v", LongGen())], 6000, 42),
            num_partitions=3)
        right = s.createDataFrame(
            gen_batch([("k", SmallIntGen()), ("w", IntegerGen())], 700, 43),
            num_partitions=3)
        return left.join(right, on="k", how="left")
    assert_tpu_and_cpu_equal_collect(
        fn, conf=conf, expect_execs=["TpuShuffledHashJoin"])
    store = MEM.get_device_store.__globals__["_STORE"]
    assert store is not None and store.spill_count > 0


def test_range_partition_ragged_string_keys():
    """Batches whose longest strings land in different char-cap buckets
    must still rank globally (per-batch subkey word counts differ)."""
    def fn(s):
        a = ["x" * 3, "zz", "a"]
        b = ["y" * 20, "x" * 17, "b"]
        return s.createDataFrame({"v": a + b, "i": list(range(6))},
                                 "v string, i int",
                                 num_partitions=2).orderBy("v")
    assert_tpu_and_cpu_equal_collect(fn, ignore_order=False,
                                     expect_execs=["TpuSort"])


def test_range_partition_after_filter_under_tiny_budget():
    """Scattered active masks + spill round-trips: the remapped pids must
    still land every row in its rank-correct range partition."""
    conf = {"spark.rapids.memory.tpu.poolSize": str(32 << 10)}
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            gen_batch([("a", LongGen()), ("b", IntegerGen())], 4000, 31),
            num_partitions=5)
        .filter(F.col("b") % 3 != 0).orderBy("a", "b"),
        conf=conf, ignore_order=False,
        expect_execs=["TpuSort", "TpuExchange"])


# -- round 4: serialized disk spill format (pickle gone) -------------------

def test_serde_roundtrip_all_types():
    """The spill/shuffle batch format round-trips every column class:
    fixed-width, strings, decimal64/128 limbs, arrays — with each codec
    (GpuColumnarBatchSerializer + TableCompressionCodec roles)."""
    from decimal import Decimal
    from spark_rapids_tpu.columnar import serde
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.sql import types as T
    schema = T.StructType([
        T.StructField("i", T.IntegerT),
        T.StructField("d", T.DoubleT),
        T.StructField("s", T.StringT),
        T.StructField("dec", T.DecimalType(12, 2)),
        T.StructField("big", T.DecimalType(30, 4)),
        T.StructField("arr", T.ArrayType(T.LongT)),
    ])
    batch = HostBatch.from_pydict({
        "i": [1, None, 3],
        "d": [1.5, float("nan"), None],
        "s": ["a", None, "日本語"],
        "dec": [Decimal("12.34"), None, Decimal("-0.05")],
        "big": [Decimal("123456789012345678901234.5678"), None,
                Decimal("-1.0000")],
        "arr": [[1, 2], None, []],
    }, schema)
    import math

    def same(a, b):
        if isinstance(a, float) and isinstance(b, float):
            return (math.isnan(a) and math.isnan(b)) or a == b
        return a == b

    want = batch.to_pydict()
    for codec in ("none", "zlib", "zstd"):
        data = serde.serialize_batch(batch, codec)
        back = serde.deserialize_batch(data).to_pydict()
        assert back.keys() == want.keys()
        for k in want:
            assert all(same(x, y) for x, y in zip(back[k], want[k])), \
                (codec, k, back[k], want[k])
        assert data[:4] == b"SRTB"


def test_disk_spill_uses_serde_not_pickle(tmp_path):
    """Force a batch through the disk tier and check the file header is
    the serde magic (pickle is gone from the spill path)."""
    import glob
    import jax.numpy as jnp
    import numpy as np
    from spark_rapids_tpu import memory
    from spark_rapids_tpu.columnar.device import DeviceBatch
    from spark_rapids_tpu.columnar.host import HostBatch
    from spark_rapids_tpu.sql import types as T
    store = memory.DeviceStore(device_budget=1, host_budget=1,
                               spill_dir=str(tmp_path), codec="zstd")
    schema = T.StructType([T.StructField("x", T.LongT)])
    hb = HostBatch.from_pydict({"x": list(range(100))}, schema)
    h1 = store.register(DeviceBatch.from_host(hb))
    h2 = store.register(DeviceBatch.from_host(hb))  # evicts h1 to disk
    files = glob.glob(str(tmp_path / "spill-*.bin"))
    assert files, "expected a disk-tier spill file (budget=1 bytes)"
    with open(files[0], "rb") as f:
        assert f.read(4) == b"SRTB"
    got = h1.get().to_host().to_pydict()
    assert got == hb.to_pydict()
    h1.close()
    h2.close()
