"""Qualification/profiling tools + Python UDF surface tests
(reference `tools` module + PythonUDF placement)."""

from spark_rapids_tpu import tools
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)
from tests.datagen import IntegerGen, SmallIntGen, gen_batch


def _session():
    return TpuSparkSession({"spark.rapids.sql.enabled": "true"})


def test_qualify_reports_placement_and_reasons():
    s = _session()
    try:
        df = s.createDataFrame({"k": [1, 2], "v": [1.0, 2.0]},
                               "k int, v double")
        q = df.filter(F.col("k") > 0).groupBy("k").agg(
            F.sum("v").alias("sv"))  # float sum falls back by default
        rep = tools.qualify(s, q)
        assert "TpuFilter" in rep.device_ops
        assert any("HashAggregate" in n for n, _ in rep.cpu_ops)
        assert 0.0 < rep.op_coverage < 1.0
        assert "Qualification" in rep.format()
    finally:
        s.stop()


def test_profile_surfaces_metrics():
    s = _session()
    try:
        df = s.createDataFrame({"k": [1, 2, 1], "v": [10, 20, 30]},
                               "k int, v int")
        prof = tools.profile(s, df.filter(F.col("v") > 5).groupBy("k")
                             .agg(F.count("*").alias("c")))
        assert prof.rows == 2
        names = [n for n, _ in prof.operators]
        assert any("TpuHashAggregate" in n for n in names)
        all_metrics = {k for _n, m in prof.operators for k in m}
        assert "numOutputRows" in all_metrics
    finally:
        s.stop()


def test_udf_executes_and_falls_back():
    double_it = F.udf(lambda x: None if x is None else x * 2, "bigint")
    assert_tpu_fallback_collect(
        lambda s: s.createDataFrame(
            gen_batch([("a", IntegerGen())], 100, 3))
        .select(double_it("a").alias("d")),
        fallback_exec="CpuProjectExec")


def test_udf_values():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        up = F.udf(lambda a, b: (a or 0) + (b or 0), "bigint")
        df = s.createDataFrame({"a": [1, None, 3], "b": [10, 20, None]},
                               "a int, b int")
        got = [r.s for r in df.select(up("a", "b").alias("s")).collect()]
        assert got == [11, 20, 3]
    finally:
        s.stop()


def test_udf_decorator_with_type():
    @F.udf("bigint")
    def plus1(x):
        return None if x is None else x + 1
    s = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = s.createDataFrame({"v": [1, None, 3]}, "v int")
        got = [r.p for r in df.select(plus1("v").alias("p")).collect()]
        assert got == [2, None, 4]
    finally:
        s.stop()


def test_rollup_agg_over_grouping_column():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        df = s.createDataFrame({"k": [1, 1, 2], "v": [5, 6, 7]},
                               "k int, v int")
        rows = {(r.k, r.mk) for r in
                df.rollup("k").agg(F.max("k").alias("mk")).collect()}
        # the max(k) resolves to the EXPANDED key (null in the total row)
        assert rows == {(1, 1), (2, 2), (None, None)}
    finally:
        s.stop()


def test_event_log_and_offline_tools(tmp_path):
    """Per-query event logs + offline qualify/profile with NO live
    session (Qualification.scala:34 / Profiler.scala:31 roles)."""
    import subprocess
    import sys

    from spark_rapids_tpu import event_log
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu import tools

    log_dir = str(tmp_path / "events")
    spark = TpuSparkSession({
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.sql.eventLog.dir": log_dir,
    })
    try:
        df = spark.createDataFrame(
            {"k": [1, 2, 1, 3], "v": [10, 20, 30, 40]},
            "k int, v bigint")
        df.groupBy("k").agg(F.sum("v").alias("s")).collect()
        df.filter(F.col("v") > 15).collect()
    finally:
        spark.stop()

    events = list(event_log.read_events(log_dir))
    assert len(events) == 2
    assert all(e["event"] == "queryCompleted" for e in events)
    assert events[0]["outputRows"] == 3
    assert any(o.get("device") for o in events[0]["ops"])

    q = tools.qualify_log(log_dir)
    assert "queries: 2" in q and "operator coverage" in q
    p = tools.profile_log(log_dir)
    assert "timeline" in p and "aggregate operator metrics" in p

    # CLI entry, offline (no session)
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "qualify",
         "--log", log_dir],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "Qualification Report (offline)" in out.stdout


# -- round 4: udf-compiler (CatalystExpressionBuilder twin) ----------------

def test_udf_compiler_device_placement():
    """F.udf(lambda x: x + 1) compiles to an expression tree and the
    projection runs on device (udf-compiler Plugin.scala:27-37 role)."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    sp = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                          "spark.rapids.sql.udfCompiler.enabled": "true"})
    try:
        df = sp.createDataFrame({"a": [1, 5, 9]}, "a int")
        plus1 = F.udf(lambda x: x + 1, "int")
        sp.start_capture()
        r = df.select(plus1(F.col("a")).alias("u")).collect()
        pstr = "\n".join(p.tree_string()
                         for p in sp.get_captured_plans())
        assert [row[0] for row in r] == [2, 6, 10]
        assert "TpuProject" in pstr, pstr
    finally:
        sp.stop()


def test_udf_compiler_conditionals_and_fallback():
    from spark_rapids_tpu.sql.session import TpuSparkSession
    results = {}
    for on in ("false", "true"):
        sp = TpuSparkSession({
            "spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.udfCompiler.enabled": on})
        try:
            df = sp.createDataFrame(
                {"a": [1, 2, 5, -3], "b": [2.0, 0.5, 1.0, 4.0]},
                "a int, b double")
            cond = F.udf(lambda x: x * 2 if x > 1 else -x, "int")
            # uses a call -> NOT compilable; must silently stay Python
            hard = F.udf(lambda x: int(str(x)) + 1, "int")
            results[on] = df.select(
                cond(F.col("a")).alias("c"),
                hard(F.col("a")).alias("h")).collect()
        finally:
            sp.stop()
    assert results["false"] == results["true"]


def test_udf_compiler_v1_mod_math_strings_locals():
    """udf-compiler v1 (CatalystExpressionBuilder.scala:29-43 role):
    Python %, builtin abs/min/max, math.* calls, string methods, and
    local-variable dataflow all compile; results match row-at-a-time
    Python execution exactly."""
    import math
    import random

    from spark_rapids_tpu.sql import types as T
    from spark_rapids_tpu.sql.session import TpuSparkSession

    random.seed(4)
    n = 200
    rows = {"x": [random.randint(-50, 50) or 1 for _ in range(n)],
            "f": [random.uniform(0.5, 100.0) for _ in range(n)],
            "s": [random.choice([" Ab ", "cd", "EEf "])
                  for _ in range(n)]}

    def local_fn(x):
        t = x * 2
        u = t + 1
        return u if t > 0 else -u

    def run(enabled, compiler):
        s = TpuSparkSession({
            "spark.rapids.sql.enabled": enabled,
            "spark.rapids.sql.udfCompiler.enabled": compiler,
            "spark.rapids.sql.incompatibleOps.enabled": "true",
            "spark.rapids.sql.variableFloatAgg.enabled": "true"})
        df = s.createDataFrame(rows, "x int, f double, s string")
        u1 = F.udf(lambda x: x % 7 - (-x) % 3, T.IntegerT)
        u2 = F.udf(lambda s_: s_.upper().strip(), T.StringT)
        u3 = F.udf(local_fn, T.IntegerT)
        u4 = F.udf(lambda x: abs(x) + min(x, 3) + max(x, 0), T.IntegerT)
        u5 = F.udf(lambda f: math.sqrt(f) + math.log(f), T.DoubleT)
        q = df.select(u1(F.col("x")).alias("m"),
                      u2(F.col("s")).alias("u"),
                      u3(F.col("x")).alias("l"),
                      u4(F.col("x")).alias("a"),
                      u5(F.col("f")).alias("sq"), "x")
        out = [tuple(r) for r in q.collect()]
        s.stop()
        return out

    plain = run("false", "false")   # row-at-a-time = ground truth
    cpu = run("false", "true")
    dev = run("true", "true")

    def close(p, q):
        return all(
            (a == b) or (isinstance(a, float)
                         and abs(a - b) <= 1e-9 * max(abs(a), abs(b)))
            for a, b in zip(p, q))
    assert all(close(p, q) for p, q in zip(plain, cpu))
    assert all(close(p, q) for p, q in zip(plain, dev))


def test_supported_ops_docs_generation():
    """docs generator derives from the LIVE registries (SupportedOpsDocs
    role): every exec and expression rule appears with its conf key."""
    from spark_rapids_tpu import overrides as O
    from spark_rapids_tpu.tools import generate_supported_ops
    md = generate_supported_ops()
    for rule in O._EXEC_RULES.values():
        assert rule.conf_key in md, rule.conf_key
    for rule in list(O._EXPR_RULES.values())[:20]:
        assert rule.conf_key in md, rule.conf_key
    assert "ArrowEvalPythonExec" in md
