"""Regressions for Spark-semantics defects found in review: zero-divisor
nulls, decimal storage, first/last null handling, grouping by expressions,
float64 sort precision, DDL parsing, self-join dedup."""

from decimal import Decimal

import pytest

from spark_rapids_tpu.sql.session import TpuSparkSession
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def spark():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "false",
                         "spark.sql.shuffle.partitions": "4"})
    yield s
    s.stop()


def test_divide_by_zero_is_null(spark):
    df = spark.createDataFrame(
        {"a": [1.0, -1.0, 0.0], "b": [0.0, 0.0, 0.0]}, "a double, b double")
    out = df.select((F.col("a") / F.col("b")).alias("d"),
                    (F.col("a") % F.col("b")).alias("m")).collect()
    assert [r.d for r in out] == [None, None, None]
    assert [r.m for r in out] == [None, None, None]
    # int zero divisor too
    df2 = spark.createDataFrame({"a": [7], "b": [0]}, "a int, b int")
    out2 = df2.select((F.col("a") / F.col("b")).alias("d"),
                      (F.col("a") % F.col("b")).alias("m")).collect()
    assert out2[0].d is None and out2[0].m is None


def test_decimal_storage_roundtrip(spark):
    df = spark.createDataFrame({"d": [Decimal("1.00"), Decimal("2.50"),
                                      None]}, "d decimal(10,2)")
    out = df.collect()
    assert out[0].d == Decimal("1.00")
    assert out[1].d == Decimal("2.50")
    assert out[2].d is None
    s = df.agg(F.min("d").alias("lo"), F.max("d").alias("hi")).collect()
    assert s[0].lo == Decimal("1.00") and s[0].hi == Decimal("2.50")


def test_first_respects_nulls(spark):
    df = spark.createDataFrame(
        {"k": [1, 1, 2, 2], "v": [None, 5, 7, None]}, "k int, v int",
        num_partitions=1)
    out = {r.k: (r.f, r.l) for r in df.groupBy("k").agg(
        F.first("v").alias("f"), F.last("v").alias("l")).collect()}
    assert out[1] == (None, 5)   # first row's null is kept
    assert out[2] == (7, None)
    out2 = {r.k: r.f for r in df.groupBy("k").agg(
        F.first("v", ignorenulls=True).alias("f")).collect()}
    assert out2[1] == 5 and out2[2] == 7


def test_group_by_expression(spark):
    df = spark.createDataFrame({"a": [1, 2, 3, 4, 5, 6]}, "a int")
    out = df.groupBy(F.col("a") % 2).agg(F.count("*").alias("c")).collect()
    got = sorted((r[0], r.c) for r in out)
    assert got == [(0, 3), (1, 3)]


def test_sort_adjacent_doubles(spark):
    vals = [1.0000000000000002, 1.0, 0.9999999999999999]
    df = spark.createDataFrame({"x": vals}, "x double")
    out = [r.x for r in df.orderBy("x").collect()]
    assert out == sorted(vals)


def test_ddl_with_decimal(spark):
    df = spark.createDataFrame({"d": [Decimal("3.14")], "i": [1]},
                               "d decimal(10,2), i int")
    assert df.schema.fields[0].data_type.scale == 2
    assert df.collect()[0].d == Decimal("3.14")


def test_count_distinct(spark):
    """DISTINCT aggregates execute via the planner's dedup-then-aggregate
    rewrite (RewriteDistinctAggregates single-group shape)."""
    df = spark.createDataFrame({"x": [1, 1, 2, None]}, "x int")
    out = df.agg(F.countDistinct("x").alias("c")).collect()
    assert out[0].c == 2


def test_drop_duplicates(spark):
    df = spark.createDataFrame(
        {"k": [1, 1, 2], "v": ["a", "b", "c"]}, "k int, v string",
        num_partitions=1)
    out = df.dropDuplicates(["k"]).collect()
    assert len(out) == 2
    assert {r.k for r in out} == {1, 2}
