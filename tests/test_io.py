"""IO layer tests: readers (all formats, 3 reader strategies), writers
(modes, partitionBy), cache serializer, and the device path over file scans.

Mirrors the reference's parquet_test.py / orc_test.py / csv_test.py
round-trip patterns (integration_tests/src/main/python)."""

import os
import shutil

import numpy as np
import pytest

from spark_rapids_tpu.sql.session import TpuSparkSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T

from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)


@pytest.fixture
def tmpdir_path(tmp_path):
    return str(tmp_path)


def _mixed_df(spark, n=500):
    rng = np.random.default_rng(7)
    k = [int(x) if x % 7 else None for x in rng.integers(0, 50, n)]
    v = [float(x) if x % 5 else None for x in rng.normal(0, 100, n)]
    s = [f"s{x}" if x % 3 else None for x in rng.integers(0, 99, n)]
    return spark.createDataFrame({"k": k, "v": v, "s": s},
                                 "k bigint, v double, s string")


def _write_dataset(path, fmt="parquet", n=500):
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = _mixed_df(spark, n)
        getattr(df.write.mode("overwrite"), fmt)(path)
    finally:
        spark.stop()


# -- round trips ------------------------------------------------------------

@pytest.mark.parametrize("fmt", ["parquet", "orc", "json"])
def test_roundtrip_self_describing(tmpdir_path, fmt):
    path = os.path.join(tmpdir_path, fmt)
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = _mixed_df(spark)
        expected = sorted((tuple(r) for r in df.collect()),
                          key=lambda t: str(t))
        getattr(df.write, fmt)(path)
        back = getattr(spark.read, fmt)(path)
        got = sorted((tuple(r) for r in back.collect()),
                     key=lambda t: str(t))
        assert got == expected
    finally:
        spark.stop()


def test_roundtrip_csv_with_schema(tmpdir_path):
    path = os.path.join(tmpdir_path, "csv")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = _mixed_df(spark)
        expected = sorted((tuple(r) for r in df.collect()),
                          key=lambda t: str(t))
        df.write.csv(path, header=True)
        back = spark.read.csv(path, schema="k bigint, v double, s string",
                              header=True)
        got = sorted((tuple(r) for r in back.collect()),
                     key=lambda t: str(t))
        assert got == expected
    finally:
        spark.stop()


def test_csv_infer_schema(tmpdir_path):
    path = os.path.join(tmpdir_path, "csv")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = spark.createDataFrame({"a": [1, 2], "b": [1.5, 2.5]},
                                   "a bigint, b double")
        df.write.csv(path, header=True)
        back = spark.read.option("inferSchema", "true") \
            .option("header", "true").format("csv").load(path)
        assert [f.data_type for f in back.schema.fields] == \
            [T.LongT, T.DoubleT]
        assert back.count() == 2
    finally:
        spark.stop()


@pytest.mark.parametrize("reader_type",
                         ["PERFILE", "MULTITHREADED", "COALESCING"])
def test_parquet_reader_strategies(tmpdir_path, reader_type):
    path = os.path.join(tmpdir_path, "multi")
    os.makedirs(path)
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        # several files -> several scan units per partition
        for i in range(4):
            df = spark.createDataFrame(
                {"a": list(range(i * 10, i * 10 + 10))}, "a bigint")
            df.write.mode("overwrite").parquet(
                os.path.join(path, f"sub{i}"))
    finally:
        spark.stop()
    spark = TpuSparkSession({
        "spark.rapids.sql.enabled": "false",
        "spark.rapids.sql.format.parquet.reader.type": reader_type})
    try:
        got = sorted(r.a for r in spark.read.parquet(path).collect())
        assert got == list(range(40))
    finally:
        spark.stop()


def test_multithreaded_reader_fault_propagates_and_cancels(tmpdir_path,
                                                           monkeypatch):
    """A decode_host future that raises mid-stream must surface the
    error to the caller AND cancel outstanding prefetch futures instead
    of leaking pool work (ISSUE 1 satellite); the shared pool must stay
    usable for the next query."""
    import time

    from spark_rapids_tpu.io import readers as RD

    path = os.path.join(tmpdir_path, "multi")
    os.makedirs(path)
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        for i in range(10):
            spark.createDataFrame(
                {"a": list(range(i * 10, i * 10 + 10))},
                "a bigint").write.mode("overwrite").parquet(
                os.path.join(path, f"sub{i}"))
    finally:
        spark.stop()

    calls = []
    real_read = RD._read_unit

    def faulty_read(fmt, unit, schema, options):
        calls.append(unit.path)
        if "sub0" in unit.path:
            raise RuntimeError("injected decode fault")
        time.sleep(0.05)  # keep later prefetches queued, not running
        return real_read(fmt, unit, schema, options)

    monkeypatch.setattr(RD, "_read_unit", faulty_read)
    conf = {
        "spark.rapids.sql.enabled": "false",
        "spark.rapids.sql.format.parquet.reader.type": "MULTITHREADED",
        "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads":
            "2",
    }
    spark = TpuSparkSession(conf)
    try:
        with pytest.raises(RuntimeError, match="injected decode fault"):
            spark.read.parquet(path).collect()
    finally:
        spark.stop()
    # the error cancelled the un-started prefetch window: the pool never
    # decoded the whole dataset
    assert len(calls) < 10, calls

    # and the shared pool is healthy for the next (fault-free) query
    monkeypatch.setattr(RD, "_read_unit", real_read)
    spark = TpuSparkSession(conf)
    try:
        got = sorted(r.a for r in spark.read.parquet(path).collect())
        assert got == list(range(100))
    finally:
        spark.stop()


def test_reader_batch_size_rows_splits_batches(tmpdir_path):
    path = os.path.join(tmpdir_path, "p")
    _write_dataset(path, n=100)
    spark = TpuSparkSession({
        "spark.rapids.sql.enabled": "false",
        "spark.rapids.sql.reader.batchSizeRows": "16"})
    try:
        physical = spark.plan_physical(spark.read.parquet(path).plan)
        batches = [b for t in physical.partitions() for b in t()]
        assert all(b.num_rows <= 16 for b in batches)
        assert sum(b.num_rows for b in batches) == 100
    finally:
        spark.stop()


# -- write modes / partitioning --------------------------------------------

def test_write_modes(tmpdir_path):
    path = os.path.join(tmpdir_path, "m")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = spark.createDataFrame({"a": [1, 2, 3]}, "a bigint")
        df.write.parquet(path)
        with pytest.raises(FileExistsError):
            df.write.parquet(path)
        df.write.mode("ignore").parquet(path)
        df.write.mode("append").parquet(path)
        assert spark.read.parquet(path).count() == 6
        df.write.mode("overwrite").parquet(path)
        assert spark.read.parquet(path).count() == 3
    finally:
        spark.stop()


def test_partitioned_write_layout(tmpdir_path):
    path = os.path.join(tmpdir_path, "part")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = spark.createDataFrame(
            {"k": [1, 1, 2, None], "v": [10, 20, 30, 40]},
            "k bigint, v bigint")
        df.write.partitionBy("k").parquet(path)
        dirs = {d for d in os.listdir(path) if not d.startswith("_")}
        assert dirs == {"k=1", "k=2", "k=__HIVE_DEFAULT_PARTITION__"}
        # data files under the partition dir exclude the partition column
        sub = spark.read.parquet(os.path.join(path, "k=1"))
        assert sub.columns == ["v"]
        assert sorted(r.v for r in sub.collect()) == [10, 20]
    finally:
        spark.stop()


# -- cache ------------------------------------------------------------------

def test_cache_materializes_once(tmpdir_path):
    path = os.path.join(tmpdir_path, "c")
    _write_dataset(path, n=50)
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        cached = spark.read.parquet(path).cache()
        assert cached.count() == 50
        rel = cached.plan
        payloads1 = rel.materialize()
        assert rel.cached_bytes > 0
        assert rel.materialize() is payloads1  # no re-execution
        assert cached.filter(F.col("k") > 10).count() > 0
    finally:
        spark.stop()


# -- device path over file scans -------------------------------------------

def test_device_agg_over_parquet_scan(tmpdir_path):
    path = os.path.join(tmpdir_path, "dev")
    _write_dataset(path, n=400)

    def q(spark):
        return (spark.read.parquet(path)
                .filter(F.col("k") > 5)
                .groupBy("k")
                .agg(F.count("v").alias("c"), F.min("k").alias("lo")))

    assert_tpu_and_cpu_equal_collect(
        q, expect_execs=["TpuHashAggregate", "TpuFilter"])


def test_device_scan_is_transparent_not_fallback(tmpdir_path):
    path = os.path.join(tmpdir_path, "dev2")
    _write_dataset(path, n=50)
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        df = spark.read.parquet(path).filter(F.col("k") >= 0)
        df.collect()
        report = spark.last_rewrite_report
        assert report is not None and report.replaced_any
        assert report.fallbacks == [], report.format()
    finally:
        spark.stop()


# -- Hive partition discovery (PartitioningAwareFileIndex twin) -------------

def test_partitionby_roundtrip_recovers_partition_column(tmpdir_path):
    p = os.path.join(tmpdir_path, "pds")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = spark.createDataFrame(
            {"k": [1, 1, 2, 2, 3], "v": [10.0, 20.0, 30.0, None, 50.0]},
            "k int, v double")
        df.write.partitionBy("k").mode("overwrite").parquet(p)
        back = spark.read.parquet(p)
        names = [f.name for f in back.plan.schema.fields]
        assert set(names) == {"k", "v"}
        rows = sorted((r.k, r.v) for r in back.collect()
                      if r.v is not None)
        assert rows == [(1, 10.0), (1, 20.0), (2, 30.0), (3, 50.0)]
        # null partition value round-trips as null (__HIVE_DEFAULT_PARTITION__)
        df2 = spark.createDataFrame({"k": [None, 5], "v": [1.0, 2.0]},
                                    "k int, v double")
        p2 = os.path.join(tmpdir_path, "pds2")
        df2.write.partitionBy("k").parquet(p2)
        back2 = {(r.k, r.v) for r in spark.read.parquet(p2).collect()}
        assert back2 == {(None, 1.0), (5, 2.0)}
    finally:
        spark.stop()


def test_partition_column_type_inference(tmpdir_path):
    root = os.path.join(tmpdir_path, "typed")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        df = spark.createDataFrame(
            {"tag": ["a", "b"], "v": [1.5, 2.5]}, "tag string, v double")
        df.write.partitionBy("tag").parquet(root)
        back = spark.read.parquet(root)
        sch = {f.name: f.data_type for f in back.plan.schema.fields}
        assert isinstance(sch["tag"], T.StringType)
        assert {(r.tag, r.v) for r in back.collect()} == {
            ("a", 1.5), ("b", 2.5)}
    finally:
        spark.stop()


def test_partitioned_scan_on_device(tmpdir_path):
    p = os.path.join(tmpdir_path, "pdev")
    _spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        _spark.createDataFrame(
            {"k": [1, 2, 1, 2, 1], "v": [1.0, 2.0, 3.0, 4.0, 5.0]},
            "k int, v double").write.partitionBy("k").parquet(p)
    finally:
        _spark.stop()
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.read.parquet(p).groupBy("k").agg(
            F.count("v").alias("c")),
        expect_execs=["TpuHashAggregate"])


# -- CSV permissive column-count handling -----------------------------------

def test_csv_more_columns_than_schema(tmpdir_path):
    f = os.path.join(tmpdir_path, "wide.csv")
    with open(f, "w") as fh:
        fh.write("a,b,c\n1,2,3\n4,5,6\n")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        got = spark.read.csv(f, schema="a bigint, b bigint",
                             header=True).collect()
        assert [(r.a, r.b) for r in got] == [(1, 2), (4, 5)]
    finally:
        spark.stop()


def test_csv_fewer_columns_than_schema(tmpdir_path):
    f = os.path.join(tmpdir_path, "narrow.csv")
    with open(f, "w") as fh:
        fh.write("a\n1\n4\n")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        got = spark.read.csv(f, schema="a bigint, b bigint",
                             header=True).collect()
        assert [(r.a, r.b) for r in got] == [(1, None), (4, None)]
    finally:
        spark.stop()


def test_partition_value_escaping_roundtrip(tmpdir_path):
    p = os.path.join(tmpdir_path, "esc")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        spark.createDataFrame(
            {"tag": ["a/b", "c=d", "plain"], "v": [1.0, 2.0, 3.0]},
            "tag string, v double").write.partitionBy("tag").parquet(p)
        back = {(r.tag, r.v) for r in spark.read.parquet(p).collect()}
        assert back == {("a/b", 1.0), ("c=d", 2.0), ("plain", 3.0)}
    finally:
        spark.stop()


def test_csv_extra_column_name_collision(tmpdir_path):
    f = os.path.join(tmpdir_path, "collide.csv")
    with open(f, "w") as fh:
        fh.write("x,y,a\n1,2,3\n")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        got = spark.read.csv(f, schema="a bigint, b bigint",
                             header=True).collect()
        assert [(r.a, r.b) for r in got] == [(1, 2)]
    finally:
        spark.stop()


def test_csv_mismatch_keeps_null_value_option(tmpdir_path):
    f = os.path.join(tmpdir_path, "nv.csv")
    with open(f, "w") as fh:
        fh.write("a\n1\nXX\n")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        got = spark.read.format("csv").schema("a bigint, b bigint") \
            .option("header", "true").option("nullValue", "XX").load(f) \
            .collect()
        assert [(r.a, r.b) for r in got] == [(1, None), (None, None)]
    finally:
        spark.stop()


def test_partition_value_not_loosely_numeric(tmpdir_path):
    """'1_0' parses with Python int() but not Arrow's cast — must stay a
    string column (Spark's strict Long.parseLong shape)."""
    p = os.path.join(tmpdir_path, "loose")
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        spark.createDataFrame(
            {"tag": ["1_0", "2_5"], "v": [1.0, 2.0]},
            "tag string, v double").write.partitionBy("tag").parquet(p)
        back = spark.read.parquet(p)
        sch = {f.name: f.data_type for f in back.plan.schema.fields}
        assert isinstance(sch["tag"], T.StringType)
        assert {(r.tag, r.v) for r in back.collect()} == {
            ("1_0", 1.0), ("2_5", 2.0)}
    finally:
        spark.stop()


# -- round 4: parquet row-group predicate pushdown -------------------------

def _write_sorted_parquet(spark, tmp_path, n=20000, parts=4):
    import numpy as np
    df = spark.createDataFrame(
        {"x": list(range(n)),
         "d": [18000 + (i % 1000) for i in range(n)],
         "s": [f"k{i:06d}" for i in range(n)]},
        "x long, d date, s string", num_partitions=parts)
    path = str(tmp_path / "push.parquet")
    df.write.mode("overwrite").parquet(path)
    return path


def test_pushdown_prunes_row_groups(tmp_path):
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.sql import functions as F
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        path = _write_sorted_parquet(spark, tmp_path)
        q = (spark.read.parquet(path).where(F.col("x") >= 15000)
             .groupBy().agg(F.count("*").alias("c")))
        spark.start_capture()
        res = q.collect()
        pstr = "\n".join(p.tree_string()
                         for p in spark.get_captured_plans())
        assert res[0][0] == 5000
        # x is globally sorted across files: low row groups must go
        assert "pushed 1 filters" in pstr and "pruned" in pstr, pstr
        assert "pruned 0 units" not in pstr, pstr
    finally:
        spark.stop()


def test_pushdown_all_pruned_keeps_global_agg(tmp_path):
    from spark_rapids_tpu.sql.session import TpuSparkSession
    from spark_rapids_tpu.sql import functions as F
    spark = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        path = _write_sorted_parquet(spark, tmp_path)
        res = (spark.read.parquet(path).where(F.col("x") > 10 ** 9)
               .groupBy().agg(F.count("*").alias("c"))).collect()
        assert res[0][0] == 0  # one global-agg row even with 0 units
    finally:
        spark.stop()


def test_pushdown_equality_and_strings_correct(tmp_path):
    from tests.harness import assert_tpu_and_cpu_equal_collect
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    path = _write_sorted_parquet(gen, tmp_path)
    gen.stop()
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.read.parquet(path)
        .where((F.col("s") == "k000042") & F.col("x").isNotNull()),
        expect_execs=["TpuFilter"])


def test_scan_fans_out_across_task_parallelism(tmp_path):
    """FilePartition.maxSplitBytes: with taskParallelism > 1 a multi-
    file dataset splits into multiple scan partitions (openCostInBytes
    weighs each unit); with the default parallelism it packs as before."""
    import re
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSparkSession
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    path = str(tmp_path / "fan.parquet")
    gen.createDataFrame({"x": list(range(40000))}, "x long",
                        num_partitions=8).write.mode("overwrite") \
        .parquet(path)
    gen.stop()

    def nparts(conf):
        sp = TpuSparkSession(conf)
        try:
            sp.start_capture()
            out = sp.read.parquet(path).groupBy().agg(
                F.count("*").alias("c")).collect()
            assert out[0][0] == 40000
            pstr = "\n".join(p.tree_string()
                             for p in sp.get_captured_plans())
            line = [ln for ln in pstr.splitlines() if "FileScan" in ln][0]
            return int(re.search(r"(\d+) partitions", line).group(1))
        finally:
            sp.stop()

    wide = nparts({"spark.rapids.sql.enabled": "true",
                   "spark.rapids.sql.taskParallelism": "4"})
    assert wide > 1, wide


def test_orc_stripe_units(tmp_path):
    """Multi-stripe ORC files split into stripe-granularity scan units
    (GpuOrcScanBase.scala:66 stripe-copy role) with identical results."""
    import pyarrow as pa
    import pyarrow.orc as po

    from spark_rapids_tpu.io.readers import list_files, plan_scan_units
    path = str(tmp_path / "t.orc")
    t = pa.table({"k": [i % 7 for i in range(200000)],
                  "v": list(range(200000))})
    po.write_table(t, path, stripe_size=64 << 10)
    units = plan_scan_units("orc", list_files([path]))
    assert len(units) == po.ORCFile(path).nstripes > 1

    def q(s):
        return s.read.orc(path).groupBy("k").agg(
            F.sum("v").alias("sv")).orderBy("k")
    assert_tpu_and_cpu_equal_collect(q, require_device=False)


def test_ml_interop_device_batches():
    """ColumnarRdd.convert role (ColumnarRdd.scala:42): a DataFrame's
    device plan hands its HBM-resident batches / jax arrays straight to
    ML code, no host round trip."""
    import numpy as np

    from spark_rapids_tpu import interop
    from spark_rapids_tpu.sql.session import TpuSparkSession
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    df = s.createDataFrame(
        {"x": [float(i) for i in range(1000)],
         "y": [i % 5 for i in range(1000)]},
        "x double, y int", num_partitions=2)
    df2 = df.filter(F.col("y") > 0)
    arrs = interop.to_jax_arrays(df2)
    assert set(arrs) == {"x", "y"}
    n = int(sum(1 for i in range(1000) if i % 5 > 0))
    assert arrs["x"].shape == (n,)
    assert float(np.asarray(arrs["x"]).sum()) == sum(
        float(i) for i in range(1000) if i % 5 > 0)
    parts = interop.to_device_batches(df2)
    assert sum(b.row_count() for p in parts for b in p) == n
    s.stop()
