"""Query-profile corpus (docs/observability.md "Reading a query
profile"): artifact schema well-formedness, bit-identical results with
profiling on/off, per-op peak-bytes sanity (owner-attributed HBM
accounting incl. under injected OOM), explain=NOT_ON_TPU|ALL output for
a forced fallback, the `tools profile` CLI, the metric-description lint
(every metric a Tpu*Exec registers must resolve in the central table),
the registry-epoch satellite, and the event-log round trip for the new
fallbackSummary/memoryByOperator fields."""

from __future__ import annotations

import glob
import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import memory as MEM
from spark_rapids_tpu import metrics as M
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.columnar.device import DeviceBatch
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           gen_batch)


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()


def _conf(profile_dir=None, **extra):
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    if profile_dir is not None:
        conf["spark.rapids.sql.profile.enabled"] = "true"
        conf["spark.rapids.sql.profile.dir"] = str(profile_dir)
    conf.update(extra)
    return conf


def _q1_silhouette(s):
    df = s.createDataFrame(
        gen_batch([("flag", KeyStringGen(cardinality=3)),
                   ("status", SmallIntGen()),
                   ("qty", LongGen()), ("price", IntegerGen())],
                  3000, 31),
        num_partitions=4)
    return (df.filter(F.col("qty") % 5 != 0)
            .groupBy("flag", "status")
            .agg(F.sum("qty").alias("sq"), F.min("price").alias("mn"),
                 F.max("price").alias("mx"), F.count("*").alias("c"))
            .orderBy("flag", "status"))


def _q3_silhouette(s):
    fact = s.createDataFrame(
        gen_batch([("k", SmallIntGen()), ("item", IntegerGen()),
                   ("amt", LongGen())], 2500, 32),
        num_partitions=3)
    dim = s.createDataFrame(
        gen_batch([("item2", IntegerGen()),
                   ("brand", KeyStringGen(cardinality=5))], 400, 33),
        num_partitions=2)
    return (fact.join(dim, fact["item"] == dim["item2"], "inner")
            .groupBy("brand").agg(F.sum("amt").alias("sa"),
                                  F.count("*").alias("c"))
            .orderBy("brand").limit(50))


def _run(df_fn, conf):
    spark = TpuSparkSession(conf)
    try:
        out = df_fn(spark)._execute().to_pydict()
        return out, spark.last_profile_path
    finally:
        spark.stop()


# ---------------------------------------------------------------------------
# Artifact schema well-formedness
# ---------------------------------------------------------------------------

def _walk_plan(entry):
    yield entry
    for fe in entry.get("fused", []):
        yield fe
    for c in entry.get("children", []):
        yield from _walk_plan(c)


def test_profile_artifact_schema_wellformed(tmp_path):
    _out, path = _run(_q1_silhouette, _conf(tmp_path / "prof"))
    assert path is not None and os.path.exists(path), path
    with open(path) as f:
        prof = json.load(f)
    for key in ("version", "queryId", "wallSeconds", "outputRows",
                "plan", "memory", "explain", "conf", "jitCaches"):
        assert key in prof, key
    assert prof["version"] == 1 and prof["outputRows"] > 0
    nodes = list(_walk_plan(prof["plan"]))
    assert any(n["op"] == "TpuHashAggregateExec" for n in nodes), nodes
    assert any(n.get("device") for n in nodes)
    # every node has op + simpleString; device nodes carry metrics with
    # numOutputRows present (zero-valued metrics kept)
    for n in nodes:
        assert n["op"] and n["simpleString"]
    device_metrics = [n["metrics"] for n in nodes
                      if n.get("device") and "metrics" in n]
    assert any("numOutputRows" in m for m in device_metrics)
    # the memory section reconciles: per-op live bytes sum to the pool
    pool = prof["memory"]["pool"]
    ops = prof["memory"]["operators"]
    assert sum(st["liveBytes"] for st in ops.values()) \
        == pool["deviceBytes"]
    if ops:
        assert pool["peakDeviceBytes"] \
            <= sum(st["peakBytes"] for st in ops.values())
    # explain: this query is fully placed
    assert prof["explain"]["coverage"] == 1.0
    assert prof["explain"]["deviceOps"]


@pytest.mark.parametrize("df_fn", [_q1_silhouette, _q3_silhouette],
                         ids=["q1", "q3"])
def test_profiled_results_bit_identical(df_fn, tmp_path):
    clean, _ = _run(df_fn, _conf())
    profiled, path = _run(df_fn, _conf(tmp_path / "prof"))
    assert profiled == clean
    assert path is not None


# ---------------------------------------------------------------------------
# Owner-attributed HBM accounting
# ---------------------------------------------------------------------------

def _batch(n=256, seed=0):
    rng = np.random.default_rng(seed)
    col = HostColumn(T.LongT, rng.integers(0, 1 << 40, n),
                     np.ones(n, dtype=bool))
    return DeviceBatch.from_host(
        HostBatch(T.StructType([T.StructField("v", T.LongT)]), [col], n))


def test_store_owner_ledger_spill_shrinks_live_peak_monotone(tmp_path):
    """Unit sanity on the ledger: registration grows live+peak, an LRU
    spill shrinks the owner's LIVE bytes while its PEAK stays put, and
    the per-op live sum always equals the pool's device bytes."""
    b1, b2, b3 = _batch(256, 1), _batch(256, 2), _batch(256, 3)
    store = MEM.DeviceStore(b1.sizeof() * 2 + 10, 1 << 30,
                            str(tmp_path))
    reg_a = M.MetricRegistry(owner="OpA")
    reg_b = M.MetricRegistry(owner="OpB")
    h1 = store.register(b1, owner="OpA", metrics=reg_a)
    assert store.owner_stats()["OpA"]["liveBytes"] == b1.sizeof()
    h2 = store.register(b2, owner="OpA", metrics=reg_a)
    peak_a = store.owner_stats()["OpA"]["peakBytes"]
    assert peak_a == b1.sizeof() + b2.sizeof()
    # third registration under a second owner forces an LRU spill of
    # OpA's oldest handle
    h3 = store.register(b3, owner="OpB", metrics=reg_b)
    st = store.owner_stats()
    assert store.spill_count >= 1
    assert st["OpA"]["liveBytes"] < peak_a          # spill shrank live
    assert st["OpA"]["peakBytes"] == peak_a         # peak is monotone
    assert sum(s["liveBytes"] for s in st.values()) \
        == store.device_bytes                        # ledger reconciles
    assert store.peak_device_bytes <= sum(
        s["peakBytes"] for s in st.values())
    # the owning exec's metrics got the attribution
    assert reg_a.value(M.PEAK_DEVICE_MEMORY) == peak_a
    assert reg_a.value(M.SPILL_BYTES) > 0
    assert reg_b.value(M.SPILL_BYTES) == 0
    for h in (h1, h2, h3):
        h.close()
    st = store.owner_stats()
    assert all(s["liveBytes"] == 0 for s in st.values())
    # reset_peaks re-bases the watermarks at current (zero) occupancy
    store.reset_peaks()
    assert store.peak_device_bytes == 0
    assert store.owner_stats() == {}


def test_peak_device_memory_is_per_instance_not_per_class(tmp_path):
    """Two exec INSTANCES of the same class must not report each
    other's bytes as their own peakDeviceMemory (the store ledger
    aggregates by class; the metric must not)."""
    b1, b2 = _batch(256, 6), _batch(256, 7)
    store = MEM.DeviceStore(1 << 30, 1 << 30, str(tmp_path))
    reg1 = M.MetricRegistry(owner="TpuShuffleExchangeExec")
    reg2 = M.MetricRegistry(owner="TpuShuffleExchangeExec")
    h1 = store.register(b1, owner="TpuShuffleExchangeExec", metrics=reg1)
    h2 = store.register(b2, owner="TpuShuffleExchangeExec", metrics=reg2)
    assert reg1.value(M.PEAK_DEVICE_MEMORY) == b1.sizeof()
    assert reg2.value(M.PEAK_DEVICE_MEMORY) == b2.sizeof()
    # the class-aggregated ledger still sees both
    assert store.owner_stats()["TpuShuffleExchangeExec"]["peakBytes"] \
        == b1.sizeof() + b2.sizeof()
    h1.close()
    h2.close()


def test_profile_peaks_rebased_per_query(tmp_path):
    """A tiny query after a big one (same session) must report its OWN
    pool/owner peaks, not the big query's high-watermark."""
    spark = TpuSparkSession(_conf(tmp_path / "prof"))
    try:
        _q1_silhouette(spark)._execute()
        big = json.load(open(spark.last_profile_path))
        (spark.createDataFrame({"k": [1, 2], "v": [3, 4]}, "k int, v int")
         .groupBy("k").agg(F.sum("v").alias("s")).orderBy("k")._execute())
        small = json.load(open(spark.last_profile_path))
    finally:
        spark.stop()
    big_peak = big["memory"]["pool"]["peakDeviceBytes"]
    small_peak = small["memory"]["pool"]["peakDeviceBytes"]
    assert 0 < small_peak < big_peak, (small_peak, big_peak)


@pytest.mark.fault
def test_per_op_peaks_sane_under_injected_oom(tmp_path):
    """Injected OOMs force retry spills; the profile's per-op ledger
    must stay consistent (live sums to pool, peaks bound the pool
    watermark) and results stay bit-identical."""
    clean, _ = _run(_q1_silhouette, _conf())
    R.reset_fault_injection()
    MEM.reset_store_peaks()
    profiled, path = _run(_q1_silhouette, _conf(
        tmp_path / "prof",
        **{"spark.rapids.sql.test.injectOOM": "3",
           "spark.rapids.sql.retry.backoffMs": "1",
           "spark.rapids.sql.retry.maxBackoffMs": "4"}))
    assert profiled == clean
    with open(path) as f:
        prof = json.load(f)
    ops = prof["memory"]["operators"]
    pool = prof["memory"]["pool"]
    assert sum(st["liveBytes"] for st in ops.values()) \
        == pool["deviceBytes"]
    for st in ops.values():
        assert st["peakBytes"] >= st["liveBytes"] >= 0
    # retry spills happened and were recorded per-plan
    metrics = {}
    for n in _walk_plan(prof["plan"]):
        for k, v in (n.get("metrics") or {}).items():
            metrics[k] = metrics.get(k, 0) + v
    assert metrics.get("retryCount", 0) > 0


def test_trace_counter_events_for_pool_occupancy(tmp_path):
    """With tracing on, store transitions sample deviceStoreBytes /
    hostStoreBytes as Chrome "C" counter events (the Perfetto HBM
    timeline)."""
    conf = _conf(**{"spark.rapids.sql.trace.enabled": "true",
                    "spark.rapids.sql.trace.dir": str(tmp_path / "tr")})
    _run(_q1_silhouette, conf)
    files = sorted(glob.glob(os.path.join(str(tmp_path / "tr"),
                                          "trace-*.json")))
    assert files
    tr = TR.load_trace(files[-1])
    series = {c["name"] for c in tr["counters"]}
    assert "deviceStoreBytes" in series, series
    assert all(isinstance(c["value"], int) for c in tr["counters"])
    assert tr["meta"]["counterCount"] == len(tr["counters"])


# ---------------------------------------------------------------------------
# Explain / fallback reasons
# ---------------------------------------------------------------------------

def test_explain_not_on_tpu_reports_forced_fallback(capsys, tmp_path):
    """A query with a known forced fallback (the Filter replacement
    disabled per-op) yields a non-empty NOT_ON_TPU report naming the op
    and the reason, and the profile aggregates it."""
    conf = _conf(tmp_path / "prof",
                 **{"spark.rapids.sql.explain": "NOT_ON_TPU",
                    "spark.rapids.sql.exec.FilterExec": "false"})
    spark = TpuSparkSession(conf)
    try:
        _q1_silhouette(spark)._execute()
        report = spark.last_rewrite_report
        path = spark.last_profile_path
    finally:
        spark.stop()
    out = capsys.readouterr().out
    assert "!Exec <CpuFilterExec> cannot run on TPU because " \
           "the exec has been disabled" in out, out
    assert report.fallbacks and report.coverage < 1.0
    with open(path) as f:
        ex = json.load(f)["explain"]
    assert any(fb["op"] == "CpuFilterExec" for fb in ex["fallbacks"])
    assert ex["reasonCounts"]


def test_explain_all_lists_device_ops(capsys):
    spark = TpuSparkSession(_conf(
        **{"spark.rapids.sql.explain": "ALL"}))
    try:
        _q1_silhouette(spark)._execute()
    finally:
        spark.stop()
    out = capsys.readouterr().out
    assert "will run on TPU" in out
    assert "TpuHashAggregateExec" in out or "HashAggregate" in out


def test_explain_not_on_gpu_alias(capsys):
    spark = TpuSparkSession(_conf(
        **{"spark.rapids.sql.explain": "NOT_ON_GPU",
           "spark.rapids.sql.exec.SortExec": "false"}))
    try:
        _q1_silhouette(spark)._execute()
    finally:
        spark.stop()
    assert "cannot run on TPU" in capsys.readouterr().out


def test_check_expr_tree_reason_names_offending_subtree():
    """The reason for a deep expression failure must render the
    offending SUBTREE, not just the expression class name."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.overrides import check_expr_tree
    from spark_rapids_tpu.sql import expressions as E
    attr = E.AttributeReference("s", T.StringT, True)
    # Upper is .incompat-gated: without incompatibleOps it falls back
    tree = E.Alias(E.Upper(attr), "u")
    reason = check_expr_tree(tree, TpuConf({}))
    assert reason is not None and "Upper" in reason
    assert "<" in reason and "s#" in reason, reason  # subtree named


# ---------------------------------------------------------------------------
# tools profile CLI
# ---------------------------------------------------------------------------

def test_tools_profile_cli_smoke(tmp_path, capsys):
    from spark_rapids_tpu.tools import _main
    pdir = tmp_path / "prof"
    _run(_q1_silhouette, _conf(pdir))
    path = sorted(glob.glob(os.path.join(str(pdir),
                                         "profile-*.json")))[0]
    assert _main(["profile", path]) == 0
    out = capsys.readouterr().out
    assert "annotated plan" in out
    assert "top memory consumers" in out
    assert "TpuHashAggregate" in out
    # directory mode renders every artifact in it
    assert _main(["profile", str(pdir)]) == 0
    # empty directory is reported, not a crash
    os.makedirs(tmp_path / "empty", exist_ok=True)
    assert _main(["profile", str(tmp_path / "empty")]) == 1
    # a path-looking argument that does NOT exist errors instead of
    # falling through to live-SQL mode and "executing" the path
    assert _main(["profile", str(tmp_path / "missing" / "p.json")]) == 1
    assert "no such profile" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Satellites: metric-description lint, registry epoch, event-log fields
# ---------------------------------------------------------------------------

def test_dynamic_metric_keys_are_described():
    """Runtime smoke for what the STATIC lint cannot see: metric keys
    built dynamically (f-string per-chip / per-encoding families like
    ``dispatchCount.chip3``) must still resolve via describe_metric.
    Literal keys and metrics.py constants are machine-checked by
    tpu-lint's `metric-key` rule (tests/test_lint.py), so one executed
    query shape suffices here."""
    spark = TpuSparkSession(_conf())
    try:
        spark.start_capture()
        _q1_silhouette(spark)._execute()
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    seen = set()

    def walk(p):
        ms = getattr(p, "metrics", None)
        if ms is not None:
            seen.update(ms.metrics.keys())
        for op in getattr(p, "fused_ops", []):
            fm = getattr(op, "metrics", None)
            if fm is not None:
                seen.update(fm.metrics.keys())
        for c in getattr(p, "children", []):
            walk(c)

    for p in plans:
        walk(p)
    assert seen, "no metrics registered?"
    undescribed = sorted(k for k in seen if M.describe_metric(k) is None)
    assert not undescribed, (
        f"metrics without an entry in metrics.METRIC_DESCRIPTIONS: "
        f"{undescribed} — add them so profile/docs/bench agree")


# The constant-is-described / description-table-in-docs directions of
# the drift guard are now STATIC: tpu-lint's `metric-key` rule checks
# every metrics.py constant against METRIC_DESCRIPTIONS and its
# `docs-drift` rule diffs docs/observability.md against the generator
# byte-for-byte (tests/test_lint.py asserts both over the real
# package). Only the dynamic-key smoke above still needs a live run.


def test_registry_epoch_scopes_process_wide_snapshot():
    """Satellite: process-wide registry_snapshot bleeds earlier runs'
    registries; an epoch stamp scopes it to registries created since
    begin_epoch()."""
    before = M.MetricRegistry(owner="Old")
    before.create("numOutputRows", M.ESSENTIAL).add(7)
    epoch = M.begin_epoch()
    after = M.MetricRegistry(owner="New")
    after.create("numOutputRows", M.ESSENTIAL).add(5)
    scoped = M.registry_snapshot(epoch=epoch)["metrics"]
    whole = M.registry_snapshot()["metrics"]
    assert scoped.get("numOutputRows", 0) < whole["numOutputRows"]
    # keep strong refs so the weak registry set cannot drop them early
    assert before.epoch < epoch <= after.epoch


def test_event_log_round_trip_fallback_summary_and_memory(tmp_path):
    from spark_rapids_tpu.event_log import read_events
    log_dir = str(tmp_path / "events")
    conf = _conf(**{"spark.rapids.sql.eventLog.dir": log_dir,
                    "spark.rapids.sql.exec.SortExec": "false"})
    _run(_q1_silhouette, conf)
    events = list(read_events(log_dir))
    assert len(events) == 1
    ev = events[0]
    assert ev["version"] == 2
    # per-query fallback summary rides along
    fs = ev["fallbackSummary"]
    assert fs["deviceOps"] and 0.0 < fs["coverage"] < 1.0
    assert fs["reasonCounts"]
    # per-op peak HBM ledger rides along and reconciles with storeStats
    mem = ev["memoryByOperator"]
    assert mem and all(set(v) == {"liveBytes", "peakBytes"}
                       for v in mem.values())
    assert sum(v["liveBytes"] for v in mem.values()) \
        == ev["storeStats"]["deviceBytes"]
