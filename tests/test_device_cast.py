"""Device cast matrix tests (GpuCast.scala:1338 / CastChecks coverage):
every supported from->to leg must bit-match the CPU oracle; unsupported
legs must fall back with a recorded reason; ANSI overflow must raise.
"""

import pytest

from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql import expressions as E
from spark_rapids_tpu.sql import types as T
from spark_rapids_tpu.sql.functions import Column
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (BooleanGen, DateGen, DoubleGen, IntegerGen,
                           LongGen, ShortGen, SmallIntGen, StringGen,
                           gen_batch)
from tests.harness import (assert_tpu_and_cpu_equal_collect,
                           assert_tpu_fallback_collect)

N = 300


def _df(spark, gens, n=N, seed=29, parts=2):
    return spark.createDataFrame(gen_batch(gens, n, seed),
                                 num_partitions=parts)


def _cast(name, to):
    return Column(E.Cast(F.col(name).expr, to)).alias("c")


NUMERIC_TARGETS = [("byte", T.ByteT), ("short", T.ShortT),
                   ("int", T.IntegerT), ("long", T.LongT),
                   ("double", T.DoubleT), ("float", T.FloatT)]


@pytest.mark.parametrize("to_name,to", NUMERIC_TARGETS,
                         ids=[n for n, _ in NUMERIC_TARGETS])
@pytest.mark.parametrize("gen", [IntegerGen(), LongGen(), DoubleGen()],
                         ids=["int", "long", "double"])
def test_numeric_to_numeric(gen, to_name, to):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("v", gen)]).select(_cast("v", to)),
        expect_execs=["TpuProject"])


def test_bool_numeric_legs():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("b", BooleanGen()), ("v", IntegerGen())])
        .select(_cast("b", T.IntegerT), _cast("v", T.BooleanT).alias("c2")),
        expect_execs=["TpuProject"])


@pytest.mark.parametrize("gen,name", [
    (IntegerGen(), "int"), (LongGen(), "long"), (SmallIntGen(), "small"),
    (BooleanGen(), "bool"), (DateGen(), "date")])
def test_to_string(gen, name):
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("v", gen)]).select(_cast("v", T.StringT)),
        expect_execs=["TpuProject"])


def test_string_to_int_parsing():
    vals = ["12", "-7", "+5", "  42  ", "99999999999999999999", "12.5",
            "abc", "", "  ", None, "9223372036854775807",
            "-9223372036854775808", "0012", "1 2"]
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame({"v": vals}, "v string",
                                    num_partitions=2)
        .select(_cast("v", T.LongT), _cast("v", T.IntegerT).alias("c2"),
                _cast("v", T.ShortT).alias("c3")),
        expect_execs=["TpuProject"])


def test_string_to_bool_parsing():
    vals = ["true", "FALSE", "t", "N", "yes", "no", "1", "0", "maybe",
            " True ", "", None]
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame({"v": vals}, "v string",
                                    num_partitions=2)
        .select(_cast("v", T.BooleanT)),
        expect_execs=["TpuProject"])


def test_string_to_date_parsing():
    vals = ["2021-03-05", "1999-12-31", "2020-02-29", "2019-02-29",
            "2021-13-01", "2021-00-10", "2021-3-5", " 2021-03-05 ",
            "2021", "garbage", "", None, "0001-01-01", "9999-12-31"]
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame({"v": vals}, "v string",
                                    num_partitions=2)
        .select(_cast("v", T.DateT)),
        expect_execs=["TpuProject"])


def test_date_string_roundtrip():
    assert_tpu_and_cpu_equal_collect(
        lambda s: _df(s, [("d", DateGen())])
        .select(Column(E.Cast(E.Cast(F.col("d").expr, T.StringT),
                              T.DateT)).alias("rt")),
        expect_execs=["TpuProject"])


def test_unsupported_cast_falls_back():
    # float -> string has Java Double.toString semantics; device declines
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("v", DoubleGen())]).select(_cast("v", T.StringT)),
        fallback_exec="CpuProjectExec")


def test_ansi_cast_overflow_raises_on_device():
    def q(s):
        return s.createDataFrame({"v": [1.0, 1e300]}, "v double") \
            .select(Column(E.Cast(F.col("v").expr, T.IntegerT,
                                  ansi=True)).alias("c"))
    for enabled in ("false", "true"):
        s = TpuSparkSession({"spark.rapids.sql.enabled": enabled})
        try:
            with pytest.raises(ArithmeticError):
                q(s).collect()
        finally:
            s.stop()
    # and the device path really ran it (no silent fallback)
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                         "spark.rapids.sql.test.forceDevice": "true"})
    try:
        with pytest.raises(ArithmeticError):
            q(s).collect()
    finally:
        s.stop()


def test_ansi_cast_ok_values_pass():
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame({"v": [1.5, -2.5, None]}, "v double")
        .select(Column(E.Cast(F.col("v").expr, T.IntegerT,
                              ansi=True)).alias("c")),
        expect_execs=["TpuProject"])


def test_ansi_cast_in_sort_key_falls_back():
    # small values: no overflow — the point is placement, not the error
    assert_tpu_fallback_collect(
        lambda s: _df(s, [("v", SmallIntGen())])
        .orderBy(Column(E.SortOrder(E.Cast(F.col("v").expr, T.LongT,
                                           ansi=True)))),
        fallback_exec="CpuSortExec")


def test_ansi_error_scoped_to_taken_branch():
    """CASE guards: the untaken branch's overflow must not raise."""
    def q(s):
        return s.createDataFrame({"v": [1.0, 1e300]}, "v double") \
            .select(Column(E.CaseWhen(
                [(E.LessThan(F.col("v").expr, E.Literal(100.0)),
                  E.Cast(F.col("v").expr, T.IntegerT, ansi=True))],
                E.Literal(0))).alias("c"))
    assert_tpu_and_cpu_equal_collect(q, expect_execs=["TpuProject"])


def test_ansi_overflow_exact_boundary():
    """2^63 rounds back onto int64 max in float space; must still raise."""
    def q(s):
        return s.createDataFrame({"v": [9.223372036854775808e18]},
                                 "v double") \
            .select(Column(E.Cast(F.col("v").expr, T.LongT,
                                  ansi=True)).alias("c"))
    for enabled in ("false", "true"):
        s = TpuSparkSession({"spark.rapids.sql.enabled": enabled})
        try:
            with pytest.raises(ArithmeticError):
                q(s).collect()
        finally:
            s.stop()


def test_sql_in_negative_literals_and_union_order():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        s.createDataFrame({"k": [-1, 2, 5]}, "k int") \
            .createOrReplaceTempView("neg")
        got = sorted(r.k for r in s.sql(
            "SELECT k FROM neg WHERE k IN (-1, 2)").collect())
        assert got == [-1, 2]
        ordered = [r.k for r in s.sql(
            "SELECT k FROM neg WHERE k > 0 UNION ALL "
            "SELECT k FROM neg WHERE k < 0 ORDER BY k LIMIT 2").collect()]
        assert ordered == [-1, 2]
    finally:
        s.stop()


def test_distinct_agg_with_expression_grouping():
    s = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        s.createDataFrame({"k": [1, 2, 3, 4], "v": [7, 7, 9, 9]},
                          "k int, v int").createOrReplaceTempView("eg")
        got = sorted((r.g, r.cv) for r in s.sql(
            "SELECT k % 2 AS g, count(DISTINCT v) AS cv FROM eg "
            "GROUP BY k % 2").collect())
        assert got == [(0, 2), (1, 2)]
    finally:
        s.stop()


def test_string_cast_edge_regressions():
    """Leading-zero big digit strings, strict date grammar, wide years."""
    vals = ["0000000000000000000001", "000", "12345-01-01", "+2021-03-05",
            "2021-03-05x", "-2021-03-05", "0000-01-01"]
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame({"v": vals}, "v string",
                                    num_partitions=1)
        .select(_cast("v", T.LongT), _cast("v", T.DateT).alias("c2")),
        expect_execs=["TpuProject"])
