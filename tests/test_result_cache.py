"""Result + subplan cache corpus (docs/caching.md): fingerprint-honest
invalidation (append / same-size rewrite / mtime-only touch / delete
all force re-execution), cache-on == cache-off == CPU bit-identity at
c=16 mixed tenants under fault injection, zero device work on a result
hit (dispatchCount delta 0), subplan build-table reuse with parity and
evict-first behavior under pool pressure (cache entries drop BEFORE
any live batch spills), cancelled-while-cached-hit returning cleanly,
history/SLO/doctor math excluding cache-served walls, and the lint
catalog fixtures for the new spans, metrics, Prometheus families,
history field, and confs."""

from __future__ import annotations

import os
import shutil
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import memory as MEM
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.serve import result_cache as RC
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen,
                           SmallIntGen, gen_batch)


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    RC.reset_subplan_cache()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()
    RC.reset_subplan_cache()


# ---------------------------------------------------------------------------
# Shared data + oracle results (the test_serve corpus shapes)
# ---------------------------------------------------------------------------

Q1S = """
SELECT flag, status, sum(qty) AS sq, min(price) AS mn,
       max(price) AS mx, count(*) AS c
FROM lineitem WHERE qty % 5 != 0
GROUP BY flag, status ORDER BY flag, status
"""

Q3S = """
SELECT brand, sum(amt) AS sa, count(*) AS c
FROM fact JOIN dim ON item = item2
GROUP BY brand ORDER BY brand LIMIT 50
"""


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("rc_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        li = gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 3000, 31), num_partitions=4)
        li.write.mode("overwrite").parquet(str(d / "lineitem"))
        fact = gen.createDataFrame(gen_batch(
            [("k", SmallIntGen()), ("item", IntegerGen()),
             ("amt", LongGen())], 2500, 32), num_partitions=3)
        fact.write.mode("overwrite").parquet(str(d / "fact"))
        dim = gen.createDataFrame(gen_batch(
            [("item2", IntegerGen()),
             ("brand", KeyStringGen(cardinality=5))], 400, 33),
            num_partitions=2)
        dim.write.mode("overwrite").parquet(str(d / "dim"))
    finally:
        gen.stop()
    return d


def _register_views(spark, data_dir) -> None:
    spark.read.parquet(str(data_dir / "lineitem")) \
        .createOrReplaceTempView("lineitem")
    spark.read.parquet(str(data_dir / "fact")) \
        .createOrReplaceTempView("fact")
    spark.read.parquet(str(data_dir / "dim")) \
        .createOrReplaceTempView("dim")


def _serial_rows(data_dir, sql, enabled="true", **extra):
    conf = {"spark.rapids.sql.enabled": enabled,
            "spark.rapids.sql.batchSizeRows": "512"}
    conf.update({k: str(v) for k, v in extra.items()})
    spark = TpuSparkSession(conf)
    try:
        _register_views(spark, data_dir)
        return [tuple(r) for r in
                spark.sql(sql)._execute().rows()]
    finally:
        spark.stop()


@pytest.fixture(scope="module")
def oracle(data_dir):
    """Serial cache-off results (and CPU cross-check) for both shapes —
    the bit-identity reference every cached response is held to."""
    q1 = _serial_rows(data_dir, Q1S)
    q3 = _serial_rows(data_dir, Q3S)
    assert q1 == _serial_rows(data_dir, Q1S, enabled="false")
    assert q3 == _serial_rows(data_dir, Q3S, enabled="false")
    return {"q1": q1, "q3": q3}


def _server(data_dir, **conf):
    from spark_rapids_tpu.serve import QueryServer
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.sql.resultCache.enabled": "true"}
    base.update({k: str(v) for k, v in conf.items()})
    srv = QueryServer(base).start()
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    srv.register_view("fact", str(data_dir / "fact"))
    srv.register_view("dim", str(data_dir / "dim"))
    return srv


# ---------------------------------------------------------------------------
# Result-cache hit: bit-identical payload, zero device work, billing
# ---------------------------------------------------------------------------

def test_hit_bit_identical_zero_device_work(data_dir, oracle):
    from spark_rapids_tpu.metrics import begin_epoch, registry_snapshot
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir)
    try:
        with ServeClient(srv.port, tenant="alice") as c:
            cold, h_cold = c.sql(Q1S)
            assert [tuple(r) for r in cold.rows()] == oracle["q1"]
            assert not h_cold.get("resultCacheHit")
            adm0 = srv.stats()["admission"]["admitted"]
            # the hit must execute NOTHING: no registries created, no
            # device program dispatched after this epoch stamp
            ep = begin_epoch()
            warm, h = c.sql(Q1S, tenant="bob")  # hits ACROSS tenants
            assert [tuple(r) for r in warm.rows()] == oracle["q1"]
            assert h["resultCacheHit"] and h["planCacheHit"]
            assert h["queueWaitMs"] == 0.0
            snap = registry_snapshot(epoch=ep)["metrics"]
            assert snap.get("dispatchCount", 0) == 0, snap
            st = srv.stats()
            rc = st["cache"]["result"]
            assert rc["hits"] == 1 and rc["entries"] >= 1
            assert rc["bytes"] > 0
            # billed on the tenant ledger without consuming a slot
            assert st["admission"]["admitted"] == adm0 + 1
            assert st["admission"]["tenants"]["bob"]["admitted"] == 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Invalidation matrix: any input change forces re-execution
# ---------------------------------------------------------------------------

def _part_files(d):
    return sorted(p for p in os.listdir(d) if p.endswith(".parquet"))


@pytest.mark.parametrize("mutation",
                         ["append", "rewrite", "touch", "delete"])
def test_invalidation_matrix(data_dir, oracle, tmp_path, mutation):
    """A file appended, rewritten in place (same size), mtime-only
    touched, or deleted must all drop the entry and fall through to a
    real execution whose result matches the CPU engine over the
    MUTATED inputs — never the stale cached bytes."""
    from spark_rapids_tpu.plan_cache import PLAN_CACHE
    from spark_rapids_tpu.serve import ServeClient
    li = tmp_path / "lineitem"
    shutil.copytree(str(data_dir / "lineitem"), str(li))
    for aux in ("fact", "dim"):
        shutil.copytree(str(data_dir / aux), str(tmp_path / aux))
    srv = _server(tmp_path)
    try:
        with ServeClient(srv.port, tenant="dash") as c:
            base, h0 = c.sql(Q1S)
            assert [tuple(r) for r in base.rows()] == oracle["q1"]
            _, h1 = c.sql(Q1S)
            assert h1["resultCacheHit"], "cache must be warm pre-mutation"

            part = str(li / _part_files(str(li))[0])
            if mutation == "append":
                shutil.copy(part, str(li / "part-zz-extra.parquet"))
            elif mutation == "rewrite":
                # identical bytes rewritten in place: size unchanged,
                # mtime_ns changes — content COULD have changed, so the
                # cache must not trust it
                with open(part, "rb") as f:
                    blob = f.read()
                time.sleep(0.01)
                with open(part, "wb") as f:
                    f.write(blob)
            elif mutation == "touch":
                st = os.stat(part)
                os.utime(part, ns=(st.st_atime_ns,
                                   st.st_mtime_ns + 1_000_000))
            else:
                os.remove(part)
            # drop the (path-keyed) plan template too, so the forced
            # re-execution re-lists and the CPU comparison below runs
            # over the mutated directory on both engines
            PLAN_CACHE.clear()

            fresh, h2 = c.sql(Q1S)
            assert not h2.get("resultCacheHit"), mutation
            rows = [tuple(r) for r in fresh.rows()]
            assert rows == _serial_rows(tmp_path, Q1S,
                                        enabled="false"), mutation
            if mutation in ("touch", "rewrite"):
                # content unchanged -> same answer, still re-executed
                assert rows == oracle["q1"]
            st = srv.stats()["cache"]["result"]
            assert st["invalidations"] >= 1
            # the re-execution repopulated with CURRENT fingerprints
            _, h3 = c.sql(Q1S)
            assert h3["resultCacheHit"]
    finally:
        srv.shutdown()


def test_register_view_invalidates(data_dir, oracle):
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir)
    try:
        with ServeClient(srv.port, tenant="a") as c:
            c.collect(Q1S)
            _, h = c.sql(Q1S)
            assert h["resultCacheHit"]
        # re-pointing ANY view bumps the generation: nothing cached
        # before it may be served after it
        srv.register_view("lineitem", str(data_dir / "lineitem"))
        with ServeClient(srv.port, tenant="a") as c:
            _, h = c.sql(Q1S)
            assert not h.get("resultCacheHit")
            assert [tuple(r) for r in c.sql(Q1S)[0].rows()] \
                == oracle["q1"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# c=16 mixed tenants, fault injection: cache-on == cache-off == CPU
# ---------------------------------------------------------------------------

def test_parity_concurrent_mixed_tenants_fault_injection(data_dir,
                                                         oracle):
    """16 concurrent mixed q1/q3 requests across 4 tenants, with OOM
    injection exercising the retry path underneath: every response —
    cold, cached, or retried — must be bit-identical to the serial
    cache-off oracle (which the oracle fixture cross-checks against
    the CPU engine)."""
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir,
                  **{"spark.rapids.sql.subplanCache.enabled": "true",
                     "spark.rapids.sql.serve.maxConcurrentQueries": 8,
                     "spark.rapids.sql.serve.maxQueued": 64,
                     "spark.rapids.sql.serve.maxConcurrentPerTenant": 8,
                     "spark.rapids.sql.test.injectOOM": "5"})
    errors: list = []
    results: dict = {}

    def worker(i: int) -> None:
        try:
            with ServeClient(srv.port, tenant=f"t{i % 4}") as c:
                kind = "q1" if i % 2 == 0 else "q3"
                rows = c.collect(Q1S if kind == "q1" else Q3S)
                results[i] = (kind, rows)
        except Exception as e:  # noqa: BLE001 - surfaced by the assert
            errors.append((i, repr(e)))

    try:
        # prime both shapes so the concurrent wave mixes cached hits
        # with (retried) executions on the same connections
        with ServeClient(srv.port, tenant="prime") as c:
            assert c.collect(Q1S) == oracle["q1"]
            assert c.collect(Q3S) == oracle["q3"]
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not errors, errors
        assert len(results) == 16
        for kind, rows in results.values():
            assert rows == oracle[kind], (
                f"{kind} diverged from the cache-off oracle")
        rc = srv.stats()["cache"]["result"]
        # both shapes were primed: the wave must be cache-served
        assert rc["hits"] >= 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Subplan cache: build-table reuse with parity, metric, cross-session
# ---------------------------------------------------------------------------

def test_subplan_cache_reuse_parity_and_metric(data_dir, oracle):
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512",
            "spark.rapids.sql.subplanCache.enabled": "true"}
    spark = TpuSparkSession(conf)
    try:
        _register_views(spark, data_dir)
        first = [tuple(r) for r in spark.sql(Q3S)._execute().rows()]
        assert first == oracle["q3"]
        sp0 = RC.subplan_cache_stats()
        assert sp0 is not None and sp0["entries"] >= 1
        again = [tuple(r) for r in spark.sql(Q3S)._execute().rows()]
        assert again == oracle["q3"]
        sp1 = RC.subplan_cache_stats()
        assert sp1["hits"] >= sp0["hits"] + 1
    finally:
        spark.stop()
    # a DIFFERENT session sharing the build-side subtree reuses the
    # same device-resident table (cross-query/cross-tenant sharing)
    spark2 = TpuSparkSession(conf)
    try:
        _register_views(spark2, data_dir)
        h_before = RC.subplan_cache_stats()["hits"]
        cross = [tuple(r) for r in spark2.sql(Q3S)._execute().rows()]
        assert cross == oracle["q3"]
        assert RC.subplan_cache_stats()["hits"] >= h_before + 1
    finally:
        spark2.stop()


def test_subplan_cache_fingerprint_invalidation(data_dir, oracle,
                                                tmp_path):
    for name in ("lineitem", "fact", "dim"):
        shutil.copytree(str(data_dir / name), str(tmp_path / name))
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512",
            # the plan cache serves the frozen template, so the second
            # run probes under the SAME subplan key and the re-stat is
            # the only thing standing between it and a stale reuse
            "spark.rapids.sql.planCache.enabled": "true",
            "spark.rapids.sql.subplanCache.enabled": "true"}
    spark = TpuSparkSession(conf)
    try:
        _register_views(spark, tmp_path)
        assert [tuple(r) for r in spark.sql(Q3S)._execute().rows()] \
            == oracle["q3"]
        # touch a build-side (dim) file: the plan cache still serves
        # the same template (same subplan key), so the next probe finds
        # the entry, re-stats, sees the mtime change, and must DROP it
        # instead of reusing the build table
        dim = str(tmp_path / "dim")
        part = os.path.join(dim, _part_files(dim)[0])
        st = os.stat(part)
        os.utime(part, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        inv0 = RC.subplan_cache_stats()["invalidations"]
        assert [tuple(r) for r in spark.sql(Q3S)._execute().rows()] \
            == oracle["q3"]
        assert RC.subplan_cache_stats()["invalidations"] >= inv0 + 1
    finally:
        spark.stop()


# ---------------------------------------------------------------------------
# Evict-first: pool pressure drops cache entries before any live spill
# ---------------------------------------------------------------------------

def _batch(n=256, seed=0):
    from spark_rapids_tpu.columnar.device import DeviceBatch
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.sql import types as T
    rng = np.random.default_rng(seed)
    col = HostColumn(T.LongT, rng.integers(0, 1 << 40, n),
                     np.ones(n, dtype=bool))
    return DeviceBatch.from_host(
        HostBatch(T.StructType([T.StructField("v", T.LongT)]), [col], n))


def test_cache_entries_drop_before_live_spill(tmp_path):
    """Under device pressure the store must DROP cache-tier entries
    (release, no spill IO) before demoting any live query's batch —
    even when the live batch is the LRU-oldest."""
    b_live, b_cache, b_new = _batch(256, 1), _batch(256, 2), \
        _batch(256, 3)
    budget = b_live.sizeof() * 2 + 10
    store = MEM.DeviceStore(budget, 1 << 30, str(tmp_path))
    h_live = store.register(b_live, owner="query")
    h_cache = store.register(b_cache, owner="subplanCache",
                             cache_entry=True)
    store.register(b_new, owner="query")  # over budget -> enforce
    assert store.cache_drop_count == 1
    assert store.cache_dropped_bytes > 0
    assert store.spill_count == 0, \
        "a live batch spilled while a cache entry was resident"
    assert h_cache.closed
    # the live batch survived on device, bit-intact
    got = np.asarray(h_live.get().columns[0].data)[:256]
    assert (got == np.asarray(b_live.columns[0].data)[:256]).all()
    st = store.stats()
    assert st["cacheDropCount"] == 1 and st["cacheDroppedBytes"] > 0


def test_subplan_cache_observes_pressure_drop_as_eviction(tmp_path):
    """A pool-dropped build table is a MISS (counted as an eviction)
    at the owning cache's next lookup, never an error."""
    store = MEM.DeviceStore(1 << 30, 1 << 30, str(tmp_path))
    cache = RC.SubplanCache(max_entries=8, max_bytes=1 << 30)
    b = _batch(128, 7)
    src = tmp_path / "src.bin"
    src.write_bytes(b"x" * 64)
    paths = (str(src),)
    captured = (paths, RC.source_fingerprints(paths))
    assert cache.put("k1", captured, b, store)
    assert cache.lookup("k1") is not None
    # the pool drops the entry out from under the cache
    store.spill_device_down(0)
    assert store.cache_drop_count == 1
    ev0 = cache.evictions
    assert cache.lookup("k1") is None
    st = cache.stats()
    assert cache.evictions == ev0 + 1
    assert st["entries"] == 0


# ---------------------------------------------------------------------------
# Cancelled while serving a cached hit
# ---------------------------------------------------------------------------

def test_cancelled_while_cached_hit_returns_cleanly(data_dir, oracle):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeCancelled
    srv = _server(data_dir)
    try:
        with ServeClient(srv.port, tenant="a") as c:
            c.collect(Q1S)  # populate
        started, release = threading.Event(), threading.Event()
        orig = srv._result_cache.lookup

        def parked_lookup(sql):
            entry = orig(sql)
            if entry is not None:
                started.set()
                release.wait(timeout=30)
            return entry

        srv._result_cache.lookup = parked_lookup
        outcome: list = []

        def submitter():
            try:
                with ServeClient(srv.port, tenant="a") as c:
                    c.sql(Q1S, query_id="q-cached")
                    outcome.append(("ok", None))
            except ServeCancelled as e:
                outcome.append(("cancelled", e))
            except Exception as e:  # noqa: BLE001 - asserted below
                outcome.append(("error", repr(e)))

        t = threading.Thread(target=submitter)
        t.start()
        assert started.wait(timeout=30), "hit never reached the cache"
        from spark_rapids_tpu.serve import ServeClient as SC
        with SC(srv.port, tenant="a") as killer:
            assert killer.cancel(query_id="q-cached") == 1
        release.set()
        t.join(timeout=60)
        srv._result_cache.lookup = orig
        assert outcome and outcome[0][0] == "cancelled", outcome
        assert outcome[0][1].where == "cached"
        # the connection protocol stayed synchronized: the SAME server
        # keeps serving, and the entry is still valid
        with ServeClient(srv.port, tenant="a") as c:
            rows, h = c.sql(Q1S)
            assert h["resultCacheHit"]
            assert [tuple(r) for r in rows.rows()] == oracle["q1"]
        assert srv.stats()["queriesCancelled"] == 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# History / SLO / doctor math excludes cache-served walls
# ---------------------------------------------------------------------------

def _rec(ts, sig="a" * 40, status="finished", wall=0.1, **kw):
    r = {"version": 1, "ts": ts, "signature": sig, "status": status,
         "wallSeconds": wall, "queueWaitSeconds": 0.0,
         "outputRows": 10}
    r.update(kw)
    return r


def test_signature_aggregates_exclude_cached_walls():
    from spark_rapids_tpu.telemetry import history as H
    t0 = time.time()
    recs = [_rec(t0 + i, wall=2.0, tenant="t") for i in range(3)]
    recs += [_rec(t0 + 10 + i, wall=0.001, tenant="t",
                  resultCacheHit=True) for i in range(5)]
    a = H.signature_aggregates(recs)["a" * 40]
    # cached records count in the histogram but not the latency math
    assert a["count"] == 8
    assert a["wallP50"] == pytest.approx(2.0)
    assert a["wallP99"] == pytest.approx(2.0)


def test_slo_window_excludes_cached_queries(tmp_path):
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.telemetry import history as H
    d = str(tmp_path / "hist")
    store = H.HistoryStore(d, max_bytes=1 << 20, max_age_days=14)
    now = time.time()
    for i in range(3):
        store.append(_rec(now - 1 - i, wall=0.2, tenant="gold"))
    for i in range(5):
        store.append(_rec(now - 1 - i, wall=0.001, tenant="gold",
                          resultCacheHit=True))
    slo = H.SloTracker(TpuConf({
        "spark.rapids.sql.telemetry.history.dir": d,
        "spark.rapids.sql.serve.slo.p99Ms": "100"}))
    out = slo.evaluate(max_age_s=0)["gold"]
    # 3 real 200ms queries burn against the 100ms objective; the 5
    # near-zero cached hits must not dilute the ratio to 3/8
    assert out["windowQueries"] == 3
    assert out["violations"] == 3
    assert out["burnRatio"] == pytest.approx(1.0)


def test_doctor_baseline_and_warm_start_exclude_cached(tmp_path):
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.telemetry import doctor as D
    from spark_rapids_tpu.telemetry import history as H
    recs = [_rec(time.time() - 100 + i, wall=3.0) for i in range(4)]
    recs += [_rec(time.time() - 50 + i, wall=0.001,
                  resultCacheHit=True) for i in range(6)]
    target = _rec(time.time(), wall=3.1)
    base = D._baseline(recs + [target], target)
    assert base["count"] == 4
    assert base["wallP50"] == pytest.approx(3.0)
    # warm start: cached walls never seed the watchdog's p99 history
    d = str(tmp_path / "hist")
    store = H.HistoryStore(d, max_bytes=1 << 20, max_age_days=14)
    for r in recs:
        store.append(r)
    out = H.warm_start(TpuConf({
        "spark.rapids.sql.telemetry.history.dir": d,
        "spark.rapids.sql.telemetry.history.warmStart": "true"}))
    assert out["records"] == 10
    assert out["walls"] == 4


# ---------------------------------------------------------------------------
# Lint-catalog + docs fixtures (satellites: every new name registered)
# ---------------------------------------------------------------------------

def test_catalogs_cover_cache_names():
    from spark_rapids_tpu.metrics import METRIC_DESCRIPTIONS
    from spark_rapids_tpu.telemetry import history as H
    from spark_rapids_tpu.telemetry.prometheus import SERVER_FAMILY_HELP
    assert "resultCacheHit" in TR.SPAN_CATALOG
    assert "cacheEntryDrop" in TR.SPAN_CATALOG
    assert "resultCacheHit" in H.HISTORY_FIELD_CATALOG
    assert "subplanCacheHits" in METRIC_DESCRIPTIONS
    for fam in ("srt_cache_result_hits_total",
                "srt_cache_result_misses_total",
                "srt_cache_result_entries",
                "srt_cache_result_bytes",
                "srt_cache_result_invalidations_total",
                "srt_cache_result_evictions_total",
                "srt_cache_subplan_hits_total",
                "srt_cache_subplan_misses_total",
                "srt_cache_subplan_entries",
                "srt_cache_subplan_bytes",
                "srt_cache_subplan_invalidations_total",
                "srt_cache_subplan_evictions_total"):
        assert fam in SERVER_FAMILY_HELP, fam


def test_cache_confs_registered_and_documented():
    from spark_rapids_tpu.conf import (RESULT_CACHE_ENABLED,
                                       RESULT_CACHE_MAX_BYTES,
                                       RESULT_CACHE_MAX_ENTRIES,
                                       SUBPLAN_CACHE_ENABLED,
                                       SUBPLAN_CACHE_MAX_BYTES,
                                       SUBPLAN_CACHE_MAX_ENTRIES,
                                       TpuConf)
    c = TpuConf({})
    assert c.get(RESULT_CACHE_ENABLED) is False
    assert c.get(SUBPLAN_CACHE_ENABLED) is False
    assert c.get(RESULT_CACHE_MAX_ENTRIES) == 256
    assert c.get(RESULT_CACHE_MAX_BYTES) == 256 << 20
    assert c.get(SUBPLAN_CACHE_MAX_ENTRIES) == 32
    assert c.get(SUBPLAN_CACHE_MAX_BYTES) == 64 << 20
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "docs", "configs.md")) as f:
        configs = f.read()
    assert "spark.rapids.sql.resultCache.enabled" in configs
    assert "spark.rapids.sql.subplanCache.enabled" in configs
    with open(os.path.join(root, "docs", "observability.md")) as f:
        obs = f.read()
    assert "srt_cache_result_hits_total" in obs
    assert "resultCacheHit" in obs
    assert os.path.exists(os.path.join(root, "docs", "caching.md"))


def test_cache_confs_excluded_from_plan_signature(data_dir):
    """resultCache.*/subplanCache.* never change what a plan computes,
    so cache-on and cache-off runs of one shape share one signature
    (baselines, quarantine, doctor history)."""
    sigs = []
    for extra in ({}, {"spark.rapids.sql.resultCache.enabled": "true",
                       "spark.rapids.sql.subplanCache.enabled": "true",
                       "spark.rapids.sql.resultCache.maxEntries": "7"}):
        conf = {"spark.rapids.sql.enabled": "true",
                "spark.rapids.sql.batchSizeRows": "512",
                # signatures are computed on the plan-cache path
                "spark.rapids.sql.planCache.enabled": "true"}
        conf.update(extra)
        spark = TpuSparkSession(conf)
        try:
            _register_views(spark, data_dir)
            spark.sql(Q1S)._execute()
            sigs.append(spark.thread_plan_signature())
        finally:
            spark.stop()
    assert sigs[0] is not None and sigs[0] == sigs[1]


def test_server_stats_and_prometheus_render_cache_section(data_dir):
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.telemetry.prometheus import render_prometheus
    from spark_rapids_tpu.telemetry.top import format_top
    srv = _server(data_dir,
                  **{"spark.rapids.sql.subplanCache.enabled": "true"})
    try:
        with ServeClient(srv.port, tenant="a") as c:
            c.collect(Q3S)
            c.collect(Q3S)
            st = c.stats()
        cache = st["cache"]
        for side in ("result", "subplan"):
            for k in ("entries", "bytes", "hits", "misses",
                      "invalidations", "evictions"):
                assert k in cache[side], (side, k)
        assert cache["result"]["hits"] >= 1
        text = render_prometheus(server_stats=st)
        assert "srt_cache_result_hits_total" in text
        assert "srt_cache_subplan_entries" in text
        frame = format_top(st)
        assert "cache:" in frame and "result" in frame
    finally:
        srv.shutdown()
