"""Behavioral coverage for session-level conf keys wired in round 4:
hasNans as a float-sort-key kernel hint, memory.tpu.debug store logging,
and the device shuffle-partition coalescing knob."""

from __future__ import annotations

import logging

import numpy as np

from spark_rapids_tpu.sql import functions as F

from tests.datagen import DoubleGen, IntegerGen, gen_batch
from tests.harness import assert_tpu_and_cpu_equal_collect


def _df(s, cols, n=512, seed=77, parts=2):
    return s.createDataFrame(gen_batch(cols, n, seed), num_partitions=parts)


def test_has_nans_false_same_results():
    """With NaN-free data, hasNans=false (drops the is-NaN sort word —
    one fewer radix pass per float key) must give identical sort/group
    results; kernel_salt() keeps compiled programs distinct per flag."""
    nonan = DoubleGen(special=False)  # no NaN/inf specials
    for flag in ("true", "false"):
        assert_tpu_and_cpu_equal_collect(
            lambda s: _df(s, [("f", nonan), ("i", IntegerGen())])
            .groupBy("f").agg(F.sum("i").alias("s"))
            .orderBy("f"),
            conf={"spark.rapids.sql.hasNans": flag},
            expect_execs=["TpuHashAggregate", "TpuSort"])


def test_has_nans_true_handles_nans():
    """Default hasNans=true keeps exact NaN grouping (all NaNs one
    group, NaN sorts greatest)."""
    assert_tpu_and_cpu_equal_collect(
        lambda s: s.createDataFrame(
            {"f": [1.0, float("nan"), 2.0, float("nan"), None],
             "i": [1, 2, 3, 4, 5]}, "f double, i long")
        .groupBy("f").agg(F.sum("i").alias("s")).orderBy("f"),
        expect_execs=["TpuHashAggregate", "TpuSort"])


def test_memory_debug_logs_spill(caplog):
    from spark_rapids_tpu import memory
    from spark_rapids_tpu.sql.session import TpuSparkSession
    spark = TpuSparkSession({
        "spark.rapids.sql.enabled": "true",
        "spark.rapids.memory.tpu.poolSize": str(1 << 16),
        "spark.rapids.memory.tpu.debug": "true",
    })
    try:
        with caplog.at_level(logging.INFO, "spark_rapids_tpu.memory"):
            df = spark.createDataFrame(
                {"k": (np.arange(4096) % 7).tolist(),
                 "v": np.arange(4096).tolist()}, "k long, v long")
            df.repartition(4, F.col("k")).groupBy("k").agg(
                F.sum("v").alias("s")).collect()
        assert memory._STORE is not None
        if memory._STORE.spill_count:
            assert any("spill device->host" in r.message
                       for r in caplog.records)
    finally:
        spark.stop()


def test_device_partitions_conf_controls_exchange():
    """devicePartitions=4 keeps a real multi-partition device split;
    auto (default) coalesces to 1 in-process — results identical."""
    for conf in ({}, {"spark.rapids.sql.shuffle.devicePartitions": "4"}):
        assert_tpu_and_cpu_equal_collect(
            lambda s: _df(s, [("i", IntegerGen())])
            .groupBy("i").agg(F.count("*").alias("c")).orderBy("i"),
            conf=dict(conf),
            expect_execs=["TpuExchange", "TpuHashAggregate"])


def test_cbo_reverts_small_device_island():
    """spark.rapids.sql.optimizer.enabled: a CPU-sandwiched single
    project island loses its transition cost and reverts to CPU; with
    the optimizer off the island stays on device (CostBasedOptimizer
    v0)."""
    from spark_rapids_tpu.sql.session import TpuSparkSession

    def plan_for(cbo: str):
        sp = TpuSparkSession({
            "spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.optimizer.enabled": cbo,
            # make the island minimal: a single device-able projection
            # over a CPU source, collected straight back to rows
        })
        try:
            df = sp.createDataFrame(
                {"a": list(range(64))}, "a int").select(
                (F.col("a") + 1).alias("b"))
            sp.start_capture()
            df.collect()
            return "\n".join(p.tree_string()
                             for p in sp.get_captured_plans())
        finally:
            sp.stop()

    on = plan_for("true")
    off = plan_for("false")
    assert "TpuProject" in off, off
    assert "TpuProject" not in on and "Project" in on, on


def test_cbo_keeps_wide_islands():
    """Aggregation islands repay their transitions and must survive the
    optimizer pass."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    sp = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                          "spark.rapids.sql.optimizer.enabled": "true"})
    try:
        df = sp.createDataFrame(
            {"k": [i % 5 for i in range(64)], "v": list(range(64))},
            "k int, v long").groupBy("k").agg(F.sum("v").alias("s"))
        sp.start_capture()
        df.collect()
        pstr = "\n".join(p.tree_string()
                         for p in sp.get_captured_plans())
        assert "TpuHashAggregate" in pstr, pstr
    finally:
        sp.stop()


def test_cbo_keeps_regex_island_on_large_input():
    """CBO v1: a SINGLE regex-heavy filter island over a large scan
    stays on device (the python re loop dwarfs the wire cost) — the v0
    pattern-match wrongly reverted every 1-op island."""
    import numpy as np
    from spark_rapids_tpu.sql.session import TpuSparkSession
    import os, shutil, tempfile
    d = tempfile.mkdtemp()
    try:
        gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
        n = 300_000
        gen.createDataFrame(
            {"s": [f"row{i:07d}" for i in range(n)]},
            "s string").write.mode("overwrite").parquet(d)
        gen.stop()
        sp = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                              "spark.rapids.sql.optimizer.enabled": "true"})
        try:
            sp.start_capture()
            df = sp.read.parquet(d).filter("s LIKE 'row00%'")
            got = df.collect()
            pstr = "\n".join(p.tree_string()
                             for p in sp.get_captured_plans())
        finally:
            sp.stop()
        assert len(got) == 100_000
        assert "TpuFilter" in pstr, pstr
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_cbo_reverts_multi_op_island_on_tiny_input():
    """CBO v1: even a TWO-op cheap island over tiny data reverts (the
    flat per-island sync latency dominates) — v0 only caught 1-op
    islands."""
    from spark_rapids_tpu.sql.session import TpuSparkSession
    sp = TpuSparkSession({"spark.rapids.sql.enabled": "true",
                          "spark.rapids.sql.optimizer.enabled": "true"})
    try:
        sp.start_capture()
        df = sp.createDataFrame({"a": list(range(64))}, "a int") \
            .filter(F.col("a") > 3).select((F.col("a") + 1).alias("b"))
        out = df.collect()
        pstr = "\n".join(p.tree_string() for p in sp.get_captured_plans())
    finally:
        sp.stop()
    assert len(out) == 60
    assert "TpuProject" not in pstr and "TpuFilter" not in pstr, pstr


# -- metric timers (ISSUE 1 satellite: drain-time overlap) ------------------

def test_timed_wall_unions_concurrent_intervals():
    """N pool threads timing the same phase concurrently must advance
    the metric by WALL time (interval union), not N stacked
    thread-times — the round-5 bench reported an 11.6s drain against a
    5.4s wall because of exactly this overlap."""
    import threading
    import time

    from spark_rapids_tpu.metrics import MetricRegistry

    reg = MetricRegistry("MODERATE")

    def work():
        with reg.timed_wall("pipelineDrainTime"):
            time.sleep(0.15)

    threads = [threading.Thread(target=work) for _ in range(4)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - start
    got = reg.value("pipelineDrainTime") / 1e9
    # concurrent intervals count once: metric <= actual wall, and far
    # below the 0.6s a per-thread sum would report
    assert got <= wall + 0.02, (got, wall)
    assert got < 0.45, got


def test_timed_wall_sums_disjoint_intervals():
    import time

    from spark_rapids_tpu.metrics import MetricRegistry

    reg = MetricRegistry("MODERATE")
    for _ in range(3):
        with reg.timed_wall("decodeTime"):
            time.sleep(0.03)
    got = reg.value("decodeTime") / 1e9
    assert 0.09 <= got < 0.3, got
